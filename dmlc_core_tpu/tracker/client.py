"""Worker-side rendezvous client (the rabit bootstrap, reimplemented).

The reference repo contains only the tracker side; the worker half lives
in downstream rabit. This client implements that wire contract so the
framework is self-contained: connect to the tracker, receive rank +
tree/ring neighbors, wire real TCP links to peers, and report
shutdown/log messages. The data plane stays with XLA collectives
(parallel/); these links carry host-side coordination only.

Env bootstrap mirrors the worker contract (SURVEY §2.6):
DMLC_TRACKER_URI/PORT, DMLC_TASK_ID as the job id for rank recovery.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Any, Dict, List, Optional, Tuple

from ..io.retry import _env_float
from ..telemetry import timeseries as _timeseries
from ..telemetry import tracing as _tracing
from .protocol import (
    CMD_METRICS,
    CMD_PRINT,
    CMD_RECOVER,
    CMD_SHUTDOWN,
    CMD_START,
    FramedSocket,
    connect_peer,
    connect_worker_retry,
    make_listener,
)

__all__ = ["RabitWorker"]


class RabitWorker:
    """One worker's view of the rendezvous.

    Peer links get explicit timeouts: dials and the incoming-link
    identify recv are capped by ``DMLC_PEER_CONNECT_TIMEOUT`` (30 s
    default) so a half-dead peer can never wedge the wiring, and wired
    links are handed over in blocking mode (consumers — the collective
    engine — manage their own IO deadlines). ``shutdown()``/``close()``
    are idempotent."""

    def __init__(
        self,
        tracker_uri: Optional[str] = None,
        tracker_port: Optional[int] = None,
        jobid: Optional[str] = None,
    ) -> None:
        self.tracker_uri = tracker_uri or os.environ["DMLC_TRACKER_URI"]
        self.tracker_port = int(
            tracker_port
            if tracker_port is not None
            else os.environ["DMLC_TRACKER_PORT"]
        )
        self.jobid = (
            jobid
            if jobid is not None
            else os.environ.get("DMLC_TASK_ID", "NULL")
        )
        self.rank = -1
        self.parent = -1
        self.world_size = -1
        self.tree_neighbors: List[int] = []
        self.ring_prev = -1
        self.ring_next = -1
        self.links: Dict[int, socket.socket] = {}
        self._listener: Optional[socket.socket] = None
        self.connect_timeout = _env_float("DMLC_PEER_CONNECT_TIMEOUT", 30.0)
        self._shut = False
        self._ts_seq = 0  # newest time-series sample seq already shipped

    # -- tracker connection helpers -----------------------------------------
    def _connect_tracker(
        self, cmd: str, rank: int, world: int,
        retry_secs: Optional[float] = None,
    ) -> FramedSocket:
        # every tracker RPC this worker makes — rendezvous, recover,
        # log, heartbeat, shutdown — rides the reconnect-with-backoff
        # dial, so a tracker crash+relaunch window (supervised restart
        # from its journal) is survived instead of fatal
        return connect_worker_retry(
            self.tracker_uri, self.tracker_port, rank, world, self.jobid, cmd,
            trace_ctx=_tracing.rpc_context(), retry_secs=retry_secs,
        )

    # -- rendezvous ----------------------------------------------------------
    def start(self, world_size: int = -1, recover_rank: int = -1) -> int:
        """Rendezvous with the tracker; wires peer links. Returns rank.

        ``recover_rank`` >= 0 re-registers after a restart (cmd=recover),
        reclaiming the previous rank (reference tracker.py:290-292).
        Re-entrant: a survivor re-joining after a peer death calls
        ``start(recover_rank=self.rank)`` with its live links intact —
        only the missing ones are re-brokered (rabit recover contract).
        """
        if self._listener is not None:
            # re-entry (recover / retry after a failed start): the old
            # accept socket is stale — peers are told the NEW port
            self._listener.close()
        self._listener = make_listener("", 0)
        self._shut = False
        my_port = self._listener.getsockname()[1]

        cmd = CMD_RECOVER if recover_rank >= 0 else CMD_START
        fs = self._connect_tracker(cmd, recover_rank, world_size)
        self.rank = fs.recv_int()
        # bind the shard-lease identity to the rendezvous rank: ranks
        # are batch-assigned in connect order, so they need not equal
        # DMLC_TASK_ID — but cmd=metrics heartbeats renew shard leases
        # BY rendezvous rank, so a lease client in this process must
        # lease under the same number (tracker/shardsvc.py)
        os.environ["DMLC_SHARD_RANK"] = str(self.rank)
        # every rendezvoused worker samples its registry on the default
        # time-series ring (DMLC_TS_INTERVAL, default 2 s; DMLC_TS=off
        # disables) — heartbeats ship the new samples so the tracker's
        # /metrics.json?window= has per-rank windowed rates
        if _timeseries.sampling_enabled():
            _timeseries.ensure_default()
        self.parent = fs.recv_int()
        self.world_size = fs.recv_int()
        n_tree = fs.recv_int()
        self.tree_neighbors = [fs.recv_int() for _ in range(n_tree)]
        self.ring_prev = fs.recv_int()
        self.ring_next = fs.recv_int()

        # brokering loop: stays on this connection until every outgoing
        # link succeeds (the tracker re-enters its loop whenever nerr != 0,
        # reference assign_rank tracker.py:104-135)
        expected = set(self.tree_neighbors)
        if self.ring_prev not in (-1, self.rank):
            expected.add(self.ring_prev)
        if self.ring_next not in (-1, self.rank):
            expected.add(self.ring_next)
        while True:
            # only report links in the current neighbor set (the tracker
            # asserts goodset ⊆ nnset)
            good = sorted(set(self.links) & expected)
            fs.send_int(len(good))
            for r in good:
                fs.send_int(r)
            n_conn = fs.recv_int()
            n_wait = fs.recv_int()
            to_connect: List[Tuple[str, int, int]] = []
            for _ in range(n_conn):
                host = fs.recv_str()
                port = fs.recv_int()
                peer_rank = fs.recv_int()
                to_connect.append((host, port, peer_rank))
            n_err = 0
            for host, port, peer_rank in to_connect:
                try:
                    # the dial AND the identifying send ride one explicit
                    # deadline ($DMLC_PEER_CONNECT_TIMEOUT): a half-dead
                    # peer fails this round of brokering instead of
                    # wedging it (the tracker re-enters on n_err != 0)
                    peer = connect_peer(
                        host, port, self.rank, timeout=self.connect_timeout
                    )
                    self.links[peer_rank] = peer
                except OSError:
                    n_err += 1
            fs.send_int(n_err)
            if n_err == 0:
                break
        fs.send_int(my_port)
        fs.close()
        self._await_peer_links(n_wait)
        return self.rank

    def _await_peer_links(self, n_wait: int) -> None:
        """Accept ``n_wait`` incoming peer links under one shared
        deadline ($DMLC_LINK_WAIT_TIMEOUT seconds total, default 300;
        <= 0 waits forever). A peer that never dials in (e.g. it wired
        to a crashed predecessor and did not re-enter rendezvous — the
        rabit recover contract asks survivors to re-join) or connects
        without identifying must fail this worker loudly so a supervisor
        can retry/abort, never hang the brokering forever. The deadline
        spans accept() AND the identifying recv; on failure the listener
        and this round's accepted links are closed, so a caller may
        retry start() cleanly."""
        raw = os.environ.get("DMLC_LINK_WAIT_TIMEOUT", "300")
        try:
            total = float(raw)
        except ValueError:
            total = 300.0
        deadline = None if total <= 0 else time.monotonic() + total
        accepted: List[socket.socket] = []
        try:
            for _ in range(n_wait):
                if deadline is not None:
                    self._listener.settimeout(
                        max(0.001, deadline - time.monotonic())
                    )
                peer, _addr = self._listener.accept()
                accepted.append(peer)
                if deadline is not None:
                    peer.settimeout(max(0.001, deadline - time.monotonic()))
                peer_rank = FramedSocket(peer).recv_int()
                peer.settimeout(None)
                self.links[peer_rank] = peer
        except (socket.timeout, TimeoutError):
            for p in accepted:
                p.close()
                self.links = {
                    r: s for r, s in self.links.items() if s is not p
                }
            self._listener.close()
            raise RuntimeError(
                f"rank {self.rank}: timed out after {total:.0f}s waiting "
                f"for incoming peer link(s) ({n_wait} expected); if this "
                "worker was relaunched, surviving peers must re-rendezvous "
                "(start(recover_rank=...)) for links to re-wire; raise "
                "$DMLC_LINK_WAIT_TIMEOUT for slow-starting clusters"
            ) from None

    # -- control messages ----------------------------------------------------
    def log(self, msg: str) -> None:
        """Relay a message through the tracker (cmd=print,
        reference tracker.py:269-271)."""
        fs = self._connect_tracker(CMD_PRINT, self.rank, -1)
        fs.send_str(msg)
        fs.close()

    def heartbeat(self, metrics: Optional[Dict[str, Any]] = None) -> None:
        """Piggyback a compact telemetry snapshot on a tracker heartbeat
        (cmd=metrics). ``metrics`` defaults to the process-global
        registry snapshot — one call ships every counter/gauge/histogram
        this worker accumulated; the tracker aggregates per rank and
        cluster-wide and serves them on its /metrics endpoint
        (docs/observability.md). Call it from the training loop at
        whatever cadence suits the job (each epoch is plenty).

        When the default time-series ring is sampling (every
        rendezvoused worker's is), the payload also carries the ring's
        NEW samples under ``timeseries`` — the increments feeding the
        tracker's windowed-rate store — and the tracker's wall-stamp
        reply is bracketed to estimate this host's clock offset
        (RTT midpoint → ``tracing.set_clock_offset``; a multi-host
        trace merge aligns timelines with it).

        Requires a completed ``start()``: without a rank the tracker
        would silently drop the frame — fail loudly at the caller
        instead."""
        if self.rank < 0:
            raise RuntimeError(
                "heartbeat() before start(): this worker has no rank yet, "
                "so the tracker would discard its metrics"
            )
        ring = None
        if metrics is None:
            from ..telemetry import default_registry

            metrics = default_registry().snapshot()
            # the default-snapshot heartbeat also ships the ring's new
            # samples; an explicit payload stays exactly what the
            # caller handed over
            ring = _timeseries.default_ring(create=False)
        shipped_seq = None
        if ring is not None:
            ring.sample()  # the series always reaches "now"
            new = ring.samples(since=self._ts_seq)
            if new:
                metrics = dict(metrics)
                metrics["timeseries"] = new
                shipped_seq = new[-1]["seq"]
        data = json.dumps(metrics, separators=(",", ":"))
        # the rendezvous string framing bounds payloads at MAX_STR
        # (1 MiB): a fat registry × many retained samples must shed its
        # OLDEST samples (already aged out of any live window) rather
        # than have the tracker call the frame hostile and drop it
        budget = FramedSocket.MAX_STR - (128 << 10)
        while len(data) > budget and len(metrics.get("timeseries", ())) > 1:
            keep = metrics["timeseries"]
            metrics["timeseries"] = keep[(len(keep) + 1) // 2 :]
            data = json.dumps(metrics, separators=(",", ":"))
        if len(data) > budget and "timeseries" in metrics:
            # even one sample blows the frame (a gigantic registry):
            # ship the bare snapshot and DON'T advance the shipped seq
            # — un-shipped samples stay eligible for the next attempt
            metrics = {k: v for k, v in metrics.items() if k != "timeseries"}
            data = json.dumps(metrics, separators=(",", ":"))
            shipped_seq = None
        with _tracing.span("dmlc:heartbeat", rank=self.rank):
            try:
                # short retry budget: a heartbeat runs on the training
                # thread's cadence, so it rides out a brief tracker
                # outage but never blocks an epoch on the full
                # DMLC_TRACKER_RETRY_SECS reconnect window — a failed
                # tick simply re-ships everything next tick
                fs = self._connect_tracker(
                    CMD_METRICS, self.rank, -1,
                    retry_secs=_env_float("DMLC_HEARTBEAT_RETRY_SECS", 2.0),
                )
            except (ConnectionError, OSError, TimeoutError):
                # tracker down: the sample stays un-shipped (seq NOT
                # advanced) and the next tick retries — a heartbeat
                # must never raise into the worker's training thread
                return
            try:
                fs.send_str(data)
                # the tracker answers with its wall stamp the moment it
                # has read the payload; offset = RTT midpoint - stamp.
                # t0 is taken AFTER the upload so the bracket spans
                # only the tracker's read-tail + reply — bracketing the
                # connect+upload would bias the midpoint by half the
                # payload's transfer time
                t0 = time.time_ns()  # noqa: L008 (RTT bracketing wall stamps for clock-offset estimation, not a duration)
                try:
                    reply = json.loads(fs.recv_str())
                    t1 = time.time_ns()  # noqa: L008 (RTT bracketing wall stamp, see above)
                    wall = reply.get("wall_ns")
                    if isinstance(wall, (int, float)):
                        _tracing.set_clock_offset(
                            (t0 + t1) / 2.0 - float(wall)
                        )
                except (ConnectionError, OSError, ValueError):
                    pass  # an old tracker replies nothing: no estimate
            except (ConnectionError, OSError, TimeoutError):
                # died mid-send: same contract — un-shipped, no raise
                return
            finally:
                fs.close()
        if shipped_seq is not None:
            # advance only after the send went through — a failed
            # heartbeat re-ships its samples next time
            self._ts_seq = shipped_seq

    def shutdown(self) -> None:
        """Signal completion (cmd=shutdown, reference tracker.py:272-277).
        Idempotent: a second call is a no-op — the tracker treats a
        duplicate shutdown from the same rank as a protocol violation,
        so teardown paths that race (atexit + explicit close) must not
        double-send it."""
        if self._shut:
            return
        self._shut = True
        fs = self._connect_tracker(CMD_SHUTDOWN, self.rank, -1)
        fs.close()
        self.close()

    def close(self) -> None:
        """Close peer links + the accept socket. Idempotent (close after
        shutdown, or close twice, is a no-op)."""
        links, self.links = self.links, {}
        for s in links.values():
            try:
                s.close()
            except OSError:
                pass
        if self._listener is not None:
            self._listener.close()
            self._listener = None
