"""Allreduce topology computation: binomial tree + node-sharing ring.

Reference: tracker/dmlc_tracker/tracker.py:165-252. Pure functions over
rank counts — unit-testable without sockets.

The tree is a binary heap ordering (parent (r+1)//2-1, children 2r+1,
2r+2): latency-optimal broadcast/reduce. The ring threads through the tree
sharing edges where possible (bandwidth-heavy allreduce + data recovery in
rabit). ``get_link_map`` relabels ranks to follow ring order so neighbor
ranks land on neighbor hosts.

On TPU these maps are superseded by the ICI mesh (parallel/mesh.py) for
the data plane; they remain for host-side coordination and rabit clients.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

__all__ = ["get_neighbors", "get_tree", "get_ring", "get_link_map"]


def get_neighbors(rank: int, n: int) -> List[int]:
    """Tree neighbors of a rank: parent first, then children
    (reference get_neighbor, tracker.py:165-175)."""
    out: List[int] = []
    parent = (rank + 1) // 2 - 1
    if parent >= 0:
        out.append(parent)
    left, right = 2 * rank + 1, 2 * rank + 2
    if left < n:
        out.append(left)
    if right < n:
        out.append(right)
    return out


def get_tree(n: int) -> Tuple[Dict[int, List[int]], Dict[int, int]]:
    """(tree_map rank→neighbors, parent_map rank→parent; root's parent -1)."""
    tree_map = {r: get_neighbors(r, n) for r in range(n)}
    parent_map = {r: (r + 1) // 2 - 1 for r in range(n)}
    return tree_map, parent_map


def _share_ring_order(
    tree_map: Dict[int, List[int]], parent_map: Dict[int, int], root: int
) -> List[int]:
    """DFS visiting order that shares edges with the tree; the last child's
    subtree is traversed in reverse so consecutive ring hops stay adjacent
    (reference find_share_ring, tracker.py:193-211)."""
    children = [v for v in tree_map[root] if v != parent_map[root]]
    if not children:
        return [root]
    order = [root]
    for i, child in enumerate(children):
        sub = _share_ring_order(tree_map, parent_map, child)
        if i == len(children) - 1:
            sub = sub[::-1]
        order.extend(sub)
    return order


def get_ring(
    tree_map: Dict[int, List[int]], parent_map: Dict[int, int]
) -> Dict[int, Tuple[int, int]]:
    """rank → (prev, next) around the shared ring (reference get_ring,
    tracker.py:212-225)."""
    assert parent_map[0] == -1
    order = _share_ring_order(tree_map, parent_map, 0)
    assert len(order) == len(tree_map), "ring must visit every rank once"
    n = len(order)
    ring: Dict[int, Tuple[int, int]] = {}
    for pos in range(n):
        ring[order[pos]] = (order[(pos - 1) % n], order[(pos + 1) % n])
    return ring


def get_link_map(
    n: int,
) -> Tuple[Dict[int, List[int]], Dict[int, int], Dict[int, Tuple[int, int]]]:
    """Tree+ring with ranks RELABELED to follow ring order, so rank i's
    ring-next is rank i+1 (reference get_link_map, tracker.py:227-252).

    Returns (tree_map, parent_map, ring_map) in the new labeling.
    """
    tree_map, parent_map = get_tree(n)
    ring_map = get_ring(tree_map, parent_map)
    relabel = {0: 0}
    k = 0
    for i in range(n - 1):
        k = ring_map[k][1]
        relabel[k] = i + 1
    tree2 = {relabel[r]: [relabel[x] for x in v] for r, v in tree_map.items()}
    parent2 = {
        relabel[r]: (relabel[p] if r != 0 else -1)
        for r, p in parent_map.items()
    }
    ring2 = {
        relabel[r]: (relabel[a], relabel[b]) for r, (a, b) in ring_map.items()
    }
    return tree2, parent2, ring2
