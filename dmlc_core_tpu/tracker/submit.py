"""dmlc-submit compatible CLI (reference tracker/dmlc_tracker/submit.py).

``python -m dmlc_core_tpu.tracker.submit --cluster local -n 2 cmd ...``
Every advertised cluster dispatches (incl. ssh/slurm, which the reference
accepted but forgot to route — SURVEY §2.6) plus the TPU-native tpu-pod.
"""

from __future__ import annotations

import logging
import os
import random
import sys
from typing import List, Optional

from . import opts
from .backends import get_backend

__all__ = ["main"]


def config_logger(args) -> None:
    fmt = "%(asctime)s %(levelname)s %(message)s"
    level = logging.DEBUG if args.log_level == "DEBUG" else logging.INFO
    if args.log_file is None:
        logging.basicConfig(format=fmt, level=level)
    else:
        logging.basicConfig(format=fmt, level=level, filename=args.log_file)
        console = logging.StreamHandler()
        console.setFormatter(logging.Formatter(fmt))
        console.setLevel(level)
        logging.getLogger("").addHandler(console)


def main(argv: Optional[List[str]] = None) -> None:
    args = opts.get_opts(argv)
    config_logger(args)
    if getattr(args, "shard_oversplit", 0):
        # env, not plumbing: the tracker process reads it when its
        # ShardService pins the micro-shard count, and workers inherit
        # it for their own display/diagnostics (the count they actually
        # use always comes from the lease response)
        os.environ["DMLC_SHARD_OVERSPLIT"] = str(args.shard_oversplit)
    if getattr(args, "shard_lease_ttl", 0.0):
        os.environ["DMLC_SHARD_LEASE_TTL"] = str(args.shard_lease_ttl)
    if getattr(args, "autoscale", ""):
        # the tracker (this process) reads DMLC_AUTOSCALE when it
        # starts its controller thread; the backend sizes the initial
        # dsserve fleet from the same bounds (docs/autoscale.md)
        os.environ["DMLC_AUTOSCALE"] = str(args.autoscale)
        if getattr(args, "autoscale_cost_ceiling", 0.0):
            os.environ["DMLC_AUTOSCALE_COST_CEILING"] = str(
                args.autoscale_cost_ceiling
            )
        if getattr(args, "autoscale_dwell", 0.0):
            os.environ["DMLC_AUTOSCALE_DWELL"] = str(args.autoscale_dwell)
    if getattr(args, "tracker_journal", None):
        # the tracker process (in-process or supervised subprocess —
        # backends/local.py) reads DMLC_TRACKER_JOURNAL when it builds
        # its control-plane journal (tracker/journal.py)
        os.makedirs(args.tracker_journal, exist_ok=True)
        os.environ["DMLC_TRACKER_JOURNAL"] = args.tracker_journal
    if getattr(args, "trace_dir", None):
        # one env export covers every process of the job: the tracker
        # (this process), workers and the block-cache daemon inherit
        # os.environ at launch, and each dumps its flight-recorder
        # rings into the directory at exit (telemetry/tracing.py)
        os.makedirs(args.trace_dir, exist_ok=True)
        os.environ["DMLC_TRACE_DIR"] = args.trace_dir
    # one trace id for the whole job: every launched process inherits
    # it, so spans from the tracker, workers and daemons share a trace
    # (telemetry/tracing.py trace contexts; hex — the opaque encoding
    # belongs to tracing, this is just a seed)
    os.environ.setdefault(
        "DMLC_TRACE_ID", f"{random.getrandbits(63) | 1:x}"
    )
    get_backend(args.cluster)(args)


if __name__ == "__main__":
    main(sys.argv[1:])
