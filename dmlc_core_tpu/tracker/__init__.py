"""Distributed launcher: rank rendezvous tracker + cluster backends.

Reference: tracker/dmlc_tracker/ (SURVEY §2.6). The control plane of the
reference's distribution story: a TCP rendezvous server that assigns ranks,
computes the tree+ring allreduce topology, brokers peer connections, and
relaunches through per-cluster backends. Wire-compatible with the
reference's protocol (magic 0xff99, int/str framing) so rabit-style
clients can connect unchanged.

TPU-native additions (SURVEY §5.8): the ``tpu-pod`` backend maps the DMLC
env contract onto jax.distributed (coordinator address, process id/count
from the pod topology); data-plane collectives are XLA's business, so the
tree/ring maps matter only for host-side coordination and legacy clients.
"""

from .topology import get_link_map, get_ring, get_tree
from .tracker import PSTracker, RabitTracker, submit, worker_env

__all__ = [
    "RabitTracker",
    "PSTracker",
    "submit",
    "worker_env",
    "get_tree",
    "get_ring",
    "get_link_map",
]
