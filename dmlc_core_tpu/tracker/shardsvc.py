"""Dynamic shard service: a tracker-leased work queue over micro-shards.

Static sharding (``part_index/num_parts`` fixed at open, io/split.py)
gates a multi-host epoch on its slowest worker: one latency-degraded or
quarantined host stalls everyone, and the supervisor's relaunch can only
restart work, never reshape it. This module moves shard *placement* into
the tracker as a leased work queue — the tf.data-service-style dynamic
dispatch pattern — while shard *content* stays exactly the static
planner's:

- the file set is deterministically oversharded into
  ``K x num_workers`` micro-shards (``DMLC_SHARD_OVERSPLIT``, default
  4). A micro-shard IS ``(part_index=i, num_parts=M)`` of the existing
  byte-range/magic-scan planner (``InputSplitBase.reset_partition`` /
  the count-indexed variant), so every worker computes identical ranges
  from the integers alone and per-shard ``(seed, epoch)`` shuffle order
  is bit-identical to a static run over the same ``M`` parts — only the
  shard→worker mapping becomes dynamic;
- the tracker's :class:`ShardLedger` grants time-bounded leases over the
  rendezvous string framing (``cmd=shard_lease|shard_renew|shard_done|
  shard_release``, protocol.py), renews them on explicit renew AND on
  the ``cmd=metrics`` heartbeat, reclaims them on expiry, supervisor
  quarantine (:func:`reclaim_task`) or voluntary ``shard_release``
  (driver close / mid-epoch restart — required because heartbeats would
  renew an abandoned lease forever), and records completions
  exactly-once — the FIRST ``shard_done`` wins, later ones answer
  ``duplicate`` — so resume and accounting survive reassignment;
- a worker that dies mid-lease costs the epoch one lease TTL, not the
  epoch: the reclaimed micro-shard re-enters the queue and the next
  idle worker steals it. Workers may join or leave mid-epoch — anyone
  who can speak the lease protocol drains whatever is left.

Emission semantics: committed work is exactly-once (commit on the
``recorded`` ack — tests/bench do); raw record emission is
at-least-once in the pathological case where a LIVE holder outlives its
TTL without renewing (renewal rides every pull and every heartbeat, so
that takes a stalled process, not a slow one). docs/sharding.md.

Telemetry (tracker-side registry): ``tracker.shards.queue_depth``
gauge, ``tracker.shards.leases_granted|renewed|reclaimed|stolen``,
``tracker.shards.completions|duplicates`` counters and the
``tracker.shards.shard_seconds`` grant→done histogram
(docs/observability.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from . import journal as _jn
from ..io.retry import is_transient as _is_transient
from .protocol import (
    CMD_SHARD_DONE,
    CMD_SHARD_LEASE,
    CMD_SHARD_RELEASE,
    CMD_SHARD_RENEW,
    connect_worker_retry,
    default_tracker_retry_secs,
)

__all__ = [
    "ShardLedger",
    "ShardService",
    "ShardLeaseClient",
    "default_oversplit",
    "default_lease_ttl",
    "active_service",
    "reclaim_task",
]


def default_oversplit() -> int:
    """``DMLC_SHARD_OVERSPLIT`` (micro-shards per worker, default 4):
    higher = finer-grained stealing (a straggler strands at most one
    micro-shard of work) at the cost of more lease round-trips and more
    window restarts; 1 degenerates to static-sized shards that can
    still move between workers."""
    try:
        return max(1, int(os.environ.get("DMLC_SHARD_OVERSPLIT", "4")))
    except ValueError:
        return 4


def default_lease_ttl() -> float:
    """``DMLC_SHARD_LEASE_TTL`` seconds (default 30): how long a granted
    lease survives without a renew before the ledger reclaims it. Renewal
    rides every driver pull and every ``cmd=metrics`` heartbeat, so the
    TTL only has to outlive a *stall*, not a shard drain."""
    try:
        return max(0.1, float(os.environ.get("DMLC_SHARD_LEASE_TTL", "30")))
    except ValueError:
        return 30.0


class _Lease:
    __slots__ = ("shard", "rank", "lease_id", "granted", "expires", "stolen")

    def __init__(
        self, shard: int, rank: int, lease_id: int, granted: float, ttl: float
    ) -> None:
        self.shard = shard
        self.rank = rank
        self.lease_id = lease_id
        self.granted = granted
        self.expires = granted + ttl
        self.stolen = False


class ShardLedger:
    """One epoch's exactly-once micro-shard ledger (caller locks).

    States per shard: queued (in ``self.queue``) → leased
    (``self.leases``) → done (``self.done``). A reclaimed shard goes
    BACK to the queue front (it has been waiting longest); its next
    grant to a different rank counts as stolen. Completions are
    recorded exactly once — ``record_done`` answers ``recorded`` for
    the first finisher regardless of current lease ownership (the
    holder may legitimately finish after its lease expired and was
    re-granted; first finisher wins, the other's later done is a
    ``duplicate``)."""

    def __init__(self, epoch: int, n_shards: int) -> None:
        self.epoch = epoch
        self.n_shards = n_shards
        self.queue: deque = deque(range(n_shards))
        self.leases: Dict[int, _Lease] = {}  # shard -> live lease
        self.done: Dict[int, int] = {}  # shard -> completing rank
        self.reclaimed_from: Dict[int, int] = {}  # shard -> last holder
        self.granted = 0
        self.reclaimed = 0
        self.stolen = 0
        self.duplicates = 0
        self._next_lease_id = 0

    # -- queries -------------------------------------------------------------
    def complete(self) -> bool:
        return len(self.done) == self.n_shards

    def queue_depth(self) -> int:
        return len(self.queue)

    # -- transitions (caller holds the service lock) -------------------------
    def reclaim_expired(self, now: float) -> List[int]:
        """Return every expired lease's shard to the queue front."""
        expired = [l for l in self.leases.values() if l.expires <= now]
        for lease in expired:
            del self.leases[lease.shard]
            self.reclaimed_from[lease.shard] = lease.rank
            self.queue.appendleft(lease.shard)
            self.reclaimed += 1
        return [l.shard for l in expired]

    def reclaim_rank(self, rank: int) -> List[int]:
        """Immediately reclaim every lease held by ``rank`` (supervisor
        failure/quarantine hook — don't wait out the TTL)."""
        held = [l for l in self.leases.values() if l.rank == rank]
        for lease in held:
            del self.leases[lease.shard]
            self.reclaimed_from[lease.shard] = lease.rank
            self.queue.appendleft(lease.shard)
            self.reclaimed += 1
        return [l.shard for l in held]

    def grant(self, rank: int, now: float, ttl: float) -> Optional[_Lease]:
        """Pop the next queued shard into a lease for ``rank``; None
        when nothing is grantable right now. Callers must run
        ``reclaim_expired(now)`` first — reclaim stays single-sited so
        the service's leases_reclaimed counter can't diverge from the
        ledger's accounting."""
        # skip (discard) shards that completed while queued: a reclaimed
        # holder may finish late — record_done marks it done but the
        # queue entry survives, and re-granting it would re-emit every
        # record of an already-committed shard
        shard = None
        while self.queue:
            cand = self.queue.popleft()
            if cand not in self.done:
                shard = cand
                break
        if shard is None:
            return None
        self._next_lease_id += 1
        lease = _Lease(shard, rank, self._next_lease_id, now, ttl)
        self.leases[shard] = lease
        self.granted += 1
        prev = self.reclaimed_from.get(shard)
        if prev is not None and prev != rank:
            self.stolen += 1
            lease.stolen = True
        return lease

    def renew_rank(self, rank: int, now: float, ttl: float) -> int:
        """Extend every lease ``rank`` still holds; returns the count
        (0 = all lost to expiry — the holder must re-lease)."""
        n = 0
        for lease in self.leases.values():
            if lease.rank == rank and lease.expires > now:
                lease.expires = now + ttl
                n += 1
        return n

    def release(self, shard: int, rank: int) -> bool:
        """Voluntary hand-back of an UNFINISHED lease (driver close /
        mid-epoch restart): back to the queue front like a reclaim —
        the shard was partially drained, so it must be re-served in
        full — but only if ``rank`` still holds it (a thief's live
        lease is not voided by the loser's late release)."""
        lease = self.leases.get(shard)
        if lease is None or lease.rank != rank or shard in self.done:
            return False
        del self.leases[shard]
        self.reclaimed_from[shard] = rank
        self.queue.appendleft(shard)
        self.reclaimed += 1
        return True

    def record_done(self, shard: int, rank: int, now: float):
        """Exactly-once completion; returns ("recorded", secs) for the
        first finisher (secs = grant→done of the finisher's lease when
        it still holds one, else None) or ("duplicate", None)."""
        if not 0 <= shard < self.n_shards:
            raise ValueError(f"shard {shard} out of range [0,{self.n_shards})")
        if shard in self.done:
            self.duplicates += 1
            return "duplicate", None
        if shard not in self.leases and shard not in self.reclaimed_from:
            # every legitimate finisher leaves a trace: a live lease, or
            # a reclaim/steal record. A done with no grant history is a
            # client bug — accepting it would mark undrained data
            # complete and the epoch would finish with a silent hole.
            raise ValueError(
                f"shard {shard} was never granted; refusing to mark it done"
            )
        self.done[shard] = rank
        lease = self.leases.pop(shard, None)
        secs = None
        if lease is not None and lease.rank == rank:
            secs = max(0.0, now - lease.granted)
        return "recorded", secs

    def wait_hint(self, now: float) -> float:
        """Suggested client backoff while everything is leased: half the
        soonest expiry (bounded) — sooner is pointless, later wastes the
        reclaim."""
        if not self.leases:
            return 0.05
        soonest = min(l.expires for l in self.leases.values())
        return min(1.0, max(0.05, (soonest - now) / 2.0))


class ShardService:
    """Thread-safe shard lease service riding the tracker.

    ``handle(cmd, rank, payload)`` maps one JSON request frame to one
    JSON response frame (see ShardLeaseClient for the client half) and
    never raises — malformed input costs that request an ``error``
    response, not the tracker a thread. Epochs are created on first
    request and capped at ``keep_epochs`` live ledgers (a completed
    epoch's ``done`` answer survives until it ages out)."""

    #: ledgers kept live; laggard requests for older epochs get "done"
    #: if the epoch completed, else an error (a 9-epochs-stale worker
    #: has left the job in every practical sense)
    keep_epochs = 8

    def __init__(
        self,
        n_workers: int,
        oversplit: Optional[int] = None,
        ttl: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        journal=None,
    ) -> None:
        self.n_workers = n_workers
        self.oversplit = oversplit if oversplit else default_oversplit()
        self.ttl = ttl if ttl is not None else default_lease_ttl()
        self._clock = clock
        #: optional tracker/journal.py Journal: ledger transitions that
        #: matter for exactly-once are appended BEFORE the response is
        #: sent, so a tracker relaunch replays to a state every client
        #: ack is consistent with (docs/robustness.md)
        self._journal = journal
        self._lock = threading.Lock()
        self._epochs: Dict[int, ShardLedger] = {}
        self._completed: Dict[int, bool] = {}  # aged-out epochs
        #: micro-shard count, pinned at the first ledger so a mid-job
        #: n_workers resize can't change shard geometry under live
        #: leases (shard content must stay deterministic for the epoch)
        self.n_shards: Optional[int] = None
        #: dataset signature pinned by the first lease request that
        #: carries one: mismatched workers fail loudly instead of
        #: draining differently-sharded bytes
        self._fileset: Optional[str] = None
        #: launcher task id (the jobid workers send at rendezvous) →
        #: rendezvous rank; fed by the tracker at rank assignment so
        #: the supervisor's task-keyed reclaim hook lands on the rank
        #: the leases were actually granted to (ranks are connect-order)
        self._task_rank: Dict[str, int] = {}
        #: counters folded out of evicted ledgers so summary() stays
        #: whole-job truthful past keep_epochs epochs
        self._retired = {
            "epochs": 0, "granted": 0, "reclaimed": 0,
            "stolen": 0, "completed": 0, "duplicates": 0,
        }
        reg = _default_registry()
        self._c_granted = reg.counter(
            "tracker.shards.leases_granted",
            help="micro-shard leases granted",
        )
        self._c_renewed = reg.counter(
            "tracker.shards.leases_renewed",
            help="lease extensions (explicit renew + metrics heartbeat)",
        )
        self._c_reclaimed = reg.counter(
            "tracker.shards.leases_reclaimed",
            help="leases reclaimed on expiry or supervisor failure",
        )
        self._c_stolen = reg.counter(
            "tracker.shards.leases_stolen",
            help="reclaimed micro-shards re-granted to a different worker",
        )
        self._c_completed = reg.counter(
            "tracker.shards.completions",
            help="micro-shards recorded done (exactly-once)",
        )
        self._c_duplicate = reg.counter(
            "tracker.shards.duplicates",
            help="shard_done for an already-completed micro-shard",
        )
        self._g_queue = reg.gauge(
            "tracker.shards.queue_depth",
            help="unleased micro-shards in the newest epoch's queue",
        )
        self._h_shard_secs = reg.histogram(
            "tracker.shards.shard_seconds",
            help="per-micro-shard grant→done seconds",
        )

    # -- ledger plumbing (lock held) -----------------------------------------
    def _ledger(self, epoch: int) -> Optional[ShardLedger]:
        led = self._epochs.get(epoch)
        if led is not None:
            return led
        if epoch in self._completed:
            return None  # aged out; _completed remembers the outcome
        # an epoch BEHIND the live window has aged out: creating it
        # would immediately evict it below and grant() would hand out
        # leases from an orphaned ledger whose dones can never land
        if self._epochs and epoch < max(self._epochs) - self.keep_epochs + 1:
            return None
        if self.n_shards is None:
            self.n_shards = self.oversplit * max(1, self.n_workers)
        led = ShardLedger(epoch, self.n_shards)
        self._epochs[epoch] = led
        while len(self._epochs) > self.keep_epochs:
            oldest = min(self._epochs)
            dropped = self._epochs[oldest]
            now = self._clock()
            if not dropped.complete() and any(
                l.expires > now for l in dropped.leases.values()
            ):
                # evicting would strand live leaseholders (their renews
                # and dones would hit a vanished ledger). A worker 8+
                # epochs ahead of a live-leased laggard has left the job
                # in practice — refuse ITS epoch instead
                del self._epochs[epoch]
                return None
            self._epochs.pop(oldest)
            self._completed[oldest] = dropped.complete()
            self._fold_retired(dropped)
            if len(self._completed) > 64:
                self._completed.pop(min(self._completed))
        return led

    def _fold_retired(self, led: ShardLedger) -> None:
        r = self._retired
        r["epochs"] += 1
        r["granted"] += led.granted
        r["reclaimed"] += led.reclaimed
        r["stolen"] += led.stolen
        r["completed"] += len(led.done)
        r["duplicates"] += led.duplicates

    def _fold_retired_all(self) -> None:
        for led in self._epochs.values():
            self._fold_retired(led)

    def _update_queue_gauge(self) -> None:
        if self._epochs:
            self._g_queue.set(self._epochs[max(self._epochs)].queue_depth())

    # -- operations ----------------------------------------------------------
    def lease(self, rank: int, epoch: int, fileset: Optional[str]) -> Dict:
        with self._lock:
            if fileset:
                if self._fileset is None:
                    self._fileset = fileset
                elif fileset != self._fileset:
                    # sequential dataset switch (train → validation):
                    # once every live ledger fully drained, a new
                    # signature starts fresh — epochs AND geometry reset
                    # (the old epochs' "done" answers belong to the old
                    # dataset and must not empty the new one's drain).
                    # An incomplete ledger means workers are draining
                    # DIFFERENT datasets concurrently — that stays loud.
                    if all(l.complete() for l in self._epochs.values()):
                        self._fold_retired_all()
                        self._epochs.clear()
                        self._completed.clear()
                        self.n_shards = None
                        self._fileset = fileset
                        if self._journal is not None:
                            self._journal.append(
                                _jn.K_DATASET_SWITCH, fileset=fileset
                            )
                    else:
                        return {
                            "status": "error",
                            "error": f"fileset signature {fileset!r} does "
                            f"not match the job's {self._fileset!r} — "
                            "workers are not reading the same dataset",
                        }
            led = self._ledger(epoch)
            if led is None:
                done = self._completed.get(epoch, False)
                return {"status": "done"} if done else {
                    "status": "error",
                    "error": f"epoch {epoch} aged out of the ledger",
                }
            now = self._clock()
            reclaimed = led.reclaim_expired(now)
            if reclaimed:
                self._c_reclaimed.inc(len(reclaimed))
            lease = led.grant(rank, now, self.ttl)
            if lease is None:
                self._update_queue_gauge()
                if led.complete():
                    return {"status": "done"}
                return {"status": "wait", "backoff": round(led.wait_hint(now), 3)}
            self._c_granted.inc()
            if lease.stolen:
                self._c_stolen.inc()
            if self._journal is not None:
                self._journal.append(
                    _jn.K_SHARD_GRANT, epoch=epoch, shard=lease.shard,
                    rank=rank, fileset=self._fileset,
                    n_shards=led.n_shards,
                )
            self._update_queue_gauge()
            return {
                "status": "lease",
                "shard": lease.shard,
                "num_shards": led.n_shards,
                "lease_id": lease.lease_id,
                "ttl": self.ttl,
                "epoch": epoch,
            }

    def renew(self, rank: int, epoch: int) -> Dict:
        with self._lock:
            led = self._epochs.get(epoch)
            if led is None:
                return {"status": "lost", "renewed": 0}
            n = led.renew_rank(rank, self._clock(), self.ttl)
            if n:
                self._c_renewed.inc(n)
            return {"status": "ok" if n else "lost", "renewed": n}

    def _stale_fileset(self, fileset: Optional[str]) -> Optional[Dict]:
        """A state-mutating request carrying a signature that is not the
        job's CURRENT dataset is a straggler from before a dataset
        switch — epoch numbers restart at the switch, so without this
        check its shard numbers land on the new ledger and mark
        undrained validation data complete (caller holds the lock)."""
        if fileset and self._fileset is not None and fileset != self._fileset:
            return {
                "status": "error",
                "error": f"fileset signature {fileset!r} is not the job's "
                f"current dataset {self._fileset!r} — stale request from "
                "before a dataset switch",
            }
        return None

    def done(self, rank: int, epoch: int, shard: int,
             fileset: Optional[str] = None) -> Dict:
        with self._lock:
            stale = self._stale_fileset(fileset)
            if stale is not None:
                return stale
            led = self._epochs.get(epoch)
            if led is None:
                done = self._completed.get(epoch, False)
                return {"status": "duplicate" if done else "error",
                        **({} if done else {"error": f"epoch {epoch} aged out"})}
            try:
                status, secs = led.record_done(shard, rank, self._clock())
            except ValueError as e:
                return {"status": "error", "error": str(e)}
            if status == "recorded":
                self._c_completed.inc()
                if secs is not None:
                    self._h_shard_secs.observe(secs)
                if self._journal is not None:
                    # journaled before the ack: once a worker hears
                    # "recorded", no tracker relaunch un-records it
                    self._journal.append(
                        _jn.K_SHARD_DONE, epoch=epoch, shard=shard,
                        rank=rank,
                    )
            else:
                self._c_duplicate.inc()
            self._update_queue_gauge()
            return {"status": status, "epoch_complete": led.complete()}

    def release(self, rank: int, epoch: int, shard: int,
                fileset: Optional[str] = None) -> Dict:
        """Driver abandonment (close / mid-epoch restart): return the
        unfinished shard to the queue NOW. Without this, the TTL
        fallback alone is not enough — a process whose rabit heartbeat
        keeps running after its source closed would renew the abandoned
        lease forever and livelock its peers on ``wait``."""
        with self._lock:
            stale = self._stale_fileset(fileset)
            if stale is not None:
                return stale
            led = self._epochs.get(epoch)
            if led is None:
                return {"status": "ok", "released": 0}
            released = led.release(int(shard), rank)
            if released:
                self._c_reclaimed.inc()
                if self._journal is not None:
                    self._journal.append(
                        _jn.K_SHARD_RELEASE, epoch=epoch,
                        shard=int(shard), rank=rank,
                    )
            self._update_queue_gauge()
            return {"status": "ok", "released": int(released)}

    def renew_all(self, rank: int) -> None:
        """Heartbeat-path renewal: extend ``rank``'s leases in every
        live epoch (cmd=metrics arrives without an epoch number)."""
        with self._lock:
            now = self._clock()
            n = 0
            for led in self._epochs.values():
                n += led.renew_rank(rank, now, self.ttl)
            if n:
                self._c_renewed.inc(n)

    def reclaim_rank(self, rank: int) -> int:
        """Supervisor hook: a task just failed/was quarantined — return
        its leases to the queue NOW instead of waiting out the TTL."""
        with self._lock:
            n = 0
            for led in self._epochs.values():
                shards = led.reclaim_rank(rank)
                n += len(shards)
            if n:
                self._c_reclaimed.inc(n)
            self._update_queue_gauge()
            return n

    def release_rank(self, rank: int) -> int:
        """Voluntary rank-wide hand-back — the retire path's cooperative
        twin of ``reclaim_rank`` (docs/autoscale.md): a departing
        leaseholder (autoscale scale-down, operator drain) returns every
        lease it still holds across all live epochs. Shards it already
        streamed stay committed, and a ``record_done`` that lands after
        the release is still honored through ``reclaimed_from`` — the
        exactly-once contract survives the retire."""
        return self.reclaim_rank(rank)

    def note_task_rank(self, jobid: str, rank: int) -> None:
        """Tracker feed at rank assignment: launcher task id (the jobid
        of the rendezvous preamble) → rendezvous rank, so task-keyed
        supervisor reclaim can translate into the lease identity space
        (leases are held by rendezvous rank once RabitWorker.start()
        exported DMLC_SHARD_RANK)."""
        if jobid and jobid != "NULL":
            with self._lock:
                self._task_rank[str(jobid)] = rank

    def resolve_task(self, task_id: int) -> int:
        """Launcher task id → lease-holder rank; identity when no
        rendezvous mapping was recorded (shard-only payloads lease
        under DMLC_TASK_ID, so task id IS the rank there)."""
        with self._lock:
            return self._task_rank.get(str(task_id), task_id)

    # -- crash recovery (tracker/journal.py) ----------------------------------
    def restore(self, state: Dict) -> Dict:
        """Rebuild the ledgers from a journal fold (tracker restart with
        ``--tracker-journal``). Completions are restored verbatim —
        exactly-once survives the crash. Every granted-but-not-done
        shard is **conservatively expired**: no lease is recreated (the
        holder may be gone, and its connection certainly is), the shard
        re-enters the queue FRONT, and its grant history lands in
        ``reclaimed_from`` so the old holder's late ``record_done`` is
        still honored instead of rejected as never-granted. Returns a
        summary for the end-of-job report's ``recovery`` section."""
        sh = (state or {}).get("shards") or {}
        with self._lock:
            self._fileset = sh.get("fileset")
            if sh.get("n_shards"):
                self.n_shards = int(sh["n_shards"])
            restored_done = 0
            expired = 0
            for estr, ep in sorted(
                (sh.get("epochs") or {}).items(), key=lambda kv: int(kv[0])
            ):
                epoch = int(estr)
                n = int(self.n_shards or 0)
                if n <= 0:
                    continue  # grants imply a pinned geometry; skip noise
                led = ShardLedger(epoch, n)
                done = {
                    int(s): int(r) for s, r in (ep.get("done") or {}).items()
                }
                outstanding = {
                    int(s): int(r)
                    for s, r in (ep.get("outstanding") or {}).items()
                    if int(s) not in done
                }
                led.done = done
                led.reclaimed_from.update(outstanding)
                # queue: expired grants first (they have been waiting
                # longest), then never-granted shards — no duplicates,
                # or a shard could be double-leased after recovery
                led.queue = deque(
                    sorted(outstanding)
                    + [
                        s for s in range(n)
                        if s not in done and s not in outstanding
                    ]
                )
                led.granted = len(done) + len(outstanding)
                led.reclaimed = len(outstanding)
                self._epochs[epoch] = led
                restored_done += len(done)
                expired += len(outstanding)
            self._update_queue_gauge()
            return {
                "epochs": len(self._epochs),
                "completions_restored": restored_done,
                "leases_expired": expired,
                "fileset": self._fileset,
                "n_shards": self.n_shards,
            }

    # -- wire adapter ---------------------------------------------------------
    def handle(self, cmd: str, rank: int, payload: str) -> str:
        """One request frame → one response frame; never raises."""
        try:
            if rank < 0:
                # negatives are protocol placeholders (print/NULL
                # clients), never lease holders. Ranks ABOVE n_workers
                # are legal: shard geometry was pinned at the first
                # lease, so an extra worker joining mid-epoch just
                # drains the queue faster (the elastic-join contract,
                # docs/sharding.md)
                return json.dumps({
                    "status": "error",
                    "error": f"shard request from invalid rank {rank}",
                })
            req = json.loads(payload) if payload else {}
            if not isinstance(req, dict):
                raise ValueError("payload must be a JSON object")
            epoch = int(req.get("epoch", 0))
            if cmd == CMD_SHARD_LEASE:
                out = self.lease(rank, epoch, req.get("fileset"))
            elif cmd == CMD_SHARD_RENEW:
                out = self.renew(rank, epoch)
            elif cmd == CMD_SHARD_DONE:
                out = self.done(rank, epoch, int(req["shard"]),
                                req.get("fileset"))
            elif cmd == CMD_SHARD_RELEASE:
                out = self.release(rank, epoch, int(req["shard"]),
                                   req.get("fileset"))
            else:
                out = {"status": "error", "error": f"unknown shard cmd {cmd!r}"}
        except (ValueError, KeyError, TypeError) as e:
            out = {"status": "error", "error": f"bad shard request: {e}"}
        return json.dumps(out, separators=(",", ":"))

    def all_complete(self) -> bool:
        """True when shard work actually happened AND every live ledger
        is fully accounted. This gates submit's downgrade of
        RendezvousNeverCompleted to a clean finish: shard chatter alone
        must not pass a partial epoch (workers that exited 0 mid-epoch
        on a swallowed error) off as a completed job."""
        with self._lock:
            if self.n_shards is None or not self._epochs:
                return False
            return all(l.complete() for l in self._epochs.values())

    def summary(self) -> Dict[str, object]:
        """End-of-job shape for the tracker report / diag tools."""
        with self._lock:
            newest = self._epochs[max(self._epochs)] if self._epochs else None
            r = self._retired  # evicted ledgers still count (long jobs)
            return {
                "n_shards": self.n_shards,
                "oversplit": self.oversplit,
                "ttl": self.ttl,
                "epochs": sorted(self._epochs),
                "epochs_retired": r["epochs"],
                "granted": r["granted"]
                + sum(l.granted for l in self._epochs.values()),
                "reclaimed": r["reclaimed"]
                + sum(l.reclaimed for l in self._epochs.values()),
                "stolen": r["stolen"]
                + sum(l.stolen for l in self._epochs.values()),
                "completed": r["completed"]
                + sum(len(l.done) for l in self._epochs.values()),
                "duplicates": r["duplicates"]
                + sum(l.duplicates for l in self._epochs.values()),
                "queue_depth": newest.queue_depth() if newest else 0,
            }


# -- process-global active service (supervisor hook) --------------------------

_active_lock = threading.Lock()
_active: Optional[ShardService] = None


def set_active(service: Optional[ShardService]) -> None:
    """Register the submit process's live shard service (RabitTracker
    start/close). The supervisor's failure hook resolves it lazily so
    supervisor.py stays free of tracker wiring."""
    global _active
    with _active_lock:
        _active = service


def active_service() -> Optional[ShardService]:
    with _active_lock:
        return _active


def reclaim_task(task_id: int, host: str) -> None:
    """Supervisor ``on_task_failure`` hook: reclaim the failed task's
    leases immediately. The task id is translated into the lease-holder
    rank through the tracker-fed mapping (rendezvous ranks are assigned
    in connect order, so they need not equal DMLC_TASK_ID); without a
    mapping the task id is the rank (shard-only payloads lease under
    DMLC_TASK_ID). No-op when no shard service is live."""
    service = active_service()
    if service is not None:
        service.reclaim_rank(service.resolve_task(task_id))


def release_task(task_id: int, host: str = "") -> None:
    """Elastic-retire escalation hook (backends/local.py): a retiring
    worker that blew through its drain grace and had to be killed gets
    its leases released NOW instead of waiting out the TTL — the
    graceful path (``DsServeServer.retire``) releases them itself, so
    this only fires on the kill branch. Same task→rank translation as
    ``reclaim_task``; no-op when no shard service is live."""
    service = active_service()
    if service is not None:
        service.release_rank(service.resolve_task(task_id))


# -- worker-side client --------------------------------------------------------


class ShardLeaseClient:
    """Worker half of the lease protocol: one short-lived connection per
    call, exactly the ``cmd=print``/``cmd=metrics`` connection shape
    (client.py), plus ONE JSON response frame.

    ``rank`` defaults to ``DMLC_SHARD_RANK`` — set by
    ``RabitWorker.start()`` to the rendezvous-assigned rank, so lease
    ownership and the ``cmd=metrics`` heartbeat (which renews leases BY
    rendezvous rank) live in the same identity space — else
    ``DMLC_TASK_ID`` (shard-only payloads never heartbeat, and the
    launcher's task id is what the supervisor reclaim hook uses). A
    defaulted rank is re-read from the environment at every ``lease()``
    — a lease is an identity pinning point — so a client constructed
    BEFORE ``start()`` still leases under the rendezvous rank once the
    drain begins, instead of freezing the pre-rendezvous task id and
    losing every heartbeat renewal. Tracker address defaults to
    ``DMLC_TRACKER_URI``/``DMLC_TRACKER_PORT``."""

    def __init__(
        self,
        tracker_uri: Optional[str] = None,
        tracker_port: Optional[int] = None,
        rank: Optional[int] = None,
        timeout: float = 30.0,
    ) -> None:
        self.tracker_uri = tracker_uri or os.environ["DMLC_TRACKER_URI"]
        self.tracker_port = int(
            tracker_port
            if tracker_port is not None
            else os.environ["DMLC_TRACKER_PORT"]
        )
        self._explicit_rank = rank is not None
        self.rank = rank if rank is not None else self._env_rank()
        self.timeout = timeout

    @staticmethod
    def _env_rank() -> int:
        try:
            return int(
                os.environ.get("DMLC_SHARD_RANK")
                or os.environ.get("DMLC_TASK_ID", "0")
            )
        except ValueError:
            return 0

    def _call(self, cmd: str, payload: Dict,
              retry_secs: Optional[float] = None) -> Dict:
        # the piggybacked trace context binds the tracker's handler
        # span to whatever wait span encloses this call (the
        # shard_lease_wait stall gets its causal arrow on a merged
        # timeline, docs/observability.md). The retrying dial rides out
        # a tracker crash+relaunch window (DMLC_TRACKER_RETRY_SECS):
        # lease/renew/done are all safe to redial — the request frame
        # is only sent on a COMPLETED handshake, and record_done is
        # exactly-once on the tracker side either way
        budget = (
            default_tracker_retry_secs()
            if retry_secs is None else float(retry_secs)
        )
        deadline = time.monotonic() + budget
        delay = 0.05
        while True:
            remaining = max(0.0, deadline - time.monotonic())
            fs = connect_worker_retry(
                self.tracker_uri, self.tracker_port, self.rank, -1,
                "NULL", cmd, self.timeout,
                trace_ctx=_tracing.rpc_context(), retry_secs=remaining,
            )
            try:
                fs.send_str(json.dumps(payload, separators=(",", ":")))
                resp = json.loads(fs.recv_str())
                if not isinstance(resp, dict):
                    raise ConnectionError(
                        "malformed shard service response"
                    )
                return resp
            except (ConnectionError, OSError) as e:
                # the dial is retried above, but the tracker can also
                # die BETWEEN the completed handshake and the response
                # (chaos SIGKILL mid-RPC): redial the WHOLE call within
                # the same budget — safe because every shard RPC is
                # idempotent tracker-side (record_done is exactly-once,
                # a replayed lease/renew/release just re-answers)
                if not _is_transient(e) or time.monotonic() >= deadline:
                    raise
                _tracing.instant(
                    "dmlc:tracker_reconnect", cmd=cmd, rank=self.rank,
                    attempt=-1, error=type(e).__name__,
                )
                time.sleep(
                    min(delay, max(0.0, deadline - time.monotonic()))
                )
                delay = min(2.0, delay * 2)
            finally:
                fs.close()

    def lease(self, epoch: int, fileset: Optional[str] = None) -> Dict:
        if not self._explicit_rank:
            # renew/done/release keep the rank the live lease was
            # granted under; a NEW lease is the safe re-pin point
            self.rank = self._env_rank()
        req: Dict = {"epoch": epoch}
        if fileset:
            req["fileset"] = fileset
        return self._call(CMD_SHARD_LEASE, req)

    def renew(self, epoch: int,
              retry_secs: Optional[float] = None) -> Dict:
        return self._call(
            CMD_SHARD_RENEW, {"epoch": epoch}, retry_secs=retry_secs
        )

    def done(self, epoch: int, shard: int,
             fileset: Optional[str] = None) -> Dict:
        req: Dict = {"epoch": epoch, "shard": shard}
        if fileset:
            req["fileset"] = fileset
        return self._call(CMD_SHARD_DONE, req)

    def release(self, epoch: int, shard: int,
                fileset: Optional[str] = None,
                retry_secs: Optional[float] = None) -> Dict:
        """``retry_secs`` bounds the reconnect budget: teardown paths
        pass a SHORT one — a release is worth a few redials (a dropped
        release leaves the shard to the lease TTL), but a closing
        process must not hang out the full crash-recovery window."""
        req: Dict = {"epoch": epoch, "shard": shard}
        if fileset:
            req["fileset"] = fileset
        return self._call(CMD_SHARD_RELEASE, req, retry_secs=retry_secs)
