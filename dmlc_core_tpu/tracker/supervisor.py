"""Cluster-level fault tolerance: supervised relaunch with backoff,
host quarantine and blacklisting.

The capability the reference implements only inside its Java YARN
ApplicationMaster (reference
tracker/yarn/src/.../ApplicationMaster.java:537-569 ``handleFailure``:
failed containers are re-requested up to ``DMLC_MAX_ATTEMPT`` — default 3,
``:76,212`` — the failing node is blacklisted, and the job aborts past the
limit). Here it is a backend-agnostic supervisor the local / tpu-pod /
kubernetes launchers share, so every cluster gets the same semantics:

- each task gets at most ``max_attempt`` total runs; one more failure
  aborts the whole job (all still-running tasks are killed);
- relaunches are spaced by EXPONENTIAL BACKOFF (the io/retry.py policy
  applied at the cluster layer): attempt k waits
  ``min(backoff_cap, relaunch_backoff * 2**(k-1))`` — a crash-looping
  task must not hammer the tracker/filesystem at poll speed;
- a host where a task just died is QUARANTINED for
  ``quarantine_secs * 2**(fails-1)`` (capped): its next placement
  prefers another healthy host instead of the immediate same-host
  retry, but a sole surviving host is still used (liveness beats
  placement hygiene). A host that accumulates ``host_fail_limit``
  failures is blacklisted outright and its tasks move to healthy hosts
  (when the backend allows re-placement — TPU pods pin task i to pod
  host i, so for them a blacklisted host means abort, documented
  divergence);
- every (re)launch exports ``DMLC_NUM_ATTEMPT`` (the attempt index, same
  env the reference local launcher uses, reference local.py:26-49), so a
  restarted worker can reconnect with ``cmd='recover'`` and the tracker
  re-issues its previous rank (tracker.py recover path, SURVEY §5.3).

Env knobs: DMLC_MAX_ATTEMPT (3), DMLC_RELAUNCH_BACKOFF (1.0s base),
DMLC_HOST_QUARANTINE (5.0s base).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

__all__ = [
    "Supervisor",
    "JobAborted",
    "RendezvousNeverCompleted",
    "default_max_attempt",
]

logger = logging.getLogger("dmlc_core_tpu.tracker")


class JobAborted(RuntimeError):
    """The job exceeded its failure budget (reference AM abort path)."""


class RendezvousNeverCompleted(RuntimeError):
    """run_in_thread's anti-wedge verdict: every task exited 0 but the
    rabit rendezvous never completed. Typed so tracker.submit can
    downgrade it to a clean finish when the job spoke the shard-lease
    protocol instead — a dynamic-shard-only payload (docs/sharding.md)
    is a dmlc client with no rendezvous to complete."""


def default_max_attempt(fallback: int = 3) -> int:
    """DMLC_MAX_ATTEMPT from the environment (reference AM reads the same
    variable, ApplicationMaster.java:212), else ``fallback``."""
    try:
        return max(1, int(os.environ.get("DMLC_MAX_ATTEMPT", fallback)))
    except ValueError:
        return max(1, fallback)


def _env_secs(name: str, fallback: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, fallback)))
    except ValueError:
        return fallback


@dataclass
class _Running:
    task_id: int
    host: str
    attempt: int
    proc: "object"  # Popen-like: poll(), kill(), wait()


class Supervisor:
    """Launch ``n_tasks`` processes and keep them alive through failures.

    ``launch(task_id, host, attempt)`` must start the task and return a
    Popen-like handle (``poll() -> Optional[int]``, ``kill()``,
    ``wait()``). The supervisor owns placement, retry budgets, backoff
    pacing, and the quarantine/blacklist; backends own command
    construction.
    """

    def __init__(
        self,
        launch: Callable[[int, str, int], object],
        hosts: Sequence[str] = ("localhost",),
        max_attempt: Optional[int] = None,
        host_fail_limit: Optional[int] = None,
        allow_replacement: bool = True,
        poll_interval: float = 0.05,
        relaunch_backoff: Optional[float] = None,
        backoff_cap: float = 30.0,
        quarantine_secs: Optional[float] = None,
        on_task_failure: Union[
            Callable[[int, str], None],
            Sequence[Callable[[int, str], None]],
            None,
        ] = None,
    ) -> None:
        self.launch = launch
        self.hosts = list(hosts)
        self.max_attempt = (
            max_attempt if max_attempt is not None else default_max_attempt()
        )
        # a host is unhealthy after this many failures on it (the reference
        # AM blacklists after a single container failure on the node; one
        # failure per host is a tight default when tasks can move, so the
        # default budget follows max_attempt instead). float('inf')
        # disables blacklisting — right when the host set is not a real
        # failure domain (a single localhost shared by every task).
        self.host_fail_limit = (
            host_fail_limit if host_fail_limit is not None else self.max_attempt
        )
        self.allow_replacement = allow_replacement
        self._thread: Optional[threading.Thread] = None
        self.poll_interval = poll_interval
        # exponential relaunch backoff: attempt k sleeps
        # min(cap, base * 2**(k-1)); 0 restores immediate relaunch
        self.relaunch_backoff = (
            relaunch_backoff
            if relaunch_backoff is not None
            else _env_secs("DMLC_RELAUNCH_BACKOFF", 1.0)
        )
        self.backoff_cap = backoff_cap
        # per-failure host quarantine, doubling per repeat offense
        self.quarantine_secs = (
            quarantine_secs
            if quarantine_secs is not None
            else _env_secs("DMLC_HOST_QUARANTINE", 5.0)
        )
        # failure observers ``(task_id, host)``, each called BEFORE the
        # relaunch is scheduled: the dynamic shard service hangs its
        # lease-reclaim here (tracker/shardsvc.reclaim_task) and the
        # collective engine its instant peer-death notification
        # (tracker/collective.notify_task_failure) — a LIST, not
        # last-writer-wins, so the two coexist. Accepts one callable or
        # a sequence; ``add_on_task_failure`` appends later. Observers
        # must not raise; exceptions are swallowed per observer (the
        # relaunch path — and the other observers — cannot ride on one).
        if on_task_failure is None:
            observers: List[Callable[[int, str], None]] = []
        elif callable(on_task_failure):
            observers = [on_task_failure]
        else:
            observers = list(on_task_failure)
        self.on_task_failure = observers
        self.failures: Dict[int, int] = {}  # task_id -> failed runs
        self.host_failures: Dict[str, int] = {}
        self.blacklist: set = set()
        self.quarantined: Dict[str, float] = {}  # host -> release monotonic
        self.placement: Dict[int, str] = {}
        self.relaunches = 0
        self.backoffs: List[float] = []  # scheduled relaunch delays
        self.error: Optional[BaseException] = None

    def add_on_task_failure(
        self, observer: Callable[[int, str], None]
    ) -> None:
        """Append a failure observer (``(task_id, host)``); every
        registered observer fires per failure, in registration order."""
        self.on_task_failure.append(observer)

    # -- placement -----------------------------------------------------------
    def _healthy_hosts(self) -> List[str]:
        return [h for h in self.hosts if h not in self.blacklist]

    def _quarantine(self, host: str) -> None:
        """Exclude a just-failed host from NEW placements for a while,
        doubling per repeat offense (capped at 16x the base)."""
        if self.quarantine_secs <= 0:
            return
        fails = self.host_failures.get(host, 1)
        hold = self.quarantine_secs * min(16.0, 2.0 ** (fails - 1))
        self.quarantined[host] = max(
            self.quarantined.get(host, 0.0), time.monotonic() + hold
        )
        logger.info("quarantining host %s for %.1fs (%d failures)",
                    host, hold, fails)

    def _pick_host(self, task_id: int, prev: Optional[str]) -> str:
        healthy = self._healthy_hosts()
        if prev is not None and prev not in healthy and not self.allow_replacement:
            raise JobAborted(
                f"host {prev!r} is blacklisted and task {task_id} cannot "
                "be re-placed on this backend"
            )
        if not healthy:
            raise JobAborted("every host is blacklisted")
        now = time.monotonic()
        calm = [h for h in healthy if self.quarantined.get(h, 0.0) <= now]
        if prev is not None:
            if prev in calm:
                return prev
            if prev in healthy and (not self.allow_replacement or not calm):
                # pinned placement (quarantine cannot move the task — the
                # relaunch backoff is the only pacing) or every healthy
                # host quarantined: liveness beats placement hygiene
                return prev
        # a quarantined prev never reaches this point with calm hosts
        # available (the branches above returned otherwise), so indexing
        # into calm IS the "no immediate same-host retry" rule
        pool = calm or healthy
        return pool[task_id % len(pool)]

    # -- failure accounting (reference handleFailure) ------------------------
    def _handle_failure(
        self, r: _Running, returncode: int
    ) -> Tuple[float, _Running]:
        """Account one failure; returns ``(ready_at, pending)`` — the
        relaunch is SCHEDULED (exponential backoff), not launched, so a
        crash-looping task cannot hammer the cluster at poll speed."""
        self.failures[r.task_id] = self.failures.get(r.task_id, 0) + 1
        self.host_failures[r.host] = self.host_failures.get(r.host, 0) + 1
        for observer in self.on_task_failure:
            try:
                observer(r.task_id, r.host)
            except Exception:
                logger.exception("on_task_failure observer failed")
        self._quarantine(r.host)
        if self.host_failures[r.host] >= self.host_fail_limit:
            if r.host not in self.blacklist:
                logger.warning("blacklisting host %s", r.host)
            self.blacklist.add(r.host)
        nfail = self.failures[r.task_id]
        if nfail >= self.max_attempt:
            raise JobAborted(
                f"task {r.task_id} failed {nfail} times "
                f"(returncode={returncode}, max_attempt={self.max_attempt})"
            )
        delay = (
            min(self.backoff_cap, self.relaunch_backoff * (2.0 ** (nfail - 1)))
            if self.relaunch_backoff > 0
            else 0.0
        )
        self.backoffs.append(delay)
        logger.info(
            "task %d failed on %s (ret=%d); relaunch attempt %d in %.1fs",
            r.task_id, r.host, returncode, nfail, delay,
        )
        return time.monotonic() + delay, _Running(r.task_id, r.host, nfail, None)

    def _relaunch(self, pending: _Running) -> _Running:
        """Launch a scheduled relaunch NOW; the host is picked at launch
        time so quarantine/blacklist state is current."""
        host = self._pick_host(pending.task_id, pending.host)
        self.relaunches += 1
        self.placement[pending.task_id] = host
        logger.info(
            "relaunching task %d attempt %d on %s",
            pending.task_id, pending.attempt, host,
        )
        return _Running(
            pending.task_id, host, pending.attempt,
            self.launch(pending.task_id, host, pending.attempt),
        )

    # -- main loop -----------------------------------------------------------
    def run(self, n_tasks: int) -> None:
        """Blocks until every task has exited 0; raises JobAborted past the
        failure budget (killing whatever still runs). Any raised error is
        also recorded on ``self.error`` for callers running this on a
        thread."""
        running: Dict[int, _Running] = {}
        deferred: List[Tuple[float, _Running]] = []  # (ready_at, pending)
        try:
            for tid in range(n_tasks):
                host = self._pick_host(tid, None)
                self.placement[tid] = host
                running[tid] = _Running(tid, host, 0, self.launch(tid, host, 0))
            while running or deferred:
                now = time.monotonic()
                due = [p for t, p in deferred if t <= now]
                deferred = [(t, p) for t, p in deferred if t > now]
                for pending in due:
                    running[pending.task_id] = self._relaunch(pending)
                finished = [
                    (tid, r.proc.poll())
                    for tid, r in running.items()
                    if r.proc.poll() is not None
                ]
                if not finished:
                    wait = self.poll_interval
                    if not running and deferred:
                        # nothing to poll: sleep straight to the
                        # earliest scheduled relaunch
                        wait = max(0.0, min(t for t, _ in deferred) - now)
                    time.sleep(wait)
                    continue
                for tid, ret in finished:
                    r = running.pop(tid)
                    if ret == 0:
                        logger.debug("task %d finished", tid)
                        continue
                    deferred.append(self._handle_failure(r, int(ret)))
        except BaseException as e:
            self.error = e
            for r in running.values():
                try:
                    r.proc.kill()
                    r.proc.wait()
                except OSError:
                    pass
            raise

    def run_in_thread(
        self, n_tasks: int, label: str = "supervisor", grace: Optional[float] = None
    ) -> Callable[[], Optional[BaseException]]:
        """Run on a daemon thread; returns an error-check callable suited
        for tracker.submit's ``abort_check`` (backends share this instead
        of each re-implementing the holder/thread/lambda plumbing).

        Anti-wedge: when every task exits 0 the tracker join normally
        returns moments later (the workers sent rabit shutdown). If it is
        STILL polling ``grace`` seconds after the supervisor finished,
        the command never completed the rendezvous (e.g. it is not a
        dmlc/rabit client) — surface that instead of hanging forever,
        which is what the reference does (tracker.py:293-311 wedge).
        ``grace`` defaults to $DMLC_RENDEZVOUS_GRACE or 10s."""
        if grace is None:
            try:
                grace = float(os.getenv("DMLC_RENDEZVOUS_GRACE", "10"))
            except ValueError:
                logger.warning("bad DMLC_RENDEZVOUS_GRACE; using 10s")
                grace = 10.0
        done_at: List[float] = []

        def body() -> None:
            try:
                self.run(n_tasks)
                done_at.append(time.monotonic())
            except Exception:
                logger.exception("%s aborted the job", label)

        def check_err() -> Optional[BaseException]:
            if self.error is not None:
                return self.error
            if done_at and time.monotonic() - done_at[0] > grace:
                return RendezvousNeverCompleted(
                    f"all {n_tasks} task(s) exited 0 but the tracker "
                    "rendezvous never completed — the launched command "
                    "does not appear to be a dmlc/rabit client "
                    "(raise $DMLC_RENDEZVOUS_GRACE if workers simply "
                    "need longer to shut down)"
                )
            return None

        self._thread = threading.Thread(target=body, daemon=True, name=label)
        self._thread.start()
        return check_err
