"""Durable tracker control plane: a journaled ledger the tracker can
crash out of and rejoin.

Every fault story before this module assumed the one process that
cannot die: the tracker, whose shard ledger, rendezvous ranks and
autoscale budget lived entirely in memory — a tracker crash mid-epoch
stranded every lease and lost exactly-once accounting for the run.
This module is the durability substrate: an append-only, CRC-framed
write-ahead log plus a periodic snapshot, both living in one journal
directory (``--tracker-journal <dir>`` / ``DMLC_TRACKER_JOURNAL``).

Files::

    <dir>/wal.log        append-only record stream (framed, see below)
    <dir>/snapshot.json  atomic-rename fold of everything <= its seq

WAL record frame (the ONLY place this framing may be written or parsed
— lint rule L018)::

    | crc32(payload) u32 | payload_len u32 | payload (UTF-8 JSON) |

The payload is ``{"seq": N, "kind": K, ...fields}``. CRC is over the
payload bytes only; the header is protected by the length/EOF scan.
Two damage shapes are distinguished on open:

- **torn tail** — the file ends before a full header+payload (the
  tracker died mid-append). Recovery truncates the tail and keeps
  everything before it: an un-acked append never reached a client, so
  dropping it is safe.
- **CRC corruption** — a record is fully present but its checksum
  disagrees. That is storage damage, not a crash artifact; recovery
  refuses with :class:`JournalError` rather than silently skipping
  committed state (``tools journal inspect`` still dumps such files).

What gets recorded (the transitions that matter for exactly-once):

- ``shard_grant`` / ``shard_done`` / ``shard_release`` /
  ``dataset_switch`` — the shard service's ledger transitions
  (shardsvc.py). On recovery every previously-granted-but-not-done
  shard is **conservatively expired**: it re-enters the queue front
  with its grant history intact, so a reconnecting worker either
  re-leases it or lands a late ``record_done`` that is still honored
  ("duplicate" for an already-done shard — exactly-once holds across
  the crash).
- ``rank_assign`` — rendezvous jobid → rank (+ world size, topology
  epoch), so a relaunched tracker re-answers ``recover_rank`` for
  workers it has never met.
- ``autoscale`` — the controller's ``cost_spent``, fleet target and
  dwell clock, so recovery neither double-spends the cost ceiling nor
  flaps the fleet (autoscale.py seeds its state from this).

Durability knob ``DMLC_TRACKER_JOURNAL_SYNC``: ``always`` (default —
fsync after every append; grants are low-rate control-plane traffic),
``interval`` (fsync every :data:`SYNC_INTERVAL_RECORDS` appends and at
snapshot), ``off`` (OS page cache only; survives tracker SIGKILL but
not host power loss). Snapshots compact the WAL: every
``snapshot_every`` appends the folded state is renamed into place and
the WAL restarts empty (replay skips WAL seqs <= the snapshot's).

docs/robustness.md has the failure matrix; docs/sharding.md the lease
lifecycle this journal makes durable.
"""

from __future__ import annotations

import binascii
import json
import os
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..utils.logging import Error

__all__ = [
    "Journal",
    "JournalError",
    "empty_state",
    "fold",
    "read_journal",
    "inspect_journal",
    "default_sync_policy",
]

#: WAL frame header: crc32(payload) u32, payload_len u32 (see module
#: docstring — this Struct is the single framing site, lint L018)
_HDR = struct.Struct("<II")

WAL_NAME = "wal.log"
SNAPSHOT_NAME = "snapshot.json"

#: record kinds — journal vocabulary, not wire commands. The two shard
#: kinds intentionally shadow the CMD_* spellings (a journal dump reads
#: like the RPC stream that produced it); this is their single literal
#: site, so writers fold through these constants, never fresh strings.
K_SHARD_GRANT = "shard_grant"
K_SHARD_DONE = "shard_done"  # noqa: L013 — record kind, not a cmd send
K_SHARD_RELEASE = "shard_release"  # noqa: L013 — record kind
K_DATASET_SWITCH = "dataset_switch"
K_RANK_ASSIGN = "rank_assign"
K_AUTOSCALE = "autoscale"

#: ``sync="interval"``: fsync once per this many appends
SYNC_INTERVAL_RECORDS = 64

_SYNC_POLICIES = ("always", "interval", "off")


class JournalError(Error):
    """Journal corruption (CRC mismatch on a fully-present record) or
    an unusable journal directory."""


def default_sync_policy() -> str:
    """``DMLC_TRACKER_JOURNAL_SYNC``: always | interval | off."""
    pol = os.environ.get("DMLC_TRACKER_JOURNAL_SYNC", "always").lower()
    return pol if pol in _SYNC_POLICIES else "always"


# -- the folded control-plane state -------------------------------------------


def empty_state() -> Dict:
    """The fold's zero value (pure JSON: string keys throughout)."""
    return {
        "shards": {"fileset": None, "n_shards": None, "epochs": {}},
        "ranks": {},  # jobid -> {"rank", "world", "topo_epoch"}
        "autoscale": None,
    }


def fold(state: Dict, rec: Dict) -> Dict:
    """Fold one WAL record into the state (mutates and returns it).

    ``epochs[e]`` keeps ``done`` (shard → finishing rank, the
    exactly-once facts) and ``outstanding`` (shard → last granted
    rank: grant history without a completion). A release keeps the
    shard in ``outstanding`` — the live ledger keeps its
    ``reclaimed_from`` entry too, so a late ``record_done`` after
    recovery is honored instead of rejected as never-granted."""
    kind = rec.get("kind")
    sh = state["shards"]
    if kind == K_SHARD_GRANT:
        if rec.get("fileset"):
            sh["fileset"] = rec["fileset"]
        if rec.get("n_shards"):
            sh["n_shards"] = int(rec["n_shards"])
        ep = sh["epochs"].setdefault(
            str(int(rec["epoch"])), {"done": {}, "outstanding": {}}
        )
        shard = str(int(rec["shard"]))
        if shard not in ep["done"]:
            ep["outstanding"][shard] = int(rec["rank"])
    elif kind == K_SHARD_DONE:
        ep = sh["epochs"].setdefault(
            str(int(rec["epoch"])), {"done": {}, "outstanding": {}}
        )
        shard = str(int(rec["shard"]))
        ep["done"][shard] = int(rec["rank"])
        ep["outstanding"].pop(shard, None)
    elif kind == K_SHARD_RELEASE:
        # outstanding survives: grant history must outlive the release
        pass
    elif kind == K_DATASET_SWITCH:
        state["shards"] = {
            "fileset": rec.get("fileset"),
            "n_shards": None,
            "epochs": {},
        }
    elif kind == K_RANK_ASSIGN:
        state["ranks"][str(rec["jobid"])] = {
            "rank": int(rec["rank"]),
            "world": int(rec.get("world", -1)),
            "topo_epoch": int(rec.get("topo_epoch", 0)),
        }
    elif kind == K_AUTOSCALE:
        state["autoscale"] = {
            k: rec[k]
            for k in (
                "target", "cost_spent", "dwell_elapsed",
                "last_direction", "direction_changes",
            )
            if k in rec
        }
    # unknown kinds are skipped: a newer tracker's journal replayed by
    # an older build degrades to what it understands
    return state


# -- low-level WAL scan --------------------------------------------------------


def _scan_wal(path: str, strict: bool):
    """Yield ``(offset, rec_or_None, crc_ok)`` per frame; returns via
    StopIteration value the torn-tail offset (None = clean EOF)."""
    records: List[Tuple[int, Optional[Dict], bool]] = []
    torn_at: Optional[int] = None
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return records, torn_at
    with f:
        off = 0
        while True:
            hdr = f.read(_HDR.size)
            if not hdr:
                break  # clean EOF
            if len(hdr) < _HDR.size:
                torn_at = off
                break
            crc, length = _HDR.unpack(hdr)
            payload = f.read(length)
            if len(payload) < length:
                torn_at = off
                break
            crc_ok = (binascii.crc32(payload) & 0xFFFFFFFF) == crc
            if not crc_ok and strict:
                raise JournalError(
                    f"journal CRC mismatch at {path}:{off} — storage "
                    "corruption, refusing to replay past committed state"
                )
            rec: Optional[Dict] = None
            if crc_ok:
                try:
                    rec = json.loads(payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    if strict:
                        raise JournalError(
                            f"journal record at {path}:{off} passed CRC "
                            "but is not JSON — refusing to replay"
                        )
            records.append((off, rec, crc_ok))
            off += _HDR.size + length
    return records, torn_at


def _load_snapshot(dirpath: str) -> Tuple[Optional[Dict], int]:
    path = os.path.join(dirpath, SNAPSHOT_NAME)
    try:
        with open(path, "r", encoding="utf-8") as f:
            snap = json.load(f)
    except FileNotFoundError:
        return None, 0
    except (ValueError, OSError) as e:
        # snapshots are atomic-rename: a torn one means storage damage
        raise JournalError(f"unreadable journal snapshot {path}: {e}")
    if not isinstance(snap, dict) or "state" not in snap:
        raise JournalError(f"malformed journal snapshot {path}")
    return snap["state"], int(snap.get("seq", 0))


def read_journal(dirpath: str) -> Tuple[Dict, int, Dict]:
    """Replay snapshot + WAL into ``(state, last_seq, info)``.

    Strict: a CRC-corrupt record raises :class:`JournalError`; a torn
    tail is tolerated (reported in ``info["torn_tail_at"]``) but NOT
    truncated here — opening a :class:`Journal` for writing does that.
    Deterministic: replaying the same directory twice yields
    byte-identical state (the unit suite pins this)."""
    state, snap_seq = _load_snapshot(dirpath)
    if state is None:
        state = empty_state()
    last_seq = snap_seq
    replayed = 0
    records, torn_at = _scan_wal(
        os.path.join(dirpath, WAL_NAME), strict=True
    )
    for _off, rec, _ok in records:
        if rec is None:
            continue
        seq = int(rec.get("seq", 0))
        if seq <= snap_seq:
            continue  # pre-snapshot tail left behind by compaction
        fold(state, rec)
        last_seq = max(last_seq, seq)
        replayed += 1
    info = {
        "snapshot_seq": snap_seq,
        "wal_records": replayed,
        "torn_tail_at": torn_at,
        "last_seq": last_seq,
    }
    return state, last_seq, info


def inspect_journal(dirpath: str) -> Dict:
    """Lenient dump for ``tools journal inspect``: never raises on
    damage — CRC-bad records are listed with ``crc_ok: false`` and a
    torn tail is flagged, so operators can look at exactly the journal
    a strict replay refused."""
    out: Dict = {
        "dir": dirpath,
        "snapshot": None,
        "records": [],
        "torn_tail_at": None,
        "crc_failures": 0,
    }
    try:
        state, snap_seq = _load_snapshot(dirpath)
        if state is not None:
            out["snapshot"] = {"seq": snap_seq, "state": state}
    except JournalError as e:
        out["snapshot"] = {"error": str(e)}
    records, torn_at = _scan_wal(
        os.path.join(dirpath, WAL_NAME), strict=False
    )
    for off, rec, crc_ok in records:
        if not crc_ok:
            out["crc_failures"] += 1
        out["records"].append({
            "offset": off,
            "crc_ok": crc_ok,
            "seq": None if rec is None else rec.get("seq"),
            "kind": None if rec is None else rec.get("kind"),
        })
    out["torn_tail_at"] = torn_at
    return out


# -- the writable journal ------------------------------------------------------


class Journal:
    """Append-only journal + snapshot compaction (thread-safe).

    Opening replays whatever the directory holds (truncating a torn
    WAL tail in place) and exposes the folded result as ``state`` /
    ``recovered`` — the tracker seeds its shard service, rank memo and
    autoscale controller from it. Every ``append`` folds the record
    into the live state so snapshots are a rename, not a re-scan."""

    def __init__(
        self,
        dirpath: str,
        sync: Optional[str] = None,
        snapshot_every: int = 256,
    ) -> None:
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self.sync = sync if sync in _SYNC_POLICIES else default_sync_policy()
        self.snapshot_every = max(1, int(snapshot_every))
        self._lock = threading.Lock()
        self.state, self.seq, self.recovery_info = read_journal(dirpath)
        self.recovered = bool(
            self.recovery_info["wal_records"]
            or self.recovery_info["snapshot_seq"]
        )
        wal = os.path.join(dirpath, WAL_NAME)
        torn = self.recovery_info["torn_tail_at"]
        if torn is not None:
            # drop the half-written tail record NOW so this process's
            # appends start on a frame boundary
            with open(wal, "r+b") as f:
                f.truncate(torn)
        self._f = open(wal, "ab")
        self._since_sync = 0
        self._since_snapshot = 0

    # -- append path ----------------------------------------------------------
    def append(self, kind: str, **fields) -> int:
        """Durably record one state transition; returns its seq."""
        with self._lock:
            if self._f is None:
                raise JournalError("journal is closed")
            self.seq += 1
            rec = {"seq": self.seq, "kind": kind, **fields}
            payload = json.dumps(
                rec, separators=(",", ":"), sort_keys=True
            ).encode("utf-8")
            crc = binascii.crc32(payload) & 0xFFFFFFFF
            self._f.write(_HDR.pack(crc, len(payload)))
            self._f.write(payload)
            self._f.flush()
            self._since_sync += 1
            if self.sync == "always" or (
                self.sync == "interval"
                and self._since_sync >= SYNC_INTERVAL_RECORDS
            ):
                os.fsync(self._f.fileno())
                self._since_sync = 0
            fold(self.state, rec)
            self._since_snapshot += 1
            if self._since_snapshot >= self.snapshot_every:
                self._snapshot_locked()
            return self.seq

    # -- snapshot / compaction -------------------------------------------------
    def snapshot(self) -> None:
        """Force a snapshot + WAL compaction now."""
        with self._lock:
            if self._f is None:
                raise JournalError("journal is closed")
            self._snapshot_locked()

    def _snapshot_locked(self) -> None:
        path = os.path.join(self.dir, SNAPSHOT_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {"seq": self.seq, "state": self.state},
                f, separators=(",", ":"), sort_keys=True,
            )
            f.flush()
            if self.sync != "off":
                os.fsync(f.fileno())
        os.replace(tmp, path)
        # WAL restart: records <= the snapshot seq are now redundant
        # (replay skips them even if this truncate never lands)
        self._f.close()
        self._f = open(os.path.join(self.dir, WAL_NAME), "wb")
        self._since_snapshot = 0
        self._since_sync = 0

    def close(self) -> None:
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.flush()
                if self.sync != "off":
                    os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()
            self._f = None
