"""Closed-loop elastic autoscaling: stall attribution → capacity decisions.

PR 14 shipped the sensors (windowed per-rank/cluster time series with
named stall fractions and the shard queue-depth history); this module
closes the loop. A controller thread in the tracker periodically reads
the windowed cluster view (``ClusterAggregator.windowed()``), classifies
the job **input-bound** (trainers starved by the data path —
``shard_lease_wait`` / ``dsserve_recv_wait`` / ``fetch_wait``) vs
**accelerator-bound** (``dispatch_slot_wait`` / ``transfer_wait``
dominate, input stalls negligible) and issues capacity decisions:
spawn additional dsserve/drain workers when input-bound, retire them
gracefully when compute-bound (docs/autoscale.md).

The control law is deliberately boring — and *pure*:

    ``decide(view, state, cfg, now) -> Action``

takes only a windowed snapshot plus explicit state/clock, so it
unit-tests by replaying canned series and powers the offline
``tools autoscale replay`` debugger over a recorded end-of-job report
(``replay()``). Guard rails, in evaluation order:

- **hysteresis**: separate up/down thresholds on the summed input-stall
  fraction — a band where the controller holds, so noise cannot flap it;
- **dwell**: a minimum quiet time after any scale action before the
  next one;
- **cost ceiling**: a hard worker×seconds budget for the elastic tier —
  once spent, scale-ups stop (existing workers keep running);
- **flap bound**: after ``max_flaps`` direction changes the controller
  refuses further reversals and only holds or continues the current
  direction.

Actuation goes through a process-global actuator registered by the
launch backend (``set_actuator`` — the ``shardsvc.set_active`` idiom);
the local backend registers an elastic ``DsServeTier`` wrapper
(backends/local.py). Every decision is emitted as a
``dmlc:autoscale_decision`` trace instant and mirrored in
``tracker.autoscale.*`` telemetry, so a merged Perfetto timeline shows
cause → scale-up → stall shrink (docs/observability.md).
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from ..telemetry.timeseries import merge_windows, windowed

__all__ = [
    "Action",
    "AutoscaleConfig",
    "AutoscaleController",
    "ControllerState",
    "accrue_cost",
    "active_actuator",
    "apply_action",
    "decide",
    "replay",
    "set_actuator",
    "signals",
]

logger = logging.getLogger("dmlc_core_tpu.tracker")

_REG = _default_registry()
_G_TARGET = _REG.gauge(
    "tracker.autoscale.target_workers",
    help="controller's current target elastic fleet size",
)
_G_ACTUAL = _REG.gauge(
    "tracker.autoscale.actual_workers",
    help="live elastic workers reported by the actuator",
)
_G_COST = _REG.gauge(
    "tracker.autoscale.cost_spent",
    help="elastic-tier worker-seconds accrued so far",
)

#: stall stages that mean the TRAINERS are starved by the input path —
#: more preprocessing/drain capacity can shrink them
INPUT_STAGES = ("shard_lease_wait", "dsserve_recv_wait", "fetch_wait")
#: stall stages that mean the accelerator side is the bottleneck —
#: extra input workers would idle
COMPUTE_STAGES = ("dispatch_slot_wait", "transfer_wait")

SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass
class AutoscaleConfig:
    """Control-law knobs (docs/autoscale.md has the full matrix)."""

    min_workers: int
    max_workers: int
    #: input-stall fraction at/above which the job is input-bound
    up_threshold: float = 0.40
    #: input-stall fraction at/below which the job is compute-bound
    down_threshold: float = 0.10
    #: minimum seconds between scale actions
    dwell_secs: float = 10.0
    #: hard elastic-tier budget in worker×seconds; 0 = unlimited
    cost_ceiling: float = 0.0
    #: controller tick / replay step
    interval: float = 2.0
    #: windowed-view width the decision reads
    window: float = 10.0
    #: direction changes allowed before reversals are refused
    max_flaps: int = 4
    #: samples a worker rank must have reported before its window counts
    min_samples: int = 2

    def __post_init__(self) -> None:
        if self.min_workers < 0 or self.max_workers < max(1, self.min_workers):
            raise ValueError(
                f"autoscale bounds {self.min_workers}:{self.max_workers} "
                "need 0 <= min <= max and max >= 1"
            )
        if not self.down_threshold < self.up_threshold:
            raise ValueError(
                f"hysteresis needs down < up ({self.down_threshold} vs "
                f"{self.up_threshold})"
            )

    @classmethod
    def from_env(cls) -> Optional["AutoscaleConfig"]:
        """``DMLC_AUTOSCALE=min:max`` (unset/empty = controller off)
        plus the knob envs the submit flags export."""
        raw = (os.environ.get("DMLC_AUTOSCALE") or "").strip()
        if not raw:
            return None
        lo, sep, hi = raw.partition(":")
        try:
            min_w, max_w = int(lo), int(hi if sep else lo)
        except ValueError:
            raise ValueError(
                f"DMLC_AUTOSCALE={raw!r}: want min:max (e.g. 1:4)"
            ) from None
        return cls(
            min_workers=min_w,
            max_workers=max_w,
            up_threshold=_env_float("DMLC_AUTOSCALE_UP", 0.40),
            down_threshold=_env_float("DMLC_AUTOSCALE_DOWN", 0.10),
            dwell_secs=_env_float("DMLC_AUTOSCALE_DWELL", 10.0),
            cost_ceiling=_env_float("DMLC_AUTOSCALE_COST_CEILING", 0.0),
            interval=max(0.1, _env_float("DMLC_AUTOSCALE_INTERVAL", 2.0)),
            window=max(0.5, _env_float("DMLC_AUTOSCALE_WINDOW", 10.0)),
            max_flaps=int(_env_float("DMLC_AUTOSCALE_MAX_FLAPS", 4)),
        )


@dataclass
class ControllerState:
    """Everything a decision depends on besides the windowed view.
    Mutated only by ``apply_action``/``accrue_cost`` so ``decide`` stays
    a pure function of (view, state, cfg, now)."""

    target: int
    last_action_t: Optional[float] = None
    last_direction: int = 0  # +1 up, -1 down, 0 never scaled
    direction_changes: int = 0
    cost_spent: float = 0.0
    last_cost_t: Optional[float] = None
    decisions: Dict[str, int] = field(default_factory=dict)


@dataclass(frozen=True)
class Action:
    kind: str  # scale_up | scale_down | hold
    reason: str
    target: int  # fleet target AFTER this action
    signals: Dict[str, Any] = field(default_factory=dict)


def signals(view: Dict[str, Any], min_samples: int = 2) -> Dict[str, Any]:
    """Classification inputs from one ``ClusterTimeSeries.window()``
    view: summed input/compute stall fractions (cluster average over
    reporting worker ranks), shard queue depth (tracker pseudo-rank
    gauge), and how many worker ranks had a usable window."""
    per_rank = view.get("per_rank") or {}
    reporting = 0
    for key, v in per_rank.items():
        if key == "tracker":
            continue
        if v.get("samples", 0) >= min_samples and v.get("span_secs", 0) > 0:
            reporting += 1
    derived = (view.get("cluster") or {}).get("derived") or {}
    stall = derived.get("stall_fraction") or {}
    qd = (
        (per_rank.get("tracker") or {})
        .get("gauges", {})
        .get("tracker.shards.queue_depth")
    ) or {}
    return {
        "input_stall": round(
            sum(float(stall.get(s, 0.0)) for s in INPUT_STAGES), 4
        ),
        "compute_stall": round(
            sum(float(stall.get(s, 0.0)) for s in COMPUTE_STAGES), 4
        ),
        "queue_depth": float(qd.get("last", 0.0) or 0.0),
        "reporting_ranks": reporting,
    }


def decide(
    view: Dict[str, Any],
    state: ControllerState,
    cfg: AutoscaleConfig,
    now: float,
) -> Action:
    """The pure control law. Evaluation order is part of the contract
    (tests pin the reasons): signal presence → hysteresis band →
    min/max bounds → cost ceiling (ups only) → flap budget → dwell →
    action."""
    sig = signals(view, cfg.min_samples)

    def hold(reason: str) -> Action:
        return Action(HOLD, reason, state.target, sig)

    if sig["reporting_ranks"] == 0:
        return hold("no_signal")
    input_stall = sig["input_stall"]
    if input_stall >= cfg.up_threshold:
        direction = 1
    elif input_stall <= cfg.down_threshold:
        direction = -1
    else:
        return hold("in_band")
    if direction > 0:
        if state.target >= cfg.max_workers:
            return hold("at_max")
        if cfg.cost_ceiling > 0 and state.cost_spent >= cfg.cost_ceiling:
            return hold("cost_ceiling")
    else:
        if state.target <= cfg.min_workers:
            return hold("at_min")
    if (
        state.last_direction != 0
        and direction != state.last_direction
        and state.direction_changes >= cfg.max_flaps
    ):
        return hold("flap_budget")
    if (
        state.last_action_t is not None
        and now - state.last_action_t < cfg.dwell_secs
    ):
        return hold("dwell")
    if direction > 0:
        return Action(SCALE_UP, "input_bound", state.target + 1, sig)
    return Action(SCALE_DOWN, "compute_bound", state.target - 1, sig)


def apply_action(state: ControllerState, action: Action, now: float) -> None:
    """Fold one decision into the state (the controller's and the
    replayer's single mutation site)."""
    state.decisions[action.kind] = state.decisions.get(action.kind, 0) + 1
    if action.kind == HOLD:
        return
    direction = 1 if action.kind == SCALE_UP else -1
    if state.last_direction != 0 and direction != state.last_direction:
        state.direction_changes += 1
    state.last_direction = direction
    state.last_action_t = now
    state.target = action.target


def accrue_cost(state: ControllerState, actual: int, now: float) -> None:
    """Integrate elastic-tier worker-seconds between ticks — the spend
    the cost ceiling caps."""
    if state.last_cost_t is not None and now > state.last_cost_t:
        state.cost_spent += max(0, int(actual)) * (now - state.last_cost_t)
    state.last_cost_t = now


def replay(
    ts_report: Dict[str, Any],
    cfg: AutoscaleConfig,
    include_holds: bool = True,
) -> List[Dict[str, Any]]:
    """Run the pure decision function over a RECORDED end-of-job time
    series (the ``timeseries`` section of a ``DMLC_METRICS_REPORT``
    file) and return the decisions it would have made — deterministic
    and offline, so thresholds can be tuned against yesterday's job
    (``tools autoscale replay``). The simulated fleet tracks the
    decisions (actual == target), so cost accrual is the plan's cost."""
    per_rank = ts_report.get("per_rank") or {}
    times = sorted(
        {s["t"] for series in per_rank.values() for s in series
         if isinstance(s, dict) and isinstance(s.get("t"), (int, float))}
    )
    out: List[Dict[str, Any]] = []
    if not times:
        return out
    t0, t_end = times[0], times[-1]
    state = ControllerState(target=cfg.min_workers)
    t = t0 + cfg.interval
    while t <= t_end + 1e-9:
        views = {
            key: windowed(
                [s for s in series if s.get("t", float("inf")) <= t],
                cfg.window,
                now=t,
            )
            for key, series in per_rank.items()
        }
        view = {
            "window_secs": cfg.window,
            "per_rank": views,
            "cluster": merge_windows(
                {k: v for k, v in views.items() if k != "tracker"}
            ),
        }
        accrue_cost(state, state.target, t)
        action = decide(view, state, cfg, t)
        apply_action(state, action, t)
        if include_holds or action.kind != HOLD:
            out.append({
                "t": round(t - t0, 3),
                "kind": action.kind,
                "reason": action.reason,
                "target": action.target,
                "cost_spent": round(state.cost_spent, 3),
                **action.signals,
            })
        t += cfg.interval
    return out


class AutoscaleController:
    """The tracker-resident closed loop: tick every ``cfg.interval``
    seconds, read the windowed cluster view, run ``decide``, actuate
    through the registered actuator, and publish the decision as a
    trace instant + ``tracker.autoscale.*`` telemetry. ``status()`` is
    the JSON section the metrics endpoint / end-of-job report / tools
    top surface (aggregate.py ``extra_sections``)."""

    def __init__(
        self,
        aggregator,
        cfg: AutoscaleConfig,
        actuator=None,
        clock=None,
        journal=None,
        recovered=None,
    ) -> None:
        import time as _time

        self.cfg = cfg
        self.aggregator = aggregator
        self.state = ControllerState(target=cfg.min_workers)
        self._actuator = actuator
        self._clock = clock or _time.monotonic
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._synced = False
        self.last: Optional[Dict[str, Any]] = None
        self.last_actual = cfg.min_workers
        self._journal = journal
        self._last_journaled: Optional[Dict[str, Any]] = None
        if recovered:
            self._restore(recovered)

    def _restore(self, rec: Dict[str, Any]) -> None:
        """Seed the control law's memory from a journaled ``autoscale``
        record so a relaunched tracker neither double-spends the cost
        ceiling (``cost_spent`` resumes where the dead tracker left it)
        nor flaps (the dwell clock resumes mid-countdown instead of
        resetting — a scale-up decided 20s before the crash still waits
        only the REMAINING dwell, and never re-fires instantly)."""
        now = self._clock()
        st = self.state
        st.target = max(
            self.cfg.min_workers,
            min(self.cfg.max_workers, int(rec.get("target", st.target))),
        )
        st.cost_spent = float(rec.get("cost_spent", 0.0))
        st.last_direction = int(rec.get("last_direction", 0))
        st.direction_changes = int(rec.get("direction_changes", 0))
        # monotonic clocks do not survive a process restart: rebuild
        # last_action_t from the journaled dwell-elapsed offset
        dwell = rec.get("dwell_elapsed")
        if dwell is not None:
            st.last_action_t = now - max(0.0, float(dwell))
        st.last_cost_t = now  # no cost accrues for the outage window
        logger.info(
            "autoscale state recovered: target=%d cost=%.1fws "
            "dwell_elapsed=%s", st.target, st.cost_spent, dwell,
        )

    def _journal_state(self, now: float) -> None:
        """Append an ``autoscale`` record when the recoverable slice of
        controller state changed (every action; cost drift throttled by
        the caller). Written inside the tick lock, BEFORE actuation —
        a crash between journal and actuation recovers to the decided
        target and the next tick re-converges the fleet."""
        if self._journal is None:
            return
        st = self.state
        rec = {
            "target": st.target,
            "cost_spent": round(st.cost_spent, 3),
            "dwell_elapsed": (
                round(now - st.last_action_t, 3)
                if st.last_action_t is not None else None
            ),
            "last_direction": st.last_direction,
            "direction_changes": st.direction_changes,
        }
        from . import journal as _jn  # local: avoid import cycle at module load
        self._journal.append(_jn.K_AUTOSCALE, **rec)
        self._last_journaled = rec

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "AutoscaleController":
        t = threading.Thread(
            target=self._run, daemon=True, name="autoscale-controller"
        )
        self._thread = t
        t.start()
        logger.info(
            "autoscale controller on: fleet %d:%d up>=%.2f down<=%.2f "
            "dwell=%.1fs ceiling=%s interval=%.1fs window=%.1fs",
            self.cfg.min_workers, self.cfg.max_workers,
            self.cfg.up_threshold, self.cfg.down_threshold,
            self.cfg.dwell_secs,
            self.cfg.cost_ceiling or "unlimited",
            self.cfg.interval, self.cfg.window,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval):
            try:
                self.tick()
            except Exception:
                # a controller bug must never take the tracker down —
                # the job runs fine at the current fleet size
                logger.exception("autoscale tick failed")

    # -- one tick ------------------------------------------------------------
    def _resolve_actuator(self):
        return self._actuator if self._actuator is not None else (
            active_actuator()
        )

    def tick(self) -> Action:
        """One control cycle (public so tests/bench can step it
        deterministically without the thread)."""
        with self._lock:
            now = self._clock()
            actuator = self._resolve_actuator()
            actual = self.state.target
            if actuator is not None:
                actual = int(actuator.actual())
                if not self._synced:
                    # adopt the launched fleet (a --dsserve N above min
                    # is the operator's opening bid, not a deviation)
                    self.state.target = max(
                        self.cfg.min_workers,
                        min(self.cfg.max_workers, actual),
                    )
                    self._synced = True
            self.last_actual = actual
            accrue_cost(self.state, actual, now)
            view = self.aggregator.windowed(self.cfg.window)
            action = decide(view, self.state, self.cfg, now)
            apply_action(self.state, action, now)
            # journal every action; journal pure cost drift only past a
            # coarse threshold so a long HOLD steady-state costs ~one
            # record a minute, not one per tick
            prev_cost = (
                self._last_journaled["cost_spent"]
                if self._last_journaled else 0.0
            )
            if action.kind != HOLD or self._last_journaled is None or (
                self.state.cost_spent - prev_cost >= 60.0
            ):
                self._journal_state(now)
            _G_TARGET.set(self.state.target)
            _G_ACTUAL.set(actual)
            _G_COST.set(round(self.state.cost_spent, 3))
            _decision_counter(action.kind).inc()
            _tracing.instant(
                "dmlc:autoscale_decision",
                kind=action.kind,
                reason=action.reason,
                target=action.target,
                actual=actual,
                **action.signals,
            )
            self.last = {
                "kind": action.kind,
                "reason": action.reason,
                "target": action.target,
                "actual": actual,
                **action.signals,
            }
            if action.kind != HOLD:
                logger.info(
                    "autoscale %s (%s): fleet %d -> %d (input_stall=%.2f "
                    "compute_stall=%.2f cost=%.1fws)",
                    action.kind, action.reason, actual, action.target,
                    action.signals.get("input_stall", 0.0),
                    action.signals.get("compute_stall", 0.0),
                    self.state.cost_spent,
                )
        # actuate OUTSIDE the lock: spawning a worker blocks on its
        # port file and status() must stay readable meanwhile
        if actuator is not None:
            try:
                if action.kind == SCALE_UP:
                    actuator.add_task()
                elif action.kind == SCALE_DOWN:
                    actuator.retire_task()
            except Exception:
                logger.exception("autoscale actuation failed")
        return action

    # -- introspection -------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "min_workers": self.cfg.min_workers,
                "max_workers": self.cfg.max_workers,
                "target": self.state.target,
                "actual": self.last_actual,
                "cost_spent": round(self.state.cost_spent, 3),
                "cost_ceiling": self.cfg.cost_ceiling,
                "direction_changes": self.state.direction_changes,
                "decisions": dict(self.state.decisions),
                "window_secs": self.cfg.window,
                "interval_secs": self.cfg.interval,
                "last": dict(self.last) if self.last else None,
            }


def _decision_counter(kind: str):
    return _REG.counter(
        "tracker.autoscale.decisions",
        help="controller decisions by kind",
        labels={"kind": kind},
    )


# -- process-global actuator (the shardsvc.set_active idiom) -------------------

_actuator_lock = threading.Lock()
_actuator = None


def set_actuator(actuator) -> None:
    """Register the launch backend's elastic actuator (an object with
    ``actual() -> int``, ``add_task() -> bool``, ``retire_task() ->
    bool``). The controller resolves it lazily per tick, so the tracker
    needs no backend wiring — and a backend without one leaves the
    controller in shadow mode (decisions recorded, nothing actuated)."""
    global _actuator
    with _actuator_lock:
        _actuator = actuator


def active_actuator():
    with _actuator_lock:
        return _actuator
