"""Submission option parsing (reference tracker/dmlc_tracker/opts.py).

Same surface as the reference CLI plus the TPU-native ``tpu-pod`` cluster.
Unknown trailing args join the command, and ``--cluster`` falls back to
$DMLC_SUBMIT_CLUSTER, as in the reference (opts.py:166-177).
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional, Set, Tuple

__all__ = ["get_opts", "get_memory_mb", "get_cache_file_set"]

CLUSTERS = [
    "local",
    "ssh",
    "mpi",
    "sge",
    "slurm",
    "yarn",
    "mesos",
    "kubernetes",
    "tpu-pod",
]


def _str2bool(v: str) -> bool:
    return str(v).lower() not in ("0", "false", "no", "off", "")


def get_memory_mb(mem_str: str) -> int:
    """'4g'/'512m' → MB (reference get_memory_mb, opts.py:39-57)."""
    s = mem_str.lower()
    if s.endswith("g"):
        return int(float(s[:-1]) * 1024)
    if s.endswith("m"):
        return int(float(s[:-1]))
    raise RuntimeError(
        f"Invalid memory specification {mem_str}, need a number ending in g or m"
    )


def get_cache_file_set(args) -> Tuple[Set[str], List[str]]:
    """Files referenced by the command that should ship to executors; the
    command is rewritten to use local basenames (reference
    get_cache_file_set, opts.py:6-36)."""
    fset = set(args.files)
    rewritten: List[str] = []
    if not args.auto_file_cache:
        return fset, list(args.command)
    for i, token in enumerate(args.command):
        if os.path.exists(token):
            fset.add(token)
            rewritten.append("./" + os.path.basename(token))
        else:
            rewritten.append(token)
    return fset, rewritten


def get_opts(args: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(description="DMLC TPU job submission.")
    parser.add_argument(
        "--cluster", type=str, choices=CLUSTERS, default=None,
        help="Cluster type; defaults to $DMLC_SUBMIT_CLUSTER.",
    )
    parser.add_argument("--num-workers", required=True, type=int)
    parser.add_argument("--worker-cores", default=1, type=int)
    parser.add_argument("--worker-memory", default="1g", type=str)
    parser.add_argument("--num-servers", default=0, type=int)
    parser.add_argument("--server-cores", default=1, type=int)
    parser.add_argument("--server-memory", default="1g", type=str)
    parser.add_argument("--jobname", default=None, type=str)
    parser.add_argument("--queue", default="default", type=str)
    parser.add_argument(
        "--log-level", default="INFO", choices=["INFO", "DEBUG"], type=str
    )
    parser.add_argument("--log-file", default=None, type=str)
    parser.add_argument("--host-ip", default=None, type=str)
    parser.add_argument(
        "--host-file", default=None, type=str,
        help="File listing host[:port], for MPI and ssh.",
    )
    parser.add_argument("--sge-log-dir", default=None, type=str)
    parser.add_argument(
        "--auto-file-cache", default=True, type=_str2bool,
        help="Ship command-referenced files and rewrite them to basenames.",
    )
    parser.add_argument("--files", default=[], action="append")
    parser.add_argument("--archives", default=[], action="append")
    parser.add_argument("--env", action="append", default=[])
    parser.add_argument("--sync-dst-dir", type=str, default=None)
    parser.add_argument("--mesos-master", type=str, default=None)
    parser.add_argument("--slurm-worker-nodes", default=None, type=int)
    parser.add_argument("--slurm-server-nodes", default=None, type=int)
    parser.add_argument("--kube-namespace", default="default", type=str)
    parser.add_argument("--kube-worker-image", default="mxnet/python", type=str)
    parser.add_argument("--kube-server-image", default="mxnet/python", type=str)
    parser.add_argument("--local-num-attempt", default=0, type=int)
    # host-level shared decoded-block cache (io/blockcache.py): start
    # ONE daemon per host and point every worker at it, so colocated
    # workers over the same compressed corpus decode each block once
    parser.add_argument(
        "--block-cache", action="store_true", default=False,
        help="Start a per-host shared decoded-block cache daemon and "
             "export DMLC_BLOCK_CACHE_SOCK to the workers (local "
             "backend; other backends launch 'tools cached serve' "
             "per host themselves — docs/recordio.md).",
    )
    parser.add_argument(
        "--block-cache-mb", default=0, type=int,
        help="Daemon budget in MB (default $DMLC_BLOCK_CACHE_MB or 1024).",
    )
    # dynamic shard service (tracker/shardsvc.py, docs/sharding.md):
    # the tracker leases micro-shards to whoever is idle; these knobs
    # shape the ledger. Workers opt IN per dataset (create(...,
    # dynamic_shards=True) / &dynamic_shards=1), so the flags only set
    # policy, they do not switch sharding modes by themselves.
    parser.add_argument(
        "--shard-oversplit", default=0, type=int,
        help="Micro-shards per worker for dynamic sharding (exports "
             "DMLC_SHARD_OVERSPLIT; default 4). Higher = finer-grained "
             "work stealing, more lease round-trips.",
    )
    parser.add_argument(
        "--shard-lease-ttl", default=0.0, type=float,
        help="Seconds a shard lease survives without a renew before "
             "the tracker reclaims it (exports DMLC_SHARD_LEASE_TTL; "
             "default 30). Renewal rides worker pulls and metrics "
             "heartbeats.",
    )
    # disaggregated preprocessing tier (dmlc_core_tpu/dsserve/,
    # docs/dsserve.md): N standalone workers running fetch→decode→
    # parse→pack next to the tracker, leasing micro-shards from the
    # shard service and streaming finished packed slots to trainers
    parser.add_argument(
        "--dsserve", default=0, type=int,
        help="Start N dsserve preprocessing workers beside the tracker "
             "and export DMLC_DSSERVE=host:port,... to the workers, "
             "who read via dsserve://$DMLC_DSSERVE/<dataset-uri> "
             "(local backend; torn down with the job).",
    )
    parser.add_argument(
        "--dsserve-host", default="127.0.0.1", type=str,
        help="Bind/advertise address for the dsserve tier.",
    )
    # closed-loop elastic autoscaling (tracker/autoscale.py,
    # docs/autoscale.md): the tracker's controller thread reads the
    # windowed stall attribution and grows/shrinks the dsserve tier
    parser.add_argument(
        "--autoscale", default="", type=str, metavar="MIN:MAX",
        help="Autoscale the dsserve tier between MIN and MAX workers "
             "(exports DMLC_AUTOSCALE; default off — fixed fleet). The "
             "tracker scales up when the input-stall fraction "
             "(shard_lease_wait + dsserve_recv_wait + fetch_wait) "
             "crosses the up threshold and retires workers gracefully "
             "when the job is accelerator-bound (docs/autoscale.md). "
             "Requires time-series sampling (DMLC_TS, on by default) "
             "and MIN >= 1. --dsserve N inside the bounds sets the "
             "opening fleet.",
    )
    parser.add_argument(
        "--autoscale-cost-ceiling", default=0.0, type=float,
        metavar="WORKER_SECS",
        help="Hard elastic-tier budget in worker x seconds (exports "
             "DMLC_AUTOSCALE_COST_CEILING; 0 = unlimited). Once spent, "
             "scale-ups stop; running workers keep running.",
    )
    parser.add_argument(
        "--autoscale-dwell", default=0.0, type=float, metavar="SECS",
        help="Minimum seconds between scale actions (exports "
             "DMLC_AUTOSCALE_DWELL; default 10) — the flap damper.",
    )
    # flight-recorder tracing (telemetry/tracing.py): one trace file
    # per process of the job — workers, cache daemon, tracker — all
    # landing in one directory for `tools trace merge`
    parser.add_argument(
        "--trace-dir", default=None, type=str,
        help="Export DMLC_TRACE_DIR to every process of the job "
             "(tracker, workers, block-cache daemon): each dumps its "
             "flight-recorder rings there at exit / on SIGUSR2; join "
             "with 'python -m dmlc_core_tpu.tools trace merge' "
             "(docs/observability.md).",
    )
    # durable control plane (tracker/journal.py): tracker state journal
    # + crash supervision (docs/robustness.md)
    parser.add_argument(
        "--tracker-journal", default=None, type=str, metavar="DIR",
        help="Journal tracker control-plane state (shard ledger, rank "
             "assignments, autoscale spend) to DIR and supervise the "
             "tracker as a restartable subprocess: a crashed tracker is "
             "relaunched on the same port, replays the journal, and "
             "reconnecting workers resume exactly-once (exports "
             "DMLC_TRACKER_JOURNAL; local backend only).",
    )
    # tpu-pod backend (TPU-native, no reference analogue)
    parser.add_argument(
        "--tpu-name", default=None, type=str,
        help="TPU pod/VM name for the tpu-pod cluster backend.",
    )
    parser.add_argument(
        "--tpu-zone", default=None, type=str,
        help="GCP zone of the TPU pod.",
    )
    parser.add_argument(
        "--tpu-project", default=None, type=str,
        help="GCP project of the TPU pod.",
    )
    parser.add_argument(
        "--dry-run", action="store_true", default=False,
        help="Print the launch commands instead of executing them.",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER)
    parsed = parser.parse_args(args)
    if not parsed.command:
        parser.error("no command to launch")
    if parsed.command and parsed.command[0] == "--":
        parsed.command = parsed.command[1:]
    if parsed.cluster is None:
        parsed.cluster = os.getenv("DMLC_SUBMIT_CLUSTER", None)
    if parsed.cluster is None:
        raise RuntimeError(
            "--cluster is not specified; set it or $DMLC_SUBMIT_CLUSTER"
        )
    if parsed.autoscale:
        lo, sep, hi = parsed.autoscale.partition(":")
        try:
            a_min, a_max = int(lo), int(hi if sep else lo)
        except ValueError:
            parser.error(
                f"--autoscale {parsed.autoscale!r}: want MIN:MAX (e.g. 1:4)"
            )
        # MIN 0 would let the controller retire the whole tier mid-
        # epoch, ending every client stream with nothing left to dial
        if not 1 <= a_min <= a_max:
            parser.error("--autoscale needs 1 <= MIN <= MAX")
    parsed.worker_memory_mb = get_memory_mb(parsed.worker_memory)
    parsed.server_memory_mb = get_memory_mb(parsed.server_memory)
    return parsed
