"""In-container bootstrap run around the user command.

Reference: tracker/dmlc_tracker/launcher.py — runs INSIDE each container:
derives the role from the task id on array schedulers (launcher.py:41-47),
unzips shipped archives (:9-16,72-74), then execs the user command with
the DMLC env intact.
"""

from __future__ import annotations

import os
import subprocess
import sys
import zipfile
from typing import List

__all__ = ["unzip_archives", "derive_role", "main"]


def unzip_archives(archives: List[str], workdir: str = ".") -> None:
    for ar in archives:
        if not os.path.exists(ar):
            continue
        with zipfile.ZipFile(ar) as zf:
            zf.extractall(workdir)


def derive_role(env: dict) -> str:
    """DMLC_ROLE, or derived from task id vs worker count on array
    schedulers (reference launcher.py:41-47)."""
    if env.get("DMLC_ROLE"):
        return env["DMLC_ROLE"]
    task_id = int(env.get("DMLC_TASK_ID", env.get("SGE_TASK_ID", 1)) or 1)
    nworker = int(env.get("DMLC_NUM_WORKER", 1))
    return "worker" if task_id < nworker else "server"


def main(argv: List[str]) -> int:
    env = os.environ.copy()
    archives = [a for a in env.get("DMLC_JOB_ARCHIVES", "").split(":") if a]
    unzip_archives(archives)
    env["DMLC_ROLE"] = derive_role(env)
    return subprocess.call(
        " ".join(argv), shell=True, executable="/bin/bash", env=env
    )


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
