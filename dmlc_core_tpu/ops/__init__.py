"""Compute ops over staged batches (jax/XLA; pallas variants can slot in).

The reference's only compute is ``Row::SDot`` (include/dmlc/data.h:137-152)
— the sparse dot its downstream learners run. Here that becomes batched,
fixed-shape ops XLA can fuse and tile:

- dense layout → plain ``x @ w`` (MXU path)
- ell layout → vectorized gather-multiply-reduce (VPU path)
"""

from .sparse import ell_matvec, ell_matmul, ell_to_dense, weighted_mean

__all__ = ["ell_matvec", "ell_matmul", "ell_to_dense", "weighted_mean"]
