"""Sparse ops on ELL (capped-CSR) batches.

Batched generalization of the reference's Row::SDot (data.h:137-152): the
scalar per-row loop becomes one gather + elementwise multiply + reduction
over the fixed K dimension, which XLA fuses into a single kernel. Padding
slots carry value 0.0, so no masking is needed in the reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ell_matvec", "ell_matmul", "ell_to_dense", "weighted_mean"]


def ell_matvec(indices: jax.Array, values: jax.Array, w: jax.Array) -> jax.Array:
    """Per-row sparse dot with a dense vector.

    indices: i32[B, K]; values: f32[B, K]; w: f32[D] → f32[B].
    Batched Row::SDot: out[b] = Σ_k values[b,k] * w[indices[b,k]].
    """
    return jnp.sum(values * jnp.take(w, indices, axis=0), axis=-1)


def ell_matmul(indices: jax.Array, values: jax.Array, table: jax.Array) -> jax.Array:
    """Sparse-dense matmul against an embedding/weight table.

    indices: i32[B, K]; values: f32[B, K]; table: f32[D, E] → f32[B, E]:
    out[b] = Σ_k values[b,k] * table[indices[b,k], :] — the FM/embedding
    gather path.
    """
    gathered = jnp.take(table, indices, axis=0)  # [B, K, E]
    return jnp.einsum("bk,bke->be", values, gathered)


def ell_to_dense(
    indices: jax.Array, values: jax.Array, num_features: int
) -> jax.Array:
    """ELL → dense f32[B, D] (duplicates accumulate, matching the host-side
    dense batcher). Use when D is small enough that the MXU matmul beats
    the gather."""
    b = indices.shape[0]
    rows = jnp.repeat(jnp.arange(b), indices.shape[1])
    dense = jnp.zeros((b, num_features), dtype=values.dtype)
    return dense.at[rows, indices.reshape(-1)].add(values.reshape(-1))


def weighted_mean(per_row: jax.Array, weights: jax.Array) -> jax.Array:
    """Weight-masked mean: padding rows (weight 0) contribute nothing."""
    total = jnp.sum(weights)
    return jnp.sum(per_row * weights) / jnp.maximum(total, 1e-9)
