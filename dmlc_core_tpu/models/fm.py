"""Factorization machine over ELL batches (the libfm-format consumer).

Second-order FM (Rendle 2010): score = w0 + Σ w_i x_i
+ ½ Σ_e [(Σ_i v_ie x_i)² - Σ_i v_ie² x_i²], computed with two embedding
gathers — the classic trick that keeps it O(B·K·E) with no D×D term. The
embedding table is the natural tensor-parallel shard target: split the E
axis over the mesh's 'model' axis (see parallel/ and __graft_entry__).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.sparse import ell_matvec, weighted_mean
from .common import bce_with_logits, sgd_update

__all__ = ["FactorizationMachine"]

Params = Dict[str, jax.Array]
Batch = Dict[str, jax.Array]


class FactorizationMachine:
    def __init__(
        self, num_features: int, embed_dim: int = 16, l2: float = 0.0
    ) -> None:
        self.num_features = num_features
        self.embed_dim = embed_dim
        self.l2 = l2

    def init(self, rng: jax.Array) -> Params:
        wkey, vkey = jax.random.split(rng)
        return {
            "w": jax.random.normal(wkey, (self.num_features,), jnp.float32)
            * 0.01,
            "v": jax.random.normal(
                vkey, (self.num_features, self.embed_dim), jnp.float32
            )
            * 0.01,
            "b": jnp.zeros((), jnp.float32),
        }

    def forward(self, params: Params, batch: Batch) -> jax.Array:
        idx, val = batch["indices"], batch["values"]
        linear = ell_matvec(idx, val, params["w"])
        emb = jnp.take(params["v"], idx, axis=0)  # [B, K, E]
        xv = emb * val[..., None]  # [B, K, E]
        sum_sq = jnp.sum(xv, axis=1) ** 2  # [B, E]
        sq_sum = jnp.sum(xv**2, axis=1)  # [B, E]
        pair = 0.5 * jnp.sum(sum_sq - sq_sum, axis=-1)  # [B]
        return linear + pair + params["b"]

    def loss(self, params: Params, batch: Batch) -> jax.Array:
        scores = self.forward(params, batch)
        per_row = bce_with_logits(scores, batch["labels"])
        data_loss = weighted_mean(per_row, batch["weights"])
        if self.l2:
            data_loss = data_loss + self.l2 * (
                jnp.sum(params["w"] ** 2) + jnp.sum(params["v"] ** 2)
            )
        return data_loss

    def loss_and_grads(
        self, params: Params, batch: Batch
    ) -> Tuple[jax.Array, Params]:
        """(loss, grads) without the update — see
        ``linear._LinearBase.loss_and_grads``: the half step a
        multi-host SGD loop allreduces before one shared update."""
        return jax.value_and_grad(self.loss)(params, batch)

    def sgd_step(
        self, params: Params, batch: Batch, lr: float = 0.05
    ) -> Tuple[Params, jax.Array]:
        loss_val, grads = self.loss_and_grads(params, batch)
        return sgd_update(params, grads, lr), loss_val
