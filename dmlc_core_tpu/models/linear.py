"""Sparse linear / logistic regression over staged batches.

Pure-functional jax models: params are pytrees, steps are jittable, and
every function takes the batch dict produced by the staging layer (either
'ell' or 'dense' layout, auto-detected by key). Loss is weight-masked so
zero-padded rows are no-ops (staging/batcher.py contract).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.sparse import ell_matvec, weighted_mean
from .common import bce_with_logits, sgd_update

__all__ = ["LinearRegression", "LogisticRegression"]

Params = Dict[str, jax.Array]
Batch = Dict[str, jax.Array]


def _scores(params: Params, batch: Batch) -> jax.Array:
    if "x" in batch:
        return batch["x"] @ params["w"] + params["b"]
    return ell_matvec(batch["indices"], batch["values"], params["w"]) + params["b"]


class _LinearBase:
    """Shared param/step machinery; subclasses define per-row loss."""

    def __init__(self, num_features: int, l2: float = 0.0) -> None:
        self.num_features = num_features
        self.l2 = l2

    def init(self, rng: jax.Array) -> Params:
        wkey, _ = jax.random.split(rng)
        return {
            "w": jax.random.normal(wkey, (self.num_features,), jnp.float32)
            * 0.01,
            "b": jnp.zeros((), jnp.float32),
        }

    def forward(self, params: Params, batch: Batch) -> jax.Array:
        raise NotImplementedError

    def per_row_loss(self, scores: jax.Array, labels: jax.Array) -> jax.Array:
        raise NotImplementedError

    def loss(self, params: Params, batch: Batch) -> jax.Array:
        per_row = self.per_row_loss(_scores(params, batch), batch["labels"])
        data_loss = weighted_mean(per_row, batch["weights"])
        if self.l2:
            data_loss = data_loss + self.l2 * jnp.sum(params["w"] ** 2)
        return data_loss

    def loss_and_grads(
        self, params: Params, batch: Batch
    ) -> Tuple[jax.Array, Params]:
        """(loss, grads) WITHOUT the update — the distributed-SGD half
        step: a multi-host loop computes grads per rank, allreduces
        them over the tracker collective (tracker/collective.py), then
        applies one shared ``sgd_update`` so every rank steps to the
        identical params (examples/train_criteo_rec.py)."""
        return jax.value_and_grad(self.loss)(params, batch)

    def sgd_step(
        self, params: Params, batch: Batch, lr: float = 0.1
    ) -> Tuple[Params, jax.Array]:
        """One SGD step; jit this (or wrap with parallel.data_parallel_step
        for SPMD over a mesh)."""
        loss_val, grads = self.loss_and_grads(params, batch)
        return sgd_update(params, grads, lr), loss_val


class LinearRegression(_LinearBase):
    """Least squares on sparse rows."""

    def forward(self, params: Params, batch: Batch) -> jax.Array:
        return _scores(params, batch)

    def per_row_loss(self, scores: jax.Array, labels: jax.Array) -> jax.Array:
        return 0.5 * (scores - labels) ** 2


class LogisticRegression(_LinearBase):
    """Binary logistic regression — the flagship learner (the classic
    distributed-XGBoost/rabit workload the reference's substrate feeds)."""

    def forward(self, params: Params, batch: Batch) -> jax.Array:
        return jax.nn.sigmoid(_scores(params, batch))

    def per_row_loss(self, scores: jax.Array, labels: jax.Array) -> jax.Array:
        return bce_with_logits(scores, labels)

    def accuracy(self, params: Params, batch: Batch) -> jax.Array:
        pred = _scores(params, batch) > 0
        y = batch["labels"] > 0.5
        hits = (pred == y).astype(jnp.float32)
        return weighted_mean(hits, batch["weights"])
