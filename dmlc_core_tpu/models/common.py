"""Shared learner pieces: stable losses and the plain SGD update."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

__all__ = ["bce_with_logits", "sgd_update"]


def bce_with_logits(scores: jax.Array, labels: jax.Array) -> jax.Array:
    """Numerically stable binary cross-entropy on logits, per row.
    Labels may be {0,1} or {-1,1} (remapped here)."""
    y = jnp.where(labels < 0.5, 0.0, 1.0)
    return jnp.clip(scores, 0) - scores * y + jnp.log1p(
        jnp.exp(-jnp.abs(scores))
    )


def sgd_update(params: Dict, grads: Dict, lr: float) -> Dict:
    return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
