"""Example downstream learners over staged batches.

The reference has no models (SURVEY header: "not a tensor/training
framework"); its consumers are XGBoost/MXNet-style learners fed by
RowBlockIter. These jitted learners play that downstream role for the TPU
build — small, pure-functional, and the flagship (sparse logistic
regression, the classic rabit/ps-lite workload) is what __graft_entry__ and
bench.py exercise.
"""

from .common import sgd_update
from .fm import FactorizationMachine
from .linear import LinearRegression, LogisticRegression

__all__ = [
    "LinearRegression",
    "LogisticRegression",
    "FactorizationMachine",
    "sgd_update",
]
