"""Token parsing helpers for text formats.

Reference: include/dmlc/strtonum.h — locale-independent ParseFloat/ParsePair
(:656-681) / ParseTriple (:697-737), the hot inner loop of all text parsers.

The TPU build's true hot loop lives in the native C++ core (native/); these
Python helpers define the exact semantics and serve as the fallback. Python's
float() is already locale-independent, matching the reference's motivation
for hand-rolled strtof.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = [
    "parse_pair",
    "parse_triple",
    "parse_float_token",
    "parse_int_token",
    "I64_MIN",
    "I64_MAX",
]

I64_MIN, I64_MAX = -(1 << 63), (1 << 63) - 1


def parse_float_token(tok: bytes) -> Optional[float]:
    """Full-token float with C-compatible grammar: PEP-515 underscores are
    rejected (the native core's from_chars never accepts them); overflow
    gives ±inf like strtod."""
    if b"_" in tok:
        return None
    try:
        return float(tok)
    except ValueError:
        return None


def parse_int_token(tok: bytes) -> Optional[int]:
    """Full-token base-10 int, C-compatible: no underscores, and values
    outside int64 are rejected (they cannot land in the CSR arrays; the
    native core's from_chars errors the same way)."""
    if b"_" in tok:
        return None
    try:
        v = int(tok)
    except ValueError:
        return None
    if not (I64_MIN <= v <= I64_MAX):
        return None
    return v


def parse_pair(token: bytes) -> Optional[Tuple[float, Optional[float]]]:
    """Parse ``a`` or ``a:b`` (reference ParsePair, strtonum.h:656-681).

    Returns (a, None) / (a, b), or None when the token is not numeric
    (the reference's r<1 'empty' result)."""
    c = token.find(b":")
    if c < 0:
        a = parse_float_token(token)
        return None if a is None else (a, None)
    a = parse_float_token(token[:c])
    b = parse_float_token(token[c + 1:])
    if a is None or b is None:
        return None
    return a, b


def parse_triple(
    token: bytes,
) -> Optional[Tuple[int, int, Optional[float]]]:
    """Parse ``a:b`` or ``a:b:c`` (reference ParseTriple, strtonum.h:697-737).

    Returns (a, b, None) / (a, b, c); None when fewer than two numbers parse
    (the reference's r<=1 skip)."""
    c1 = token.find(b":")
    if c1 < 0:
        return None
    c2 = token.find(b":", c1 + 1)
    a = parse_int_token(token[:c1])
    if a is None:
        return None
    if c2 < 0:
        b = parse_int_token(token[c1 + 1:])
        return None if b is None else (a, b, None)
    b = parse_int_token(token[c1 + 1: c2])
    v = parse_float_token(token[c2 + 1:])
    if b is None or v is None:
        return None
    return a, b, v
