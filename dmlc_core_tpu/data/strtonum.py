"""Token parsing helpers for text formats.

Reference: include/dmlc/strtonum.h — locale-independent ParseFloat/ParsePair
(:656-681) / ParseTriple (:697-737), the hot inner loop of all text parsers.

The TPU build's true hot loop lives in the native C++ core (native/); these
Python helpers define the exact semantics and serve as the fallback. Python's
float() is already locale-independent, matching the reference's motivation
for hand-rolled strtof.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["parse_pair", "parse_triple"]


def parse_pair(token: bytes) -> Optional[Tuple[float, Optional[float]]]:
    """Parse ``a`` or ``a:b`` (reference ParsePair, strtonum.h:656-681).

    Returns (a, None) / (a, b), or None when the token is not numeric
    (the reference's r<1 'empty' result)."""
    c = token.find(b":")
    try:
        if c < 0:
            return float(token), None
        return float(token[:c]), float(token[c + 1:])
    except ValueError:
        return None


def parse_triple(
    token: bytes,
) -> Optional[Tuple[int, int, Optional[float]]]:
    """Parse ``a:b`` or ``a:b:c`` (reference ParseTriple, strtonum.h:697-737).

    Returns (a, b, None) / (a, b, c); None when fewer than two numbers parse
    (the reference's r<=1 skip)."""
    c1 = token.find(b":")
    if c1 < 0:
        return None
    c2 = token.find(b":", c1 + 1)
    try:
        if c2 < 0:
            return int(token[:c1]), int(token[c1 + 1:]), None
        return (
            int(token[:c1]),
            int(token[c1 + 1: c2]),
            float(token[c2 + 1:]),
        )
    except ValueError:
        return None
