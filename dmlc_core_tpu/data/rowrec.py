"""rowrec: binary sparse-row payloads inside RecordIO containers.

The reference treats RecordIO payloads as opaque bytes (include/dmlc/
recordio.h:16-45) and parses *text* formats into RowBlocks; its Criteo-scale
path is therefore text parse bound. The TPU-first redesign stores rows
pre-parsed, so the .rec → HBM hot loop is a frame scan + memcpy instead of
a float parse — this is what lets RecordIO staging saturate infeed
(BASELINE.md north star #2).

Per-record payload wire format (little-endian, mirrors the field set of
reference data.h Row / row_block.h:189-215 Save):

    label   f32
    weight  f32
    nnz     u32
    indices u32[nnz]
    values  f32[nnz]

The RecordIO framing on top (magic/cflag multipart escape) is the
reference-compatible codec in io/recordio.py; float payload bytes CAN
collide with the magic word, so multipart chains genuinely occur and are
exercised by tests/test_rowrec.py.

Components:
- encode_rows / decode_record: the codec (numpy-vectorized encode).
- write_rowrec: RowBlock stream → .rec file via RecordIOWriter.
- RowRecParser: Parser producing RowBlocks from a sharded .rec URI
  (InputSplit type='recordio' → RecordIOChunkReader), registered as
  format 'rowrec' in data/__init__.py. The fused native path
  (staging/fused.py ell_batches) bypasses this and fills ELL buffers
  directly (native/fastparse.cc dmlc_parse_rowrec_ell).
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Optional, Tuple

import numpy as np

from ..io import split as io_split
from ..io.recordio import (
    KMAGIC,
    IndexedRecordIOWriter,
    RecordIOChunkReader,
    RecordIOWriter,
)
from ..io.stream import Stream
from ..utils.logging import check
from .parser import Parser
from .row_block import RowBlock

__all__ = [
    "encode_row",
    "encode_rows",
    "decode_record",
    "decode_records",
    "write_rowrec",
    "RowRecParser",
]

_HEAD = struct.Struct("<ffI")  # label, weight, nnz


def encode_row(
    label: float,
    indices: np.ndarray,
    values: Optional[np.ndarray] = None,
    weight: float = 1.0,
) -> bytes:
    """One sparse row → rowrec payload bytes."""
    idx = np.ascontiguousarray(indices, dtype="<u4")
    val = (
        np.ones(len(idx), dtype="<f4")
        if values is None
        else np.ascontiguousarray(values, dtype="<f4")
    )
    check(len(idx) == len(val), "indices/values length mismatch")
    return _HEAD.pack(label, weight, len(idx)) + idx.tobytes() + val.tobytes()


def encode_rows(block: RowBlock) -> List[bytes]:
    """RowBlock → list of per-row payloads (vectorized slicing)."""
    nnz = np.diff(block.offset)
    idx = block.index.astype("<u4", copy=False)
    val = (
        np.ones(block.nnz, dtype="<f4")
        if block.value is None
        else block.value.astype("<f4", copy=False)
    )
    weights = (
        np.ones(block.size, dtype=np.float32)
        if block.weight is None
        else block.weight
    )
    out: List[bytes] = []
    for i in range(block.size):
        b, e = int(block.offset[i]), int(block.offset[i + 1])
        out.append(
            _HEAD.pack(float(block.label[i]), float(weights[i]), int(nnz[i]))
            + idx[b:e].tobytes()
            + val[b:e].tobytes()
        )
    return out


def decode_record(payload) -> tuple:
    """One payload → (label, weight, indices u32, values f32)."""
    mv = memoryview(payload)
    check(len(mv) >= 12, "rowrec payload shorter than its header")
    label, weight, n = _HEAD.unpack_from(mv, 0)
    check(len(mv) >= 12 + 8 * n, "rowrec payload shorter than declared nnz")
    idx = np.frombuffer(mv, dtype="<u4", count=n, offset=12)
    val = np.frombuffer(mv, dtype="<f4", count=n, offset=12 + 4 * n)
    return label, weight, idx, val


def decode_records(records: Iterable) -> RowBlock:
    """Record payloads → one RowBlock (the generic/fallback decode path)."""
    labels: List[float] = []
    weights: List[float] = []
    offsets: List[int] = [0]
    idx_parts: List[np.ndarray] = []
    val_parts: List[np.ndarray] = []
    total = 0
    for rec in records:
        label, weight, idx, val = decode_record(rec)
        labels.append(label)
        weights.append(weight)
        total += len(idx)
        offsets.append(total)
        idx_parts.append(idx)
        val_parts.append(val)
    index = (
        np.concatenate(idx_parts).astype(np.uint32, copy=False)
        if idx_parts
        else np.empty(0, dtype=np.uint32)
    )
    value = (
        np.concatenate(val_parts).astype(np.float32, copy=False)
        if val_parts
        else np.empty(0, dtype=np.float32)
    )
    return RowBlock(
        offset=np.asarray(offsets, dtype=np.int64),
        label=np.asarray(labels, dtype=np.float32),
        index=index,
        value=value,
        weight=np.asarray(weights, dtype=np.float32),
    )


def encode_block_frames(
    block: RowBlock,
) -> Optional[Tuple[bytes, np.ndarray]]:
    """Vectorized whole-block RecordIO framing: every row of the block →
    a single-part (cflag 0) frame, assembled with numpy scatters instead
    of per-row Python. Returns (framed bytes, per-record frame-start
    byte offsets), or None when any aligned payload word collides with
    the RecordIO magic — those rows need the writer's multipart escape,
    so the caller falls back to the exact per-row path. Output is
    byte-identical to RecordIOWriter over encode_rows (asserted in
    tests/test_rowrec.py)."""
    n = block.size
    if n == 0:
        return b"", np.empty(0, dtype=np.int64)
    nnz = np.diff(block.offset).astype(np.int64)
    p_words = 3 + 2 * nnz           # payload: label, weight, nnz, idx, val
    if int(p_words.max()) * 4 >= 1 << 29:
        return None  # > 2^29-byte record: let the writer's check diagnose
    # collision pre-check on the source words (label/weight/index/value
    # are the only payload words that can equal the magic: lrec carries
    # cflag bits and nnz is size-bounded) — colliding blocks skip the
    # build entirely and take the writer's multipart escape
    labels = np.ascontiguousarray(block.label, dtype="<f4")
    weights = (
        np.ones(n, dtype="<f4")
        if block.weight is None
        else np.ascontiguousarray(block.weight, dtype="<f4")
    )
    idx = np.ascontiguousarray(block.index, dtype="<u4")
    total = int(block.offset[-1])
    val = (
        np.ones(total, dtype="<f4")
        if block.value is None
        else np.ascontiguousarray(block.value, dtype="<f4")
    )
    if (
        bool((labels.view("<u4") == KMAGIC).any())
        or bool((weights.view("<u4") == KMAGIC).any())
        or bool((idx == KMAGIC).any())
        or bool((val.view("<u4") == KMAGIC).any())
    ):
        return None
    f_words = 2 + p_words           # + magic, lrec
    fstart = np.zeros(n, dtype=np.int64)
    np.cumsum(f_words[:-1], out=fstart[1:])
    out = np.zeros(int(fstart[-1] + f_words[-1]), dtype="<u4")
    out[fstart] = KMAGIC
    out[fstart + 1] = (p_words * 4).astype("<u4")  # lrec: cflag 0 | len
    out[fstart + 2] = labels.view("<u4")
    out[fstart + 3] = weights.view("<u4")
    out[fstart + 4] = nnz.astype("<u4")
    if total:
        within = np.arange(total, dtype=np.int64) - np.repeat(
            block.offset[:-1], nnz
        )
        idx_at = np.repeat(fstart + 5, nnz) + within
        out[idx_at] = idx
        out[idx_at + np.repeat(nnz, nnz)] = val.view("<u4")
    return out.tobytes(), fstart * 4


def write_rowrec(
    stream: Stream,
    blocks: Iterable[RowBlock],
    index_stream: Optional[Stream] = None,
    codec=None,
    level: Optional[int] = None,
) -> int:
    """Write RowBlocks as rowrec RecordIO frames; returns rows written.

    With ``index_stream``, also emits the ``key offset`` index that an
    IndexedRecordIOSplitter shards by record count (enabling
    ``uri?index=<index_uri>&shuffle=1`` reads). Collision-free blocks
    take the vectorized whole-block framer (~20x the per-row path);
    blocks containing the aligned magic word fall back row-by-row for
    the multipart escape. With a ``codec`` (io/codec.py name, e.g.
    'zlib'), rows are buffered into compressed blocks and the index
    carries block:in-offset pairs (docs/recordio.md); the vectorized
    framer output feeds the block buffer unchanged."""
    writer = (
        RecordIOWriter(stream, codec=codec, level=level)
        if index_stream is None
        else IndexedRecordIOWriter(
            stream, index_stream, codec=codec, level=level
        )
    )
    n = 0
    for blk in blocks:
        fast = encode_block_frames(blk)
        if fast is None:
            for payload in encode_rows(blk):
                writer.write_record(payload)
                n += 1
            continue
        writer.write_framed_block(*fast)
        n += blk.size
    writer.flush_block()
    return n


class RowRecParser(Parser):
    """Sharded .rec → RowBlock parser (format='rowrec').

    Pulls whole-record chunks from an InputSplit (type='recordio', so
    byte-range sharding snaps to record heads — reference
    src/io/recordio_split.cc), then decodes each chunk's records into one
    RowBlock. Decode is cheap (memcpy-shaped) relative to text parse, so no
    per-chunk thread fan-out is needed; ThreadedParser provides parse-ahead.
    """

    def __init__(
        self,
        source: Optional[io_split.InputSplit] = None,
        args: Optional[dict] = None,
        nthread: Optional[int] = None,
        index_dtype=np.uint32,
        uri: Optional[str] = None,
        part_index: int = 0,
        num_parts: int = 1,
    ) -> None:
        if source is None:
            check(uri is not None, "RowRecParser needs a source or a uri")
            # URI sugar (?shuffle_parts=N&seed=S etc.) is honored inside
            # io_split.create, so a full URI is all that's needed here
            source = io_split.create(uri, part_index, num_parts, type="recordio")
        self._source = source
        self._bytes = 0
        self._index_dtype = index_dtype

    def parse_next(self) -> Optional[List[RowBlock]]:
        chunk = self._source.next_chunk()
        if chunk is None:
            return None
        self._bytes += len(chunk)
        blk = decode_records(RecordIOChunkReader(chunk, 0, 1))
        if blk.index.dtype != self._index_dtype:
            blk.index = blk.index.astype(self._index_dtype)
        return [blk]

    def before_first(self) -> None:
        self._source.before_first()
        self._bytes = 0

    def bytes_read(self) -> int:
        return self._bytes

    def close(self) -> None:
        self._source.close()
