"""rowrec: binary sparse-row payloads inside RecordIO containers.

The reference treats RecordIO payloads as opaque bytes (include/dmlc/
recordio.h:16-45) and parses *text* formats into RowBlocks; its Criteo-scale
path is therefore text parse bound. The TPU-first redesign stores rows
pre-parsed, so the .rec → HBM hot loop is a frame scan + memcpy instead of
a float parse — this is what lets RecordIO staging saturate infeed
(BASELINE.md north star #2).

Per-record payload wire format (little-endian, mirrors the field set of
reference data.h Row / row_block.h:189-215 Save):

    label   f32
    weight  f32
    nnz     u32
    indices u32[nnz]
    values  f32[nnz]

The RecordIO framing on top (magic/cflag multipart escape) is the
reference-compatible codec in io/recordio.py; float payload bytes CAN
collide with the magic word, so multipart chains genuinely occur and are
exercised by tests/test_rowrec.py.

Components:
- encode_rows / decode_record: the codec (numpy-vectorized encode).
- write_rowrec: RowBlock stream → .rec file via RecordIOWriter.
- RowRecParser: Parser producing RowBlocks from a sharded .rec URI
  (InputSplit type='recordio' → RecordIOChunkReader), registered as
  format 'rowrec' in data/__init__.py. The fused native path
  (staging/fused.py ell_batches) bypasses this and fills ELL buffers
  directly (native/fastparse.cc dmlc_parse_rowrec_ell).
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Optional

import numpy as np

from ..io import split as io_split
from ..io.recordio import (
    IndexedRecordIOWriter,
    RecordIOChunkReader,
    RecordIOWriter,
)
from ..io.stream import Stream
from ..utils.logging import check
from .parser import Parser
from .row_block import RowBlock

__all__ = [
    "encode_row",
    "encode_rows",
    "decode_record",
    "decode_records",
    "write_rowrec",
    "RowRecParser",
]

_HEAD = struct.Struct("<ffI")  # label, weight, nnz


def encode_row(
    label: float,
    indices: np.ndarray,
    values: Optional[np.ndarray] = None,
    weight: float = 1.0,
) -> bytes:
    """One sparse row → rowrec payload bytes."""
    idx = np.ascontiguousarray(indices, dtype="<u4")
    val = (
        np.ones(len(idx), dtype="<f4")
        if values is None
        else np.ascontiguousarray(values, dtype="<f4")
    )
    check(len(idx) == len(val), "indices/values length mismatch")
    return _HEAD.pack(label, weight, len(idx)) + idx.tobytes() + val.tobytes()


def encode_rows(block: RowBlock) -> List[bytes]:
    """RowBlock → list of per-row payloads (vectorized slicing)."""
    nnz = np.diff(block.offset)
    idx = block.index.astype("<u4", copy=False)
    val = (
        np.ones(block.nnz, dtype="<f4")
        if block.value is None
        else block.value.astype("<f4", copy=False)
    )
    weights = (
        np.ones(block.size, dtype=np.float32)
        if block.weight is None
        else block.weight
    )
    out: List[bytes] = []
    for i in range(block.size):
        b, e = int(block.offset[i]), int(block.offset[i + 1])
        out.append(
            _HEAD.pack(float(block.label[i]), float(weights[i]), int(nnz[i]))
            + idx[b:e].tobytes()
            + val[b:e].tobytes()
        )
    return out


def decode_record(payload) -> tuple:
    """One payload → (label, weight, indices u32, values f32)."""
    mv = memoryview(payload)
    check(len(mv) >= 12, "rowrec payload shorter than its header")
    label, weight, n = _HEAD.unpack_from(mv, 0)
    check(len(mv) >= 12 + 8 * n, "rowrec payload shorter than declared nnz")
    idx = np.frombuffer(mv, dtype="<u4", count=n, offset=12)
    val = np.frombuffer(mv, dtype="<f4", count=n, offset=12 + 4 * n)
    return label, weight, idx, val


def decode_records(records: Iterable) -> RowBlock:
    """Record payloads → one RowBlock (the generic/fallback decode path)."""
    labels: List[float] = []
    weights: List[float] = []
    offsets: List[int] = [0]
    idx_parts: List[np.ndarray] = []
    val_parts: List[np.ndarray] = []
    total = 0
    for rec in records:
        label, weight, idx, val = decode_record(rec)
        labels.append(label)
        weights.append(weight)
        total += len(idx)
        offsets.append(total)
        idx_parts.append(idx)
        val_parts.append(val)
    index = (
        np.concatenate(idx_parts).astype(np.uint32, copy=False)
        if idx_parts
        else np.empty(0, dtype=np.uint32)
    )
    value = (
        np.concatenate(val_parts).astype(np.float32, copy=False)
        if val_parts
        else np.empty(0, dtype=np.float32)
    )
    return RowBlock(
        offset=np.asarray(offsets, dtype=np.int64),
        label=np.asarray(labels, dtype=np.float32),
        index=index,
        value=value,
        weight=np.asarray(weights, dtype=np.float32),
    )


def write_rowrec(
    stream: Stream,
    blocks: Iterable[RowBlock],
    index_stream: Optional[Stream] = None,
) -> int:
    """Write RowBlocks as rowrec RecordIO frames; returns rows written.

    With ``index_stream``, also emits the ``key offset`` index that an
    IndexedRecordIOSplitter shards by record count (enabling
    ``uri?index=<index_uri>&shuffle=1`` reads)."""
    writer = (
        RecordIOWriter(stream)
        if index_stream is None
        else IndexedRecordIOWriter(stream, index_stream)
    )
    n = 0
    for blk in blocks:
        for payload in encode_rows(blk):
            writer.write_record(payload)
            n += 1
    return n


class RowRecParser(Parser):
    """Sharded .rec → RowBlock parser (format='rowrec').

    Pulls whole-record chunks from an InputSplit (type='recordio', so
    byte-range sharding snaps to record heads — reference
    src/io/recordio_split.cc), then decodes each chunk's records into one
    RowBlock. Decode is cheap (memcpy-shaped) relative to text parse, so no
    per-chunk thread fan-out is needed; ThreadedParser provides parse-ahead.
    """

    def __init__(
        self,
        source: Optional[io_split.InputSplit] = None,
        args: Optional[dict] = None,
        nthread: Optional[int] = None,
        index_dtype=np.uint32,
        uri: Optional[str] = None,
        part_index: int = 0,
        num_parts: int = 1,
    ) -> None:
        if source is None:
            check(uri is not None, "RowRecParser needs a source or a uri")
            # URI sugar (?shuffle_parts=N&seed=S etc.) is honored inside
            # io_split.create, so a full URI is all that's needed here
            source = io_split.create(uri, part_index, num_parts, type="recordio")
        self._source = source
        self._bytes = 0
        self._index_dtype = index_dtype

    def parse_next(self) -> Optional[List[RowBlock]]:
        chunk = self._source.next_chunk()
        if chunk is None:
            return None
        self._bytes += len(chunk)
        blk = decode_records(RecordIOChunkReader(chunk, 0, 1))
        if blk.index.dtype != self._index_dtype:
            blk.index = blk.index.astype(self._index_dtype)
        return [blk]

    def before_first(self) -> None:
        self._source.before_first()
        self._bytes = 0

    def bytes_read(self) -> int:
        return self._bytes

    def close(self) -> None:
        self._source.close()
