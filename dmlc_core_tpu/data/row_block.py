"""Sparse row-major data blocks as contiguous numpy CSR arrays.

Reference: include/dmlc/data.h (Row :74-162, RowBlock :175-236,364-394) and
src/data/row_block.h (RowBlockContainer).

TPU-native rethink: the reference stores C++ pointer-based CSR views; here a
RowBlock *is* the set of contiguous numpy arrays that the staging layer
(staging/batcher.py) reshapes into fixed-shape device batches — no per-row
objects on the hot path. ``Row`` is a cheap accessor view used by tests and
small consumers, mirroring ``RowBlock::operator[]`` (data.h:364-382).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..io import serializer
from ..io.stream import Stream
from ..utils.logging import check, check_eq

__all__ = ["Row", "RowBlock", "RowBlockContainer", "REAL_T", "INDEX_T"]

# reference data.h:26-32: real_t = float, index_t = unsigned
REAL_T = np.float32
INDEX_T = np.uint64


class Row:
    """One sparse instance: a zero-copy view into a RowBlock
    (reference data.h:74-162)."""

    __slots__ = ("label", "weight", "qid", "field", "index", "value")

    def __init__(self, label, weight, qid, field, index, value) -> None:
        self.label = label
        self.weight = weight
        self.qid = qid
        self.field = field
        self.index = index
        self.value = value

    def __len__(self) -> int:
        return len(self.index)

    def get_value(self, i: int):
        """value[i], or 1 when values are absent (reference data.h:120-127)."""
        return REAL_T(1.0) if self.value is None else self.value[i]

    def sdot(self, weight: np.ndarray) -> float:
        """Sparse dot with a dense weight vector (reference SDot,
        data.h:137-152) — vectorized gather instead of the scalar loop."""
        idx = np.asarray(self.index, dtype=np.int64)
        if self.value is None:
            return float(weight[idx].sum())
        return float(weight[idx] @ self.value)

    def __repr__(self) -> str:
        return f"Row(label={self.label}, nnz={len(self)})"


class RowBlock:
    """A batch of sparse rows in CSR layout (reference data.h:175-236).

    Arrays (all numpy, contiguous):
      offset : int64[size+1]   — CSR row offsets
      label  : float32[size]
      weight : float32[size] | None  (None = all 1.0)
      qid    : int64[size]   | None
      field  : int64[nnz]    | None
      index  : uint32/uint64[nnz]
      value  : real[nnz]     | None  (None = all 1.0, binary features)
    """

    __slots__ = ("offset", "label", "weight", "qid", "field", "index", "value")

    def __init__(
        self,
        offset: np.ndarray,
        label: np.ndarray,
        index: np.ndarray,
        value: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        qid: Optional[np.ndarray] = None,
        field: Optional[np.ndarray] = None,
    ) -> None:
        self.offset = np.ascontiguousarray(offset, dtype=np.int64)
        self.label = np.ascontiguousarray(label)
        self.index = np.ascontiguousarray(index)
        self.value = None if value is None else np.ascontiguousarray(value)
        self.weight = None if weight is None else np.ascontiguousarray(weight)
        self.qid = None if qid is None else np.ascontiguousarray(qid)
        self.field = None if field is None else np.ascontiguousarray(field)
        check_eq(int(self.offset[0]), 0, "offset must start at 0")
        check_eq(len(self.label), self.size, "label size mismatch")
        check_eq(int(self.offset[-1]), len(self.index), "offset/index mismatch")
        if self.value is not None:
            check_eq(len(self.value), self.nnz, "value size mismatch")
        if self.field is not None:
            check_eq(len(self.field), self.nnz, "field size mismatch")
        if self.weight is not None:
            check_eq(len(self.weight), self.size, "weight size mismatch")
        if self.qid is not None:
            check_eq(len(self.qid), self.size, "qid size mismatch")

    @property
    def size(self) -> int:
        return len(self.offset) - 1

    def __len__(self) -> int:
        return self.size

    @property
    def nnz(self) -> int:
        return len(self.index)

    def get_weight(self, i: int):
        return REAL_T(1.0) if self.weight is None else self.weight[i]

    def __getitem__(self, i: int) -> Row:
        """Row view (reference data.h:364-382)."""
        check(0 <= i < self.size, f"row index {i} out of range")
        lo, hi = int(self.offset[i]), int(self.offset[i + 1])
        return Row(
            label=self.label[i],
            weight=self.get_weight(i),
            qid=None if self.qid is None else self.qid[i],
            field=None if self.field is None else self.field[lo:hi],
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
        )

    def __iter__(self) -> Iterator[Row]:
        for i in range(self.size):
            yield self[i]

    def slice(self, begin: int, end: int) -> "RowBlock":
        """Zero-copy sub-block (reference Slice, data.h:384-394).

        Offsets are rebased so the slice is self-contained."""
        check(0 <= begin <= end <= self.size, "invalid slice range")
        lo, hi = int(self.offset[begin]), int(self.offset[end])
        return RowBlock(
            offset=self.offset[begin : end + 1] - lo,
            label=self.label[begin:end],
            weight=None if self.weight is None else self.weight[begin:end],
            qid=None if self.qid is None else self.qid[begin:end],
            field=None if self.field is None else self.field[lo:hi],
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
        )

    def mem_cost_bytes(self) -> int:
        """Approximate memory cost (reference MemCostBytes, data.h:203-214)."""
        cost = self.offset.nbytes + self.label.nbytes
        for a in (self.weight, self.qid, self.field, self.value):
            if a is not None:
                cost += a.nbytes
        cost += self.index.nbytes
        return cost

    def max_index(self) -> int:
        return int(self.index.max()) if len(self.index) else 0

    # -- serialization (backs DiskRowIter page cache) ------------------------
    def save(self, stream: Stream) -> None:
        """Binary page format: presence mask + dtype-tagged arrays
        (reference RowBlockContainer::Save, src/data/row_block.h:189-200)."""
        mask = (
            (1 if self.weight is not None else 0)
            | (2 if self.qid is not None else 0)
            | (4 if self.field is not None else 0)
            | (8 if self.value is not None else 0)
        )
        serializer.write_scalar(stream, mask, "uint32")
        serializer.write_ndarray(stream, self.offset)
        serializer.write_ndarray(stream, self.label)
        serializer.write_ndarray(stream, self.index)
        if self.weight is not None:
            serializer.write_ndarray(stream, self.weight)
        if self.qid is not None:
            serializer.write_ndarray(stream, self.qid)
        if self.field is not None:
            serializer.write_ndarray(stream, self.field)
        if self.value is not None:
            serializer.write_ndarray(stream, self.value)

    @staticmethod
    def load(stream: Stream) -> Optional["RowBlock"]:
        """Inverse of save; None at clean end-of-stream (reference
        RowBlockContainer::Load, src/data/row_block.h:202-215)."""
        mask = serializer.try_read_scalar(stream, "uint32")
        if mask is None:
            return None
        offset = serializer.read_ndarray(stream)
        label = serializer.read_ndarray(stream)
        index = serializer.read_ndarray(stream)
        weight = serializer.read_ndarray(stream) if mask & 1 else None
        qid = serializer.read_ndarray(stream) if mask & 2 else None
        field = serializer.read_ndarray(stream) if mask & 4 else None
        value = serializer.read_ndarray(stream) if mask & 8 else None
        return RowBlock(
            offset=offset, label=label, index=index,
            value=value, weight=weight, qid=qid, field=field,
        )

    @staticmethod
    def concat(blocks: Sequence["RowBlock"]) -> "RowBlock":
        """Concatenate blocks into one (used by batcher + Push(RowBlock))."""
        check(len(blocks) > 0, "cannot concat zero blocks")
        if len(blocks) == 1:
            return blocks[0]
        offsets = [blocks[0].offset]
        base = int(blocks[0].offset[-1])
        for b in blocks[1:]:
            offsets.append(b.offset[1:] + base)
            base += int(b.offset[-1])

        def cat(name: str, fill_missing=None):
            parts = [getattr(b, name) for b in blocks]
            if all(p is None for p in parts):
                return None
            if any(p is None for p in parts):
                # mixed presence: materialize default for the missing ones
                out = []
                for b, p in zip(blocks, parts):
                    if p is not None:
                        out.append(p)
                    else:
                        n = b.nnz if name in ("field", "value") else b.size
                        out.append(np.full(n, fill_missing))
                parts = out
            return np.concatenate(parts)

        return RowBlock(
            offset=np.concatenate(offsets),
            label=np.concatenate([b.label for b in blocks]),
            index=np.concatenate([b.index for b in blocks]),
            value=cat("value", REAL_T(1.0)),
            weight=cat("weight", REAL_T(1.0)),
            qid=cat("qid", np.int64(0)),
            field=cat("field", np.int64(0)),
        )


class RowBlockContainer:
    """Growable RowBlock builder (reference src/data/row_block.h:28-218).

    Append-only Python lists of numpy chunks; ``to_block`` concatenates once.
    Unlike the reference's element-wise ``Push(Row)``, bulk pushes are the
    norm — parsers emit whole numpy arrays per slice.
    """

    def __init__(self, index_dtype=INDEX_T) -> None:
        self.index_dtype = index_dtype
        self.clear()

    def clear(self) -> None:
        self._blocks: List[RowBlock] = []
        self._rows: List[Tuple] = []
        self.max_index = 0
        self.max_field = 0

    @property
    def size(self) -> int:
        n = sum(b.size for b in self._blocks) + len(self._rows)
        return n

    def mem_cost_bytes(self) -> int:
        return sum(b.mem_cost_bytes() for b in self._blocks) + sum(
            48 + len(r[4]) * 12 for r in self._rows
        )

    def push_row(
        self,
        label: float,
        index: Sequence[int],
        value: Optional[Sequence[float]] = None,
        weight: float = 1.0,
        qid: int = 0,
        field: Optional[Sequence[int]] = None,
    ) -> None:
        """Push one row (reference Push(Row), row_block.h:89-120)."""
        idx = np.asarray(index, dtype=self.index_dtype)
        if value is not None:
            check_eq(len(value), len(idx), "push_row: value/index length mismatch")
        if field is not None:
            check_eq(len(field), len(idx), "push_row: field/index length mismatch")
        if len(idx):
            self.max_index = max(self.max_index, int(idx.max()))
        if field is not None and len(field):
            self.max_field = max(self.max_field, int(max(field)))
        self._rows.append((label, weight, qid, field, idx, value))

    def push_block(self, block: RowBlock) -> None:
        """Push a whole block (reference Push(RowBlock), row_block.h:122-166)."""
        self._flush_rows()
        self._blocks.append(block)
        if block.nnz:
            self.max_index = max(self.max_index, block.max_index())
        if block.field is not None and len(block.field):
            self.max_field = max(self.max_field, int(block.field.max()))

    def _flush_rows(self) -> None:
        if not self._rows:
            return
        rows = self._rows
        self._rows = []
        sizes = [len(r[4]) for r in rows]
        offset = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum(sizes, out=offset[1:])
        label = np.array([r[0] for r in rows], dtype=REAL_T)
        weight = np.array([r[1] for r in rows], dtype=REAL_T)
        qid = np.array([r[2] for r in rows], dtype=np.int64)
        index = (
            np.concatenate([r[4] for r in rows])
            if rows
            else np.empty(0, dtype=self.index_dtype)
        ).astype(self.index_dtype, copy=False)
        has_value = any(r[5] is not None for r in rows)
        value = (
            np.concatenate(
                [
                    np.asarray(
                        r[5] if r[5] is not None else np.ones(len(r[4]), dtype=REAL_T),
                        dtype=REAL_T,
                    )
                    for r in rows
                ]
            )
            if has_value
            else None
        )
        has_field = any(r[3] is not None for r in rows)
        field = (
            np.concatenate(
                [
                    np.asarray(
                        r[3] if r[3] is not None else np.zeros(len(r[4]), np.int64),
                        dtype=np.int64,
                    )
                    for r in rows
                ]
            )
            if has_field
            else None
        )
        # drop all-default weight/qid so the block stays lean
        if np.all(weight == 1.0):
            weight = None
        if np.all(qid == 0):
            qid = None
        self._blocks.append(
            RowBlock(
                offset=offset, label=label, index=index,
                value=value, weight=weight, qid=qid, field=field,
            )
        )

    def to_block(self) -> RowBlock:
        """Materialize the full CSR block (reference GetBlock,
        row_block.h:169-188)."""
        self._flush_rows()
        if not self._blocks:
            return RowBlock(
                offset=np.zeros(1, dtype=np.int64),
                label=np.empty(0, dtype=REAL_T),
                index=np.empty(0, dtype=self.index_dtype),
            )
        return RowBlock.concat(self._blocks)

    def save(self, stream: Stream) -> None:
        self.to_block().save(stream)

    def load(self, stream: Stream) -> bool:
        blk = RowBlock.load(stream)
        if blk is None:
            return False
        self.clear()
        self.push_block(blk)
        return True
