"""LibFM text format parser.

Reference: src/data/libfm_parser.h. Line grammar::

    label[:weight] field:index[:value] field:index[:value] ...

Tokens with fewer than two numbers are skipped (reference ParseTriple r<=1,
libfm_parser.h:109-113). ``indexing_mode`` as in libsvm, but auto-detect
requires BOTH all field ids and all feature ids > 0
(libfm_parser.h:132-144).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..io.split import InputSplit
from ..params.parameter import Parameter, field
from ..utils.logging import check_eq
from . import native
from .row_block import INDEX_T, REAL_T, RowBlock
from .strtonum import parse_pair, parse_triple
from .text_parser import TextParserBase

__all__ = ["LibFMParser", "LibFMParserParam"]


class LibFMParserParam(Parameter):
    """Reference LibFMParserParam (libfm_parser.h:24-39)."""

    format = field(str, default="libfm", help="File format")
    indexing_mode = field(
        int,
        default=0,
        help=(
            "If >0, treat all field and feature indices as 1-based. "
            "If =0, 0-based. If <0, auto-detect."
        ),
    )


class LibFMParser(TextParserBase):
    def __init__(
        self,
        source: InputSplit,
        args: Optional[dict] = None,
        nthread: Optional[int] = None,
        index_dtype=INDEX_T,
    ) -> None:
        super().__init__(source, nthread)
        self.param = LibFMParserParam()
        self.param.init(args or {}, allow_unknown=True)
        check_eq(self.param.format, "libfm", "format mismatch")
        self.index_dtype = index_dtype

    def parse_block(self, data: bytes) -> RowBlock:
        if native.AVAILABLE:
            arrays = native.parse_libfm(data, self.param.indexing_mode)
            if arrays is not None:
                offset, label, weight, fields, index, value = arrays
                return RowBlock(
                    offset=offset,
                    label=label,
                    index=index.astype(self.index_dtype, copy=False),
                    value=value,
                    weight=weight,
                    field=fields,
                )
        return self._parse_block_py(data)

    def _parse_block_py(self, data: bytes) -> RowBlock:
        labels = []
        weights = []
        fields = []
        index = []
        values = []
        offset = [0]
        any_value = False
        for line in data.splitlines():
            toks = line.split()
            if not toks:
                continue
            lw = parse_pair(toks[0])
            if lw is None:
                continue
            label, weight = lw
            for t in toks[1:]:
                triple = parse_triple(t)
                if triple is None:
                    continue
                fid, feat, val = triple
                fields.append(fid)
                index.append(feat)
                values.append(val)
                if val is not None:
                    any_value = True
            labels.append(label)
            weights.append(weight)
            offset.append(len(index))
        field_arr = np.asarray(fields, dtype=np.int64)
        idx_arr = np.asarray(index, dtype=np.int64)
        mode = self.param.indexing_mode
        if mode > 0 or (
            mode < 0
            and len(idx_arr)
            and idx_arr.min() > 0
            and len(field_arr)
            and field_arr.min() > 0
        ):
            idx_arr = idx_arr - 1
            field_arr = field_arr - 1
        has_weight = any(w is not None for w in weights)
        return RowBlock(
            offset=np.asarray(offset, dtype=np.int64),
            label=np.asarray(labels, dtype=REAL_T),
            index=idx_arr.astype(self.index_dtype, copy=False),
            value=(
                np.asarray(
                    [1.0 if v is None else v for v in values], dtype=REAL_T
                )
                if any_value
                else None
            ),
            weight=(
                np.asarray(
                    [1.0 if w is None else w for w in weights], dtype=REAL_T
                )
                if has_weight
                else None
            ),
            field=field_arr,
        )
