"""Data layer: sparse RowBlocks, multi-threaded text parsers, row iterators.

Reference: include/dmlc/data.h + src/data/ + src/data.cc (factory wiring).
TPU-first design notes in row_block.py; the staging layer (staging/) turns
these ragged blocks into fixed-shape device batches.
"""

from __future__ import annotations

from typing import Optional

from ..io import split as io_split
from ..io.uri import URISpec, rejoin_query, uri_int
from ..utils.logging import Error
from .csv_parser import CSVParser, CSVParserParam
from .libfm_parser import LibFMParser, LibFMParserParam
from .libsvm_parser import LibSVMParser, LibSVMParserParam
from .parser import PARSER_REGISTRY, Parser, ThreadedParser
from .row_block import INDEX_T, REAL_T, Row, RowBlock, RowBlockContainer
from .row_iter import BasicRowIter, DiskRowIter, RowBlockIter
from .rowrec import RowRecParser, write_rowrec
from .text_parser import TextParserBase

__all__ = [
    "Row",
    "RowBlock",
    "RowBlockContainer",
    "Parser",
    "ThreadedParser",
    "TextParserBase",
    "LibSVMParser",
    "CSVParser",
    "LibFMParser",
    "LibSVMParserParam",
    "CSVParserParam",
    "LibFMParserParam",
    "RowRecParser",
    "write_rowrec",
    "RowBlockIter",
    "BasicRowIter",
    "DiskRowIter",
    "create_parser",
    "create_row_block_iter",
    "PARSER_REGISTRY",
    "REAL_T",
    "INDEX_T",
]


# -- parser registry (reference data.cc:223-256) -----------------------------
def _make_text_source(uri: str, part_index: int, num_parts: int):
    return io_split.create(uri, part_index, num_parts, type="text")


@PARSER_REGISTRY.register("libsvm")
def _create_libsvm(uri, args, part_index, num_parts, nthread=None, index_dtype=INDEX_T):
    return LibSVMParser(
        _make_text_source(uri, part_index, num_parts), args, nthread, index_dtype
    )


@PARSER_REGISTRY.register("csv")
def _create_csv(uri, args, part_index, num_parts, nthread=None, index_dtype=INDEX_T):
    return CSVParser(
        _make_text_source(uri, part_index, num_parts), args, nthread, index_dtype
    )


@PARSER_REGISTRY.register("libfm")
def _create_libfm(uri, args, part_index, num_parts, nthread=None, index_dtype=INDEX_T):
    return LibFMParser(
        _make_text_source(uri, part_index, num_parts), args, nthread, index_dtype
    )


@PARSER_REGISTRY.register("rowrec")
def _create_rowrec(uri, args, part_index, num_parts, nthread=None, index_dtype=INDEX_T):
    # re-attach the query args so io_split.create resolves ALL the URI
    # sugar itself (?shuffle_parts=N&seed=S macro-shuffle,
    # ?index=<uri>&shuffle=1 count-indexed reads) — one resolver, no drift
    return RowRecParser(
        io_split.create(
            uri + rejoin_query(args), part_index, num_parts, type="recordio"
        ),
        args,
        nthread,
        index_dtype,
    )


def create_parser(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type: str = "auto",
    nthread: Optional[int] = None,
    index_dtype=INDEX_T,
    threaded: bool = True,
) -> Parser:
    """Parser factory (reference CreateParser_, src/data.cc:62-85).

    'auto' resolves ``?format=`` from the URI, defaulting to libsvm.
    The parser is wrapped in a parse-ahead thread (reference data.cc:30-32)
    unless ``threaded=False``.
    """
    spec = URISpec(uri, part_index, num_parts)
    ptype = type
    if ptype == "auto":
        ptype = spec.args.get("format", "libsvm")
    entry = PARSER_REGISTRY.find(ptype)
    if entry is None:
        raise Error(f"Unknown data type {ptype!r}")
    # re-attach query args (parser params ride the URI, reference uri_spec.h)
    base = entry(
        spec.uri, spec.args, part_index, num_parts, nthread, index_dtype
    )
    return ThreadedParser(base) if threaded else base


def create_row_block_iter(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type: str = "auto",
    nthread: Optional[int] = None,
    index_dtype=INDEX_T,
) -> RowBlockIter:
    """RowBlockIter factory (reference CreateIter_, src/data.cc:87-107):
    ``uri#cachefile`` → DiskRowIter, else eager BasicRowIter."""
    spec = URISpec(uri, part_index, num_parts)

    def make_parser() -> Parser:
        return create_parser(
            spec.uri + rejoin_query(spec.args),
            part_index,
            num_parts,
            type,
            nthread,
            index_dtype,
        )

    if spec.cache_file:
        # a warm cache never touches the raw data source — which is also
        # why epoch shuffling cannot ride it: the first epoch's order
        # would be frozen into the cache (same guard as io_split.create).
        # normalize_shuffle understands every spelling of the option
        # (0/1/record/batch/window) — uri_int here would crash on the
        # string modes instead of explaining the real conflict
        if uri_int(spec.args, "shuffle_parts", 0) or (
            "index" in spec.args
            and io_split.normalize_shuffle(spec.args.get("shuffle", "0"))
        ):
            raise Error(
                "epoch shuffling with a #cachefile would freeze the first "
                "epoch's shuffle order into the cache; pick one"
            )
        return DiskRowIter(make_parser, spec.cache_file, reuse_cache=True)
    return BasicRowIter(make_parser())
