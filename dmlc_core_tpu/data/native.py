"""ctypes bridge to the native C++ parse core (native/libdmlc_tpu_native.so).

The native library implements the hot loops — libsvm/csv/libfm chunk parsing
into CSR arrays — releasing the GIL so TextParserBase's thread fan-out gets
real parallelism (the reference gets this from std::thread,
src/data/text_parser.h:110-146). Every entry point has a pure-Python
fallback in the corresponding parser module; if the library is missing or
fails to load, AVAILABLE stays False and nothing breaks.

Calling convention: the caller passes the chunk buffer; the library parses
into library-owned growable buffers and returns sizes; the bridge copies
into fresh numpy arrays and frees the native buffers. One copy per ~8MB
chunk is noise next to parse cost, and fresh arrays keep ownership simple.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["AVAILABLE", "parse_libsvm", "parse_csv", "parse_libfm", "load"]

AVAILABLE = False
_LIB = None
_LOCK = threading.Lock()

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CANDIDATES = (
    os.path.join(_REPO_ROOT, "native", "libdmlc_tpu_native.so"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "libdmlc_tpu_native.so"),
)


class _ParseResult(ctypes.Structure):
    """Mirrors native/fastparse.cc struct ParseResult."""

    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("n_elems", ctypes.c_int64),
        ("offset", ctypes.POINTER(ctypes.c_int64)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("qid", ctypes.POINTER(ctypes.c_int64)),
        ("field", ctypes.POINTER(ctypes.c_int64)),
        ("index", ctypes.POINTER(ctypes.c_uint64)),
        ("value", ctypes.POINTER(ctypes.c_float)),
        ("has_weight", ctypes.c_int32),
        ("has_qid", ctypes.c_int32),
        ("has_field", ctypes.c_int32),
        ("has_value", ctypes.c_int32),
        ("error", ctypes.c_char_p),
    ]


def load(path: Optional[str] = None) -> bool:
    """Load the native library (idempotent). Returns availability."""
    global AVAILABLE, _LIB
    with _LOCK:
        if _LIB is not None:
            return AVAILABLE
        if os.environ.get("DMLC_TPU_NO_NATIVE", "0") == "1":
            return False
        paths = (path,) if path else _CANDIDATES
        for p in paths:
            if p is None or not os.path.exists(p):
                continue
            try:
                lib = ctypes.CDLL(p)
            except OSError:
                continue
            for fn in ("dmlc_parse_libsvm", "dmlc_parse_csv", "dmlc_parse_libfm"):
                getattr(lib, fn).restype = ctypes.POINTER(_ParseResult)
            lib.dmlc_parse_libsvm.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32]
            lib.dmlc_parse_csv.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32]
            lib.dmlc_parse_libfm.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32]
            lib.dmlc_free_result.argtypes = [ctypes.POINTER(_ParseResult)]
            lib.dmlc_free_result.restype = None
            _LIB = lib
            AVAILABLE = True
            return True
        return False


def _copy_out(res_ptr):
    """ParseResult → numpy arrays (copies), then free native buffers."""
    res = res_ptr.contents
    try:
        if res.error:
            from ..utils.logging import Error

            raise Error(res.error.decode())
        n, m = res.n_rows, res.n_elems
        offset = np.ctypeslib.as_array(res.offset, (n + 1,)).copy()
        label = np.ctypeslib.as_array(res.label, (n,)).copy() if n else np.empty(0, np.float32)
        weight = (
            np.ctypeslib.as_array(res.weight, (n,)).copy()
            if res.has_weight and n else None
        )
        qid = (
            np.ctypeslib.as_array(res.qid, (n,)).copy()
            if res.has_qid and n else None
        )
        field = (
            np.ctypeslib.as_array(res.field, (m,)).copy()
            if res.has_field and m else (np.empty(0, np.int64) if res.has_field else None)
        )
        index = (
            np.ctypeslib.as_array(res.index, (m,)).copy()
            if m else np.empty(0, np.uint64)
        )
        value = (
            np.ctypeslib.as_array(res.value, (m,)).copy()
            if res.has_value and m else (np.empty(0, np.float32) if res.has_value else None)
        )
        return offset, label, weight, qid, field, index, value
    finally:
        _LIB.dmlc_free_result(res_ptr)


def parse_libsvm(data: bytes, indexing_mode: int):
    """→ (offset, label, weight, qid, index, value) or None if unavailable."""
    if not AVAILABLE:
        return None
    res = _LIB.dmlc_parse_libsvm(data, len(data), indexing_mode)
    offset, label, weight, qid, _field, index, value = _copy_out(res)
    return offset, label, weight, qid, index, value


def parse_csv(data: bytes, delimiter: int, label_column: int, weight_column: int):
    """→ (offset, label, weight, index, value) or None if unavailable."""
    if not AVAILABLE:
        return None
    res = _LIB.dmlc_parse_csv(data, len(data), delimiter, label_column, weight_column)
    offset, label, weight, _qid, _field, index, value = _copy_out(res)
    return offset, label, weight, index, value


def parse_libfm(data: bytes, indexing_mode: int):
    """→ (offset, label, weight, field, index, value) or None."""
    if not AVAILABLE:
        return None
    res = _LIB.dmlc_parse_libfm(data, len(data), indexing_mode)
    offset, label, weight, _qid, field, index, value = _copy_out(res)
    return offset, label, weight, field, index, value


load()
