"""ctypes bridge to the native C++ parse core (native/libdmlc_tpu_native.so).

The native library implements the hot loops — libsvm/csv/libfm chunk parsing
into CSR arrays — releasing the GIL so TextParserBase's thread fan-out gets
real parallelism (the reference gets this from std::thread,
src/data/text_parser.h:110-146). Every entry point has a pure-Python
fallback in the corresponding parser module; if the library is missing or
fails to load, AVAILABLE stays False and nothing breaks.

Calling convention: the caller passes the chunk buffer; the library parses
into library-owned growable buffers and returns sizes; the bridge copies
into fresh numpy arrays and frees the native buffers. One copy per ~8MB
chunk is noise next to parse cost, and fresh arrays keep ownership simple.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "AVAILABLE",
    "HAS_DENSE",
    "HAS_ELL",
    "HAS_CSV_DENSE",
    "HAS_GATHER_ELL",
    "HAS_LIBFM_ELL",
    "HAS_LIBSVM_ELL",
    "parse_libsvm",
    "parse_csv",
    "parse_libfm",
    "parse_libsvm_dense",
    "parse_csv_dense",
    "parse_rowrec_ell",
    "parse_rowrec_gather_ell",
    "parse_libfm_ell",
    "parse_libsvm_ell",
    "shuffle_mt19937",
    "source_hash",
    "walk_record_spans",
    "load",
]

AVAILABLE = False
HAS_DENSE = False      # fused libsvm->dense-batch kernel present in the .so
HAS_ELL = False        # fused recordio rowrec->ELL-batch kernel present
HAS_CSV_DENSE = False  # fused csv->dense-batch kernel present
HAS_GATHER_ELL = False  # shuffled-read (buf,starts,sizes)->ELL gather kernel
HAS_LIBFM_ELL = False  # fused libfm->ELL-batch kernel present
HAS_LIBSVM_ELL = False  # fused libsvm->ELL-batch kernel present
HAS_SHUFFLE = False    # CPython-parity MT19937 Fisher-Yates kernel present
HAS_WALK_SPANS = False  # batched point-read frame walk kernel present
_LIB = None
_LOCK = threading.Lock()

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CANDIDATES = (
    os.path.join(_REPO_ROOT, "native", "libdmlc_tpu_native.so"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "libdmlc_tpu_native.so"),
)


class _ParseResult(ctypes.Structure):
    """Mirrors native/fastparse.cc struct ParseResult."""

    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("n_elems", ctypes.c_int64),
        ("offset", ctypes.POINTER(ctypes.c_int64)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("qid", ctypes.POINTER(ctypes.c_int64)),
        ("field", ctypes.POINTER(ctypes.c_int64)),
        ("index", ctypes.POINTER(ctypes.c_uint64)),
        ("value", ctypes.POINTER(ctypes.c_float)),
        ("has_weight", ctypes.c_int32),
        ("has_qid", ctypes.c_int32),
        ("has_field", ctypes.c_int32),
        ("has_value", ctypes.c_int32),
        ("error", ctypes.c_char_p),
    ]


class _DenseResult(ctypes.Structure):
    """Mirrors native/fastparse.cc struct DenseResult."""

    _fields_ = [
        ("rows_written", ctypes.c_int64),
        ("bytes_consumed", ctypes.c_int64),
        ("truncated", ctypes.c_int64),
        ("has_cr", ctypes.c_int64),
    ]


class _EllResult(ctypes.Structure):
    """Mirrors native/fastparse.cc struct EllResult."""

    _fields_ = [
        ("rows_written", ctypes.c_int64),
        ("bytes_consumed", ctypes.c_int64),
        ("truncated", ctypes.c_int64),
        ("bad_records", ctypes.c_int64),
        ("corrupt", ctypes.c_int64),
    ]


class _CsvDenseResult(ctypes.Structure):
    """Mirrors native/fastparse.cc struct CsvDenseResult."""

    _fields_ = [
        ("rows_written", ctypes.c_int64),
        ("bytes_consumed", ctypes.c_int64),
        ("truncated", ctypes.c_int64),
        ("has_cr", ctypes.c_int64),
        ("bad_lines", ctypes.c_int64),
    ]


def load(path: Optional[str] = None, force: bool = False) -> bool:
    """Load the native library (idempotent). Returns availability.

    ``force`` re-opens the .so even if one is already loaded — used after
    an in-session rebuild (the rebuilt file is a new inode, so dlopen
    returns a fresh handle; the old one is left to the process lifetime).
    """
    global AVAILABLE, HAS_DENSE, HAS_ELL, HAS_CSV_DENSE, HAS_GATHER_ELL, \
        HAS_LIBFM_ELL, HAS_LIBSVM_ELL, HAS_SHUFFLE, HAS_WALK_SPANS, _LIB
    with _LOCK:
        if _LIB is not None and not force:
            return AVAILABLE
        if force:
            _LIB = None
            AVAILABLE = HAS_DENSE = HAS_ELL = HAS_CSV_DENSE = False
            HAS_GATHER_ELL = HAS_LIBFM_ELL = HAS_LIBSVM_ELL = False
            HAS_SHUFFLE = HAS_WALK_SPANS = False
        if os.environ.get("DMLC_TPU_NO_NATIVE", "0") == "1":
            return False
        paths = (path,) if path else _CANDIDATES
        for p in paths:
            if p is None or not os.path.exists(p):
                continue
            try:
                lib = ctypes.CDLL(p)
            except OSError:
                continue
            for fn in ("dmlc_parse_libsvm", "dmlc_parse_csv", "dmlc_parse_libfm"):
                getattr(lib, fn).restype = ctypes.POINTER(_ParseResult)
            lib.dmlc_parse_libsvm.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32]
            lib.dmlc_parse_csv.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32]
            lib.dmlc_parse_libfm.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32]
            lib.dmlc_free_result.argtypes = [ctypes.POINTER(_ParseResult)]
            lib.dmlc_free_result.restype = None
            # fused dense kernel: absent in older builds of the .so
            if hasattr(lib, "dmlc_parse_libsvm_dense"):
                lib.dmlc_parse_libsvm_dense.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_int64, ctypes.c_int32,
                    ctypes.POINTER(_DenseResult)]
                lib.dmlc_parse_libsvm_dense.restype = None
                HAS_DENSE = True
            # fused csv->dense kernel: absent in older builds
            if hasattr(lib, "dmlc_parse_csv_dense"):
                lib.dmlc_parse_csv_dense.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
                    ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_int32, ctypes.POINTER(_CsvDenseResult)]
                lib.dmlc_parse_csv_dense.restype = None
                HAS_CSV_DENSE = True
            # fused recordio rowrec->ELL kernel: absent in older builds
            if hasattr(lib, "dmlc_parse_rowrec_ell"):
                lib.dmlc_parse_rowrec_ell.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_int64,
                    ctypes.POINTER(_EllResult)]
                lib.dmlc_parse_rowrec_ell.restype = None
                HAS_ELL = True
            # shuffled-read gather kernel: absent in older builds
            if hasattr(lib, "dmlc_parse_rowrec_gather_ell"):
                lib.dmlc_parse_rowrec_gather_ell.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_int64, ctypes.POINTER(_EllResult)]
                lib.dmlc_parse_rowrec_gather_ell.restype = None
                HAS_GATHER_ELL = True
            # CPython-parity shuffle kernel: absent in older builds
            if hasattr(lib, "dmlc_shuffle_mt19937"):
                lib.dmlc_shuffle_mt19937.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
                    ctypes.c_void_p]
                lib.dmlc_shuffle_mt19937.restype = None
                HAS_SHUFFLE = True
            # fused libfm->ELL kernel: absent in older builds
            if hasattr(lib, "dmlc_parse_libfm_ell"):
                lib.dmlc_parse_libfm_ell.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_int32, ctypes.POINTER(_DenseResult)]
                lib.dmlc_parse_libfm_ell.restype = None
                HAS_LIBFM_ELL = True
            if hasattr(lib, "dmlc_parse_libsvm_ell"):
                lib.dmlc_parse_libsvm_ell.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_int32, ctypes.POINTER(_DenseResult)]
                lib.dmlc_parse_libsvm_ell.restype = None
                HAS_LIBSVM_ELL = True
            # batched point-read frame walk: absent in older builds
            if hasattr(lib, "dmlc_walk_record_spans"):
                lib.dmlc_walk_record_spans.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64)]
                lib.dmlc_walk_record_spans.restype = None
                HAS_WALK_SPANS = True
            if hasattr(lib, "dmlc_source_hash"):
                lib.dmlc_source_hash.restype = ctypes.c_char_p
                lib.dmlc_source_hash.argtypes = []
            _LIB = lib
            AVAILABLE = True
            return True
        return False


def source_hash() -> str:
    """sha256 of the fastparse.cc the loaded .so was built from, or ''
    (older builds). bench.py compares this against the on-disk source so a
    failed rebuild can't silently benchmark a stale binary."""
    if not AVAILABLE or not hasattr(_LIB, "dmlc_source_hash"):
        return ""
    return _LIB.dmlc_source_hash().decode()


def _memmove_out(ptr, n: int, dtype) -> np.ndarray:
    """Copy n elements from a native pointer into a fresh numpy array.

    ctypes.memmove is a plain memcpy; the np.ctypeslib.as_array route used
    previously built a ctypes array *type* per call, which cost more than
    the copy itself on large chunks.
    """
    arr = np.empty(n, dtype=dtype)
    if n:
        ctypes.memmove(arr.ctypes.data, ctypes.cast(ptr, ctypes.c_void_p),
                       n * arr.itemsize)
    return arr


def _copy_out(res_ptr):
    """ParseResult → numpy arrays (copies), then free native buffers."""
    res = res_ptr.contents
    try:
        if res.error:
            from ..utils.logging import Error

            raise Error(res.error.decode())
        n, m = res.n_rows, res.n_elems
        offset = _memmove_out(res.offset, n + 1, np.int64)
        label = _memmove_out(res.label, n, np.float32)
        weight = _memmove_out(res.weight, n, np.float32) if res.has_weight else None
        qid = _memmove_out(res.qid, n, np.int64) if res.has_qid else None
        field = _memmove_out(res.field, m, np.int64) if res.has_field else None
        index = _memmove_out(res.index, m, np.uint64)
        value = _memmove_out(res.value, m, np.float32) if res.has_value else None
        return offset, label, weight, qid, field, index, value
    finally:
        _LIB.dmlc_free_result(res_ptr)


def parse_libsvm(data: bytes, indexing_mode: int):
    """→ (offset, label, weight, qid, index, value) or None if unavailable."""
    if not AVAILABLE:
        return None
    res = _LIB.dmlc_parse_libsvm(data, len(data), indexing_mode)
    offset, label, weight, qid, _field, index, value = _copy_out(res)
    return offset, label, weight, qid, index, value


def parse_csv(data: bytes, delimiter: int, label_column: int, weight_column: int):
    """→ (offset, label, weight, index, value) or None if unavailable."""
    if not AVAILABLE:
        return None
    res = _LIB.dmlc_parse_csv(data, len(data), delimiter, label_column, weight_column)
    offset, label, weight, _qid, _field, index, value = _copy_out(res)
    return offset, label, weight, index, value


def parse_libfm(data: bytes, indexing_mode: int):
    """→ (offset, label, weight, field, index, value) or None."""
    if not AVAILABLE:
        return None
    res = _LIB.dmlc_parse_libfm(data, len(data), indexing_mode)
    offset, label, weight, _qid, field, index, value = _copy_out(res)
    return offset, label, weight, field, index, value


def parse_libsvm_dense(
    chunk,
    offset: int,
    base: int,
    x: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    row_start: int,
    cr_hint: int = -1,
) -> Optional[Tuple[int, int, int, int]]:
    """Fused libsvm parse → dense batch rows, zero-copy in and out.

    Parses ``chunk[offset:]`` (bytes/bytearray/memoryview, not sliced — the
    native side receives a pointer at the offset) into rows
    ``row_start..`` of the caller-owned buffers:

    - ``x``: C-contiguous [capacity, D] float32 or float16
    - ``labels``/``weights``: float32 [capacity]

    ``base`` is the resolved indexing base (0 or 1 — subtracted from every
    parsed feature id; callers resolve the libsvm auto mode themselves).
    ``cr_hint``: -1 on the first call for a chunk (the kernel probes for
    '\\r' once); pass the returned ``has_cr`` on resumed calls for the
    same chunk so the probe isn't repeated. Stops at buffer-full or
    chunk-end. Returns (rows_written, bytes_consumed, truncated_features,
    has_cr), or None if the kernel is missing. The rows written are fully
    initialized (zeroed before scatter), so ring buffers can be reused
    without clearing.
    """
    if not HAS_DENSE:
        return None
    from ..utils.logging import check

    mem = np.frombuffer(chunk, dtype=np.uint8)  # no copy, works on bytes
    # memory-safety preconditions: the kernel writes through raw pointers
    # assuming contiguous f32/f16 layout — never assert (stripped under -O)
    check(x.flags.c_contiguous and x.dtype in (np.float32, np.float16),
          "x must be C-contiguous float32/float16")
    check(labels.flags.c_contiguous and labels.dtype == np.float32
          and weights.flags.c_contiguous and weights.dtype == np.float32,
          "labels/weights must be C-contiguous float32")
    capacity, D = x.shape
    check(len(labels) >= capacity and len(weights) >= capacity,
          "labels/weights shorter than x capacity")
    res = _DenseResult()
    _LIB.dmlc_parse_libsvm_dense(
        ctypes.c_void_p(mem.ctypes.data + offset),
        ctypes.c_int64(mem.size - offset),
        ctypes.c_int32(base),
        ctypes.c_int64(D),
        ctypes.c_int32(1 if x.dtype == np.float16 else 0),
        ctypes.c_void_p(x.ctypes.data),
        ctypes.c_void_p(labels.ctypes.data),
        ctypes.c_void_p(weights.ctypes.data),
        ctypes.c_int64(row_start),
        ctypes.c_int64(capacity),
        ctypes.c_int32(cr_hint),
        ctypes.byref(res),
    )
    return res.rows_written, res.bytes_consumed, res.truncated, res.has_cr


def parse_csv_dense(
    chunk,
    offset: int,
    delimiter: int,
    label_column: int,
    weight_column: int,
    x: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    row_start: int,
    cr_hint: int = -1,
) -> Optional[Tuple[int, int, int, int, int]]:
    """Fused csv parse → dense batch rows (same buffer contract as
    ``parse_libsvm_dense``). ``weight_column`` -1 = none. Returns
    (rows_written, bytes_consumed, truncated, has_cr, bad_lines) — a
    nonzero ``bad_lines`` means a non-empty line had no delimiter, which
    the generic CSVParser treats as a malformed file. None if missing."""
    if not HAS_CSV_DENSE:
        return None
    from ..utils.logging import check

    mem = np.frombuffer(chunk, dtype=np.uint8)
    check(x.flags.c_contiguous and x.dtype in (np.float32, np.float16),
          "x must be C-contiguous float32/float16")
    check(labels.flags.c_contiguous and labels.dtype == np.float32
          and weights.flags.c_contiguous and weights.dtype == np.float32,
          "labels/weights must be C-contiguous float32")
    capacity, D = x.shape
    check(len(labels) >= capacity and len(weights) >= capacity,
          "labels/weights shorter than x capacity")
    res = _CsvDenseResult()
    _LIB.dmlc_parse_csv_dense(
        ctypes.c_void_p(mem.ctypes.data + offset),
        ctypes.c_int64(mem.size - offset),
        ctypes.c_int32(delimiter),
        ctypes.c_int32(label_column),
        ctypes.c_int32(weight_column),
        ctypes.c_int64(D),
        ctypes.c_int32(1 if x.dtype == np.float16 else 0),
        ctypes.c_void_p(x.ctypes.data),
        ctypes.c_void_p(labels.ctypes.data),
        ctypes.c_void_p(weights.ctypes.data),
        ctypes.c_int64(row_start),
        ctypes.c_int64(capacity),
        ctypes.c_int32(cr_hint),
        ctypes.byref(res),
    )
    return (res.rows_written, res.bytes_consumed, res.truncated,
            res.has_cr, res.bad_lines)


def parse_rowrec_ell(
    chunk,
    offset: int,
    indices: np.ndarray,
    values: np.ndarray,
    nnz: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    row_start: int,
) -> Optional[Tuple[int, int, int, int]]:
    """Fused RecordIO frame scan + rowrec decode → ELL batch rows.

    Parses complete RecordIO records from ``chunk[offset:]`` into rows
    ``row_start..`` of the caller-owned ELL buffers:

    - ``indices``: C-contiguous [capacity, K] int32
    - ``values``: C-contiguous [capacity, K] float32 or float16
    - ``nnz``: int32 [capacity]; ``labels``/``weights``: float32 [capacity]

    Stops at buffer-full or at a trailing partial record (the caller's next
    window must resume at ``offset + bytes_consumed``). Rows with more than
    K features keep the first K (dropped count in ``truncated``). Returns
    (rows_written, bytes_consumed, truncated, bad_records, corrupt) —
    ``corrupt`` set when a full frame header is present but carries no
    magic (broken stream, fail fast; a trailing partial is NOT corrupt) —
    or None if the kernel is missing.
    """
    if not HAS_ELL:
        return None
    mem = np.frombuffer(chunk, dtype=np.uint8)
    capacity, K = _check_ell_buffers(indices, values, nnz, labels, weights)
    res = _EllResult()
    _LIB.dmlc_parse_rowrec_ell(
        ctypes.c_void_p(mem.ctypes.data + offset),
        ctypes.c_int64(mem.size - offset),
        ctypes.c_int64(K),
        ctypes.c_int32(1 if values.dtype == np.float16 else 0),
        ctypes.c_void_p(indices.ctypes.data),
        ctypes.c_void_p(values.ctypes.data),
        ctypes.c_void_p(nnz.ctypes.data),
        ctypes.c_void_p(labels.ctypes.data),
        ctypes.c_void_p(weights.ctypes.data),
        ctypes.c_int64(row_start),
        ctypes.c_int64(capacity),
        ctypes.byref(res),
    )
    return (res.rows_written, res.bytes_consumed, res.truncated,
            res.bad_records, res.corrupt)


def _check_ell_buffers(indices, values, nnz, labels, weights):
    """Shared memory-safety preconditions for the ELL-output kernels."""
    from ..utils.logging import check

    check(indices.flags.c_contiguous and indices.dtype == np.int32,
          "indices must be C-contiguous int32")
    check(values.flags.c_contiguous
          and values.dtype in (np.float32, np.float16),
          "values must be C-contiguous float32/float16")
    check(nnz.flags.c_contiguous and nnz.dtype == np.int32,
          "nnz must be C-contiguous int32")
    check(labels.flags.c_contiguous and labels.dtype == np.float32
          and weights.flags.c_contiguous and weights.dtype == np.float32,
          "labels/weights must be C-contiguous float32")
    capacity, K = indices.shape
    check(values.shape == (capacity, K), "values shape != indices shape")
    check(len(nnz) >= capacity and len(labels) >= capacity
          and len(weights) >= capacity, "1-D buffers shorter than capacity")
    return capacity, K


def parse_rowrec_gather_ell(
    buf: np.ndarray,
    starts: np.ndarray,
    sizes: np.ndarray,
    lo: int,
    n_recs: int,
    indices: np.ndarray,
    values: np.ndarray,
    nnz: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    row_start: int,
) -> Optional[Tuple[int, int, int, int, int]]:
    """Shuffled-read gather: parse framed rowrec records at
    ``(starts[lo + i], sizes[lo + i])`` byte slices of ``buf`` — the
    ``next_gather_batch`` emission of a windowed shuffle
    (io/split.py) — straight into rows ``row_start..`` of the
    caller-owned ELL buffers (contract of ``parse_rowrec_ell``). One
    call per batch, no per-record Python, no re-framing copy.

    ``buf`` is uint8 1-D; ``starts``/``sizes`` are int64, consumed from
    position ``lo`` (pointer offset — resumed calls never re-slice).
    Stops at buffer-full. Returns (rows_written, recs_consumed,
    truncated, bad_records, corrupt) — ``corrupt`` set when a slice
    holds no complete record (the index and data disagree; callers fail
    fast) — or None if the kernel is missing.
    """
    if not HAS_GATHER_ELL:
        return None
    from ..utils.logging import check

    capacity, K = _check_ell_buffers(indices, values, nnz, labels, weights)
    check(buf.flags.c_contiguous and buf.dtype == np.uint8,
          "gather buf must be C-contiguous uint8")
    check(starts.flags.c_contiguous and starts.dtype == np.int64
          and sizes.flags.c_contiguous and sizes.dtype == np.int64,
          "starts/sizes must be C-contiguous int64")
    check(0 <= lo and lo + n_recs <= len(starts)
          and len(sizes) >= len(starts),
          "gather range outside starts/sizes")
    res = _EllResult()
    _LIB.dmlc_parse_rowrec_gather_ell(
        ctypes.c_void_p(buf.ctypes.data),
        ctypes.c_void_p(starts.ctypes.data + lo * 8),
        ctypes.c_void_p(sizes.ctypes.data + lo * 8),
        ctypes.c_int64(n_recs),
        ctypes.c_int64(K),
        ctypes.c_int32(1 if values.dtype == np.float16 else 0),
        ctypes.c_void_p(indices.ctypes.data),
        ctypes.c_void_p(values.ctypes.data),
        ctypes.c_void_p(nnz.ctypes.data),
        ctypes.c_void_p(labels.ctypes.data),
        ctypes.c_void_p(weights.ctypes.data),
        ctypes.c_int64(row_start),
        ctypes.c_int64(capacity),
        ctypes.byref(res),
    )
    return (res.rows_written, res.bytes_consumed, res.truncated,
            res.bad_records, res.corrupt)


def shuffle_mt19937(rnd, perm: np.ndarray) -> bool:
    """Fisher-Yates shuffle ``perm`` (int64, C-contiguous) in place,
    BIT-IDENTICAL to ``rnd.shuffle(perm)`` for a CPython
    ``random.Random`` — same Mersenne-Twister draws, same rejection
    sampling, same swaps — at native speed (the shuffled-read
    permutation is pinned to random.Random order, docs/shuffle.md).

    Returns False (caller falls back to ``rnd.shuffle``) when the
    kernel is missing or ``len(perm) >= 2**31`` (getrandbits there
    consumes multiple words per call, which the kernel does not
    mirror). ``rnd`` is left untouched — callers derive a fresh
    (seed, epoch) Random per epoch, so its post-shuffle state is never
    observed.
    """
    if not HAS_SHUFFLE or len(perm) >= (1 << 31):
        return False
    from ..utils.logging import check

    check(perm.flags.c_contiguous and perm.dtype == np.int64,
          "shuffle perm must be C-contiguous int64")
    state = rnd.getstate()
    check(
        state[0] == 3 and len(state[1]) == 625,
        "unsupported random.Random state version",
    )
    key = np.asarray(state[1][:624], dtype=np.uint32)
    _LIB.dmlc_shuffle_mt19937(
        ctypes.c_void_p(key.ctypes.data),
        ctypes.c_int32(state[1][624]),
        ctypes.c_int64(len(perm)),
        ctypes.c_void_p(perm.ctypes.data),
    )
    return True


def walk_record_spans(
    buf: np.ndarray, starts: np.ndarray, sizes: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray, int, int]]:
    """Batched point-read frame walk (io/lookup.py): each
    ``(starts[i], sizes[i])`` byte slice of ``buf`` must begin at a
    RecordIO frame head; returns ``(payload_offs, payload_lens,
    n_multipart, n_corrupt)`` with ``payload_offs[i]`` the record's
    payload offset into ``buf`` for single-frame records, ``-2`` for a
    multi-part chain (the caller reassembles those few in Python — the
    payload is not a contiguous slice), ``-1`` for a slice that holds
    no valid head (index/data mismatch; callers fail fast). One native
    call per block in place of a per-record Python walk. None if the
    kernel is missing."""
    if not HAS_WALK_SPANS:
        return None
    from ..utils.logging import check

    check(buf.flags.c_contiguous and buf.dtype == np.uint8,
          "walk buf must be C-contiguous uint8")
    check(starts.flags.c_contiguous and starts.dtype == np.int64
          and sizes.flags.c_contiguous and sizes.dtype == np.int64
          and len(sizes) == len(starts),
          "starts/sizes must be matching C-contiguous int64")
    n = len(starts)
    out_off = np.empty(n, dtype=np.int64)
    out_len = np.empty(n, dtype=np.int64)
    nm = ctypes.c_int64()
    nc = ctypes.c_int64()
    _LIB.dmlc_walk_record_spans(
        ctypes.c_void_p(buf.ctypes.data),
        ctypes.c_void_p(starts.ctypes.data),
        ctypes.c_void_p(sizes.ctypes.data),
        ctypes.c_int64(n),
        ctypes.c_void_p(out_off.ctypes.data),
        ctypes.c_void_p(out_len.ctypes.data),
        ctypes.byref(nm),
        ctypes.byref(nc),
    )
    return out_off, out_len, int(nm.value), int(nc.value)


def parse_libfm_ell(
    chunk,
    offset: int,
    base: int,
    indices: np.ndarray,
    values: np.ndarray,
    nnz: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    row_start: int,
    cr_hint: int = -1,
) -> Optional[Tuple[int, int, int, int]]:
    """Fused libfm text parse → ELL batch rows (buffer contract of
    ``parse_rowrec_ell``, resumable-chunk contract of
    ``parse_libsvm_dense``). ``base`` is the resolved indexing base —
    callers resolve libfm auto mode against the file head. Returns
    (rows_written, bytes_consumed, truncated, has_cr), or None if the
    kernel is missing."""
    if not HAS_LIBFM_ELL:
        return None
    mem = np.frombuffer(chunk, dtype=np.uint8)
    capacity, K = _check_ell_buffers(indices, values, nnz, labels, weights)
    res = _DenseResult()
    _LIB.dmlc_parse_libfm_ell(
        ctypes.c_void_p(mem.ctypes.data + offset),
        ctypes.c_int64(mem.size - offset),
        ctypes.c_int32(base),
        ctypes.c_int64(K),
        ctypes.c_int32(1 if values.dtype == np.float16 else 0),
        ctypes.c_void_p(indices.ctypes.data),
        ctypes.c_void_p(values.ctypes.data),
        ctypes.c_void_p(nnz.ctypes.data),
        ctypes.c_void_p(labels.ctypes.data),
        ctypes.c_void_p(weights.ctypes.data),
        ctypes.c_int64(row_start),
        ctypes.c_int64(capacity),
        ctypes.c_int32(cr_hint),
        ctypes.byref(res),
    )
    return res.rows_written, res.bytes_consumed, res.truncated, res.has_cr


def parse_libsvm_ell(
    chunk,
    offset: int,
    base: int,
    indices: np.ndarray,
    values: np.ndarray,
    nnz: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    row_start: int,
    cr_hint: int = -1,
) -> Optional[Tuple[int, int, int, int]]:
    """Fused libsvm text parse → ELL batch rows (buffer contract of
    ``parse_rowrec_ell``, resumable-chunk contract of
    ``parse_libsvm_dense``). ``base`` is the resolved indexing base —
    callers resolve libsvm auto mode against the file head. Returns
    (rows_written, bytes_consumed, truncated, has_cr), or None if the
    kernel is missing."""
    if not HAS_LIBSVM_ELL:
        return None
    mem = np.frombuffer(chunk, dtype=np.uint8)
    capacity, K = _check_ell_buffers(indices, values, nnz, labels, weights)
    res = _DenseResult()
    _LIB.dmlc_parse_libsvm_ell(
        ctypes.c_void_p(mem.ctypes.data + offset),
        ctypes.c_int64(mem.size - offset),
        ctypes.c_int32(base),
        ctypes.c_int64(K),
        ctypes.c_int32(1 if values.dtype == np.float16 else 0),
        ctypes.c_void_p(indices.ctypes.data),
        ctypes.c_void_p(values.ctypes.data),
        ctypes.c_void_p(nnz.ctypes.data),
        ctypes.c_void_p(labels.ctypes.data),
        ctypes.c_void_p(weights.ctypes.data),
        ctypes.c_int64(row_start),
        ctypes.c_int64(capacity),
        ctypes.c_int32(cr_hint),
        ctypes.byref(res),
    )
    return res.rows_written, res.bytes_consumed, res.truncated, res.has_cr


load()
