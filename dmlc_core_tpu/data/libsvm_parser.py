"""LibSVM text format parser.

Reference: src/data/libsvm_parser.h. Line grammar::

    label[:weight] [qid:n] index[:value] index[:value] ...  [# comment]

- ``#`` starts a comment; blank / comment-only lines are skipped
  (reference IgnoreCommentAndBlank, libsvm_parser.h:87-103).
- Features may omit ``:value`` (binary features, value treated as 1.0 —
  reference data.h:120-127). Divergence from the reference: a block mixing
  valued and unvalued features gets 1.0 filled in for the unvalued ones
  (the reference silently misaligns arrays in that case).
- ``indexing_mode`` param: >0 forces 1-based, 0 forces 0-based, <0
  auto-detects à la sklearn.load_svmlight_file (all ids > 0 ⇒ 1-based;
  reference libsvm_parser.h:159-168).

The native C++ core (native/fastparse.cc) replaces ``parse_block`` when
loaded; this numpy/bytes implementation is the semantic definition.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..io.split import InputSplit
from ..params.parameter import Parameter, field
from ..utils.logging import check_eq
from . import native
from .row_block import INDEX_T, REAL_T, RowBlock
from .strtonum import parse_float_token, parse_int_token, parse_pair
from .text_parser import TextParserBase

__all__ = ["LibSVMParser", "LibSVMParserParam"]


class LibSVMParserParam(Parameter):
    """Reference LibSVMParserParam (libsvm_parser.h:24-39)."""

    format = field(str, default="libsvm", help="File format")
    indexing_mode = field(
        int,
        default=0,
        help=(
            "If >0, treat all feature indices as 1-based. If =0, 0-based. "
            "If <0, auto-detect (all ids > 0 means 1-based)."
        ),
    )


class LibSVMParser(TextParserBase):
    def __init__(
        self,
        source: InputSplit,
        args: Optional[dict] = None,
        nthread: Optional[int] = None,
        index_dtype=INDEX_T,
    ) -> None:
        super().__init__(source, nthread)
        self.param = LibSVMParserParam()
        self.param.init(args or {}, allow_unknown=True)
        check_eq(self.param.format, "libsvm", "format mismatch")
        self.index_dtype = index_dtype

    def parse_block(self, data: bytes) -> RowBlock:
        if native.AVAILABLE:
            arrays = native.parse_libsvm(data, self.param.indexing_mode)
            if arrays is not None:
                return self._block_from_native(arrays)
        return self._parse_block_py(data)

    def _block_from_native(self, arrays) -> RowBlock:
        offset, label, weight, qid, index, value = arrays
        return RowBlock(
            offset=offset,
            label=label,
            index=index.astype(self.index_dtype, copy=False),
            value=value,
            weight=weight,
            qid=qid,
        )

    def _parse_block_py(self, data: bytes) -> RowBlock:
        labels = []
        weights = []
        qids = []
        index = []
        values = []
        offset = [0]
        any_value = False
        min_feat = None
        for line in data.splitlines():
            hash_pos = line.find(b"#")
            if hash_pos >= 0:
                line = line[:hash_pos]
            toks = line.split()
            if not toks:
                continue
            lw = parse_pair(toks[0])
            if lw is None:
                continue
            label, weight = lw
            start = 1
            qid = None
            if len(toks) > 1 and toks[1].startswith(b"qid:"):
                # garbage/overflow qid -> 0, keep parsing (reference atoll)
                qid = parse_int_token(toks[1][4:]) or 0
                start = 2
            row_vals = []
            for t in toks[start:]:
                c = t.find(b":")
                if c < 0:
                    feat, val = parse_int_token(t), None
                else:
                    feat = parse_int_token(t[:c])
                    val = parse_float_token(t[c + 1:])
                    if val is None:
                        feat = None
                if feat is None:
                    continue  # malformed token: reference ParsePair r<1 skip
                index.append(feat)
                row_vals.append(val)
            if any(v is not None for v in row_vals):
                any_value = True
            values.extend(row_vals)
            labels.append(label)
            weights.append(weight)
            qids.append(qid)
            offset.append(len(index))
        idx_arr = np.asarray(index, dtype=np.int64)
        if len(idx_arr):
            min_feat = int(idx_arr.min())
        mode = self.param.indexing_mode
        if mode > 0 or (mode < 0 and min_feat is not None and min_feat > 0):
            idx_arr = idx_arr - 1
        value_arr = (
            np.asarray(
                [1.0 if v is None else v for v in values], dtype=REAL_T
            )
            if any_value
            else None
        )
        has_weight = any(w is not None for w in weights)
        has_qid = any(q is not None for q in qids)
        return RowBlock(
            offset=np.asarray(offset, dtype=np.int64),
            label=np.asarray(labels, dtype=REAL_T),
            index=idx_arr.astype(self.index_dtype, copy=False),
            value=value_arr,
            weight=(
                np.asarray(
                    [1.0 if w is None else w for w in weights], dtype=REAL_T
                )
                if has_weight
                else None
            ),
            qid=(
                np.asarray([0 if q is None else q for q in qids], np.int64)
                if has_qid
                else None
            ),
        )
