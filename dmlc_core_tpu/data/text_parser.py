"""Text parser base: chunked, multi-threaded line parsing.

Reference: src/data/text_parser.h. ``fill_data`` pulls one ~8MB chunk from the
InputSplit, splits it at line boundaries into N slices, and parses slices in
parallel into RowBlocks. With the native C++ core loaded (native/), slice
parsing releases the GIL and the thread fan-out gives true parallelism; the
pure-Python fallback keeps identical semantics.

Worker exceptions propagate to the caller (reference OMPException,
include/dmlc/common.h:53-87) via concurrent.futures result().
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from ..io.split import InputSplit
from .parser import Parser
from .row_block import RowBlock

__all__ = ["TextParserBase", "default_parser_threads"]

_BOM = b"\xef\xbb\xbf"


def default_parser_threads(nthread: Optional[int]) -> int:
    """Parser fan-out width.

    Deliberate divergence from the reference heuristic
    min(requested, max(procs/2 - 4, 1)) (text_parser.h:33-34, default 2
    from data.cc:29): that throttle assumes the learner competes for host
    CPU, but on a TPU host the CPU idles during the device step, so the
    parser gets every USABLE core by default — usable meaning the
    affinity-mask/cgroup-quota-aware count (utils/cpus.py), not the raw
    host core count a container may never see. Requests are still capped
    at that count (extra threads only add GIL churn);
    ``DMLC_PARSE_THREADS`` overrides both (``DMLC_TPU_PARSER_THREADS``
    kept as a legacy alias).
    """
    from ..utils.cpus import parse_threads

    return parse_threads(nthread)


class TextParserBase(Parser):
    """Chunk → line-aligned slices → parallel parse_block
    (reference text_parser.h:110-146)."""

    def __init__(self, source: InputSplit, nthread: Optional[int] = None) -> None:
        self.source = source
        self.nthread = default_parser_threads(nthread)
        self._bytes_read = 0
        self._pool: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=self.nthread, thread_name_prefix="parse")
            if self.nthread > 1
            else None
        )

    # -- subclass hook -------------------------------------------------------
    def parse_block(self, data: bytes) -> RowBlock:
        """Parse a byte slice of whole lines into one RowBlock."""
        raise NotImplementedError

    # -- Parser interface ----------------------------------------------------
    def bytes_read(self) -> int:
        return self._bytes_read

    def before_first(self) -> None:
        self.source.before_first()
        self._bytes_read = 0

    def parse_next(self) -> Optional[List[RowBlock]]:
        return self.fill_data()

    def fill_data(self) -> Optional[List[RowBlock]]:
        """One chunk, fanned out across parser threads (reference
        FillData, text_parser.h:110-146)."""
        chunk = self.source.next_chunk()
        if chunk is None:
            return None
        first_chunk = self._bytes_read == 0
        self._bytes_read += len(chunk)
        if first_chunk and chunk.startswith(_BOM):
            # UTF-8 BOM skip, beginning of input only (text_parser.h:81-95);
            # later chunks may legitimately start with these bytes
            chunk = chunk[len(_BOM):]
        slices = self._split_slices(chunk, self.nthread)
        if self._pool is None or len(slices) == 1:
            return [self.parse_block(s) for s in slices]
        futures = [self._pool.submit(self.parse_block, s) for s in slices]
        return [f.result() for f in futures]  # re-raises worker exceptions

    @staticmethod
    def _split_slices(chunk: bytes, nslice: int) -> List[bytes]:
        """Cut a chunk into ≤nslice pieces ending at line boundaries
        (reference BackFindEndLine usage, text_parser.h:120-133)."""
        n = len(chunk)
        if nslice <= 1 or n < 4096:
            return [chunk] if n else []
        step = (n + nslice - 1) // nslice
        out: List[bytes] = []
        begin = 0
        while begin < n:
            end = min(begin + step, n)
            if end < n:
                nl = chunk.rfind(b"\n", begin, end)
                if nl < 0:
                    # no newline inside the slice: extend to the next one
                    nl = chunk.find(b"\n", end)
                    end = n if nl < 0 else nl + 1
                else:
                    end = nl + 1
            piece = chunk[begin:end]
            if piece:
                out.append(piece)
            begin = end
        return out

    def close(self) -> None:
        if self._pool is not None:
            # shutdown(wait=False) returns while parse_block futures
            # still hold their chunk slices — closing the split under a
            # live worker is a use-after-close. Cancel what never
            # started and WAIT for what did; parse_block is pure CPU on
            # an in-memory slice, so the wait is bounded by one block.
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._pool = None
        self.source.close()
