"""Dense CSV format parser.

Reference: src/data/csv_parser.h. Every non-label/weight column becomes a
dense feature with running index 0..k-1; empty or non-numeric cells parse
as 0 (matching the reference's strtof behavior). Params: ``label_column``
(default -1 → label 0.0), ``weight_column`` (float dtype only),
``delimiter`` (default ","). dtype ∈ {float32, int32, int64}
(reference csv_parser.h:95-111).
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from ..io.split import InputSplit
from ..params.parameter import Parameter, field
from ..utils.logging import Error, check, check_eq
from . import native
from .strtonum import I64_MAX, I64_MIN
from .row_block import INDEX_T, REAL_T, RowBlock
from .text_parser import TextParserBase

__all__ = ["CSVParser", "CSVParserParam"]

_DTYPES = {"float32": np.float32, "int32": np.int32, "int64": np.int64}

_FLOAT_PREFIX = re.compile(
    rb"[+-]?(\d+\.?\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?|inf(inity)?|nan)",
    re.IGNORECASE,
)
_INT_PREFIX = re.compile(rb"([+-]?)(0[xX][0-9a-fA-F]+|[0-9]+)")


def _parse_cell(cell: bytes, is_float: bool):
    """C strtof/strtoll(base 0) prefix semantics (reference
    csv_parser.h:98-106): parse the longest numeric prefix, 0 if none.
    PEP-515 underscores are never accepted (C grammar); int overflow
    clamps like strtoll."""
    if is_float:
        if b"_" not in cell:
            try:
                return float(cell)
            except ValueError:
                pass
        m = _FLOAT_PREFIX.match(cell.strip())
        return float(m.group(0)) if m else 0.0
    if b"_" not in cell:
        try:
            return _clamp_i64(int(cell, 0))
        except ValueError:
            pass
    m = _INT_PREFIX.match(cell.strip())
    if not m:
        return 0
    sign, digits = m.group(1), m.group(2)
    if digits[:2].lower() == b"0x":
        val = int(digits, 16)
    elif digits.startswith(b"0") and len(digits) > 1:
        val = int(re.match(rb"0[0-7]*", digits).group(0), 8)
    else:
        val = int(digits)
    return _clamp_i64(-val if sign == b"-" else val)


def _clamp_i64(v: int) -> int:
    return min(max(v, I64_MIN), I64_MAX)


class CSVParserParam(Parameter):
    """Reference CSVParserParam (csv_parser.h:23-39)."""

    format = field(str, default="csv", help="File format.")
    label_column = field(
        int, default=-1,
        help="Column index (0-based) that will put into label.",
    )
    delimiter = field(
        str, default=",", help="Delimiter used in the csv file."
    )
    weight_column = field(
        int, default=-1,
        help="Column index that will put into instance weights.",
    )
    dtype = field(
        str, default="float32", enum={k: k for k in _DTYPES},
        help="Value dtype (reference DType dispatch, data.cc:138-210).",
    )


class CSVParser(TextParserBase):
    def __init__(
        self,
        source: InputSplit,
        args: Optional[dict] = None,
        nthread: Optional[int] = None,
        index_dtype=INDEX_T,
    ) -> None:
        super().__init__(source, nthread)
        self.param = CSVParserParam()
        self.param.init(args or {}, allow_unknown=True)
        check_eq(self.param.format, "csv", "format mismatch")
        check(
            self.param.label_column != self.param.weight_column
            or self.param.label_column < 0,
            "Must have distinct columns for labels and instance weights",
        )
        check_eq(len(self.param.delimiter), 1, "delimiter must be one char")
        self.dtype = _DTYPES[self.param.dtype]
        self.index_dtype = index_dtype

    def parse_block(self, data: bytes) -> RowBlock:
        if native.AVAILABLE and self.param.dtype == "float32":
            arrays = native.parse_csv(
                data,
                ord(self.param.delimiter),
                self.param.label_column,
                self.param.weight_column,
            )
            if arrays is not None:
                offset, label, weight, index, value = arrays
                return RowBlock(
                    offset=offset,
                    label=label,
                    index=index.astype(self.index_dtype, copy=False),
                    value=value,
                    weight=weight,
                )
        return self._parse_block_py(data)

    def _parse_block_py(self, data: bytes) -> RowBlock:
        delim = self.param.delimiter.encode()
        lcol, wcol = self.param.label_column, self.param.weight_column
        is_float = self.dtype == np.float32
        labels = []
        weights = []
        index = []
        values = []
        offset = [0]
        any_weight = False
        for line in data.splitlines():
            if not line:
                continue
            cells = line.split(delim)
            label = 0.0
            weight = None
            k = 0
            for col, cell in enumerate(cells):
                v = _parse_cell(cell, is_float)
                if col == lcol:
                    label = v
                elif is_float and col == wcol:
                    weight = v
                    any_weight = True
                else:
                    values.append(v)
                    index.append(k)
                    k += 1
            if k == 0:
                # reference csv_parser.h:123-126: fatal whenever a line
                # yields no feature at all
                raise Error(
                    f"Delimiter {self.param.delimiter!r} is not found in "
                    "the line. Expected it to separate fields."
                )
            labels.append(label)
            weights.append(weight)
            offset.append(len(index))
        return RowBlock(
            offset=np.asarray(offset, dtype=np.int64),
            label=np.asarray(labels, dtype=REAL_T),
            index=np.asarray(index, dtype=self.index_dtype),
            value=np.asarray(values, dtype=self.dtype),
            weight=(
                np.asarray(
                    [1.0 if w is None else w for w in weights], dtype=REAL_T
                )
                if any_weight
                else None
            ),
        )
