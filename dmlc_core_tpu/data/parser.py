"""Parser interfaces + threaded parse-ahead wrapper.

Reference: include/dmlc/data.h:280-361 (Parser interface + registry),
src/data/parser.h (ParserImpl, ThreadedParser).

A Parser is a pull iterator of RowBlock batches. ``ThreadedParser`` moves
parsing onto a background thread with a bounded queue of 8 batches
(reference parser.h:75), so downstream batching/staging overlaps with parse.
"""

from __future__ import annotations

from typing import List, Optional

from ..concurrency.threaded_iter import ThreadedIter
from ..params.registry import Registry
from .row_block import RowBlock

__all__ = ["Parser", "ThreadedParser", "PARSER_REGISTRY"]

# reference data.h:341-356 ParserFactoryReg; entries registered in __init__.py
PARSER_REGISTRY: Registry = Registry("parser")


class Parser:
    """Pull interface producing lists of RowBlocks (reference
    data.h:293-320, parser.h:24-68)."""

    def parse_next(self) -> Optional[List[RowBlock]]:
        """Parse the next batch of blocks; None at end of data."""
        raise NotImplementedError

    def before_first(self) -> None:
        raise NotImplementedError

    def bytes_read(self) -> int:
        """Bytes of source consumed so far (throughput accounting,
        reference data.h:310-312)."""
        raise NotImplementedError

    def __iter__(self):
        """Iterate single RowBlocks (flattened batches)."""
        while True:
            blocks = self.parse_next()
            if blocks is None:
                return
            for b in blocks:
                if b.size:
                    yield b

    def close(self) -> None:
        pass


class ThreadedParser(Parser):
    """Parse-ahead wrapper: base parser runs on a producer thread, batches
    cross to the consumer via a bounded queue (reference ThreadedParser,
    src/data/parser.h:71-126, capacity 8)."""

    def __init__(self, base: Parser, max_capacity: int = 8) -> None:
        self._base = base
        self._first_epoch = True
        #: bytes consumed by batches DELIVERED to the consumer — see
        #: bytes_read()
        self._bytes_delivered = 0
        self._iter: ThreadedIter[List[RowBlock]] = ThreadedIter(
            self._produce, max_capacity=max_capacity, name="threaded-parser"
        )

    def _produce(self):
        # skip the rewind on the very first epoch so non-rewindable sources
        # (stdin) work; same guard as ThreadedInputSplit (io/split.py)
        if self._first_epoch:
            self._first_epoch = False
        else:
            self._base.before_first()
        while True:
            blocks = self._base.parse_next()
            if blocks is None:
                return
            # snapshot the count HERE, on the producer thread, after
            # parse_next returned: the base is between chunks, so the
            # number is consistent — and it crosses the queue WITH its
            # batch, becoming visible only when the batch is delivered
            yield blocks, self._base.bytes_read()

    def parse_next(self) -> Optional[List[RowBlock]]:
        item = self._iter.next()
        if item is None:
            return None
        blocks, watermark = item
        self._bytes_delivered = watermark
        return blocks

    def before_first(self) -> None:
        self._iter.before_first()
        self._bytes_delivered = 0

    def bytes_read(self) -> int:
        """Bytes of source behind the batches the CONSUMER has seen.

        Reading ``self._base.bytes_read()`` directly races the producer
        thread, which may be mid-chunk parsing batches still sitting in
        the queue — over-reporting bytes not yet delivered (and making
        throughput-per-byte accounting jitter with queue depth). The
        watermark crosses the queue attached to each batch, so this is
        exact at every batch boundary."""
        return self._bytes_delivered

    def close(self) -> None:
        self._iter.destroy()
        self._base.close()
