"""Row-block iterators: eager in-memory and disk-cached epochs.

Reference: src/data/basic_row_iter.h (BasicRowIter: eager full load with
MB/sec logging every 10MB) and src/data/disk_row_iter.h (DiskRowIter: parse
once into 64MB serialized pages, replay epochs through a ThreadedIter).
Public interface mirrors RowBlockIter (include/dmlc/data.h:254-274):
before_first / next() → RowBlock / num_col.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, Optional, Union

from ..concurrency.threaded_iter import ThreadedIter
from ..io.stream import FileStream
from ..utils.logging import check, log_info
from ..utils.timer import get_time
from .parser import Parser
from .row_block import RowBlock, RowBlockContainer

__all__ = ["RowBlockIter", "BasicRowIter", "DiskRowIter", "PAGE_SIZE"]

PAGE_SIZE = 64 << 20  # reference disk_row_iter.h:32


class RowBlockIter:
    """Reference RowBlockIter interface (data.h:254-274)."""

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def num_col(self) -> int:
        """Maximum feature dimension (max index + 1, data.h:272-274)."""
        raise NotImplementedError

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            blk = self.next()
            if blk is None:
                return
            yield blk

    def close(self) -> None:
        pass


def _log_throughput(bytes_read: int, tstart: float, final: bool = False) -> None:
    tdiff = max(get_time() - tstart, 1e-9)
    mb = bytes_read >> 20
    if final:
        log_info(f"finish reading at {mb / tdiff:.2f} MB/sec")
    else:
        log_info(f"{mb}MB read, {mb / tdiff:.2f} MB/sec")


class BasicRowIter(RowBlockIter):
    """Eager full in-memory load (reference basic_row_iter.h)."""

    def __init__(self, parser: Parser) -> None:
        container = RowBlockContainer()
        tstart = get_time()
        bytes_expect = 10 << 20
        while True:
            blocks = parser.parse_next()
            if blocks is None:
                break
            for b in blocks:
                if b.size:
                    container.push_block(b)
            if parser.bytes_read() >= bytes_expect:
                _log_throughput(parser.bytes_read(), tstart)
                bytes_expect += 10 << 20
        _log_throughput(parser.bytes_read(), tstart, final=True)
        self._block = container.to_block()
        self._num_col = container.max_index + 1 if self._block.nnz else 0
        self._served = False
        parser.close()

    def before_first(self) -> None:
        self._served = False

    def next(self) -> Optional[RowBlock]:
        if self._served:
            return None
        self._served = True
        return self._block

    def value(self) -> RowBlock:
        return self._block

    def num_col(self) -> int:
        return self._num_col


class DiskRowIter(RowBlockIter):
    """Parse once → serialized 64MB pages on disk; epochs replay the cache
    via a prefetch thread (reference disk_row_iter.h)."""

    def __init__(
        self,
        parser: Union[Parser, Callable[[], Parser]],
        cache_file: str,
        reuse_cache: bool = True,
    ) -> None:
        """``parser`` may be a factory so the warm-cache path never opens
        (or starts prefetching from) the raw data source at all."""
        self.cache_file = cache_file
        self._num_col = 0
        meta = cache_file + ".meta"
        if not (reuse_cache and self._try_load_meta(meta)):
            p = parser() if callable(parser) else parser
            self._build_cache(p, meta)
            p.close()
            check(
                os.path.exists(cache_file),
                f"failed to build cache file {cache_file}",
            )
        elif not callable(parser):
            parser.close()
        self._iter: ThreadedIter[RowBlock] = ThreadedIter(
            self._read_pages, max_capacity=2, name="disk-row-iter"
        )

    def _try_load_meta(self, meta: str) -> bool:
        if not (os.path.exists(self.cache_file) and os.path.exists(meta)):
            return False
        try:
            with open(meta, "r") as f:
                self._num_col = int(f.read().strip())
        except (ValueError, OSError):
            return False  # truncated/corrupt meta: rebuild the cache
        return True

    def _build_cache(self, parser: Parser, meta: str) -> None:
        tstart = get_time()
        with FileStream(self.cache_file, "w") as fo:
            container = RowBlockContainer()
            while True:
                blocks = parser.parse_next()
                if blocks is None:
                    break
                for b in blocks:
                    if b.size:
                        container.push_block(b)
                if container.mem_cost_bytes() >= PAGE_SIZE:
                    _log_throughput(parser.bytes_read(), tstart)
                    self._num_col = max(self._num_col, container.max_index + 1)
                    container.save(fo)
                    container.clear()
            if container.size:
                self._num_col = max(self._num_col, container.max_index + 1)
                container.save(fo)
        with open(meta, "w") as f:
            f.write(str(self._num_col))
        _log_throughput(parser.bytes_read(), tstart, final=True)

    def _read_pages(self) -> Iterator[RowBlock]:
        with FileStream(self.cache_file, "r") as fi:
            while True:
                blk = RowBlock.load(fi)
                if blk is None:
                    return
                yield blk

    def before_first(self) -> None:
        self._iter.before_first()

    def next(self) -> Optional[RowBlock]:
        return self._iter.next()

    def num_col(self) -> int:
        return self._num_col

    def close(self) -> None:
        self._iter.destroy()
