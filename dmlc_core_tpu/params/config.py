"""``key = value`` config-file parser.

Reference: include/dmlc/config.h (Config, config.h:40-175) + src/config.cc
tokenizer FSM (config.cc:30-128). Feature parity:

- ``#`` comments to end of line
- quoted string values with escape handling ("\\"", "\\n", "\\\\")
- multi-value mode: repeated keys accumulate instead of overwrite
  (config.h:57-60)
- proto-style string output (config.h:102; ToProtoString)
- iteration in insertion order
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from ..utils.logging import Error

__all__ = ["Config"]


def _tokenize(text: str) -> List[str]:
    """FSM tokenizer over k = v pairs (reference config.cc:30-128)."""
    tokens: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c.isspace():
            i += 1
        elif c == "=":
            tokens.append("=")
            i += 1
        elif c == '"':
            i += 1
            buf = []
            closed = False
            while i < n:
                ch = text[i]
                if ch == "\\" and i + 1 < n:
                    nxt = text[i + 1]
                    buf.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(nxt, nxt))
                    i += 2
                elif ch == '"':
                    i += 1
                    closed = True
                    break
                else:
                    buf.append(ch)
                    i += 1
            if not closed:
                raise Error("Config: unterminated quoted string")
            tokens.append('"' + "".join(buf))  # marker prefix, stripped later
        else:
            j = i
            while j < n and not text[j].isspace() and text[j] not in "=#":
                j += 1
            tokens.append(text[i:j])
            i = j
    return tokens


class Config:
    """Ordered key=value config with optional multi-value semantics."""

    def __init__(self, text: str = "", multi_value: bool = False) -> None:
        self.multi_value = multi_value
        self._order: List[Tuple[str, str]] = []
        self._map: Dict[str, List[str]] = {}
        if text:
            self.load(text)

    def load(self, text: str) -> None:
        tokens = _tokenize(text)
        for i in range(0, len(tokens), 3):
            key = tokens[i]
            if key == "=" or key.startswith('"'):
                raise Error(f"Config: invalid key {key!r}")
            if i + 2 >= len(tokens) or tokens[i + 1] != "=":
                raise Error(f"Config: expected 'key = value' near {key!r}")
            val = tokens[i + 2]
            if val == "=":
                raise Error(f"Config: invalid value '=' for key {key!r}")
            if val.startswith('"'):
                val = val[1:]
            self.set(key, val)

    def set(self, key: str, value: str) -> None:
        value = str(value)
        if key in self._map and not self.multi_value:
            # overwrite: drop previous from order
            self._order = [(k, v) for (k, v) in self._order if k != key]
            self._map[key] = [value]
        else:
            self._map.setdefault(key, [] if self.multi_value else [])
            if self.multi_value:
                self._map[key].append(value)
            else:
                self._map[key] = [value]
        self._order.append((key, value))

    def get(self, key: str) -> str:
        """Latest value for key (reference GetParam, config.h:70-76)."""
        vals = self._map.get(key)
        if not vals:
            raise Error(f"Config: key {key!r} not found")
        return vals[-1]

    def get_all(self, key: str) -> List[str]:
        return list(self._map.get(key, []))

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        """Iterate (key, value) in insertion order (reference iterator,
        config.h:110-150)."""
        return iter(self._order)

    def to_proto_string(self) -> str:
        """proto-style 'key : "value"' lines (reference ToProtoString,
        config.h:102)."""
        out = []
        for key, val in self._order:
            esc = val.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
            out.append(f'{key} : "{esc}"')
        return "\n".join(out) + ("\n" if out else "")
