"""Declarative, self-documenting parameter structs.

TPU-native rethink of the reference Parameter module (reference:
include/dmlc/parameter.h). The reference does struct reflection without RTTI
via byte offsets (parameter.h:628-650); in Python the natural mechanism is a
metaclass collecting ``field()`` descriptors. Feature parity:

- declare fields with type, default, range, enum values, aliases
  (DMLC_DECLARE_FIELD + set_default/set_range/add_enum/set_lower_bound,
  reference parameter.h:658-704,766-782)
- ``init(kwargs)`` with unknown-arg policies and "did you mean" suggestions
  (reference parameter.h:140-165,395-435,511-545)
- ``to_dict`` / ``update`` (__DICT__, reference parameter.h:181-190)
- JSON save/load (reference parameter.h:190-202)
- docstring generation (__DOC__, reference parameter.h:214-218 and
  doc/parameter.md)
- typed env access lives in utils.env (reference parameter.h:1068-1096)

Parser params (libsvm/csv/libfm) and launcher opts build on this, exactly as
in the reference (SURVEY §5.6).
"""

from __future__ import annotations

import difflib
import json
from typing import Any, Dict, List, Optional, Sequence, Type

from ..utils.common import parse_bool
from ..utils.logging import Error

__all__ = ["field", "Parameter", "ParamError"]


class ParamError(Error):
    """Raised on bad parameter values/unknown keys (reference throws dmlc::Error)."""


class field:
    """A declared parameter field (reference FieldEntry, parameter.h:569-800).

    Supported types: bool, int, float, str, and optional variants (allow
    None default, like dmlc::optional fields).
    """

    __slots__ = (
        "type",
        "default",
        "help",
        "lower",
        "upper",
        "enum",
        "aliases",
        "name",
        "required",
    )

    def __init__(
        self,
        type: Type,
        default: Any = None,
        help: str = "",
        lower: Any = None,
        upper: Any = None,
        enum: Optional[Dict[str, Any]] = None,
        aliases: Sequence[str] = (),
        required: bool = False,
    ) -> None:
        self.type = type
        self.default = default
        self.help = help
        self.lower = lower
        self.upper = upper
        # enum maps string name -> stored value (reference add_enum,
        # parameter.h:766-782, stores int; we allow any value type).
        self.enum = dict(enum) if enum else None
        self.aliases = tuple(aliases)
        self.required = required
        self.name = ""  # filled by the metaclass

    # -- value coercion & checking ------------------------------------------
    def coerce(self, value: Any) -> Any:
        """str→typed conversion mirroring the reference's istream-based Set
        (parameter.h:588-607) plus enum lookup."""
        if self.enum is not None:
            if isinstance(value, str) and value in self.enum:
                value = self.enum[value]
            elif value not in self.enum.values():
                raise ParamError(
                    f"Invalid value {value!r} for parameter {self.name}; "
                    f"expected one of {sorted(self.enum)}"
                )
            return value
        if value is None:
            return None
        if isinstance(value, str) and value == "None" and self.default is None:
            # optional fields round-trip None as the string "None", mirroring
            # dmlc::optional's "None" stream parsing (reference optional.h:205).
            return None
        ty = self.type
        try:
            if ty is bool:
                if isinstance(value, str):
                    return parse_bool(value)
                return bool(value)
            if ty is int:
                if isinstance(value, bool):
                    return int(value)
                if isinstance(value, float) and not value.is_integer():
                    raise ValueError(value)
                return int(value)
            if ty is float:
                return float(value)
            if ty is str:
                return str(value)
            return ty(value)
        except (TypeError, ValueError) as e:
            raise ParamError(
                f"Invalid value {value!r} for parameter {self.name} "
                f"(expected {ty.__name__})"
            ) from e

    def check_range(self, value: Any) -> None:
        """Range enforcement (reference FieldEntryNumeric, parameter.h:658-704)."""
        if value is None:
            return
        if self.lower is not None and value < self.lower:
            raise ParamError(
                f"Parameter {self.name}={value!r} out of range: expected >= {self.lower}"
            )
        if self.upper is not None and value > self.upper:
            raise ParamError(
                f"Parameter {self.name}={value!r} out of range: expected <= {self.upper}"
            )

    def describe(self) -> str:
        """One docstring line (reference FieldAccessEntry description fields)."""
        parts = [f"{self.name} : {self.type.__name__}"]
        if self.enum is not None:
            parts[0] = f"{self.name} : {{{', '.join(sorted(self.enum))}}}"
        if self.required:
            parts.append("required")
        else:
            parts.append(f"default={self.default!r}")
        if self.lower is not None or self.upper is not None:
            lo = self.lower if self.lower is not None else "-inf"
            hi = self.upper if self.upper is not None else "+inf"
            parts.append(f"range=[{lo}, {hi}]")
        head = ", ".join(parts)
        return f"{head}\n    {self.help}" if self.help else head


class _ParameterMeta(type):
    def __new__(mcls, name, bases, ns):
        fields: Dict[str, field] = {}
        for base in bases:
            fields.update(getattr(base, "__fields__", {}))
        for key, val in list(ns.items()):
            if isinstance(val, field):
                val.name = key
                fields[key] = val
                ns.pop(key)
        ns["__fields__"] = fields
        alias_map: Dict[str, str] = {}
        for key, f in fields.items():
            for a in f.aliases:
                alias_map[a] = key
        ns["__aliases__"] = alias_map
        return super().__new__(mcls, name, bases, ns)


class Parameter(metaclass=_ParameterMeta):
    """Base class for declarative parameter structs.

    Usage (compare reference example/parameter.cc and doc/parameter.md)::

        class MyParam(Parameter):
            num_hidden = field(int, default=64, lower=1, help="hidden units")
            act = field(str, default="relu", enum={"relu": "relu", "tanh": "tanh"})

        p = MyParam(num_hidden=128)
        leftover = p.init({"num_hidden": "256", "foo": 1}, allow_unknown=True)
    """

    __fields__: Dict[str, field] = {}
    __aliases__: Dict[str, str] = {}

    def __init__(self, **kwargs: Any) -> None:
        object.__setattr__(self, "_set_fields", set())
        for key, f in self.__fields__.items():
            object.__setattr__(self, key, f.default)
        if kwargs:
            self.init(kwargs)

    # -- core init ----------------------------------------------------------
    def init(
        self,
        kwargs: Dict[str, Any],
        allow_unknown: bool = False,
    ) -> Dict[str, Any]:
        """Set fields from kwargs; returns unknown entries.

        Mirrors Parameter::Init / InitAllowUnknown (reference
        parameter.h:140-165). Unknown keys raise with a near-miss suggestion
        (reference FindAlias/suggestion logic, parameter.h:511-545) unless
        ``allow_unknown``.
        """
        unknown: Dict[str, Any] = {}
        seen = set()
        for key, value in kwargs.items():
            canon = self.__aliases__.get(key, key)
            f = self.__fields__.get(canon)
            if f is None:
                if allow_unknown:
                    unknown[key] = value
                    continue
                hint = difflib.get_close_matches(key, list(self.__fields__), n=1)
                suggest = f" Did you mean {hint[0]!r}?" if hint else ""
                raise ParamError(
                    f"Unknown parameter {key!r} for {type(self).__name__}.{suggest}"
                )
            val = f.coerce(value)
            f.check_range(val)
            object.__setattr__(self, canon, val)
            seen.add(canon)
        self._set_fields.update(seen)
        for key, f in self.__fields__.items():
            if f.required and key not in self._set_fields:
                raise ParamError(
                    f"Required parameter {key!r} of {type(self).__name__} not set"
                )
        return unknown

    def __setattr__(self, key: str, value: Any) -> None:
        f = self.__fields__.get(key)
        if f is None:
            raise AttributeError(
                f"{type(self).__name__} has no parameter {key!r}"
            )
        val = f.coerce(value)
        f.check_range(val)
        object.__setattr__(self, key, val)
        self._set_fields.add(key)

    # -- reflection ---------------------------------------------------------
    def to_dict(self) -> Dict[str, str]:
        """__DICT__: everything stringified (reference parameter.h:181-190)."""
        out = {}
        for key, f in self.__fields__.items():
            val = getattr(self, key)
            if f.enum is not None:
                for name, ev in f.enum.items():
                    if ev == val:
                        val = name
                        break
            out[key] = str(val)
        return out

    def update(self, other: Dict[str, Any]) -> None:
        self.init(dict(other), allow_unknown=False)

    def save_json(self) -> str:
        """JSON round-trip (reference Parameter::Save, parameter.h:190-196)."""
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def load_json(self, text: str) -> None:
        """Reference Parameter::Load (parameter.h:197-202)."""
        self.init(json.loads(text))

    @classmethod
    def doc(cls) -> str:
        """__DOC__ docstring generation (reference parameter.h:214-218)."""
        lines = [f"Parameters of {cls.__name__}", "-" * (14 + len(cls.__name__))]
        for key in cls.__fields__:
            lines.append(cls.__fields__[key].describe())
        return "\n".join(lines)

    @classmethod
    def field_names(cls) -> List[str]:
        return list(cls.__fields__)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Parameter):
            return NotImplemented
        return type(self) is type(other) and all(
            getattr(self, k) == getattr(other, k) for k in self.__fields__
        )

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in self.__fields__)
        return f"{type(self).__name__}({inner})"
