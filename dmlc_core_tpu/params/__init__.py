"""Declarative parameters, registries, and config files (reference:
include/dmlc/parameter.h, registry.h, config.h)."""

from .parameter import Parameter, field, ParamError  # noqa: F401
from .registry import Registry, RegistryEntry  # noqa: F401
from .config import Config  # noqa: F401
