"""Named-factory registry: the plugin system.

Reference: include/dmlc/registry.h. The reference keeps one mutex-guarded
singleton Registry<EntryType> per entry type (registry.h:26-126) with fluent
metadata on entries (FunctionRegEntryBase, registry.h:150-226) and macro
registration (DMLC_REGISTRY_ENABLE/REGISTER, registry.h:234-252). Python
import side effects replace the static-initializer FILE_TAG/LINK_TAG trick
(registry.h:263-308).

Parsers, filesystems, splitters, launcher backends all register here.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

from ..utils.logging import Error

T = TypeVar("T")

__all__ = ["Registry", "RegistryEntry"]


class RegistryEntry(Generic[T]):
    """Entry with fluent metadata (reference FunctionRegEntryBase,
    registry.h:150-226)."""

    def __init__(self, name: str, body: Callable[..., T]) -> None:
        self.name = name
        self.body = body
        self.description = ""
        self.arguments: List[Dict[str, str]] = []
        self.return_type = ""

    def describe(self, description: str) -> "RegistryEntry[T]":
        self.description = description
        return self

    def add_argument(self, name: str, type: str, description: str) -> "RegistryEntry[T]":
        self.arguments.append(
            {"name": name, "type": type, "description": description}
        )
        return self

    def set_return_type(self, t: str) -> "RegistryEntry[T]":
        self.return_type = t
        return self

    def __call__(self, *args: Any, **kwargs: Any) -> T:
        return self.body(*args, **kwargs)


class Registry(Generic[T]):
    """Name → factory registry (reference Registry<T>, registry.h:26-126).

    Instantiate one per plugin kind::

        PARSER_REGISTRY = Registry("parser")

        @PARSER_REGISTRY.register("libsvm")
        def make_libsvm(source, params): ...
    """

    _instances: Dict[str, "Registry"] = {}
    _instances_lock = threading.Lock()

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self._lock = threading.Lock()
        self._entries: Dict[str, RegistryEntry[T]] = {}
        with Registry._instances_lock:
            if kind in Registry._instances:
                raise Error(f"Registry {kind!r} already exists; use Registry.get()")
            Registry._instances[kind] = self

    @classmethod
    def get(cls, kind: str) -> "Registry":
        """Singleton access (reference Registry::Get, registry.h:235-241)."""
        with cls._instances_lock:
            reg = cls._instances.get(kind)
        if reg is None:
            raise Error(f"No registry of kind {kind!r}")
        return reg

    def register(
        self, name: str, override: bool = False
    ) -> Callable[[Callable[..., T]], Callable[..., T]]:
        """Decorator form of __REGISTER__ (reference registry.h:89-105)."""

        def deco(body: Callable[..., T]) -> Callable[..., T]:
            self.add(name, body, override=override)
            return body

        return deco

    def add(
        self, name: str, body: Callable[..., T], override: bool = False
    ) -> RegistryEntry[T]:
        with self._lock:
            if name in self._entries and not override:
                raise Error(f"{self.kind} {name!r} already registered")
            entry = RegistryEntry(name, body)
            self._entries[name] = entry
            return entry

    def find(self, name: str) -> Optional[RegistryEntry[T]]:
        """Reference Registry::Find (registry.h:48-56); None when missing."""
        with self._lock:
            return self._entries.get(name)

    def lookup(self, name: str) -> RegistryEntry[T]:
        entry = self.find(name)
        if entry is None:
            raise Error(
                f"Unknown {self.kind} {name!r}; registered: {sorted(self.names())}"
            )
        return entry

    def create(self, name: str, *args: Any, **kwargs: Any) -> T:
        return self.lookup(name)(*args, **kwargs)

    def names(self) -> List[str]:
        """Reference ListAllNames (registry.h:40-46)."""
        with self._lock:
            return list(self._entries)

    def remove(self, name: str) -> None:
        with self._lock:
            self._entries.pop(name, None)
