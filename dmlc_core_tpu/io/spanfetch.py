"""Concurrent ranged span fetcher for remote window loads.

The window loader (io/split.py) plans a shuffle window as coalesced
byte spans and, until this module, read them one ``seek``+``read`` at a
time on a single connection — fine for local disks (the mmap/pread
``_SpanReader`` fast path, which stays untouched), latency-bound on
object stores: remote window fill time was ``span_latency × n_spans``.

``SpanFetcher`` owns a small pool of per-file seekable streams — each
wrapped in ``RetryingReadStream`` so the PR-2 backoff/resume semantics
hold PER CONNECTION — and issues a window's planned spans as parallel
ranged reads:

- **bounded in-flight bytes** (``DMLC_FETCH_INFLIGHT_MB``, default 64):
  the submission loop never commits more than the budget to flight
  (one span is always allowed, so a span larger than the whole budget
  still fetches — serially);
- **cgroup-aware default concurrency** (``DMLC_FETCH_THREADS``; default
  ``min(16, 2 × available_cpus())`` via utils/cpus.py — fetch threads
  park on the network, so they oversubscribe cores 2× but still respect
  a container quota). ``DMLC_FETCH_THREADS=1`` is the serial baseline
  the ``rec_remote_latency`` bench config scores against;
- **adaptive concurrency**: an AIMD ramp — concurrency starts low,
  +1 per evaluation window while delivered bandwidth keeps improving,
  halved when it collapses (the link is saturated and extra streams
  only add contention) — and collapses to 1 when the planned spans are
  byte-contiguous (a single sequential stream is already optimal: no
  seeks, no ranged-request latency to overlap);
- **completion-order delivery** (``fetch_iter``): spans are handed to
  the caller as they land, so the compressed window loader submits each
  span's blocks to the PR-5 decode pool immediately — fetch → decode →
  gather fully overlapped inside one window;
- **in-place reassembly** (``fetch_into``): the uncompressed path hands
  one preallocated window buffer and per-span base offsets; workers
  write each span directly at its planned position — no parts list, no
  join copy.

Byte/order contract: the fetcher changes WHEN bytes arrive, never what
they are — window buffers and epoch order are bit-identical to the
serial path for every shuffle mode and both container formats
(tests/test_split_gather.py, tests/test_faults.py chaos suites).

Telemetry (docs/observability.md): ``io.fetch.inflight_bytes`` gauge,
``io.fetch.concurrency_peak`` gauge, ``io.fetch.span_wait_seconds``
histogram (consumer-side wait per completed span — the remote-read
analogue of ``gather_refill``), ``io.fetch.spans``/``io.fetch.bytes``
counters, and ``io.fetch.reopens`` — remote stream re-establishments
(an ``HttpReadStream.seek()`` to a non-current offset tears the
connection down; a serial-fallback seek storm shows up here). Trace
spans: ``dmlc:span_fetch`` per ranged read on the worker threads (work)
and ``dmlc:fetch_wait`` on the consumer (a WAIT stage in the stall
report — telemetry/tracing.py).

Lint L012 confines thread-pool creation inside ``dmlc_core_tpu/io/`` to
this module and codec.py's decode pool: an ad-hoc executor would bypass
the cgroup-aware sizing and the in-flight byte budget.
"""

from __future__ import annotations

import bisect
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from ..utils.cpus import available_cpus
from ..utils.env import get_env
from ..utils.logging import Error, check
from .retry import RetryingReadStream, RetryPolicy
from .stream import SeekStream

__all__ = [
    "SpanFetcher",
    "count_stream_reopen",
    "fetch_threads",
    "inflight_budget_bytes",
    "iter_file_segments",
    "reopens_total",
]

_REG = _default_registry()
_INFLIGHT = _REG.gauge(
    "io.fetch.inflight_bytes",
    help="span-fetch bytes currently committed to flight",
)
_PEAK = _REG.gauge(
    "io.fetch.concurrency_peak",
    help="max concurrent span fetches observed",
)
_WAIT = _REG.histogram(
    "io.fetch.span_wait_seconds",
    help="consumer wait for the next completed span",
)
_FETCH_SPANS = _REG.counter(
    "io.fetch.spans", help="ranged span reads completed by the fetcher"
)
_FETCH_BYTES = _REG.counter(
    "io.fetch.bytes", help="bytes delivered by the span fetcher"
)
_REOPENS = _REG.counter(
    "io.fetch.reopens",
    help="remote stream connections torn down by a repositioning seek",
)
# same series the split layer ticks (registry get-or-create returns the
# shared counter): a fetcher positioned read IS a seek in the I/O shape
_SEEKS = _REG.counter("io.split.seeks", help="stream seek() calls")


def count_stream_reopen(n: int = 1) -> None:
    """Called by remote streams (io/cloudfs.py HttpReadStream) when a
    ``seek()`` to a non-current offset drops a live connection — the
    next read re-establishes it. Serial-fallback seek storms over HTTP
    backends become visible as this counter racing ``io.split.seeks``."""
    _REOPENS.inc(n)


def reopens_total() -> int:
    """Process-total reopen count (io_stats snapshots delta against it)."""
    return int(_REOPENS.value())


def fetch_threads() -> int:
    """Fetch pool size: ``DMLC_FETCH_THREADS`` wins (1 = the serial
    baseline — the fetcher disengages entirely), else
    ``min(16, 2 × available_cpus())``: fetch threads spend their lives
    parked on the network, so they oversubscribe the usable-CPU count
    (affinity/cgroup-quota aware, utils/cpus.py) 2×, capped where more
    connections stop helping any single object store."""
    env = get_env("DMLC_FETCH_THREADS", 0)
    if env > 0:
        return env
    return max(2, min(16, 2 * available_cpus()))


def inflight_budget_bytes() -> int:
    """In-flight byte budget (``DMLC_FETCH_INFLIGHT_MB``, default 64):
    bounds fetch memory no matter how wide the concurrency ramps."""
    return max(1, get_env("DMLC_FETCH_INFLIGHT_MB", 64)) << 20


def iter_file_segments(
    file_offset: List[int], n_files: int, offset: int, size: int
) -> Iterator[Tuple[int, int, int, int]]:
    """Walk the per-file segments covering absolute dataset range
    ``[offset, offset + size)``: yields ``(file_ptr, rel_offset, take,
    out_base)`` per segment. The ONE copy of the boundary arithmetic
    every span read shares (``_SpanReader.read``/``readinto`` and the
    fetcher workers) — callers perform the I/O primitive and stop
    iterating on a short segment."""
    written = 0
    while written < size:
        fp = bisect.bisect_right(file_offset, offset) - 1
        if fp >= n_files:
            return
        avail = file_offset[fp + 1] - offset
        if avail <= 0:
            return
        take = min(size - written, avail)
        yield fp, offset - file_offset[fp], take, written
        written += take
        offset += take


# AIMD evaluation window: completions per bandwidth sample
_AIMD_WINDOW = 8
# ramp thresholds, deliberately asymmetric: +1 stream while delivered
# bandwidth holds (a plateau means latency still dominates — more
# overlap can only help, and the pool cap + byte budget bound the
# overshoot), halve only on a GENUINE collapse (>60% down — a
# saturated or thrashing link). Samples are per-window and latency
# spikes land stochastically, so twitchier thresholds (e.g. halve at
# -30%) read one unlucky spike burst as saturation and give back most
# of the overlap win mid-drain.
_AIMD_UP = 0.9
_AIMD_DOWN = 0.4


class SpanFetcher:
    """Parallel positioned reads over a split's file table, by absolute
    dataset offset (spans may cross file boundaries — the index is
    global, mirroring ``_SpanReader``).

    One fetcher serves one splitter; the window loader calls it from
    the readahead thread, one batch of spans at a time. Streams are
    pooled per file on a free-list — a worker acquires a connection,
    seeks (contiguous reuse is a no-op seek), reads its span, and
    returns the connection for the next span that lands nearby.
    """

    def __init__(
        self,
        files,
        file_offset: List[int],
        filesys,
        threads: Optional[int] = None,
        inflight_bytes: Optional[int] = None,
    ) -> None:
        self._files = files
        self._file_offset = file_offset
        self._filesys = filesys
        self._threads = max(1, threads if threads else fetch_threads())
        self._budget = (
            inflight_bytes if inflight_bytes else inflight_budget_bytes()
        )
        self._pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()
        self._free: Dict[int, List[SeekStream]] = {}
        self._closed = False
        # AIMD state: current target concurrency + last sampled bandwidth
        self._target = min(2, self._threads)
        self._last_bw = 0.0
        self._win_bytes = 0
        self._win_done = 0
        self._win_t0 = 0.0
        # I/O-shape counters (io_stats plumbing)
        self.spans = 0
        self.bytes = 0
        self.seeks = 0
        self.concurrency_peak = 0

    # -- stream pool ---------------------------------------------------------
    def _open_stream(self, fp: int) -> SeekStream:
        path = self._files[fp].path
        fs = self._filesys

        def open_inner() -> SeekStream:
            s = fs.open(path, "r")
            check(
                isinstance(s, SeekStream), "input files must be seekable"
            )
            return s  # type: ignore[return-value]

        # one RetryPolicy per CONNECTION: its cumulative backoff budget
        # bounds a single limping stream, not the whole window
        return RetryingReadStream(open_inner, policy=RetryPolicy())

    def _acquire(self, fp: int) -> SeekStream:
        with self._lock:
            free = self._free.get(fp)
            if free:
                return free.pop()
        return self._open_stream(fp)

    def _release(self, fp: int, stream: SeekStream) -> None:
        with self._lock:
            if not self._closed:
                self._free.setdefault(fp, []).append(stream)
                return
        # a worker finishing after close(): the free-list snapshot is
        # gone, so pooling would leak the connection — close it here
        try:
            stream.close()
        except (OSError, Error):
            pass

    def _read_span_into(self, begin: int, out: memoryview) -> int:
        """Fill ``out`` with the span at absolute dataset offset
        ``begin``; returns bytes written. Crosses file boundaries via
        the shared segment walk; each per-file segment is one
        positioned read on a pooled connection."""
        written = 0
        for fp, rel, take, base in iter_file_segments(
            self._file_offset, len(self._files), begin, len(out)
        ):
            stream = self._acquire(fp)
            try:
                if stream.tell() != rel:
                    # pool workers race on this attribute: the lock
                    # keeps the per-splitter io_stats() count exact
                    # next to the thread-sharded registry series
                    with self._lock:
                        self.seeks += 1
                    _SEEKS.inc()
                stream.seek(rel)
                got = 0
                while got < take:
                    data = stream.read(take - got)
                    if not data:
                        break
                    out[base + got : base + got + len(data)] = data
                    got += len(data)
            finally:
                self._release(fp, stream)
            written = base + got
            if got < take:
                break
        return written

    # -- scheduler -----------------------------------------------------------
    def _observe(self, nbytes: int) -> None:
        """AIMD bandwidth sampling: every ``_AIMD_WINDOW`` completions,
        compare delivered bandwidth against the last sample — additive
        increase while it improves, multiplicative decrease when it
        collapses."""
        now = time.perf_counter()
        if self._win_done == 0:
            self._win_t0 = now
        self._win_done += 1
        self._win_bytes += nbytes
        if self._win_done < _AIMD_WINDOW:
            return
        dt = max(now - self._win_t0, 1e-9)
        bw = self._win_bytes / dt
        if self._last_bw <= 0.0 or bw >= self._last_bw * _AIMD_UP:
            self._target = min(self._target + 1, self._threads)
        elif bw < self._last_bw * _AIMD_DOWN:
            self._target = max(1, self._target // 2)
        else:
            self._target = max(1, self._target - 1)
        self._last_bw = bw
        self._win_done = 0
        self._win_bytes = 0

    def _run(
        self,
        spans: List[Tuple[int, int]],
        make_sink: Callable[[int, int], memoryview],
    ) -> Iterator[Tuple[int, memoryview]]:
        """Fetch ``spans`` (``[(begin, nbytes), ...]``) concurrently,
        yielding ``(span_index, filled_view)`` in COMPLETION order.
        ``make_sink(si, nbytes)`` returns the writable view worker
        ``si`` fills (a fresh buffer for ``fetch_iter``, a slice of the
        shared window buffer for ``fetch_into``). Worker errors
        re-raise here (after the in-flight ones drain, so no worker is
        left writing into a buffer the caller discards)."""
        n = len(spans)
        if n == 0:
            return
        contiguous = all(
            spans[i][0] + spans[i][1] == spans[i + 1][0]
            for i in range(n - 1)
        )
        if self._pool is None and not (contiguous or self._threads <= 1):
            self._pool = ThreadPoolExecutor(
                max_workers=self._threads,
                thread_name_prefix="span-fetch",
            )
        if self._pool is None or contiguous or self._threads <= 1:
            # serial fast path: contiguous spans stream best on ONE
            # connection — no seeks to overlap, parallelism would only
            # split a sequential read into racing ranged requests
            for si, (begin, nbytes) in enumerate(spans):
                sink = make_sink(si, nbytes)
                with _tracing.span("dmlc:span_fetch", bytes=nbytes):
                    got = self._read_span_into(begin, sink)
                check(got == nbytes, "span read truncated")
                self.spans += 1
                self.bytes += nbytes
                self.concurrency_peak = max(self.concurrency_peak, 1)
                _FETCH_SPANS.inc()
                _FETCH_BYTES.inc(nbytes)
                yield si, sink
            return

        # fresh bandwidth sample per batch: a partial window carried
        # across _run() calls would fold the consumer's decode/gather
        # time between batches into dt and read a healthy link as a
        # collapse (spurious halving at every batch boundary)
        self._win_done = 0
        self._win_bytes = 0
        out: "queue.SimpleQueue" = queue.SimpleQueue()
        state = {"inflight": 0, "inflight_bytes": 0, "next": 0}

        def worker(si: int, begin: int, nbytes: int) -> None:
            try:
                sink = make_sink(si, nbytes)
                with _tracing.span("dmlc:span_fetch", bytes=nbytes):
                    got = self._read_span_into(begin, sink)
                out.put((si, sink, nbytes, got, None))
            except BaseException as e:  # re-raised on the consumer side
                out.put((si, None, nbytes, 0, e))

        def submit_ready() -> None:
            # contiguous plans never reach here (serial fast path above)
            limit = min(self._target, self._threads)
            while state["next"] < n and state["inflight"] < limit:
                begin, nbytes = spans[state["next"]]
                if (
                    state["inflight"] > 0
                    and state["inflight_bytes"] + nbytes > self._budget
                ):
                    return  # budget full; resubmit as completions land
                si = state["next"]
                state["next"] += 1
                state["inflight"] += 1
                state["inflight_bytes"] += nbytes
                _INFLIGHT.inc(nbytes)
                if state["inflight"] > self.concurrency_peak:
                    self.concurrency_peak = state["inflight"]
                    # high-water mark: the gauge only rises within a
                    # measurement scope, so a later low-concurrency
                    # fetcher can't clobber an earlier fetcher's true
                    # peak — and reset_peak_gauges() rewinds it at
                    # scope boundaries (per bench config)
                    _PEAK.set_max(self.concurrency_peak)
                self._pool.submit(worker, si, begin, nbytes)

        submit_ready()
        done = 0
        error: Optional[BaseException] = None
        try:
            while done < n and (error is None or state["inflight"] > 0):
                t0 = time.perf_counter()
                with _tracing.span("dmlc:fetch_wait"):
                    si, sink, nbytes, got, err = out.get()
                _WAIT.observe(time.perf_counter() - t0)
                done += 1
                state["inflight"] -= 1
                state["inflight_bytes"] -= nbytes
                _INFLIGHT.dec(nbytes)
                if err is not None:
                    error = error or err
                    continue  # drain in-flight workers before raising
                if error is None and got != nbytes:
                    error = Error("span read truncated")
                    continue
                if error is not None:
                    continue
                self.spans += 1
                self.bytes += nbytes
                _FETCH_SPANS.inc()
                _FETCH_BYTES.inc(nbytes)
                self._observe(nbytes)
                submit_ready()
                yield si, sink
            if error is not None:
                raise error
        finally:
            # an abandoned generator (consumer raised mid-iteration)
            # leaves submitted-but-unconsumed spans in flight; settle
            # their gauge contribution here — the orphan workers finish
            # into a dead queue and release their streams normally
            if state["inflight_bytes"]:
                _INFLIGHT.dec(state["inflight_bytes"])
                state["inflight_bytes"] = 0

    # -- public API ----------------------------------------------------------
    def fetch_iter(
        self, spans: List[Tuple[int, int]]
    ) -> Iterator[Tuple[int, memoryview]]:
        """Yield ``(span_index, span_bytes_view)`` in COMPLETION order —
        the compressed window loader hands each landed span's blocks to
        the decode pool immediately, overlapping fetch and decode."""
        return self._run(
            spans, lambda _si, nbytes: memoryview(bytearray(nbytes))
        )

    def fetch_into(
        self,
        spans: List[Tuple[int, int]],
        out: memoryview,
        bases: List[int],
    ) -> None:
        """Fetch every span into ``out`` at its planned base offset
        (disjoint slices — workers write concurrently without overlap);
        blocks until the whole window buffer is assembled."""
        check(len(spans) == len(bases), "spans/bases length mismatch")
        sink = memoryview(out)
        for _ in self._run(
            spans,
            lambda si, nbytes: sink[bases[si] : bases[si] + nbytes],
        ):
            pass

    def close(self) -> None:
        """Release pooled connections and the worker pool WITHOUT
        joining in-flight reads: a stalled remote fetch (orphaned
        readahead window limping through its retry budget) must not
        block the splitter's close — the same contract as
        ``ThreadedIter.destroy``. Workers that finish later find
        ``_closed`` set and close their own streams in ``_release``."""
        with self._lock:
            self._closed = True
            streams = [s for free in self._free.values() for s in free]
            self._free.clear()
        for s in streams:
            try:
                s.close()
            except (OSError, Error):
                pass
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
