"""Host-level shared decoded-block cache: decode once per host, serve
every colocated process.

PR 5 gave each process a bytes-bounded decoded-block LRU (io/codec.py)
and PR 6 made every shuffle mode hammer it through the windowed gather
path — but the cache is per-process: N trainers (or N data-parallel
workers sharing one host) over the same hot corpus fetch and decode the
same blocks N times, multiplying remote-link bytes and decode-pool CPU
by the colocation factor. This module is the tf.data-service-style
fix (Audibert et al., tf.data; Graur et al., Cachew — ROADMAP open item
4): one per-host daemon owns a shared decoded-block store and serves
blocks to any number of client processes.

Architecture
------------
- **Data plane: named shared memory.** Every cached block lives in one
  named POSIX shared-memory segment (``io.shm.ShmSegment``, the
  primitive under ``multiprocessing.shared_memory`` without its
  resource-tracker coupling — shared with the dsserve same-host
  transport), so a cache hit is a zero-copy mapped view of the decoded
  bytes — the socket never carries payload.
  ``BlockCacheClient.get_view`` hands out the leased mapping itself;
  ``get`` copies out of it (one memcpy at RAM speed, still no decode
  and no remote fetch).
- **Control plane: UNIX-domain socket, length-prefixed JSON frames**
  (4-byte LE length + UTF-8 JSON — the rendezvous protocol's framing
  idiom with JSON in place of the raw string payload). Ops: ``lookup``
  (grants a lease), ``release``, ``publish`` (adopt a client-written
  segment), ``stats``, ``flush``, ``ping``.
- **Content addressing.** Keys are the PR-5 cache identities (file set
  path+size+mtime_ns/etag + total size + block-layout digest + block
  file offset) flattened to a sha1 hex string
  (``codec.wire_block_key``), so two processes over the same file set
  agree on identity and an in-place rewrite can never serve stale
  bytes.
- **Leases gate eviction.** ``lookup`` grants a lease; LRU eviction and
  ``flush`` skip leased entries, so a mapped view is never unlinked
  under a reader. Leases auto-release when the owning connection drops
  (a crashed reader cannot wedge eviction).
- **Publish races resolve to one winner.** Both racers decode, both
  publish; the first segment is adopted, the loser is told
  ``duplicate`` and unlinks its own copy — and its next lookup hits.
- **Admission control + per-tenant quotas.** A block larger than the
  tenant budget is rejected outright; a full tenant evicts its own LRU
  unleased entries first, so one greedy job cannot flush another
  tenant's working set.

Graceful fallback: clients make ONE connect attempt per process and
cache the negative result (``default_client``); any socket error marks
the client dead. Every caller treats a dead/absent daemon as a plain
miss, so with no daemon (or one killed mid-read) the two-level lookup
in ``codec.DecodeContext`` degrades to PR-5 in-process behavior with no
error surfaced to the iterator.

Env knobs: ``DMLC_BLOCK_CACHE`` (``off``/``0`` force-disables the
client tier), ``DMLC_BLOCK_CACHE_SOCK`` (socket path; default
``$TMPDIR/dmlc-blockcache-<uid>.sock``), ``DMLC_BLOCK_CACHE_MB``
(daemon budget, default 1024), ``DMLC_BLOCK_CACHE_TENANT_MB``
(per-tenant quota, default the whole budget),
``DMLC_BLOCK_CACHE_TENANT`` (client tenant label, default
``$DMLC_JOB_ID`` then ``default``).

Telemetry (docs/observability.md): ``io.blockcache.{hits,misses,
publishes,evictions,leases,bytes}`` — counters/gauges labeled
``tenant=...``; the daemon ticks the authoritative set on its own
registry (served on ``/metrics`` when ``metrics_port`` is given), and
each client mirrors its own hits/misses/publishes/bytes_from_cache so
per-process exporters show the shared-tier win.

Lint L010 makes this file (with io/lookup.py) the only raw ``socket``
site inside ``dmlc_core_tpu/io/``; segment construction itself lives in
``io/shm.py`` (lint L019) — the same single-site pattern as L006
(urlopen), L008 (time.time), L009 (compression).

CLI: ``python -m dmlc_core_tpu.tools cached serve|stats|flush`` —
docs/tools.md; ``dmlc-submit --block-cache`` starts one daemon per host
(tracker/backends/local.py).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import socket
import struct
import tempfile
import threading
import weakref
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from ..utils.env import get_env
from ..utils.logging import Error, check
from .shm import ShmSegment as _ShmSegment

__all__ = [
    "BlockCacheClient",
    "BlockCacheDaemon",
    "LeasedView",
    "default_client",
    "default_sock_path",
    "reset_default_client",
]

logger = logging.getLogger("dmlc_core_tpu.io.blockcache")

#: segment names are (pid, ordinal) — the ordinal is PROCESS-global so
#: two clients in one process can never mint the same name
_NAME_SEQ = itertools.count(1)

#: control frames are metadata only (payload rides shared memory) —
#: anything larger is a corrupt or hostile peer, not a real message
MAX_FRAME = 1 << 20

_REG = _default_registry()


def _tick(name: str, tenant: str, n: float = 1) -> None:
    _REG.counter(f"io.blockcache.{name}", labels={"tenant": tenant}).inc(n)


def _gauge(name: str, tenant: str):
    return _REG.gauge(f"io.blockcache.{name}", labels={"tenant": tenant})


def default_sock_path() -> str:
    """Rendezvous point for one daemon per (host, uid):
    ``DMLC_BLOCK_CACHE_SOCK`` wins, else a uid-scoped name under the
    system temp dir — colocated processes of one user meet at the same
    daemon with zero launcher plumbing."""
    env = os.environ.get("DMLC_BLOCK_CACHE_SOCK", "")
    if env:
        return env
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(
        tempfile.gettempdir(), f"dmlc-blockcache-{uid}.sock"
    )


def default_tenant() -> str:
    """Quota/telemetry identity of this process's cache traffic."""
    return (
        os.environ.get("DMLC_BLOCK_CACHE_TENANT")
        or os.environ.get("DMLC_JOB_ID")
        or "default"
    )


# -- wire framing -------------------------------------------------------------
def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_all(sock: socket.socket, nbytes: int) -> bytes:
    chunks = []
    nread = 0
    while nread < nbytes:
        chunk = sock.recv(min(nbytes - nread, 65536))
        if not chunk:
            raise ConnectionError("peer closed during recv")
        chunks.append(chunk)
        nread += len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> dict:
    (n,) = struct.unpack("<I", _recv_all(sock, 4))
    if n > MAX_FRAME:
        raise ConnectionError(f"oversized control frame ({n} bytes)")
    return json.loads(_recv_all(sock, n).decode())


# -- daemon -------------------------------------------------------------------
class _Entry:
    __slots__ = ("shm", "size", "tenant", "leases")

    def __init__(self, shm: _ShmSegment, size: int, tenant: str) -> None:
        self.shm = shm
        self.size = size
        self.tenant = tenant
        self.leases = 0


class BlockCacheDaemon:
    """The per-host cache service: one shared decoded-block store, any
    number of client processes.

    ``start()`` binds the UNIX socket and serves on daemon threads;
    ``close()`` stops the service and unlinks every owned segment.
    ``serve_forever()`` blocks (the CLI's foreground mode). Thread-safe
    throughout — one lock guards the store; shm reads/writes happen in
    the clients, never under it.
    """

    def __init__(
        self,
        sock_path: Optional[str] = None,
        max_bytes: Optional[int] = None,
        tenant_max_bytes: Optional[int] = None,
        metrics_port: int = 0,
    ) -> None:
        self.sock_path = sock_path or default_sock_path()
        self.max_bytes = (
            max_bytes
            if max_bytes is not None
            else get_env("DMLC_BLOCK_CACHE_MB", 1024) * (1 << 20)
        )
        self.tenant_max_bytes = (
            tenant_max_bytes
            if tenant_max_bytes is not None
            else get_env("DMLC_BLOCK_CACHE_TENANT_MB", 0) * (1 << 20)
        ) or self.max_bytes
        check(self.max_bytes > 0, "block cache budget must be positive")
        self.metrics_port = metrics_port
        self._lock = threading.Lock()
        self._store: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self._tenant_bytes: Dict[str, int] = {}
        self._leases: Dict[int, str] = {}  # lease id -> key
        self._lease_seq = itertools.count(1)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._metrics_server = None
        self._conns: set = set()  # live client sockets (severed on close)
        self._closed = threading.Event()
        self.hits = 0
        self.misses = 0
        self.publishes = 0
        self.evictions = 0
        self.rejected = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "BlockCacheDaemon":
        check(self._sock is None, "daemon already started")
        if os.path.exists(self.sock_path):
            # stale socket files survive a SIGKILL'd daemon; a LIVE one
            # answers a connect — refuse to fight it for the path
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                probe.settimeout(0.5)
                probe.connect(self.sock_path)
            except OSError:
                os.unlink(self.sock_path)
            else:
                probe.close()
                raise Error(
                    f"a block-cache daemon is already serving "
                    f"{self.sock_path!r}"
                )
            finally:
                probe.close()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.sock_path)
        srv.listen(64)
        self._sock = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="blockcache-accept"
        )
        self._accept_thread.start()
        if self.metrics_port:
            self._metrics_server = _serve_daemon_metrics(
                self, self.metrics_port
            )
        logger.info(
            "block-cache daemon serving %s (budget %d MB)",
            self.sock_path, self.max_bytes >> 20,
        )
        return self

    def serve_forever(self) -> None:
        """Block until ``close()`` (foreground CLI mode)."""
        if self._sock is None:
            self.start()
        self._closed.wait()

    def close(self) -> None:
        """Stop serving and unlink every owned segment. Idempotent."""
        if self._closed.is_set():
            return
        self._closed.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # sever live client connections too — a closed daemon must look
        # exactly like a killed one (clients mark themselves dead and
        # fall back in-process), not like an eternally-missing store
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._metrics_server is not None:
            try:
                self._metrics_server.shutdown()
                self._metrics_server.server_close()
            except Exception:
                pass
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
        with self._lock:
            for key in list(self._store):
                self._drop(key, unlink=True)

    # -- store (call under self._lock) ---------------------------------------
    def _drop(self, key: str, unlink: bool) -> None:
        e = self._store.pop(key)
        self._bytes -= e.size
        self._tenant_bytes[e.tenant] = (
            self._tenant_bytes.get(e.tenant, 0) - e.size
        )
        _gauge("bytes", e.tenant).set(
            max(self._tenant_bytes.get(e.tenant, 0), 0)
        )
        try:
            e.shm.close()
        except (OSError, BufferError):
            pass
        if unlink:
            try:
                e.shm.unlink()
            except OSError:
                pass

    def _evict_one(self, tenant: Optional[str]) -> bool:
        """Evict the LRU UNLEASED entry (of ``tenant`` when given);
        False when everything eligible is leased — a mapped view is
        never unlinked under a reader."""
        for key, e in self._store.items():
            if e.leases == 0 and (tenant is None or e.tenant == tenant):
                t = e.tenant
                size = e.size
                self._drop(key, unlink=True)
                self.evictions += 1
                _tick("evictions", t)
                # instants, not spans: an eviction is a moment on the
                # daemon timeline, and WHEN they cluster is the story
                _tracing.instant(
                    "dmlc:blockcache_evict", tenant=t, bytes=size
                )
                return True
        return False

    def _admit(self, tenant: str, size: int) -> bool:
        if size > self.max_bytes or size > self.tenant_max_bytes:
            return False  # admission: larger than any budget it rides
        while self._bytes + size > self.max_bytes:
            if not self._evict_one(None):
                return False
        while self._tenant_bytes.get(tenant, 0) + size > (
            self.tenant_max_bytes
        ):
            if not self._evict_one(tenant):
                return False
        return True

    # -- request handlers ----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="blockcache-conn",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        held: set = set()  # lease ids granted to THIS connection
        with self._lock:
            self._conns.add(conn)
        try:
            while True:
                try:
                    req = _recv_frame(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                try:
                    resp = self._handle(req, held)
                except Exception as e:  # one bad request, not the daemon
                    logger.exception("block-cache request failed")
                    resp = {"ok": False, "error": str(e)}
                if resp is None or req.get("oneway"):
                    # no reply to a one-way request EVEN on error: an
                    # unexpected frame would be consumed as the reply
                    # to the peer's next request, desyncing the stream
                    continue
                try:
                    _send_frame(conn, resp)
                except OSError:
                    return
        finally:
            # a dropped connection releases its leases — a crashed
            # reader must not wedge eviction forever
            with self._lock:
                self._conns.discard(conn)
                for lease in held:
                    self._release_lease(lease)
            try:
                conn.close()
            except OSError:
                pass

    def _release_lease(self, lease: int) -> None:
        key = self._leases.pop(lease, None)
        if key is None:
            return
        e = self._store.get(key)
        if e is not None and e.leases > 0:
            e.leases -= 1
            _gauge("leases", e.tenant).inc(-1)

    def _lookup_one(self, key: str, tenant: str, held: set) -> dict:
        """Single-key lookup under self._lock; grants a lease on hit."""
        e = self._store.get(key)
        if e is None:
            self.misses += 1
            _tick("misses", tenant)
            return {"hit": False}
        self._store.move_to_end(key)
        lease = next(self._lease_seq)
        e.leases += 1
        self._leases[lease] = key
        held.add(lease)
        self.hits += 1
        _tick("hits", tenant)
        _gauge("leases", e.tenant).inc(1)
        return {
            "hit": True, "shm": e.shm.name, "size": e.size, "lease": lease,
        }

    def _handle(self, req: dict, held: set) -> Optional[dict]:
        # per-op HANDLER span on the daemon's connection thread: the
        # merged timeline shows lookup/publish/flush service time next
        # to the client windows waiting on them (op names are a bounded
        # set), with a flow arrow from the requesting span (the "tc"
        # trace context the client piggybacks on the control frame)
        with _tracing.handler_span(
            f"dmlc:blockcache_{req.get('op')}", req.get("tc")
        ):
            return self._handle_inner(req, held)

    def _handle_inner(self, req: dict, held: set) -> Optional[dict]:
        op = req.get("op")
        tenant = str(req.get("tenant") or "default")
        if op == "ping":
            return {"ok": True, "pid": os.getpid()}
        if op == "lookup":
            with self._lock:
                out = self._lookup_one(str(req.get("key")), tenant, held)
            out["ok"] = True
            return out
        if op == "lookup_many":
            # one round trip serves a whole window/batch of blocks —
            # per-block RTTs would eat the decode win on small blocks
            keys = [str(k) for k in req.get("keys", ())]
            with self._lock:
                results = [
                    self._lookup_one(k, tenant, held) for k in keys
                ]
            return {"ok": True, "results": results}
        if op == "release":
            leases = req.get("leases")
            if leases is None:
                leases = [req.get("lease", 0)]
            with self._lock:
                for lease in leases:
                    lease = int(lease)
                    if lease not in held:
                        # only the granting connection may release: a
                        # buggy/hostile peer guessing small sequential
                        # ids must not void ANOTHER reader's
                        # never-unlinked-under-a-reader protection
                        continue
                    self._release_lease(lease)
                    held.discard(lease)
            # releases are fire-and-forget (oneway): the reply would be
            # a pure RTT tax on every cache hit
            return None if req.get("oneway") else {"ok": True}
        if op == "publish":
            key = str(req.get("key"))
            size = int(req.get("size", 0))
            name = str(req.get("shm"))
            with self._lock:
                if key in self._store:
                    # the race's loser: a copy already serves this key
                    self._store.move_to_end(key)
                    return {"ok": True, "adopted": False,
                            "reason": "duplicate"}
                if not self._admit(tenant, size):
                    self.rejected += 1
                    return {"ok": True, "adopted": False, "reason": "quota"}
                try:
                    shm = _ShmSegment(name)
                except (OSError, ValueError) as e:
                    return {"ok": False, "error": f"cannot adopt: {e}"}
                self._store[key] = _Entry(shm, size, tenant)
                self._bytes += size
                self._tenant_bytes[tenant] = (
                    self._tenant_bytes.get(tenant, 0) + size
                )
                self.publishes += 1
                _tick("publishes", tenant)
                _tick("bytes_published", tenant, size)
                _gauge("bytes", tenant).set(self._tenant_bytes[tenant])
                return {"ok": True, "adopted": True}
        if op == "stats":
            return {"ok": True, "stats": self.stats()}
        if op == "flush":
            with self._lock:
                n = 0
                while self._evict_one(None):
                    n += 1
            return {"ok": True, "evicted": n}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def stats(self) -> dict:
        with self._lock:
            tenants = {}
            leased = 0
            for e in self._store.values():
                t = tenants.setdefault(
                    e.tenant, {"entries": 0, "bytes": 0, "leases": 0}
                )
                t["entries"] += 1
                t["bytes"] += e.size
                t["leases"] += e.leases
                leased += e.leases
            return {
                "pid": os.getpid(),
                "sock": self.sock_path,
                "entries": len(self._store),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "tenant_max_bytes": self.tenant_max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "publishes": self.publishes,
                "evictions": self.evictions,
                "rejected": self.rejected,
                "active_leases": leased,
                "tenants": tenants,
            }


def _serve_daemon_metrics(daemon: "BlockCacheDaemon", port: int):
    """Daemon self-metrics: the process registry (io.blockcache.* per
    tenant) rendered as Prometheus text on a loopback ``/metrics``
    (the shared single-process exporter, telemetry/export.py)."""
    from ..telemetry.export import serve_metrics_http

    return serve_metrics_http(
        port, registry=_REG, json_provider=daemon.stats,
        name="blockcache-metrics-http",
    )


# -- client -------------------------------------------------------------------
class LeasedView:
    """A leased zero-copy view of one cached block: the mapped shared
    memory itself, valid until ``close()`` (or GC). While the lease is
    held the daemon will not evict/unlink the segment — the
    eviction-under-reader guarantee the concurrency suite pins."""

    def __init__(self, client: "BlockCacheClient", shm, size: int,
                 lease: int) -> None:
        self._shm = shm
        self._size = size
        self._closed = False
        self._finalizer = weakref.finalize(
            self, LeasedView._cleanup, client, shm, lease
        )

    @staticmethod
    def _cleanup(client: "BlockCacheClient", shm, lease: int) -> None:
        try:
            shm.close()
        except (OSError, BufferError):
            pass
        client._release(lease)

    @property
    def view(self) -> memoryview:
        check(not self._closed, "LeasedView is closed")
        return self._shm.buf[: self._size]

    def tobytes(self) -> bytes:
        return bytes(self.view)

    def __len__(self) -> int:
        return self._size

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._finalizer()


class BlockCacheClient:
    """One process's connection to the host daemon.

    Every method degrades to a miss/no-op on ANY failure: the first
    socket error marks the client dead (``alive`` False) and later
    calls return immediately, so a daemon killed mid-run costs nothing
    but the shared tier. Thread-safe — the readahead threads of many
    splits share one connection behind a lock.
    """

    def __init__(self, sock_path: Optional[str] = None,
                 tenant: Optional[str] = None,
                 timeout: float = 5.0) -> None:
        self.sock_path = sock_path or default_sock_path()
        self.tenant = tenant or default_tenant()
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._dead = False
        self.hits = 0
        self.misses = 0
        self.publishes = 0

    @property
    def alive(self) -> bool:
        return not self._dead

    def connect(self) -> bool:
        """One attempt; False (and dead) on failure."""
        with self._lock:
            return self._connect_locked()

    def _connect_locked(self) -> bool:
        if self._sock is not None:
            return True
        if self._dead:
            return False
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self._timeout)
            s.connect(self.sock_path)
            self._sock = s
            return True
        except OSError:
            self._dead = True
            return False

    def _request_ex(
        self, obj: dict, oneway: bool = False
    ) -> Tuple[Optional[dict], bool]:
        """(raw reply | None on transport failure, delivered).
        ``delivered`` is whether the full request frame went out — when
        False the daemon cannot have acted on it (a partial frame drops
        the connection), which is what lets publish() distinguish
        'declined/never seen' (safe to unlink) from 'reply lost'
        (daemon may hold the segment). Error replies come back as-is —
        the caller decides; ``_request`` filters them to None."""
        with self._lock:
            if not self._connect_locked():
                return None, False
            sent = False
            try:
                # causal link: the daemon's per-op handler span binds
                # to whatever span encloses this request (a window
                # loader's miss path, a lookup batch)
                tc = _tracing.rpc_context()
                if tc:
                    obj = {**obj, "tc": tc}
                _send_frame(self._sock, obj)
                sent = True
                if oneway:
                    # sent == succeeded for oneway; shaped like a real
                    # reply so _request's ok-filter treats it as one
                    return {"ok": True}, True
                resp = _recv_frame(self._sock)
            except (OSError, ConnectionError, ValueError):
                self._mark_dead_locked()
                return None, sent
        return resp, True

    def _request(
        self, obj: dict, oneway: bool = False
    ) -> Optional[dict]:
        resp, _delivered = self._request_ex(obj, oneway)
        if resp is not None and not resp.get("ok"):
            logger.debug("block-cache request failed: %s", resp)
            return None
        return resp

    def _mark_dead_locked(self) -> None:
        self._dead = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _release(self, *leases: Optional[int]) -> None:
        live = [int(x) for x in leases if x]
        if live:
            # fire-and-forget: a release reply would tax every hit with
            # a second round trip for a boolean nobody reads
            self._request(
                {"op": "release", "leases": live, "oneway": True},
                oneway=True,
            )

    def _lookup(self, key: str) -> Optional[Tuple[object, int, int]]:
        """(shm, size, lease) for a hit; None otherwise. The lease is
        already held, so the segment cannot vanish before mapping."""
        r = self._request(
            {"op": "lookup", "key": key, "tenant": self.tenant}
        )
        if r is None:
            return None
        if not r.get("hit"):
            self.misses += 1
            _tick("misses", self.tenant)
            return None
        try:
            shm = _ShmSegment(r["shm"])
        except (OSError, ValueError):
            self._release(r.get("lease"))
            self.misses += 1
            _tick("misses", self.tenant)
            return None
        self.hits += 1
        _tick("hits", self.tenant)
        _tick("bytes_from_cache", self.tenant, int(r["size"]))
        return shm, int(r["size"]), int(r["lease"])

    def get(self, key: str) -> Optional[bytes]:
        """Block bytes for ``key``, or None. Copies out of the mapped
        view (no socket copy, no decode) and releases the lease."""
        return self.get_many([key]).get(key)

    #: keys per lookup_many frame — bounds the reply against MAX_FRAME
    _BATCH = 512

    def get_many(self, keys) -> Dict[str, bytes]:
        """Bytes for every cached key among ``keys`` in ONE control
        round trip per ``_BATCH`` (plus a oneway lease release) — the
        bulk-hit path the window loader and batched sequential reads
        ride; per-block round trips would eat the decode win on
        small blocks."""
        keys = list(keys)
        out: Dict[str, bytes] = {}
        for at in range(0, len(keys), self._BATCH):
            chunk = keys[at: at + self._BATCH]
            r = self._request({
                "op": "lookup_many", "keys": chunk, "tenant": self.tenant,
            })
            if r is None:
                self.misses += len(chunk)
                _tick("misses", self.tenant, len(chunk))
                continue  # dead client: later chunks return instantly
            leases = []  # every granted lease, released win or lose
            hit_n = 0
            miss_n = 0
            nbytes = 0
            for key, res in zip(chunk, r.get("results", ())):
                if not res.get("hit"):
                    self.misses += 1
                    miss_n += 1
                    continue
                leases.append(res.get("lease"))
                try:
                    shm = _ShmSegment(res["shm"])
                except (OSError, ValueError):
                    # leased but unmappable (e.g. a racing teardown):
                    # this key yielded no data — it is a MISS in every
                    # counter, and the caller will decode it
                    self.misses += 1
                    miss_n += 1
                    continue
                try:
                    size = int(res["size"])
                    out[key] = bytes(shm.buf[:size])
                    nbytes += size
                    self.hits += 1
                    hit_n += 1
                finally:
                    try:
                        shm.close()
                    except (OSError, BufferError):
                        pass
            if miss_n:
                _tick("misses", self.tenant, miss_n)
            if hit_n:
                _tick("hits", self.tenant, hit_n)
                _tick("bytes_from_cache", self.tenant, nbytes)
            self._release(*leases)
        return out

    def get_view(self, key: str) -> Optional[LeasedView]:
        """Zero-copy leased view of the block, or None; the caller owns
        the lease until ``close()``."""
        got = self._lookup(key)
        if got is None:
            return None
        shm, size, lease = got
        return LeasedView(self, shm, size, lease)

    def publish(self, key: str, data) -> bool:
        """Offer decoded bytes to the host tier: write them into a
        fresh segment and ask the daemon to adopt it. False when the
        daemon is absent, another publisher won the race (its copy now
        serves the key), or admission/quota rejected it — the losing
        segment is unlinked either way."""
        if self._dead:
            return False
        size = len(data)
        if size == 0:
            return False
        try:
            shm = _ShmSegment(
                f"dmlcblk-{os.getpid()}-{next(_NAME_SEQ)}",
                create=True, size=size,
            )
        except (OSError, ValueError):
            return False
        # tri-state: True = adopted, False = safe to unlink (daemon
        # explicitly declined, or the request never reached it), None =
        # outcome UNKNOWN — the full request went out but the reply was
        # lost. Unlinking on unknown would tear down a segment the
        # daemon may have adopted, poisoning that key host-wide (every
        # lookup hits a name no one can map, every re-publish is
        # rejected as duplicate), so the unknown case leaks the segment
        # instead — bounded by the one in-flight publish of a dying
        # connection, and empty whenever the daemon DID adopt.
        adopted: Optional[bool] = False
        try:
            shm.buf[:size] = (
                data
                if isinstance(data, (bytes, bytearray, memoryview))
                else bytes(data)
            )
            r, delivered = self._request_ex({
                "op": "publish", "key": key, "tenant": self.tenant,
                "shm": shm.name, "size": size,
            })
            if r is not None:
                # ANY reply — adopted, declined, or an error — means
                # the daemon does not hold the segment unless it said
                # adopted:true
                adopted = bool(r.get("adopted"))
            elif delivered:
                adopted = None  # reply lost: daemon may hold the name
        finally:
            try:
                shm.close()
            except (OSError, BufferError):  # pragma: no cover
                pass
            if adopted is False:
                try:
                    shm.unlink()
                except OSError:  # pragma: no cover
                    pass
        if adopted:
            self.publishes += 1
            _tick("publishes", self.tenant)
        return bool(adopted)

    def stats(self) -> Optional[dict]:
        r = self._request({"op": "stats"})
        return r["stats"] if r else None

    def flush(self) -> Optional[int]:
        r = self._request({"op": "flush"})
        return int(r["evicted"]) if r else None

    def ping(self) -> bool:
        return self._request({"op": "ping"}) is not None


# -- per-process default client (one attempt, cached outcome) -----------------
_DEFAULT: Optional[BlockCacheClient] = None
_DEFAULT_RESOLVED = False
_DEFAULT_LOCK = threading.Lock()


def default_client() -> Optional[BlockCacheClient]:
    """The process-wide shared-tier client, or None when disabled
    (``DMLC_BLOCK_CACHE=off``) or no daemon answered the ONE connect
    attempt (negative result cached — a missing daemon costs one
    connect() per process, ever). A client that dies later keeps
    returning with ``alive`` False; callers treat it as a miss."""
    global _DEFAULT, _DEFAULT_RESOLVED
    if _DEFAULT_RESOLVED:
        return _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT_RESOLVED:
            return _DEFAULT
        mode = os.environ.get("DMLC_BLOCK_CACHE", "auto").strip().lower()
        if mode in ("off", "0", "false", "no", "disabled"):
            _DEFAULT = None
        else:
            client = BlockCacheClient()
            _DEFAULT = client if client.connect() else None
        _DEFAULT_RESOLVED = True
        return _DEFAULT


def reset_default_client() -> None:
    """Forget the cached connect outcome (tests; a daemon started after
    this process first looked for one)."""
    global _DEFAULT, _DEFAULT_RESOLVED
    with _DEFAULT_LOCK:
        if _DEFAULT is not None:
            _DEFAULT.close()
        _DEFAULT = None
        _DEFAULT_RESOLVED = False
