"""URI parsing and the dataset-option URI sugar.

Reference: io::URI (include/dmlc/io.h:525-559) and io::URISpec
(src/io/uri_spec.h:21-75). A dataset URI can carry per-dataset options and a
cache-file hint::

    gs://bucket/path/train.libsvm?format=libsvm&nthread=4#cachefile

The cache file gets a ``.splitN.partK`` suffix per shard
(reference uri_spec.h:42-75).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

__all__ = ["URI", "URISpec", "uri_int", "rejoin_query"]


def uri_int(
    args: Mapping[str, str],
    key: str,
    default: int,
    minimum: Optional[int] = None,
) -> int:
    """Integer URI option with an error that names the bad parameter.
    ``minimum`` rejects out-of-range values with the same loud error
    (e.g. ``?window=0`` must not silently build a degenerate split)."""
    from ..utils.logging import Error  # local import: logging imports nothing back

    raw = args.get(key)
    if raw is None:
        return default
    try:
        value = int(raw)
    except (TypeError, ValueError):
        raise Error(f"URI option {key}={raw!r} is not an integer") from None
    if minimum is not None and value < minimum:
        raise Error(f"URI option {key}={value} must be >= {minimum}")
    return value


def rejoin_query(args: Mapping[str, str]) -> str:
    """Re-serialize parsed URI args as ``?k=v&...`` ('' when empty) —
    the inverse of URISpec's query parse, shared so option
    serialization cannot drift between call sites."""
    if not args:
        return ""
    return "?" + "&".join(f"{k}={v}" for k, v in args.items())


class URI:
    """protocol/host/path decomposition (reference io.h:525-559).

    ``file:///a/b`` → protocol='file://', host='', path='/a/b'
    ``/a/b``        → protocol='', host='', path='/a/b'
    ``gs://b/k``    → protocol='gs://', host='b', path='/k'
    """

    __slots__ = ("protocol", "host", "path")

    def __init__(self, uri: str) -> None:
        pos = uri.find("://")
        if pos < 0:
            self.protocol = ""
            rest = uri
        else:
            self.protocol = uri[: pos + 3]
            rest = uri[pos + 3 :]
        if self.protocol in ("", "file://"):
            # local paths keep everything as path (reference treats
            # file://host/path host as part of nothing useful)
            self.host = ""
            self.path = rest
        else:
            slash = rest.find("/")
            if slash < 0:
                self.host, self.path = rest, ""
            else:
                self.host, self.path = rest[:slash], rest[slash:]

    @property
    def name(self) -> str:
        """Canonical string form (reference URI::name)."""
        return f"{self.protocol}{self.host}{self.path}"

    def __repr__(self) -> str:
        return f"URI({self.name!r})"


class URISpec:
    """URI + ``?k=v&k2=v2`` args + ``#cachefile`` hint (reference
    src/io/uri_spec.h:21-75)."""

    __slots__ = ("uri", "args", "cache_file")

    def __init__(self, uri: str, part_index: int = 0, num_parts: int = 1) -> None:
        self.args: Dict[str, str] = {}
        self.cache_file = ""
        base = uri
        if "#" in base:
            base, _, cache = base.partition("#")
            if num_parts != 1:
                cache = f"{cache}.split{num_parts}.part{part_index}"
            self.cache_file = cache
        if "?" in base:
            base, _, query = base.partition("?")
            for kv in query.split("&"):
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                self.args[k] = v
        self.uri = base

    def __repr__(self) -> str:
        return f"URISpec(uri={self.uri!r}, args={self.args}, cache={self.cache_file!r})"
