"""FileSystem abstraction with URI-protocol dispatch.

Reference: dmlc::FileSystem (include/dmlc/io.h:582-631), protocol dispatch in
FileSystem::GetInstance (src/io.cc:30-71), LocalFileSystem
(src/io/local_filesys.cc), TemporaryDirectory (include/dmlc/filesystem.h +
src/io/filesys.cc).

Backends register in FS_REGISTRY by protocol. Bundled here:

- ``file://`` / bare paths → LocalFileSystem
- ``mem://``  → MemoryFileSystem (testing stand-in for object stores; the
  reference tests against real S3 — we keep tests hermetic)

Cloud backends (``gs://``, ``s3://``, ``http(s)://``, ``hdfs://``,
``azure://``) register on import of ``cloudfs``.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Dict, List, NamedTuple

from ..params.registry import Registry
from ..utils.logging import Error
from .stream import FileStream, MemoryStream, Stream
from .uri import URI

__all__ = [
    "FileInfo",
    "FileSystem",
    "LocalFileSystem",
    "MemoryFileSystem",
    "TemporaryDirectory",
    "FS_REGISTRY",
]


class FileInfo(NamedTuple):
    """Reference io.h:560-578 (FileInfo: path, size, type).

    ``etag`` extends the reference: the backend's change token when one
    is cheap to surface (S3/GCS/HTTP ETag, WebHDFS modificationTime) —
    "" when the backend has none. The decoded-block cache identity
    folds it in, so an IN-PLACE remote rewrite (same path, same size,
    same block geometry) can never serve stale decoded bytes from a
    cache keyed before the rewrite (io/split.py)."""

    path: str
    size: int
    type: str  # 'file' | 'directory'
    etag: str = ""


FS_REGISTRY: Registry = Registry("filesystem")


class FileSystem:
    """Abstract filesystem (reference io.h:582-631)."""

    def open(self, uri: str, mode: str = "r") -> Stream:
        """Open for read/write/append; read streams are seekable
        (reference OpenForRead, io.h:600-612)."""
        raise NotImplementedError

    def get_path_info(self, uri: str) -> FileInfo:
        raise NotImplementedError

    def list_directory(self, uri: str) -> List[FileInfo]:
        raise NotImplementedError

    def exists(self, uri: str) -> bool:
        try:
            self.get_path_info(uri)
            return True
        except (OSError, Error):
            return False

    def delete(self, uri: str, recursive: bool = False) -> None:
        """Remove a file/object; with ``recursive``, a directory/prefix.

        The reference's FileSystem has no delete — its tests clean up via
        shell — but checkpoint retention (§5.4) needs real deletion on
        every backend a checkpoint can be written to, or remote stores
        accumulate stale steps forever. Raises on unsupported backends.
        """
        raise Error(f"{type(self).__name__} does not support delete")

    def copy(self, src_uri: str, dst_uri: str) -> None:
        """Copy one file/object within this filesystem. The default
        streams the bytes through this process; object-store backends
        override with a server-side copy (S3/GCS PUT + copy-source), so
        the checkpoint tmp-key rename never re-uploads the payload."""
        src = self.open(src_uri, "r")
        try:
            dst = self.open(dst_uri, "w")
            try:
                while True:
                    chunk = src.read(1 << 20)
                    if not chunk:
                        break
                    dst.write(chunk)
            finally:
                dst.close()
        finally:
            src.close()

    def rename(self, src_uri: str, dst_uri: str) -> None:
        """Move a file/object (crash-consistent commit primitive for
        checkpoint._write_atomic's remote tmp-key path). Default is
        copy-then-delete — NOT atomic, but ordered so a crash leaves
        either no destination or a complete one, never a torn one;
        backends with a real rename (WebHDFS op=RENAME) override."""
        self.copy(src_uri, dst_uri)
        self.delete(src_uri)

    def list_directory_recursive(self, uri: str) -> List[FileInfo]:
        """BFS expansion (reference ListDirectoryRecursive,
        src/io/filesys.cc:9-25)."""
        out: List[FileInfo] = []
        queue = [uri]
        while queue:
            cur = queue.pop(0)
            for info in self.list_directory(cur):
                if info.type == "directory":
                    queue.append(info.path)
                else:
                    out.append(info)
        return out

    @staticmethod
    def get_instance(uri: str) -> "FileSystem":
        """Protocol dispatch (reference FileSystem::GetInstance,
        src/io.cc:30-71)."""
        proto = URI(uri).protocol or "file://"
        entry = FS_REGISTRY.find(proto)
        if entry is None:
            # any miss: load the cloud backends (and the fault-injection
            # wrapper) once and re-check, so cloudfs.py / faults.py stay
            # the sources of truth for their protocols
            from . import cloudfs, faults  # noqa: F401 — register backends

            entry = FS_REGISTRY.find(proto)
        if entry is None:
            raise Error(
                f"unknown filesystem protocol {proto!r} in {uri!r}; "
                f"registered: {sorted(FS_REGISTRY.names())}"
            )
        return entry()


class LocalFileSystem(FileSystem):
    """Reference src/io/local_filesys.cc. Singleton via registry body."""

    _instance: "LocalFileSystem" = None  # type: ignore[assignment]

    @classmethod
    def instance(cls) -> "LocalFileSystem":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    @staticmethod
    def _path(uri: str) -> str:
        u = URI(uri)
        return u.path if u.protocol == "file://" else uri

    def open(self, uri: str, mode: str = "r") -> Stream:
        return FileStream(self._path(uri), mode)

    def get_path_info(self, uri: str) -> FileInfo:
        path = self._path(uri)
        st = os.stat(path)  # follows symlinks, like reference :69-97
        kind = "directory" if os.path.isdir(path) else "file"
        return FileInfo(path=uri, size=st.st_size, type=kind)

    def list_directory(self, uri: str) -> List[FileInfo]:
        path = self._path(uri)
        prefix = uri.rstrip("/")
        out = []
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            try:
                st = os.stat(full)
            except OSError:
                continue  # dangling symlink — skip, like reference :99-145
            kind = "directory" if os.path.isdir(full) else "file"
            out.append(FileInfo(path=f"{prefix}/{name}", size=st.st_size, type=kind))
        return out

    def delete(self, uri: str, recursive: bool = False) -> None:
        path = self._path(uri)
        if recursive and os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.remove(path)


class MemoryFileSystem(FileSystem):
    """Process-global in-memory store under ``mem://`` — the hermetic test
    stand-in for object stores (no reference analogue; reference tests hit
    real S3, test/README.md:3-30)."""

    _store: Dict[str, bytes] = {}

    class _WriteBack(MemoryStream):
        def __init__(self, store: Dict[str, bytes], key: str, init: bytes = b"") -> None:
            super().__init__()
            if init:
                self.write(init)
            self._store, self._key = store, key
            self._closed = False

        def flush(self) -> None:
            if not self._closed:
                self._store[self._key] = self.getvalue()

        def close(self) -> None:
            if self._closed:
                return
            self.flush()
            self._closed = True
            super().close()

    def open(self, uri: str, mode: str = "r") -> Stream:
        if mode == "r":
            if uri not in self._store:
                raise Error(f"mem:// key not found: {uri}")
            return MemoryStream(self._store[uri])
        if mode == "w":
            return self._WriteBack(self._store, uri)
        if mode == "a":
            return self._WriteBack(self._store, uri, self._store.get(uri, b""))
        raise Error(f"invalid mode {mode!r}")

    def get_path_info(self, uri: str) -> FileInfo:
        if uri in self._store:
            return FileInfo(path=uri, size=len(self._store[uri]), type="file")
        prefix = uri.rstrip("/") + "/"
        if any(k.startswith(prefix) for k in self._store):
            return FileInfo(path=uri, size=0, type="directory")
        raise Error(f"mem:// key not found: {uri}")

    def list_directory(self, uri: str) -> List[FileInfo]:
        prefix = uri.rstrip("/") + "/"
        seen: Dict[str, FileInfo] = {}
        for key, data in sorted(self._store.items()):
            if not key.startswith(prefix):
                continue
            rest = key[len(prefix) :]
            head = rest.split("/", 1)[0]
            full = prefix + head
            if "/" in rest:
                seen.setdefault(full, FileInfo(path=full, size=0, type="directory"))
            else:
                seen[full] = FileInfo(path=full, size=len(data), type="file")
        return list(seen.values())

    def delete(self, uri: str, recursive: bool = False) -> None:
        if uri in self._store:
            del self._store[uri]
            return
        prefix = uri.rstrip("/") + "/"
        keys = [k for k in self._store if k.startswith(prefix)]
        if not keys:
            raise Error(f"mem:// key not found: {uri}")
        if not recursive:
            raise Error(f"mem:// {uri} is a prefix; pass recursive=True")
        for k in keys:
            del self._store[k]

    @classmethod
    def reset(cls) -> None:
        cls._store.clear()


FS_REGISTRY.add("file://", LocalFileSystem.instance)
FS_REGISTRY.add("mem://", MemoryFileSystem)


class TemporaryDirectory:
    """mkdtemp + recursive delete (reference include/dmlc/filesystem.h:34-158).

    Usable as a context manager; also deletes on GC like the reference's
    destructor.
    """

    def __init__(self, prefix: str = "dmlctmp") -> None:
        self.path = tempfile.mkdtemp(prefix=prefix)

    def __enter__(self) -> "TemporaryDirectory":
        return self

    def __exit__(self, *exc) -> None:
        self.cleanup()

    def cleanup(self) -> None:
        if self.path and os.path.isdir(self.path):
            shutil.rmtree(self.path, ignore_errors=True)
        self.path = ""

    def __del__(self) -> None:  # reference ~TemporaryDirectory
        try:
            self.cleanup()
        except Exception:
            pass
