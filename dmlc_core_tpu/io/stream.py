"""Stream / SeekStream abstraction and concrete byte streams.

Reference: dmlc::Stream / dmlc::SeekStream (include/dmlc/io.h:30-129),
MemoryFixedSizeStream / MemoryStringStream (include/dmlc/memory_io.h:21-105),
local FileStream (src/io/local_filesys.cc:27-67).

Design: Python already has a rich binary-file protocol; the Stream class is a
thin uniform wrapper so URI-dispatched backends (local, memory, gs/s3/http)
and the serializer all meet one interface. ``Stream.create(uri, mode)`` is
the factory (reference Stream::Create, src/io.cc:132-138).
"""

from __future__ import annotations

import io as _pyio
from typing import Optional, Union

from ..utils.logging import Error, check

__all__ = [
    "Stream",
    "SeekStream",
    "MemoryStream",
    "FileStream",
    "Serializable",
    "StreamIO",
    "wrap_text",
]


class Stream:
    """Sequential byte stream (reference io.h:30-106)."""

    def read(self, n: int = -1) -> bytes:
        raise NotImplementedError

    def write(self, data: bytes) -> int:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- framed helpers (Stream::Write<T>/Read<T> live in serializer.py) ----
    def read_exact(self, n: int) -> bytes:
        """Read exactly n bytes or raise (consumers needing the
        read-or-EOF distinction use read())."""
        buf = self.read(n)
        if len(buf) != n:
            raise Error(f"Stream: expected {n} bytes, got {len(buf)}")
        return buf

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- factory ------------------------------------------------------------
    @staticmethod
    def create(uri: str, mode: str = "r", allow_null: bool = False) -> Optional["Stream"]:
        """URI-dispatched stream factory (reference Stream::Create,
        src/io.cc:132-138). mode: 'r'|'w'|'a' (binary always).

        ``allow_null`` forgives only the open itself (missing file); an
        unknown protocol or bad mode is always fatal, as in the reference
        (src/io.cc:30-71 makes protocol dispatch unconditional).
        """
        from .filesystem import FileSystem  # local import: filesystem imports us

        check(mode in ("r", "w", "a"), f"invalid stream mode {mode!r}")
        fs = FileSystem.get_instance(uri)
        try:
            return fs.open(uri, mode)
        except (OSError, Error):
            if allow_null:
                return None
            raise


class SeekStream(Stream):
    """Stream with random access (reference io.h:109-129)."""

    def seek(self, pos: int) -> None:
        raise NotImplementedError

    def tell(self) -> int:
        raise NotImplementedError

    @staticmethod
    def create_for_read(uri: str, allow_null: bool = False) -> Optional["SeekStream"]:
        """Reference SeekStream::CreateForRead (io.cc:140-145)."""
        s = Stream.create(uri, "r", allow_null=allow_null)
        if s is not None:
            check(isinstance(s, SeekStream), f"{uri} does not support seeking")
        return s  # type: ignore[return-value]


class _FileLike(SeekStream):
    """Adapter over any Python binary file object."""

    def __init__(self, fp) -> None:
        self._fp = fp

    def read(self, n: int = -1) -> bytes:
        return self._fp.read(n)

    def write(self, data: Union[bytes, bytearray, memoryview]) -> int:
        return self._fp.write(data)

    def seek(self, pos: int) -> None:
        self._fp.seek(pos)

    def tell(self) -> int:
        return self._fp.tell()

    def flush(self) -> None:
        self._fp.flush()

    def close(self) -> None:
        self._fp.close()


class FileStream(_FileLike):
    """Local-file stream (reference FileStream, src/io/local_filesys.cc:27-67)."""

    def __init__(self, path: str, mode: str = "r") -> None:
        check(mode in ("r", "w", "a"), f"invalid stream mode {mode!r}")
        super().__init__(open(path, mode + "b"))
        self.path = path


class MemoryStream(_FileLike):
    """In-memory seekable stream (reference MemoryStringStream,
    include/dmlc/memory_io.h:66-105)."""

    def __init__(self, data: bytes = b"") -> None:
        super().__init__(_pyio.BytesIO(data))

    def getvalue(self) -> bytes:
        return self._fp.getvalue()


class Serializable:
    """Interface for objects serializable to/from a Stream
    (reference io.h:132-146)."""

    def save(self, stream: Stream) -> None:
        raise NotImplementedError

    def load(self, stream: Stream) -> None:
        raise NotImplementedError


class StreamIO(_pyio.RawIOBase):
    """``io.RawIOBase`` adapter over any Stream — the analogue of the
    reference's ``dmlc::ostream``/``dmlc::istream`` std-stream adapters
    (include/dmlc/io.h:318-443): third-party code wanting the standard
    file protocol (``readinto``, ``io.BufferedReader`` buffering,
    ``io.TextIOWrapper`` text/newline decoding, csv module, pickle,
    np.load...) gets it over URI-dispatched backends (gs://, s3://,
    mem://...).

    ``mode``: 'r', 'w', or 'rw' — the direction(s) the underlying Stream
    was opened for (the reference has separate istream/ostream; one
    adapter class with a declared mode covers both). ``close_stream``:
    whether closing the wrapper closes the underlying Stream (the
    reference adapters keep the Stream caller-owned; default matches
    that — pass True for a self-contained handle).
    """

    def __init__(
        self,
        stream: Stream,
        mode: str = "r",
        close_stream: bool = False,
    ) -> None:
        super().__init__()
        check(mode in ("r", "w", "rw"), f"StreamIO mode {mode!r}")
        self._stream = stream
        self._mode = mode
        self._close_stream = close_stream

    # -- capabilities --------------------------------------------------------
    def readable(self) -> bool:
        return "r" in self._mode

    def writable(self) -> bool:
        return "w" in self._mode

    def seekable(self) -> bool:
        return isinstance(self._stream, SeekStream)

    # -- RawIOBase primitives ------------------------------------------------
    # failure modes follow the io protocol (io.UnsupportedOperation, an
    # OSError), NOT the framework's Error — the adapter exists for
    # third-party code that guards with `except OSError` stdlib-style

    def readinto(self, b) -> int:
        if "r" not in self._mode:
            raise _pyio.UnsupportedOperation("not readable")
        data = self._stream.read(len(b))
        n = len(data)
        b[:n] = data
        return n

    def write(self, b) -> int:
        if "w" not in self._mode:
            raise _pyio.UnsupportedOperation("not writable")
        # every in-repo backend takes any buffer-protocol object; no copy
        return self._stream.write(b)

    def seek(self, pos: int, whence: int = _pyio.SEEK_SET) -> int:
        if not isinstance(self._stream, SeekStream):
            raise _pyio.UnsupportedOperation("stream is not seekable")
        if whence == _pyio.SEEK_SET:
            target = pos
        elif whence == _pyio.SEEK_CUR:
            target = self._stream.tell() + pos
        else:
            raise OSError("StreamIO supports SEEK_SET and SEEK_CUR only")
        self._stream.seek(target)
        return target

    def tell(self) -> int:
        if not isinstance(self._stream, SeekStream):
            raise _pyio.UnsupportedOperation("stream is not seekable")
        return self._stream.tell()

    def flush(self) -> None:
        if not self.closed:
            self._stream.flush()

    def close(self) -> None:
        if not self.closed:
            try:
                super().close()  # flushes via flush()
            finally:
                if self._close_stream:
                    self._stream.close()


def wrap_text(
    stream: Stream, mode: str = "r", **kwargs
) -> _pyio.TextIOWrapper:
    """Text-mode view of a Stream (``dmlc::ostream/istream`` use case):
    ``wrap_text(Stream.create("gs://bucket/x.csv"))`` reads decoded
    lines; ``wrap_text(s, "w")`` writes them. Keyword args pass through
    to ``io.TextIOWrapper`` (encoding, newline, ...). Closing the
    wrapper closes the Stream."""
    raw = StreamIO(stream, mode=mode, close_stream=True)
    if mode == "rw":
        buf: _pyio.BufferedIOBase = _pyio.BufferedRandom(raw)
    elif mode == "w":
        buf = _pyio.BufferedWriter(raw)
    else:
        buf = _pyio.BufferedReader(raw)
    return _pyio.TextIOWrapper(buf, **kwargs)
