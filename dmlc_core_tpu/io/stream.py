"""Stream / SeekStream abstraction and concrete byte streams.

Reference: dmlc::Stream / dmlc::SeekStream (include/dmlc/io.h:30-129),
MemoryFixedSizeStream / MemoryStringStream (include/dmlc/memory_io.h:21-105),
local FileStream (src/io/local_filesys.cc:27-67).

Design: Python already has a rich binary-file protocol; the Stream class is a
thin uniform wrapper so URI-dispatched backends (local, memory, gs/s3/http)
and the serializer all meet one interface. ``Stream.create(uri, mode)`` is
the factory (reference Stream::Create, src/io.cc:132-138).
"""

from __future__ import annotations

import io as _pyio
from typing import Optional, Union

from ..utils.logging import Error, check

__all__ = ["Stream", "SeekStream", "MemoryStream", "FileStream", "Serializable"]


class Stream:
    """Sequential byte stream (reference io.h:30-106)."""

    def read(self, n: int = -1) -> bytes:
        raise NotImplementedError

    def write(self, data: bytes) -> int:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- framed helpers (Stream::Write<T>/Read<T> live in serializer.py) ----
    def read_exact(self, n: int) -> bytes:
        """Read exactly n bytes or raise (consumers needing the
        read-or-EOF distinction use read())."""
        buf = self.read(n)
        if len(buf) != n:
            raise Error(f"Stream: expected {n} bytes, got {len(buf)}")
        return buf

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- factory ------------------------------------------------------------
    @staticmethod
    def create(uri: str, mode: str = "r", allow_null: bool = False) -> Optional["Stream"]:
        """URI-dispatched stream factory (reference Stream::Create,
        src/io.cc:132-138). mode: 'r'|'w'|'a' (binary always).

        ``allow_null`` forgives only the open itself (missing file); an
        unknown protocol or bad mode is always fatal, as in the reference
        (src/io.cc:30-71 makes protocol dispatch unconditional).
        """
        from .filesystem import FileSystem  # local import: filesystem imports us

        check(mode in ("r", "w", "a"), f"invalid stream mode {mode!r}")
        fs = FileSystem.get_instance(uri)
        try:
            return fs.open(uri, mode)
        except (OSError, Error):
            if allow_null:
                return None
            raise


class SeekStream(Stream):
    """Stream with random access (reference io.h:109-129)."""

    def seek(self, pos: int) -> None:
        raise NotImplementedError

    def tell(self) -> int:
        raise NotImplementedError

    @staticmethod
    def create_for_read(uri: str, allow_null: bool = False) -> Optional["SeekStream"]:
        """Reference SeekStream::CreateForRead (io.cc:140-145)."""
        s = Stream.create(uri, "r", allow_null=allow_null)
        if s is not None:
            check(isinstance(s, SeekStream), f"{uri} does not support seeking")
        return s  # type: ignore[return-value]


class _FileLike(SeekStream):
    """Adapter over any Python binary file object."""

    def __init__(self, fp) -> None:
        self._fp = fp

    def read(self, n: int = -1) -> bytes:
        return self._fp.read(n)

    def write(self, data: Union[bytes, bytearray, memoryview]) -> int:
        return self._fp.write(data)

    def seek(self, pos: int) -> None:
        self._fp.seek(pos)

    def tell(self) -> int:
        return self._fp.tell()

    def flush(self) -> None:
        self._fp.flush()

    def close(self) -> None:
        self._fp.close()


class FileStream(_FileLike):
    """Local-file stream (reference FileStream, src/io/local_filesys.cc:27-67)."""

    def __init__(self, path: str, mode: str = "r") -> None:
        check(mode in ("r", "w", "a"), f"invalid stream mode {mode!r}")
        super().__init__(open(path, mode + "b"))
        self.path = path


class MemoryStream(_FileLike):
    """In-memory seekable stream (reference MemoryStringStream,
    include/dmlc/memory_io.h:66-105)."""

    def __init__(self, data: bytes = b"") -> None:
        super().__init__(_pyio.BytesIO(data))

    def getvalue(self) -> bytes:
        return self._fp.getvalue()


class Serializable:
    """Interface for objects serializable to/from a Stream
    (reference io.h:132-146)."""

    def save(self, stream: Stream) -> None:
        raise NotImplementedError

    def load(self, stream: Stream) -> None:
        raise NotImplementedError
