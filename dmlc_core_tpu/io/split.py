"""Record-aligned sharded input splits — data parallelism over byte ranges.

Reference: include/dmlc/io.h:155-302 (InputSplit interface),
src/io/input_split_base.{h,cc} (partition math), line_split.cc,
recordio_split.cc, indexed_recordio_split.cc, single_file_split.h,
threaded_input_split.h, cached_input_split.h, input_split_shuffle.h.

Every worker reads a disjoint, record-aligned slice of a URI set:
``create(uri, part_index, num_parts, type)``. This is the reference's only
model-training parallelism (SURVEY §2.9) and the axis the TPU staging layer
sources from the process mesh (``parallel/``): rank ↔ jax.process_index().

Semantics ported exactly (this is where the bugs live — SURVEY §7 hard part
3); the *implementation* is Pythonic: chunks are bytes, records are bytes
views, hot scans are vectorized numpy, and the native C++ core replaces the
inner loops when present.
"""

from __future__ import annotations

import bisect
import random
import re
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..concurrency.threaded_iter import ThreadedIter
from ..utils.logging import Error, check, check_eq
from . import serializer
from .filesystem import FileInfo, FileSystem
from .recordio import (
    RecordIOChunkReader,
    first_head_in_words,
    last_head_in_words,
)
from .stream import SeekStream, Stream
from .uri import URISpec, uri_int

__all__ = [
    "InputSplit",
    "InputSplitBase",
    "LineSplitter",
    "RecordIOSplitter",
    "IndexedRecordIOSplitter",
    "SingleFileSplit",
    "ThreadedInputSplit",
    "CachedInputSplit",
    "InputSplitShuffle",
    "create",
]

# 8 MB chunk buffer (reference kBufferSize = 2<<20 uint32 words,
# src/io/input_split_base.h:39-40)
DEFAULT_BUFFER_BYTES = (2 << 20) * 4


class InputSplit:
    """Public interface (reference io.h:155-302)."""

    def next_record(self) -> Optional[bytes]:
        """Next record or None at end of split. For text: one line (no
        trailing newline). For recordio: one record payload, header stripped."""
        raise NotImplementedError

    def next_chunk(self) -> Optional[bytes]:
        """A chunk of whole records (parse fan-out unit), or None."""
        raise NotImplementedError

    def next_batch(self, n_records: int) -> Optional[bytes]:
        """Chunk with a record-count hint.

        The default IGNORES the hint by design — exact parity with the
        reference, whose base InputSplit::NextBatch is ``return
        NextChunk(out_chunk)`` (io.h:230-232) and whose InputSplitBase::
        NextBatchEx forwards to NextChunkEx (input_split_base.h:115-117).
        Only IndexedRecordIOSplitter honors n_records (there as here:
        next_batch_ex below), because only count-indexed splits can seek
        per record."""
        return self.next_chunk()

    def before_first(self) -> None:
        raise NotImplementedError

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise NotImplementedError

    def total_size(self) -> int:
        raise NotImplementedError

    def hint_chunk_size(self, nbytes: int) -> None:
        pass

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        """Split a chunk produced by next_chunk back into records."""
        raise NotImplementedError

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec

    def close(self) -> None:
        pass


def _expand_uris(filesys: FileSystem, uri: str) -> List[str]:
    """';'-separated URI list with regex glob expansion (reference
    ConvertToURIs, input_split_base.cc:96-147, DMLC_USE_REGEX)."""
    out: List[str] = []
    for part in uri.split(";"):
        if not part:
            continue
        name = part
        pos = name.rfind("/")
        if pos < 0 or pos + 1 == len(name):
            out.append(name)
            continue
        parent = name[:pos]
        try:
            listing = filesys.list_directory(parent)
        except (OSError, Error):
            out.append(name)  # parent unlistable: let GetPathInfo report
            continue
        stripped = name.rstrip("/")
        exact = [f for f in listing if f.path.rstrip("/") == stripped]
        if exact:
            out.append(exact[0].path)
            continue
        try:
            pattern = re.compile(stripped)
        except re.error as e:
            raise Error(f"bad regex {stripped!r} in input URI: {e}") from e
        matched = False
        for f in listing:
            if f.type != "file" or f.size == 0:
                continue
            if pattern.fullmatch(f.path.rstrip("/")):
                out.append(f.path)
                matched = True
        if not matched and not exact:
            out.append(name)  # fall through to the missing-file error
    return out


class InputSplitBase(InputSplit):
    """Byte-range sharding core (reference src/io/input_split_base.{h,cc}).

    Subclasses define the record format via ``_align``, ``_is_text``,
    ``seek_record_begin``, ``find_last_record_begin``, ``extract_records``.
    """

    _align = 1
    _is_text = False

    def __init__(
        self,
        uri: str,
        part_index: int = 0,
        num_parts: int = 1,
        filesys: Optional[FileSystem] = None,
        recurse_directories: bool = False,
    ) -> None:
        self.filesys = filesys or FileSystem.get_instance(uri.split(";")[0])
        self._init_files(uri, recurse_directories)
        self.buffer_size = DEFAULT_BUFFER_BYTES
        self._fs: Optional[Stream] = None
        self._file_ptr = 0
        self.offset_begin = 0
        self.offset_end = 0
        self.offset_curr = 0
        self._overflow = b""
        self._rec_iter: Optional[Iterator[bytes]] = None
        self.reset_partition(part_index, num_parts)

    # -- file table ----------------------------------------------------------
    def _init_files(self, uri: str, recurse: bool) -> None:
        """Reference InitInputFileInfo (input_split_base.cc:149-175):
        expand URIs, descend directories, keep non-empty files."""
        files: List[FileInfo] = []
        for path in _expand_uris(self.filesys, uri):
            try:
                info = self.filesys.get_path_info(path)
            except (OSError, Error):
                continue  # missing candidates fall to the aggregate error
            if info.type == "directory":
                listing = (
                    self.filesys.list_directory_recursive(info.path)
                    if recurse
                    else self.filesys.list_directory(info.path)
                )
                files.extend(
                    f for f in listing if f.type == "file" and f.size != 0
                )
            elif info.size != 0:
                files.append(info)
        if not files:
            raise Error(f"Cannot find any files that match the URI pattern {uri!r}")
        self.files = files
        offsets = [0]
        for f in files:
            if f.size % self._align != 0:
                raise Error(f"file {f.path} does not align by {self._align} bytes")
            offsets.append(offsets[-1] + f.size)
        self.file_offset = offsets

    def total_size(self) -> int:
        return self.file_offset[-1]

    def hint_chunk_size(self, nbytes: int) -> None:
        self.buffer_size = max(nbytes, 1024)

    # -- format hooks --------------------------------------------------------
    def seek_record_begin(self, stream: Stream) -> int:
        """Bytes to skip from the stream's position to the next record
        start."""
        raise NotImplementedError

    def find_last_record_begin(self, data: bytes) -> int:
        """Offset of the last record start within data (0 if none)."""
        raise NotImplementedError

    # -- partition math ------------------------------------------------------
    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Byte-range computation + record alignment (reference
        ResetPartition, input_split_base.cc:30-64)."""
        ntotal = self.file_offset[-1]
        nstep = (ntotal + num_parts - 1) // num_parts
        nstep = ((nstep + self._align - 1) // self._align) * self._align
        self.offset_begin = min(nstep * part_index, ntotal)
        self.offset_end = min(nstep * (part_index + 1), ntotal)
        self.offset_curr = self.offset_begin
        self._overflow = b""
        self._rec_iter = None
        if self.offset_begin == self.offset_end:
            self._close_fs()
            return
        file_ptr = bisect.bisect_right(self.file_offset, self.offset_begin) - 1
        file_ptr_end = bisect.bisect_right(self.file_offset, self.offset_end) - 1
        # snap the END forward to the next record boundary, unless it already
        # sits on a file boundary (file starts are record starts)
        if self.offset_end != self.file_offset[file_ptr_end]:
            with self._open(file_ptr_end) as fs:
                fs.seek(self.offset_end - self.file_offset[file_ptr_end])
                self.offset_end += self.seek_record_begin(fs)
        # snap the BEGIN forward the same way
        if self.offset_begin != self.file_offset[file_ptr]:
            with self._open(file_ptr) as fs:
                fs.seek(self.offset_begin - self.file_offset[file_ptr])
                self.offset_begin += self.seek_record_begin(fs)
        self.offset_curr = self.offset_begin
        self.before_first()

    def _open(self, file_ptr: int) -> SeekStream:
        s = self.filesys.open(self.files[file_ptr].path, "r")
        check(isinstance(s, SeekStream), "input files must be seekable")
        return s  # type: ignore[return-value]

    def _close_fs(self) -> None:
        if self._fs is not None:
            self._fs.close()
            self._fs = None

    def before_first(self) -> None:
        """Seek back to the partition start (reference
        input_split_base.cc:66-82)."""
        if self.offset_begin >= self.offset_end:
            return
        fp = bisect.bisect_right(self.file_offset, self.offset_begin) - 1
        self._close_fs()
        self._file_ptr = fp
        self._fs = self._open(fp)
        self._fs.seek(self.offset_begin - self.file_offset[fp])
        self.offset_curr = self.offset_begin
        self._overflow = b""
        self._rec_iter = None

    # -- reading -------------------------------------------------------------
    def _read(self, size: int) -> bytes:
        """Multi-file read with NOEOL newline injection at text file joins
        (reference Read, input_split_base.cc:177-219 and PR#385)."""
        # snapping can push offset_begin past offset_end (degenerate tail
        # partition) — reference Read guards this (input_split_base.cc:183)
        if (
            self._fs is None
            or self.offset_begin >= self.offset_end
            or self.offset_curr >= self.offset_end
        ):
            return b""
        size = min(size, self.offset_end - self.offset_curr)
        if size == 0:
            return b""
        out: List[bytes] = []
        nleft = size
        while nleft > 0:
            data = self._fs.read(nleft)
            if data:
                out.append(data)
                nleft -= len(data)
                self.offset_curr += len(data)
                continue
            # current file exhausted
            if self._is_text:
                out.append(b"\n")  # join NOEOL text files safely
                nleft -= 1
            check_eq(
                self.offset_curr,
                self.file_offset[self._file_ptr + 1],
                "file offset not calculated correctly",
            )
            if self._file_ptr + 1 >= len(self.files):
                break
            self._file_ptr += 1
            self._fs.close()
            self._fs = self._open(self._file_ptr)
        return b"".join(out)

    def _read_chunk(self, max_size: int) -> Optional[bytes]:
        """One buffer of COMPLETE records; keeps the partial-record tail as
        overflow (reference ReadChunk, input_split_base.cc:221-258).

        Returns None at end of split, b'' when the buffer is too small for
        one record (caller doubles), else the record bytes.
        """
        olen = len(self._overflow)
        if max_size <= olen:
            return b""
        data = self._overflow + self._read(max_size - olen)
        if len(data) == 0:
            return None
        self._overflow = b""
        if self._is_text:
            if len(data) == olen:
                # no new bytes: the final record has no trailing newline
                # (reference PR#452 NOEOL-at-EOF fix)
                data += b"\n"
        elif len(data) != max_size:
            # non-text last buffer: partition end is a record boundary
            return data
        cut = self.find_last_record_begin(data)
        self._overflow = data[cut:]
        return data[:cut]

    def _next_chunk_ex(self) -> Optional[bytes]:
        """Grow-on-zero buffer loop (reference Chunk::Load,
        input_split_base.cc:260-277)."""
        size = self.buffer_size
        while True:
            chunk = self._read_chunk(size)
            if chunk is None:
                return None
            if len(chunk) == 0:
                size *= 2
                continue
            return chunk

    def next_chunk(self) -> Optional[bytes]:
        return self._next_chunk_ex()

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self._next_chunk_ex()
            if chunk is None:
                return None
            self._rec_iter = self.extract_records(chunk)

    def close(self) -> None:
        self._close_fs()


class LineSplitter(InputSplitBase):
    """record = text line (reference src/io/line_split.{h,cc}); align=1."""

    _align = 1
    _is_text = True

    def seek_record_begin(self, stream: Stream) -> int:
        """Skip to just after the next newline run (reference
        line_split.cc:9-26); buffered instead of byte-at-a-time."""
        nstep = 0
        seen_newline = False
        while True:
            buf = stream.read(65536)
            if not buf:
                return nstep
            i = 0
            if not seen_newline:
                j = _find_newline(buf)
                if j < 0:
                    nstep += len(buf)
                    continue
                nstep += j + 1
                seen_newline = True
                i = j + 1
            while i < len(buf) and buf[i] in (0x0A, 0x0D):
                nstep += 1
                i += 1
            if i < len(buf):
                return nstep

    def find_last_record_begin(self, data: bytes) -> int:
        """Reference line_split.cc:27-34."""
        cut = max(data.rfind(b"\n"), data.rfind(b"\r"))
        return cut + 1 if cut > 0 else 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        """Non-empty lines; consecutive newlines collapse (reference
        ExtractNextRecord, line_split.cc:36-55 absorbs newline runs)."""
        for line in chunk.replace(b"\r", b"\n").split(b"\n"):
            if line:
                yield line


def _find_newline(buf: bytes) -> int:
    a, b = buf.find(b"\n"), buf.find(b"\r")
    if a < 0:
        return b
    if b < 0:
        return a
    return min(a, b)


class RecordIOSplitter(InputSplitBase):
    """record = RecordIO frame (reference src/io/recordio_split.{h,cc});
    align=4."""

    _align = 4
    _is_text = False

    def seek_record_begin(self, stream: Stream) -> int:
        """Scan forward for a record head (reference recordio_split.cc:9-25),
        buffered with one-word overlap across blocks."""
        pos = 0  # absolute offset of buf[0] from the scan start
        buf = b""
        while True:
            data = stream.read(1 << 16)
            buf += data
            usable = len(buf) & ~3
            if usable >= 8:
                words = np.frombuffer(buf[:usable], dtype="<u4")
                hit = first_head_in_words(words)
                if hit >= 0:
                    return pos + hit * 4
            if not data:
                return pos + len(buf)  # EOF: skip everything (reference :12)
            # keep the last word: it may be the magic of a header whose lrec
            # arrives in the next block
            keep = max(usable - 4, 0)
            pos += keep
            buf = buf[keep:]

    def find_last_record_begin(self, data: bytes) -> int:
        """Reference recordio_split.cc:26-42 (backward scan → we take the
        last forward hit; same record head)."""
        usable = len(data) & ~3
        hit = last_head_in_words(np.frombuffer(data[:usable], dtype="<u4"))
        return hit * 4 if hit >= 0 else 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        for rec in RecordIOChunkReader(chunk, 0, 1):
            yield bytes(rec)


class IndexedRecordIOSplitter(RecordIOSplitter):
    """Shards by RECORD COUNT via an external index file, with optional
    per-epoch shuffled batched reads (reference
    src/io/indexed_recordio_split.{h,cc}).

    Index file: whitespace-separated ``index offset`` pairs
    (ReadIndexFile, indexed_recordio_split.cc:43-62).

    ``shuffle`` modes:

    - ``True`` / ``'record'``: full per-record permutation — one seek
      per record, exactly the reference's NextBatchEx shuffle
      (indexed_recordio_split.cc:159-191). Statistically perfect,
      seek-bound on every real filesystem.
    - ``'batch'``: permute SPANS of ``batch_size`` contiguous records
      and read each span with one coalesced seek (records inside a span
      keep file order). The chunk-shuffle trade every production reader
      makes (the reference's own ImageRecordIter-style consumers
      re-shuffle in a client-side buffer); sequential-read throughput at
      shuffle granularity ``batch_size``.
    """

    KRAND_MAGIC = 111  # reference indexed_recordio_split.h:82

    def __init__(
        self,
        uri: str,
        index_uri: str,
        part_index: int = 0,
        num_parts: int = 1,
        batch_size: int = 256,
        shuffle=False,
        seed: int = 0,
        epoch: int = 0,
        skip_records: int = 0,
        filesys: Optional[FileSystem] = None,
    ) -> None:
        """``epoch``/``skip_records``: data-position fast-forward (§5.4
        mid-epoch resume). The permutation is derived from (seed, epoch)
        alone — a DOCUMENTED divergence from the reference's persistent
        RNG (indexed_recordio_split.cc:221-233 reshuffles with carried
        state), which makes any epoch's read order reproducible without
        replaying the epochs before it. ``skip_records`` skips that many
        records of the starting epoch arithmetically (no I/O); in
        ``shuffle='batch'`` mode it must land on a span boundary — the
        positions a batch-granular consumer naturally checkpoints at."""
        if shuffle in (False, None, 0):
            self.shuffle_mode: Optional[str] = None
        elif shuffle in ("batch", 2):
            self.shuffle_mode = "batch"
        else:
            self.shuffle_mode = "record"
        self.shuffle = self.shuffle_mode is not None
        self.batch_size = batch_size
        self._seed = seed
        self.epoch = epoch - 1  # before_first() increments into `epoch`
        self._skip_next = skip_records
        self.records_consumed = 0
        self._index: List[Tuple[int, int]] = []  # (offset, size)
        self._index_uri = index_uri
        self.index_begin = 0
        self.index_end = 0
        self._current = 0
        self._n_overflow = 0
        self._permutation: List[int] = []
        super().__init__(uri, part_index, num_parts, filesys=filesys)

    def _read_index_file(self) -> None:
        stream = Stream.create(self._index_uri, "r")
        with stream:
            text = stream.read().decode()
        offsets = sorted(int(tok) for i, tok in enumerate(text.split()) if i % 2 == 1)
        if not offsets:
            raise Error(f"empty index file {self._index_uri!r}")
        total = self.file_offset[-1]
        self._index = [
            (offsets[i], (offsets[i + 1] if i + 1 < len(offsets) else total) - offsets[i])
            for i in range(len(offsets))
        ]

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Record-count range (reference indexed_recordio_split.cc:12-41)."""
        if not self._index:
            self._read_index_file()
        ntotal = len(self._index)
        nstep = (ntotal + num_parts - 1) // num_parts
        if part_index * nstep >= ntotal:
            self.offset_begin = self.offset_end = self.offset_curr = 0
            self.index_begin = self.index_end = 0
            self._permutation = []
            self._current = 0
            self._n_overflow = 0
            self._overflow = b""
            self._rec_iter = None
            self._close_fs()
            return
        self.index_begin = part_index * nstep
        self.offset_begin = self._index[self.index_begin][0]
        self.index_end = min((part_index + 1) * nstep, ntotal)
        if self.index_end < ntotal:
            self.offset_end = self._index[self.index_end][0]
        else:
            self.offset_end = self.file_offset[-1]
        self._n_overflow = 0
        self.before_first()

    def before_first(self) -> None:
        """Starts the next epoch: derives the permutation from
        (seed, epoch) — deterministic per epoch, so a resume can rebuild
        epoch N's exact read order directly (reference
        indexed_recordio_split.cc:221-233 reshuffles with persistent RNG
        state instead; divergence documented on __init__)."""
        if self.index_end <= self.index_begin:
            return
        self.epoch += 1
        rnd = random.Random(
            self.KRAND_MAGIC + self._seed + 1_000_003 * self.epoch
        )
        if self.shuffle_mode == "batch":
            # permute span STARTS; each span is batch_size contiguous
            # records read in one seek. Only FULL spans are shuffled —
            # the remainder span (ntotal % batch_size records) always
            # reads last, so every multiple of batch_size is a span
            # boundary and therefore a resumable position (skip_records
            # would otherwise land inside the short span whenever the
            # shuffle placed it early)
            total = self.index_end - self.index_begin
            full_end = self.index_begin + (total // self.batch_size) * (
                self.batch_size
            )
            self._permutation = list(
                range(self.index_begin, full_end, self.batch_size)
            )
            rnd.shuffle(self._permutation)
            if full_end < self.index_end:
                self._permutation.append(full_end)
            self._current = 0
        elif self.shuffle_mode == "record":
            self._permutation = list(range(self.index_begin, self.index_end))
            rnd.shuffle(self._permutation)
            self._current = 0
        else:
            self._current = self.index_begin
        self._n_overflow = 0
        self.records_consumed = 0
        if self._skip_next:
            self._fast_forward(self._skip_next)
            self._skip_next = 0
        super().before_first()

    def _fast_forward(self, n: int) -> None:
        """Skip ``n`` records of the CURRENT epoch arithmetically."""
        total = self.index_end - self.index_begin
        check(
            0 <= n <= total,
            f"skip_records={n} outside this shard's {total} records",
        )
        if self.shuffle_mode == "batch":
            # walk permuted spans, accumulating their true lengths (the
            # span containing index_end is short)
            done = 0
            while done < n and self._current < len(self._permutation):
                s = self._permutation[self._current]
                span = min(s + self.batch_size, self.index_end) - s
                check(
                    done + span <= n,
                    f"skip_records={n} lands inside a shuffled span of "
                    f"{span} (checkpoint at span boundaries — batch_size="
                    f"{self.batch_size} multiples)",
                )
                done += span
                self._current += 1
        elif self.shuffle_mode == "record":
            self._current = n
        else:
            self._current = self.index_begin + n
        self.records_consumed = n

    def _read_at(self, offset: int, size: int) -> bytes:
        """Seek to an absolute dataset offset and read (the shuffle path's
        per-record random I/O, reference indexed_recordio_split.cc:163-191)."""
        fp = bisect.bisect_right(self.file_offset, offset) - 1
        if fp != self._file_ptr or self._fs is None:
            self._close_fs()
            self._file_ptr = fp
            self._fs = self._open(fp)
        self._fs.seek(offset - self.file_offset[fp])
        self.offset_curr = offset
        out: List[bytes] = []
        nleft = size
        while nleft > 0:
            data = self._fs.read(nleft)
            if not data:
                if self._file_ptr + 1 >= len(self.files):
                    break
                self._file_ptr += 1
                self._fs.close()
                self._fs = self._open(self._file_ptr)
                continue
            out.append(data)
            nleft -= len(data)
            self.offset_curr += len(data)
        return b"".join(out)

    def next_batch_ex(self, n_records: int) -> Optional[bytes]:
        """Reference NextBatchEx (indexed_recordio_split.cc:159-212):
        record-shuffled = per-record seeks; batch-shuffled = one
        coalesced seek per permuted span; sequential = one span."""
        if self.shuffle_mode == "batch":
            if self._current >= len(self._permutation):
                return None
            s = self._permutation[self._current]
            self._current += 1
            e = min(s + self.batch_size, self.index_end)
            begin_off = self._index[s][0]
            end_off = (
                self._index[e][0]
                if e < len(self._index)
                else self.file_offset[-1]
            )
            chunk = self._read_at(begin_off, end_off - begin_off)
            if chunk:
                self.records_consumed += e - s
            return chunk if chunk else None
        if self.shuffle:
            n = self._n_overflow or n_records
            parts: List[bytes] = []
            while len(parts) < n and self._current < len(self._permutation):
                off, size = self._index[self._permutation[self._current]]
                parts.append(self._read_at(off, size))
                self._current += 1
            if not parts:
                return None
            self._n_overflow = n - len(parts)
            self.records_consumed += len(parts)
            return b"".join(parts)
        n = self._n_overflow or n_records
        last = min(self._current + n, self.index_end)
        self._n_overflow = self._current + n - last
        if last <= self._current:
            return None
        begin_off = self._index[self._current][0]
        end_off = (
            self._index[last][0] if last < len(self._index) else self.file_offset[-1]
        )
        chunk = self._read_at(begin_off, end_off - begin_off)
        if chunk:
            self.records_consumed += last - self._current
        self._current = last
        return chunk if chunk else None

    def next_chunk(self) -> Optional[bytes]:
        return self.next_batch_ex(self.batch_size)

    def next_batch(self, n_records: int) -> Optional[bytes]:
        return self.next_batch_ex(n_records)

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self.next_batch_ex(self.batch_size)
            if chunk is None:
                return None
            self._rec_iter = self.extract_records(chunk)


class SingleFileSplit(InputSplit):
    """stdin / single-file text split without sharding (reference
    src/io/single_file_split.h)."""

    def __init__(self, path: str = "-") -> None:
        self._path = path
        self._stream = None
        self._buffer = b""
        self._eof = False
        self._rec_iter: Optional[Iterator[bytes]] = None
        self._size = 0
        self.before_first()

    def _open(self):
        if self._path == "-":
            import sys

            return sys.stdin.buffer
        return open(self._path, "rb")

    def before_first(self) -> None:
        if self._path == "-" and self._stream is not None:
            raise Error("cannot rewind stdin")
        if self._stream is not None and self._path != "-":
            self._stream.close()
        self._stream = self._open()
        self._eof = False
        self._rec_iter = None
        self._overflow = b""

    def total_size(self) -> int:
        if self._path == "-":
            return 0
        import os

        return os.path.getsize(self._path)

    def next_chunk(self) -> Optional[bytes]:
        while not self._eof:
            data = self._stream.read(DEFAULT_BUFFER_BYTES)
            if not data:
                self._eof = True
                if self._overflow:
                    out, self._overflow = self._overflow + b"\n", b""
                    return out
                return None
            data = self._overflow + data
            cut = max(data.rfind(b"\n"), data.rfind(b"\r"))
            if cut <= 0:
                self._overflow = data
                continue
            self._overflow = data[cut + 1 :]
            return data[: cut + 1]
        return None

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        for line in chunk.replace(b"\r", b"\n").split(b"\n"):
            if line:
                yield line

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._rec_iter = self.extract_records(chunk)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check_eq(num_parts, 1, "SingleFileSplit does not shard")


class ThreadedInputSplit(InputSplit):
    """Read-ahead wrapper: prefetch chunks on a background thread with
    double buffering (reference src/io/threaded_input_split.h,
    set_max_capacity(2) at :33)."""

    def __init__(self, base: InputSplitBase, max_capacity: int = 2) -> None:
        self._base = base
        self._cap = max_capacity
        self._rec_iter: Optional[Iterator[bytes]] = None
        self._first_epoch = True
        self._iter: ThreadedIter[bytes] = ThreadedIter(
            self._produce, max_capacity=max_capacity, name="split-prefetch"
        )

    def _produce(self):
        if not self._first_epoch:
            self._base.before_first()
        self._first_epoch = False
        while True:
            chunk = self._base.next_chunk()
            if chunk is None:
                return
            yield chunk

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self._iter.next()
            if chunk is None:
                return None
            self._rec_iter = self._base.extract_records(chunk)

    def before_first(self) -> None:
        self._rec_iter = None
        self._iter.before_first()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._iter.destroy()
        self._base.reset_partition(part_index, num_parts)
        self._first_epoch = True
        self._rec_iter = None
        self._iter = ThreadedIter(
            self._produce, max_capacity=self._cap, name="split-prefetch"
        )

    def total_size(self) -> int:
        return self._base.total_size()

    def hint_chunk_size(self, nbytes: int) -> None:
        self._base.hint_chunk_size(nbytes)

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def close(self) -> None:
        self._iter.destroy()
        self._base.close()


class CachedInputSplit(InputSplit):
    """First epoch streams chunks to a local cache file while serving them;
    later epochs replay the cache (reference src/io/cached_input_split.h:
    InitPreprocIter :148-164, InitCachedIter :166-189)."""

    def __init__(self, base: InputSplit, cache_file: str) -> None:
        self._base = base
        self._cache_file = cache_file
        self._cache_complete = False
        self._rec_iter: Optional[Iterator[bytes]] = None
        self._iter: ThreadedIter[bytes] = ThreadedIter(
            self._produce_preproc, name="split-cache-build"
        )

    def _produce_preproc(self):
        out = Stream.create(self._cache_file, "w")
        try:
            while True:
                chunk = self._base.next_chunk()
                if chunk is None:
                    break
                serializer.write_bytes(out, chunk)
                yield chunk
            self._cache_complete = True
        finally:
            out.close()

    def _produce_cached(self):
        stream = Stream.create(self._cache_file, "r")
        try:
            while True:
                n = serializer.try_read_scalar(stream, "uint64")
                if n is None:
                    return
                yield stream.read_exact(n)
        finally:
            stream.close()

    def before_first(self) -> None:
        self._rec_iter = None
        if self._cache_complete:
            self._iter.destroy()
            self._iter = ThreadedIter(self._produce_cached, name="split-cache-replay")
        else:
            # first pass didn't finish: rebuild the cache from scratch
            self._iter.destroy()
            self._base.before_first()
            self._iter = ThreadedIter(self._produce_preproc, name="split-cache-build")

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self._iter.next()
            if chunk is None:
                return None
            self._rec_iter = self._base.extract_records(chunk)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._cache_complete = False
        self._iter.destroy()
        self._base.reset_partition(part_index, num_parts)
        self._iter = ThreadedIter(self._produce_preproc, name="split-cache-build")
        self._rec_iter = None

    def total_size(self) -> int:
        return self._base.total_size()

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def close(self) -> None:
        self._iter.destroy()
        self._base.close()


class InputSplitShuffle(InputSplit):
    """Macro-shuffle: over-partition into num_parts * num_shuffle_parts
    sub-parts and visit this rank's sub-parts in a seeded shuffled order,
    reshuffled each epoch (reference include/dmlc/input_split_shuffle.h:
    24-33, 100-119; kRandMagic_=666 :151)."""

    KRAND_MAGIC = 666

    def __init__(
        self,
        base: InputSplit,
        part_index: int,
        num_parts: int,
        num_shuffle_parts: int,
        seed: int = 0,
    ) -> None:
        check(num_shuffle_parts > 0, "num_shuffle_parts must be positive")
        self._base = base
        self._num_total = num_parts * num_shuffle_parts
        self._sub_parts = [
            part_index * num_shuffle_parts + i for i in range(num_shuffle_parts)
        ]
        self._rnd = random.Random(self.KRAND_MAGIC + seed)
        self._order: List[int] = []
        self._cursor = 0
        self.before_first()

    def before_first(self) -> None:
        self._order = list(self._sub_parts)
        self._rnd.shuffle(self._order)
        self._cursor = 0
        self._base.reset_partition(self._order[0], self._num_total)

    def _advance(self) -> bool:
        self._cursor += 1
        if self._cursor >= len(self._order):
            return False
        self._base.reset_partition(self._order[self._cursor], self._num_total)
        return True

    def next_record(self) -> Optional[bytes]:
        while True:
            rec = self._base.next_record()
            if rec is not None:
                return rec
            if not self._advance():
                return None

    def next_chunk(self) -> Optional[bytes]:
        while True:
            chunk = self._base.next_chunk()
            if chunk is not None:
                return chunk
            if not self._advance():
                return None

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        nsp = len(self._sub_parts)
        self._sub_parts = [part_index * nsp + i for i in range(nsp)]
        self._num_total = num_parts * nsp
        self.before_first()

    def total_size(self) -> int:
        return self._base.total_size()

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def close(self) -> None:
        self._base.close()


def create(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type: str = "text",
    index_uri: Optional[str] = None,
    shuffle=None,  # None | bool | 'record' | 'batch'
    seed: int = 0,
    batch_size: Optional[int] = None,
    recurse_directories: bool = False,
    num_shuffle_parts: int = 0,
    threaded: bool = True,
    epoch: int = 0,
    skip_records: int = 0,
) -> InputSplit:
    """InputSplit factory (reference InputSplit::Create, src/io.cc:81-130).

    - ``uri`` may carry ``#cachefile`` sugar → CachedInputSplit
      (reference io.cc:120-124)
    - default wraps the split in a read-ahead thread (reference io.cc:119-122)
    - ``type``: 'text' | 'recordio' | 'indexed_recordio'
    """
    check(
        num_parts >= 1 and 0 <= part_index < num_parts,
        f"invalid shard ({part_index}, {num_parts}): need "
        "0 <= part_index < num_parts (reference io.cc CHECK)",
    )
    spec = URISpec(uri, part_index, num_parts)
    # per-dataset options ride the URI (reference-style sugar); explicit
    # keyword args win when both are given:
    #   ?shuffle_parts=N&seed=S       macro-shuffle, any record type
    #   ?index=<uri>[&shuffle=1][&batch_size=N]   count-indexed recordio
    if num_shuffle_parts == 0:
        num_shuffle_parts = uri_int(spec.args, "shuffle_parts", 0)
    if type == "recordio" and (index_uri is not None or "index" in spec.args):
        if index_uri is None:
            index_uri = str(spec.args["index"])
        type = "indexed_recordio"
    if seed == 0:
        seed = uri_int(spec.args, "seed", 0)
    def norm_shuffle(v):
        """None/0/False → off; 'batch'/2 → coalesced span shuffle;
        'record'/1/True → per-record shuffle (reference semantics)."""
        if v in (None, False, 0, "0", ""):
            return False
        if v in ("batch", 2, "2"):
            return "batch"
        if v in ("record", "1", 1, True):
            return "record"
        raise Error(f"invalid shuffle={v!r}: use 0/1/record/batch")

    if type == "indexed_recordio":
        if shuffle is None:
            shuffle = spec.args.get("shuffle", "0")
        shuffle = norm_shuffle(shuffle)
        if batch_size is None:
            batch_size = uri_int(spec.args, "batch_size", 256)
        # data-position resume sugar (?epoch=E&skip_records=N): start at
        # epoch E's deterministic permutation, N records in (§5.4)
        if epoch == 0:
            epoch = uri_int(spec.args, "epoch", 0)
        if skip_records == 0:
            skip_records = uri_int(spec.args, "skip_records", 0)
        check(
            not (shuffle and spec.cache_file),
            "indexed shuffle with a #cachefile would freeze the first "
            "epoch's shuffle order into the cache; pick one",
        )
    else:
        shuffle = norm_shuffle(shuffle)
        # position fast-forward needs count-indexed access; silently
        # starting at record 0 would make a resume retrain duplicate
        # data — refuse loudly (the check() idiom of the sugar below)
        check(
            epoch == 0
            and skip_records == 0
            and "epoch" not in spec.args
            and "skip_records" not in spec.args,
            f"epoch/skip_records require an indexed recordio source "
            f"(?index=<uri>), not type={type!r}",
        )
    batch_size = 256 if batch_size is None else batch_size
    if type == "text" and spec.uri == "-":
        return SingleFileSplit("-")
    if type == "text":
        base: InputSplitBase = LineSplitter(
            spec.uri, part_index, num_parts, recurse_directories=recurse_directories
        )
    elif type == "recordio":
        base = RecordIOSplitter(
            spec.uri, part_index, num_parts, recurse_directories=recurse_directories
        )
    elif type == "indexed_recordio":
        check(index_uri is not None, "indexed_recordio requires index_uri")
        base = IndexedRecordIOSplitter(
            spec.uri,
            index_uri,  # type: ignore[arg-type]
            part_index,
            num_parts,
            batch_size=batch_size,
            shuffle=shuffle,
            seed=seed,
            epoch=epoch,
            skip_records=skip_records,
        )
    else:
        raise Error(f"unknown InputSplit type {type!r}")
    split: InputSplit = base
    if num_shuffle_parts > 0:
        check(
            not spec.cache_file,
            "num_shuffle_parts with a #cachefile would freeze the first "
            "epoch's shuffle order into the cache; pick one",
        )
        shuffled = InputSplitShuffle(
            base, part_index, num_parts, num_shuffle_parts, seed
        )
        # shuffling must not cost the read-ahead thread the unshuffled
        # path gets
        return ThreadedInputSplit(shuffled) if threaded else shuffled
    if spec.cache_file:
        # cached OR threaded, never both: CachedInputSplit prefetches
        # internally (reference io.cc:119-124 chooses exactly one wrapper)
        return CachedInputSplit(base, spec.cache_file)
    if threaded:
        return ThreadedInputSplit(base)
    return split
