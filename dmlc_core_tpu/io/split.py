"""Record-aligned sharded input splits — data parallelism over byte ranges.

Reference: include/dmlc/io.h:155-302 (InputSplit interface),
src/io/input_split_base.{h,cc} (partition math), line_split.cc,
recordio_split.cc, indexed_recordio_split.cc, single_file_split.h,
threaded_input_split.h, cached_input_split.h, input_split_shuffle.h.

Every worker reads a disjoint, record-aligned slice of a URI set:
``create(uri, part_index, num_parts, type)``. This is the reference's only
model-training parallelism (SURVEY §2.9) and the axis the TPU staging layer
sources from the process mesh (``parallel/``): rank ↔ jax.process_index().

Semantics ported exactly (this is where the bugs live — SURVEY §7 hard part
3); the *implementation* is Pythonic: chunks are bytes, records are bytes
views, hot scans are vectorized numpy, and the native C++ core replaces the
inner loops when present.
"""

from __future__ import annotations

import bisect
import hashlib
import mmap
import os
import random
import re
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..concurrency.threaded_iter import ThreadedIter
from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from ..utils.logging import Error, check, check_eq
from ..utils.profiler import annotate
from . import codec as _codec
from . import retry as _retry
from . import serializer
from . import spanfetch as _spanfetch
from .filesystem import FileInfo, FileSystem
from .recordio import (
    RecordIOChunkReader,
    decode_chunk,
    first_head_in_words,
    last_head_in_words,
    scan_compressed_blob,
)
from .stream import SeekStream, Stream
from .uri import URISpec, uri_int

__all__ = [
    "InputSplit",
    "InputSplitBase",
    "LineSplitter",
    "RecordIOSplitter",
    "IndexedRecordIOSplitter",
    "SingleFileSplit",
    "ThreadedInputSplit",
    "CachedInputSplit",
    "InputSplitShuffle",
    "DynamicShardSource",
    "create",
    "fileset_signature",
    "normalize_shuffle",
    "plan_coalesced_spans",
]

# 8 MB chunk buffer (reference kBufferSize = 2<<20 uint32 words,
# src/io/input_split_base.h:39-40)
DEFAULT_BUFFER_BYTES = (2 << 20) * 4

# telemetry mirrors of the per-instance I/O-shape counters: the same
# increments feed both the split's io_stats() (per-instance, exact) and
# these process-global registry series (fleet view via heartbeats);
# coalescing shows up globally as spans ≪ records, the pread fast path
# as a flat io.split.seeks
_REG = _default_registry()
_SPANS = _REG.counter("io.split.spans", help="positioned reads issued")
_SEEKS = _REG.counter("io.split.seeks", help="stream seek() calls")
_BYTES_READ = _REG.counter("io.split.bytes_read", help="bytes read by splits")
_INDEX_EVICTIONS = _REG.counter(
    "io.split.index_cache_evictions",
    help="parsed sidecar indexes evicted from the bytes-bounded LRU",
)
_RECORDS = _REG.counter("io.split.records", help="records emitted by splits")
_GATHER_BATCHES = _REG.counter(
    "io.split.gather_batches",
    help="zero-copy (buf, starts, sizes) gather batches emitted",
)
_GATHER_BYTES = _REG.counter(
    "io.split.gather_bytes", help="record bytes referenced by gather batches"
)
_GATHER_FALLBACK = _REG.counter(
    "io.split.gather_fallback_batches",
    help="shuffled emissions that re-framed bytes instead of gathering",
)


class InputSplit:
    """Public interface (reference io.h:155-302)."""

    def next_record(self) -> Optional[bytes]:
        """Next record or None at end of split. For text: one line (no
        trailing newline). For recordio: one record payload, header stripped."""
        raise NotImplementedError

    def next_chunk(self) -> Optional[bytes]:
        """A chunk of whole records (parse fan-out unit), or None."""
        raise NotImplementedError

    def next_batch(self, n_records: int) -> Optional[bytes]:
        """Chunk with a record-count hint.

        The default IGNORES the hint by design — exact parity with the
        reference, whose base InputSplit::NextBatch is ``return
        NextChunk(out_chunk)`` (io.h:230-232) and whose InputSplitBase::
        NextBatchEx forwards to NextChunkEx (input_split_base.h:115-117).
        Only IndexedRecordIOSplitter honors n_records (there as here:
        next_batch_ex below), because only count-indexed splits can seek
        per record."""
        return self.next_chunk()

    def before_first(self) -> None:
        raise NotImplementedError

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise NotImplementedError

    def total_size(self) -> int:
        raise NotImplementedError

    def hint_chunk_size(self, nbytes: int) -> None:
        pass

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        """Split a chunk produced by next_chunk back into records."""
        raise NotImplementedError

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec

    def close(self) -> None:
        pass


def _expand_uris(filesys: FileSystem, uri: str) -> List[str]:
    """';'-separated URI list with regex glob expansion (reference
    ConvertToURIs, input_split_base.cc:96-147, DMLC_USE_REGEX)."""
    out: List[str] = []
    for part in uri.split(";"):
        if not part:
            continue
        name = part
        pos = name.rfind("/")
        if pos < 0 or pos + 1 == len(name):
            out.append(name)
            continue
        parent = name[:pos]
        try:
            listing = filesys.list_directory(parent)
        except (OSError, Error):
            out.append(name)  # parent unlistable: let GetPathInfo report
            continue
        stripped = name.rstrip("/")
        exact = [f for f in listing if f.path.rstrip("/") == stripped]
        if exact:
            out.append(exact[0].path)
            continue
        try:
            pattern = re.compile(stripped)
        except re.error as e:
            raise Error(f"bad regex {stripped!r} in input URI: {e}") from e
        matched = False
        for f in listing:
            if f.type != "file" or f.size == 0:
                continue
            if pattern.fullmatch(f.path.rstrip("/")):
                out.append(f.path)
                matched = True
        if not matched and not exact:
            out.append(name)  # fall through to the missing-file error
    return out


class InputSplitBase(InputSplit):
    """Byte-range sharding core (reference src/io/input_split_base.{h,cc}).

    Subclasses define the record format via ``_align``, ``_is_text``,
    ``seek_record_begin``, ``find_last_record_begin``, ``extract_records``.
    """

    _align = 1
    _is_text = False

    def __init__(
        self,
        uri: str,
        part_index: int = 0,
        num_parts: int = 1,
        filesys: Optional[FileSystem] = None,
        recurse_directories: bool = False,
    ) -> None:
        self.filesys = filesys or FileSystem.get_instance(uri.split(";")[0])
        # retry/fault counters are process-global (io/retry.py); the
        # snapshot makes io_stats() report this split's delta — same
        # idiom for the remote-stream reopen counter (io/spanfetch.py)
        self._retry_snap = _retry.stats()
        self._reopen_snap = _spanfetch.reopens_total()
        self._init_files(uri, recurse_directories)
        self.buffer_size = DEFAULT_BUFFER_BYTES
        self._fs: Optional[Stream] = None
        self._file_ptr = 0
        self.offset_begin = 0
        self.offset_end = 0
        self.offset_curr = 0
        self._overflow = b""
        self._rec_iter: Optional[Iterator[bytes]] = None
        self.reset_partition(part_index, num_parts)

    # -- file table ----------------------------------------------------------
    def _init_files(self, uri: str, recurse: bool) -> None:
        """Reference InitInputFileInfo (input_split_base.cc:149-175):
        expand URIs, descend directories, keep non-empty files."""
        files: List[FileInfo] = []
        for path in _expand_uris(self.filesys, uri):
            try:
                info = self.filesys.get_path_info(path)
            except (OSError, Error):
                continue  # missing candidates fall to the aggregate error
            if info.type == "directory":
                listing = (
                    self.filesys.list_directory_recursive(info.path)
                    if recurse
                    else self.filesys.list_directory(info.path)
                )
                files.extend(
                    f for f in listing if f.type == "file" and f.size != 0
                )
            elif info.size != 0:
                files.append(info)
        if not files:
            raise Error(f"Cannot find any files that match the URI pattern {uri!r}")
        self.files = files
        offsets = [0]
        for f in files:
            if f.size % self._align != 0:
                raise Error(f"file {f.path} does not align by {self._align} bytes")
            offsets.append(offsets[-1] + f.size)
        self.file_offset = offsets

    def total_size(self) -> int:
        return self.file_offset[-1]

    def hint_chunk_size(self, nbytes: int) -> None:
        self.buffer_size = max(nbytes, 1024)

    # -- format hooks --------------------------------------------------------
    def seek_record_begin(self, stream: Stream) -> int:
        """Bytes to skip from the stream's position to the next record
        start."""
        raise NotImplementedError

    def find_last_record_begin(self, data: bytes) -> int:
        """Offset of the last record start within data (0 if none)."""
        raise NotImplementedError

    # -- partition math ------------------------------------------------------
    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Byte-range computation + record alignment (reference
        ResetPartition, input_split_base.cc:30-64)."""
        ntotal = self.file_offset[-1]
        nstep = (ntotal + num_parts - 1) // num_parts
        nstep = ((nstep + self._align - 1) // self._align) * self._align
        self.offset_begin = min(nstep * part_index, ntotal)
        self.offset_end = min(nstep * (part_index + 1), ntotal)
        self.offset_curr = self.offset_begin
        self._overflow = b""
        self._rec_iter = None
        if self.offset_begin == self.offset_end:
            self._close_fs()
            return
        file_ptr = bisect.bisect_right(self.file_offset, self.offset_begin) - 1
        file_ptr_end = bisect.bisect_right(self.file_offset, self.offset_end) - 1
        # snap the END forward to the next record boundary, unless it already
        # sits on a file boundary (file starts are record starts)
        if self.offset_end != self.file_offset[file_ptr_end]:
            with self._open(file_ptr_end) as fs:
                fs.seek(self.offset_end - self.file_offset[file_ptr_end])
                self.offset_end += self.seek_record_begin(fs)
        # snap the BEGIN forward the same way
        if self.offset_begin != self.file_offset[file_ptr]:
            with self._open(file_ptr) as fs:
                fs.seek(self.offset_begin - self.file_offset[file_ptr])
                self.offset_begin += self.seek_record_begin(fs)
        self.offset_curr = self.offset_begin
        self.before_first()

    def _open(self, file_ptr: int) -> SeekStream:
        s = self.filesys.open(self.files[file_ptr].path, "r")
        check(isinstance(s, SeekStream), "input files must be seekable")
        return s  # type: ignore[return-value]

    def _close_fs(self) -> None:
        if self._fs is not None:
            self._fs.close()
            self._fs = None

    def before_first(self) -> None:
        """Seek back to the partition start (reference
        input_split_base.cc:66-82)."""
        if self.offset_begin >= self.offset_end:
            return
        fp = bisect.bisect_right(self.file_offset, self.offset_begin) - 1
        self._close_fs()
        self._file_ptr = fp
        self._fs = self._open(fp)
        self._fs.seek(self.offset_begin - self.file_offset[fp])
        self.offset_curr = self.offset_begin
        self._overflow = b""
        self._rec_iter = None

    # -- reading -------------------------------------------------------------
    def _read(self, size: int) -> bytes:
        """Multi-file read with NOEOL newline injection at text file joins
        (reference Read, input_split_base.cc:177-219 and PR#385)."""
        # snapping can push offset_begin past offset_end (degenerate tail
        # partition) — reference Read guards this (input_split_base.cc:183)
        if (
            self._fs is None
            or self.offset_begin >= self.offset_end
            or self.offset_curr >= self.offset_end
        ):
            return b""
        size = min(size, self.offset_end - self.offset_curr)
        if size == 0:
            return b""
        out: List[bytes] = []
        nleft = size
        while nleft > 0:
            data = self._fs.read(nleft)
            if data:
                out.append(data)
                nleft -= len(data)
                self.offset_curr += len(data)
                continue
            # current file exhausted
            if self._is_text:
                out.append(b"\n")  # join NOEOL text files safely
                nleft -= 1
            check_eq(
                self.offset_curr,
                self.file_offset[self._file_ptr + 1],
                "file offset not calculated correctly",
            )
            if self._file_ptr + 1 >= len(self.files):
                break
            self._file_ptr += 1
            self._fs.close()
            self._fs = self._open(self._file_ptr)
        return b"".join(out)

    def _read_chunk(self, max_size: int) -> Optional[bytes]:
        """One buffer of COMPLETE records; keeps the partial-record tail as
        overflow (reference ReadChunk, input_split_base.cc:221-258).

        Returns None at end of split, b'' when the buffer is too small for
        one record (caller doubles), else the record bytes.
        """
        olen = len(self._overflow)
        if max_size <= olen:
            return b""
        data = self._overflow + self._read(max_size - olen)
        if len(data) == 0:
            return None
        self._overflow = b""
        if self._is_text:
            if len(data) == olen:
                # no new bytes: the final record has no trailing newline
                # (reference PR#452 NOEOL-at-EOF fix)
                data += b"\n"
        elif len(data) != max_size:
            # non-text last buffer: partition end is a record boundary
            return data
        cut = self.find_last_record_begin(data)
        self._overflow = data[cut:]
        return data[:cut]

    def _next_chunk_ex(self) -> Optional[bytes]:
        """Grow-on-zero buffer loop (reference Chunk::Load,
        input_split_base.cc:260-277)."""
        size = self.buffer_size
        while True:
            chunk = self._read_chunk(size)
            if chunk is None:
                return None
            if len(chunk) == 0:
                size *= 2
                continue
            return chunk

    def next_chunk(self) -> Optional[bytes]:
        return self._next_chunk_ex()

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self._next_chunk_ex()
            if chunk is None:
                return None
            self._rec_iter = self.extract_records(chunk)

    def io_stats(self) -> Dict[str, object]:
        """Robustness counters since construction: transient-failure
        ``retries`` healed, ``backoff_secs`` slept, ``faults_injected``
        by a fault:// source. Counters are process-global deltas —
        exact when one split is active, overlapping otherwise.
        IndexedRecordIOSplitter extends this with its I/O-shape
        counters (spans/seeks/bytes). ``reopens``: remote stream
        connections torn down by a repositioning seek since
        construction (io.fetch.reopens — a serial seek storm over an
        HTTP backend pays one reconnect per count)."""
        return {
            "mode": "sequential",
            "reopens": _spanfetch.reopens_total() - self._reopen_snap,
            **_retry.stats_delta(self._retry_snap),
        }

    def close(self) -> None:
        self._close_fs()


class LineSplitter(InputSplitBase):
    """record = text line (reference src/io/line_split.{h,cc}); align=1."""

    _align = 1
    _is_text = True

    def seek_record_begin(self, stream: Stream) -> int:
        """Skip to just after the next newline run (reference
        line_split.cc:9-26); buffered instead of byte-at-a-time."""
        nstep = 0
        seen_newline = False
        while True:
            buf = stream.read(65536)
            if not buf:
                return nstep
            i = 0
            if not seen_newline:
                j = _find_newline(buf)
                if j < 0:
                    nstep += len(buf)
                    continue
                nstep += j + 1
                seen_newline = True
                i = j + 1
            while i < len(buf) and buf[i] in (0x0A, 0x0D):
                nstep += 1
                i += 1
            if i < len(buf):
                return nstep

    def find_last_record_begin(self, data: bytes) -> int:
        """Reference line_split.cc:27-34."""
        cut = max(data.rfind(b"\n"), data.rfind(b"\r"))
        return cut + 1 if cut > 0 else 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        """Non-empty lines; consecutive newlines collapse (reference
        ExtractNextRecord, line_split.cc:36-55 absorbs newline runs)."""
        for line in chunk.replace(b"\r", b"\n").split(b"\n"):
            if line:
                yield line


def _find_newline(buf: bytes) -> int:
    a, b = buf.find(b"\n"), buf.find(b"\r")
    if a < 0:
        return b
    if b < 0:
        return a
    return min(a, b)


class RecordIOSplitter(InputSplitBase):
    """record = RecordIO frame (reference src/io/recordio_split.{h,cc});
    align=4.

    Compressed-block-aware: chunks are decoded (io/recordio.decode_chunk
    — one vectorized detection pass for v1 files, parallel per-block
    decompression for compressed ones) before leaving ``next_chunk``,
    so every downstream consumer — extract_records, the fused native
    kernels, RowRecParser, the staging layer — sees pure v1 frames and
    works on compressed files unchanged. Byte-range sharding still
    snaps to heads via the magic scan (compressed blocks are heads with
    their reserved cflags), and a block is atomic to one shard."""

    _align = 4
    _is_text = False

    def __init__(
        self,
        uri: str,
        part_index: int = 0,
        num_parts: int = 1,
        filesys: Optional[FileSystem] = None,
        recurse_directories: bool = False,
        decode_ctx: Optional[_codec.DecodeContext] = None,
    ) -> None:
        """``decode_ctx``: the block-decode seam (L1 LRU + shared host
        tier + pool, io/codec.py DecodeContext) — injectable so tests
        can pin a private cache or a fake daemon; defaults to the
        process-global two-level context."""
        # set BEFORE super().__init__: reset_partition runs inside it
        # and the decode paths must already have their seam
        self._decode_ctx = (
            decode_ctx
            if decode_ctx is not None
            else _codec.default_decode_context()
        )
        super().__init__(
            uri,
            part_index,
            num_parts,
            filesys=filesys,
            recurse_directories=recurse_directories,
        )

    def _next_chunk_ex(self) -> Optional[bytes]:
        chunk = super()._next_chunk_ex()
        if chunk is None:
            return None
        return decode_chunk(chunk, ctx=self._decode_ctx)

    def seek_record_begin(self, stream: Stream) -> int:
        """Scan forward for a record head (reference recordio_split.cc:9-25),
        buffered with one-word overlap across blocks."""
        pos = 0  # absolute offset of buf[0] from the scan start
        buf = b""
        while True:
            data = stream.read(1 << 16)
            buf += data
            usable = len(buf) & ~3
            if usable >= 8:
                words = np.frombuffer(buf[:usable], dtype="<u4")
                hit = first_head_in_words(words)
                if hit >= 0:
                    return pos + hit * 4
            if not data:
                return pos + len(buf)  # EOF: skip everything (reference :12)
            # keep the last word: it may be the magic of a header whose lrec
            # arrives in the next block
            keep = max(usable - 4, 0)
            pos += keep
            buf = buf[keep:]

    def find_last_record_begin(self, data: bytes) -> int:
        """Reference recordio_split.cc:26-42 (backward scan → we take the
        last forward hit; same record head)."""
        usable = len(data) & ~3
        hit = last_head_in_words(np.frombuffer(data[:usable], dtype="<u4"))
        return hit * 4 if hit >= 0 else 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        for rec in RecordIOChunkReader(chunk, 0, 1):
            yield bytes(rec)


def normalize_shuffle(v):
    """Canonicalize a shuffle option (keyword arg or URI string).

    None/0/False → False (off); 'record'/1/True → per-record shuffle
    (reference semantics); 'batch'/2 → coalesced span shuffle;
    'window'/3 → windowed shuffle with coalesced I/O. One resolver for
    the factory and every URI-sugar guard, so option parsing cannot
    drift between call sites."""
    if v in (None, False, 0, "0", ""):
        return False
    if v in ("batch", 2, "2"):
        return "batch"
    if v in ("window", 3, "3"):
        return "window"
    if v in ("record", "1", 1, True):
        return "record"
    raise Error(f"invalid shuffle={v!r}: use 0/1/record/batch/window")


def _plan_span_bounds(
    offs: np.ndarray, sizes: np.ndarray, merge_gap: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized span-planner core: offset-sort the records, then cut
    the sorted run wherever the byte gap from the running span end to
    the next record's start exceeds ``merge_gap``.

    Returns ``(order, starts, ends)``: ``order`` indexes the inputs
    offset-sorted; span j covers sorted positions
    ``order[starts[j]:ends[j]]``. This is the hot path (one call per
    shuffle window, arrays the size of the window); the tuple-level
    ``plan_coalesced_spans`` wraps it for callers and tests."""
    order = np.argsort(offs, kind="stable")
    soffs = offs[order]
    # running max handles entries contained inside a predecessor
    run_end = np.maximum.accumulate(soffs + sizes[order])
    breaks = np.flatnonzero(soffs[1:] - run_end[:-1] > merge_gap) + 1
    starts = np.concatenate(([0], breaks))
    ends = np.concatenate((breaks, [len(offs)]))
    return order, starts, ends


def plan_coalesced_spans(
    entries: List[Tuple[int, int, int]], merge_gap: int
) -> List[Tuple[int, int, List[Tuple[int, int, int]]]]:
    """Coalesce record reads into large contiguous spans.

    ``entries`` is ``[(offset, size, tag), ...]`` in any order; the
    planner sorts by offset and merges a record into the preceding span
    when the gap between the span's end and the record's start is at
    most ``merge_gap`` bytes (0 merges only byte-adjacent records).
    Returns ``[(span_begin, span_end, members)]`` with ``members`` the
    entries the span covers, offset-sorted — one positioned read per
    span serves every member, trading at most ``merge_gap`` wasted
    bytes per merge for one less seek."""
    if not entries:
        return []
    offs = np.asarray([e[0] for e in entries], dtype=np.int64)
    sizes = np.asarray([e[1] for e in entries], dtype=np.int64)
    order, starts, ends = _plan_span_bounds(offs, sizes, merge_gap)
    out: List[Tuple[int, int, List[Tuple[int, int, int]]]] = []
    for s, e in zip(starts.tolist(), ends.tolist()):
        members = [entries[i] for i in order[s:e].tolist()]
        span_end = max(m[0] + m[1] for m in members)
        out.append((members[0][0], span_end, members))
    return out


def _native_shuffle(rnd: random.Random, perm: np.ndarray) -> bool:
    """Shuffle ``perm`` in place bit-identically to ``rnd.shuffle``
    via the native MT19937 kernel; False = caller must fall back to
    ``rnd.shuffle`` (kernel missing, or the permutation is too large
    for the single-word getrandbits rule)."""
    try:
        from ..data import native as _native
    except ImportError:  # data layer unavailable (minimal installs)
        return False
    return _native.shuffle_mt19937(rnd, perm)


def _index_stat_key(index_uri: str, total: int):
    """Cache key for a LOCAL index file — (uri, mtime_ns, size, total)
    — or None (remote/unstattable: no caching, a stat per construction
    there would be the network round trip the cache exists to avoid and
    a stale remote index served forever is worse than a re-read)."""
    path = (
        index_uri[len("file://"):]
        if index_uri.startswith("file://")
        else index_uri
    )
    if "://" in path:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (index_uri, st.st_mtime_ns, st.st_size, total)


def _load_index_uri(index_uri: str, total: int) -> Dict[str, np.ndarray]:
    stream = Stream.create(index_uri, "r")
    with stream:
        text = stream.read().decode()
    return _parse_index_text(text, total, index_uri)


# parsed-index LRU: keyed by (uri, mtime_ns, size, total), bounded by
# TOTAL ARRAY BYTES (DMLC_INDEX_CACHE_MB, default 256) — an lru_cache
# by entry count would pin multi-GB parses for the process lifetime
# long after every splitter referencing them closed
_INDEX_CACHE: "OrderedDict[Tuple, Dict[str, np.ndarray]]" = OrderedDict()
_INDEX_CACHE_BYTES = 0
_INDEX_CACHE_LOCK = threading.Lock()


def _index_cache_budget() -> int:
    return max(0, int(os.environ.get("DMLC_INDEX_CACHE_MB", "256"))) << 20


def _load_index_cached(stat_key) -> Dict[str, np.ndarray]:
    """Parsed index arrays keyed by (uri, mtime_ns, size, total): a
    sharded/threaded fan-out constructs one splitter per sub-shard and
    must not re-read and re-parse the same (possibly large) index file
    per thread — but a rewritten local file re-parses (mtime key). The
    arrays are shared read-only across splitters; parses bigger than
    the whole budget are served uncached."""
    global _INDEX_CACHE_BYTES
    with _INDEX_CACHE_LOCK:
        data = _INDEX_CACHE.get(stat_key)
        if data is not None:
            _INDEX_CACHE.move_to_end(stat_key)
            return data
    data = _load_index_uri(stat_key[0], stat_key[3])
    nbytes = sum(v.nbytes for v in data.values())
    budget = _index_cache_budget()
    if nbytes <= budget:
        with _INDEX_CACHE_LOCK:
            if stat_key not in _INDEX_CACHE:
                _INDEX_CACHE[stat_key] = data
                _INDEX_CACHE_BYTES += nbytes
            _INDEX_CACHE.move_to_end(stat_key)
            while _INDEX_CACHE_BYTES > budget and len(_INDEX_CACHE) > 1:
                _k, old = _INDEX_CACHE.popitem(last=False)
                _INDEX_CACHE_BYTES -= sum(v.nbytes for v in old.values())
                # a many-corpus serve daemon cycling indexes shows up
                # here, not as silent RSS growth (docs/observability.md)
                _INDEX_EVICTIONS.inc()
    return data


def _parse_index_keys(kvals: List[str], index_uri: str) -> np.ndarray:
    """The sidecar's key column as a numpy array (int64 when every key
    parses as an integer — the writer's default ordinals and the common
    user-key shape — else the raw strings), REJECTING duplicates with a
    checked Error: the epoch paths never read keys, but the point-read
    path (io/lookup.py) resolves by them, and a duplicated key silently
    serving whichever record sorts last is a wrong-answer hazard, not a
    formatting nit."""
    try:
        keys = np.asarray(kvals, dtype=np.int64)
    except (ValueError, OverflowError):
        keys = np.asarray(kvals)
    ks = np.sort(keys)
    dup = np.nonzero(ks[1:] == ks[:-1])[0]
    if dup.size:
        raise Error(
            f"index file {index_uri!r}: duplicate key {ks[int(dup[0])]!r} "
            f"({dup.size + 1 if dup.size == 1 else 'several'} keys repeat) "
            f"— a point lookup would silently return an arbitrary one of "
            f"the records sharing it"
        )
    return keys


def _parse_index_text(
    text: str, total: int, index_uri: str
) -> Dict[str, np.ndarray]:
    """Vectorized index parse → read-only numpy arrays. v1 sidecar
    (``key offset``): {'offs', 'sizes'}; compressed-block sidecar
    (``key block:inoff``, docs/recordio.md): the record→block geometry.
    Both carry ``keys`` — the key column in the SAME record order as the
    offset arrays, so the point-read path (io/lookup.py) resolves
    key→position without a second parse. One C-speed str→int64
    conversion instead of a 2-per-record Python loop — the index parse
    sits on every indexed construction's critical path (it gated the
    shuffled-epoch rebuild)."""
    toks = text.split()
    vals = toks[1::2]
    if not vals:
        raise Error(f"empty index file {index_uri!r}")
    check(
        len(toks) % 2 == 0,
        f"index file {index_uri!r}: odd token count (truncated or "
        f"malformed key/offset pairs)",
    )
    keys = _parse_index_keys(toks[0::2], index_uri)
    mixed = Error(
        f"index file {index_uri!r} mixes v1 and compressed-block offsets"
    )
    if ":" in vals[0]:
        out = _parse_compressed_index(vals, keys, total, index_uri, mixed)
    else:
        try:
            raw = np.asarray(vals, dtype=np.int64)
        except ValueError:
            raise mixed from None
        order = np.argsort(raw, kind="stable")
        offs = raw[order]
        sizes = np.concatenate(
            (np.diff(offs), [total - int(offs[-1])])
        ).astype(np.int64)
        out = {"offs": offs, "sizes": sizes, "keys": keys[order]}
    for v in out.values():
        v.setflags(write=False)  # cached arrays are shared across splits
    return out


_COMPRESSED_INDEX_RE = re.compile(r"\d+:\d+(?: \d+:\d+)*")


def _parse_compressed_index(
    vals: List[str], keys: np.ndarray, total: int, index_uri: str,
    mixed: Error,
) -> Dict[str, np.ndarray]:
    """Compressed sidecar: ``key  <block>:<in>`` per record — the block
    frame's file offset and the record's frame start inside the DECODED
    block. Records sort by (block, in-offset), i.e. file order,
    matching the v1 offset sort. Fully vectorized — one C-speed
    ``:``→space rewrite, one numeric text parse, one lexsort: the
    Python tuple-sort this replaces cost ~1s per 400k records and sat
    on every indexed construction, so both shared-cache bench readers
    were paying it before a single block decoded."""
    joined = " ".join(vals)
    # exactly `int:int` per entry, validated in ONE C-speed regex pass:
    # a v1 entry mixed in ('12345'), junk, or a double-colon entry all
    # fail here — an aggregate token-count check alone can be fooled by
    # counts that coincidentally balance ('1:2:3' next to '4'), and
    # np.fromstring's early-stop-with-warning path must never be
    # reached (warnings filters are process-global and index parses run
    # on fan-out threads)
    if _COMPRESSED_INDEX_RE.fullmatch(joined) is None:
        raise mixed
    nums = np.fromstring(
        joined.replace(":", " "), dtype=np.int64, sep=" "
    )
    check_eq(nums.size, 2 * len(vals), "compressed index parse")
    boff = nums[0::2]
    inoff = nums[1::2]
    order = np.lexsort((inoff, boff))
    rec_boff = boff[order]
    rec_inoff = inoff[order]
    boffs, inv = np.unique(rec_boff, return_inverse=True)
    rec_block = inv.astype(np.int64)
    block_sizes = np.concatenate(
        (np.diff(boffs), [total - int(boffs[-1])])
    ).astype(np.int64)
    check(
        bool((block_sizes > 0).all()) and int(boffs[0]) >= 0,
        f"index file {index_uri!r}: block offsets outside the "
        f"{total}-byte dataset",
    )
    # next record's in-block offset within the same block; -1 = the
    # block's last record (slice runs to the decoded end)
    nxt = np.full(len(rec_boff), -1, dtype=np.int64)
    same = rec_block[1:] == rec_block[:-1]
    nxt[:-1][same] = rec_inoff[1:][same]
    return {
        "rec_block": rec_block,
        "rec_inoff": rec_inoff,
        "rec_next": nxt,
        "block_offs": boffs,
        "block_sizes": block_sizes,
        "keys": keys[order],
    }


class _SpanReader:
    """Positioned span reads over a split's file table, by absolute
    dataset offset (spans may cross file boundaries — the index is
    global).

    Local files are served as ZERO-COPY ``mmap`` views: a span "read"
    is a memoryview of the page cache — no buffer allocation, no
    memcpy, no seek syscall, and no shared stream cursor, so the
    window-shuffle readahead thread can plan while the consumer thread
    drains without racing ``InputSplitBase._fs`` — and the gather
    kernel parses shuffled records straight out of the mapping. Views
    stay valid until ``close()`` (which defers unmapping while any
    view is still exported — the ``BufferError`` guard below). Files
    that cannot map (empty, special) fall back to ``os.pread`` on a
    cached descriptor; remote backends fall back to one private
    SeekStream per file (seek+read pairs, counted in ``seeks``)."""

    def __init__(
        self,
        files: List[FileInfo],
        file_offset: List[int],
        filesys: FileSystem,
    ) -> None:
        self._files = files
        self._file_offset = file_offset
        self._filesys = filesys
        self._fds: Dict[int, int] = {}
        self._mmaps: Dict[int, mmap.mmap] = {}
        self._streams: Dict[int, SeekStream] = {}
        self.seeks = 0

    def _local_path(self, fp: int) -> Optional[str]:
        path = self._files[fp].path
        if path.startswith("file://"):
            return path[len("file://"):]
        return None if "://" in path else path

    def _read_in_file(self, fp: int, rel_off: int, size: int):
        mm = self._mmaps.get(fp)
        if mm is not None:
            return memoryview(mm)[rel_off : rel_off + size]
        fd = self._fds.get(fp)
        if fd is None and fp not in self._streams:
            local = self._local_path(fp)
            if local is not None:
                fd = os.open(local, os.O_RDONLY)
                try:
                    mm = mmap.mmap(fd, 0, access=mmap.ACCESS_READ)
                except (OSError, ValueError):
                    self._fds[fp] = fd  # unmappable: pread fallback
                else:
                    os.close(fd)
                    fd = None
                    self._mmaps[fp] = mm
                    return memoryview(mm)[rel_off : rel_off + size]
            else:
                s = self._filesys.open(self._files[fp].path, "r")
                check(
                    isinstance(s, SeekStream), "input files must be seekable"
                )
                self._streams[fp] = s  # type: ignore[assignment]
        out: List[bytes] = []
        if fd is not None:
            while size > 0:
                data = os.pread(fd, size, rel_off)
                if not data:
                    break
                out.append(data)
                rel_off += len(data)
                size -= len(data)
        else:
            stream = self._streams[fp]
            stream.seek(rel_off)
            self.seeks += 1
            _SEEKS.inc()
            while size > 0:
                data = stream.read(size)
                if not data:
                    break
                out.append(data)
                size -= len(data)
        return out[0] if len(out) == 1 else b"".join(out)

    def read(self, offset: int, size: int):
        """Span bytes at absolute dataset ``offset`` — a zero-copy
        memoryview when one mmapped file covers the span, else joined
        bytes. File-boundary walk shared with the fetcher
        (``spanfetch.iter_file_segments``)."""
        out: List[bytes] = []
        for fp, rel, take, _base in _spanfetch.iter_file_segments(
            self._file_offset, len(self._files), offset, size
        ):
            data = self._read_in_file(fp, rel, take)
            if not data:
                break
            out.append(data)
            if len(data) < take:
                break
        return out[0] if len(out) == 1 else b"".join(out)

    def readinto(self, offset: int, out: memoryview) -> int:
        """Fill ``out`` with the span at absolute dataset ``offset``;
        returns bytes written. The readinto form of ``read`` for the
        preallocated window buffer: the mmap fast path copies straight
        from the page cache into the caller's buffer (one memcpy, no
        intermediate bytes object), so a multi-span window never holds
        both a parts list and its join."""
        written = 0
        for fp, rel, take, base in _spanfetch.iter_file_segments(
            self._file_offset, len(self._files), offset, len(out)
        ):
            data = self._read_in_file(fp, rel, take)
            if not data:
                break
            out[base : base + len(data)] = data
            written = base + len(data)
            if len(data) < take:
                break
        return written

    def close(self) -> None:
        for fd in self._fds.values():
            try:
                os.close(fd)
            except OSError:
                pass
        self._fds.clear()
        for mm in self._mmaps.values():
            try:
                mm.close()
            except BufferError:
                pass  # a handed-out span view is still alive; GC finishes
        self._mmaps.clear()
        for s in self._streams.values():
            s.close()
        self._streams.clear()


class IndexedRecordIOSplitter(RecordIOSplitter):
    """Shards by RECORD COUNT via an external index file, with optional
    per-epoch shuffled batched reads (reference
    src/io/indexed_recordio_split.{h,cc}).

    Index file: whitespace-separated ``index offset`` pairs
    (ReadIndexFile, indexed_recordio_split.cc:43-62).

    ``shuffle`` modes — all three ride ONE emission path (the window
    machinery below: coalesced-span loads into a client-side buffer,
    vectorized/index-driven emission, optional zero-copy
    ``next_gather_batch``); they differ only in the permutation they
    emit and how it is cut into windows:

    - ``True`` / ``'record'``: full per-record permutation — the
      reference's NextBatchEx shuffle order
      (indexed_recordio_split.cc:159-191) served as ONE window covering
      the whole shard on local uncompressed files: every byte is read
      once through coalesced spans (a zero-copy mmap of the page
      cache) and records leave the buffer in permutation order.
      Compressed or remote sources bound the window to ``window``
      records instead (same order — windows only cut the global
      permutation — but a shard-wide buffer there would materialize
      the whole shard in RAM).
      ``legacy_shuffle=True`` (URI: ``&legacy_shuffle=1``) forces the
      reference's literal one-seek-per-record loop instead — same
      order, kept for A/B measurement of the gather fast path.
    - ``'batch'``: permute SPANS of ``batch_size`` contiguous records
      (records inside a span keep file order) — the chunk-shuffle trade
      every production reader makes; the span-expanded per-record
      permutation is served through the same windowed loader, so each
      window's spans coalesce and prefetch like window mode.
    - ``'window'``: full per-record permutation (identical epoch order
      to ``'record'`` for the same seed) with bounded memory — the
      permutation is cut into windows of ``window`` records, each
      window's index entries are sorted by byte offset and merged into
      large spans (``plan_coalesced_spans``, gap threshold
      ``merge_gap``), the spans are read with one positioned read each
      (``os.pread``/mmap on local files — no seek syscalls,
      thread-safe; REMOTE files ride the concurrent span fetcher,
      io/spanfetch.py — parallel ranged reads on pooled retrying
      connections, ``DMLC_FETCH_THREADS``/``DMLC_FETCH_INFLIGHT_MB``,
      with fetch→decode overlap on compressed shards), and the
      window's records are emitted from the client-side buffer
      in permutation order. A ThreadedIter readahead stage loads window
      k+1's spans while the consumer drains window k. Memory is bounded
      by ~2-3 windows of records; read amplification is bounded by the
      merged gap bytes.

    Emission from the buffer is batched and index-driven, never
    per-record Python: ``next_batch_ex`` re-frames whole batches with
    one fancy-index gather (the NumPy fallback path), and
    ``next_gather_batch`` hands ``(buf, starts, sizes)`` views straight
    to a native gather kernel (staging/fused.py) with zero copies —
    docs/shuffle.md.
    """

    KRAND_MAGIC = 111  # reference indexed_recordio_split.h:82

    def __init__(
        self,
        uri: str,
        index_uri: str,
        part_index: int = 0,
        num_parts: int = 1,
        batch_size: int = 256,
        shuffle=False,
        seed: int = 0,
        epoch: int = 0,
        skip_records: int = 0,
        window: int = 65536,
        merge_gap: int = 65536,
        readahead: bool = True,
        legacy_shuffle: bool = False,
        filesys: Optional[FileSystem] = None,
        decode_ctx: Optional[_codec.DecodeContext] = None,
    ) -> None:
        """``epoch``/``skip_records``: data-position fast-forward (§5.4
        mid-epoch resume). The permutation is derived from (seed, epoch)
        alone — a DOCUMENTED divergence from the reference's persistent
        RNG (indexed_recordio_split.cc:221-233 reshuffles with carried
        state), which makes any epoch's read order reproducible without
        replaying the epochs before it. ``skip_records`` skips that many
        records of the starting epoch arithmetically (no I/O); in
        ``shuffle='batch'`` mode it must land on a span boundary and in
        ``shuffle='window'`` on a window boundary — the positions a
        batch-/window-granular consumer naturally checkpoints at.

        ``window``/``merge_gap``/``readahead`` apply to
        ``shuffle='window'``: records per shuffle window, the byte gap
        up to which adjacent reads coalesce into one span, and whether
        a background thread prefetches the next window's spans."""
        # one resolver with the factory/URI path (normalize_shuffle), so
        # a typo'd mode raises here too instead of silently degrading to
        # the per-record seek storm
        mode = normalize_shuffle(shuffle)
        self.shuffle_mode: Optional[str] = mode if mode else None
        self.shuffle = self.shuffle_mode is not None
        # legacy escape hatch: the reference's literal per-record seek
        # loop for shuffle='record' (A/B baseline for the gather path)
        self._legacy_record = bool(legacy_shuffle) and mode == "record"
        self.batch_size = batch_size
        check(window >= 1, f"window={window} must be >= 1")
        check(merge_gap >= 0, f"merge_gap={merge_gap} must be >= 0")
        self.window = window
        self.merge_gap = merge_gap
        self._readahead = readahead
        # window-shuffle pipeline state (set before super().__init__ —
        # reset_partition/before_first run inside it and tear these
        # down). A loaded window is (buf, rel, size): span bytes plus
        # per-record start/length in permutation order.
        _WinBuf = Tuple[np.ndarray, np.ndarray, np.ndarray]
        self._win_iter: Optional[ThreadedIter[_WinBuf]] = None
        self._win_gen: Optional[Iterator[_WinBuf]] = None
        self._win_buf: Optional[_WinBuf] = None
        self._win_pos = 0
        self._win_start = 0
        self._win_skip = 0
        self._all_local: Optional[bool] = None  # resolved lazily from files
        self._span_reader: Optional[_SpanReader] = None
        self._span_fetcher: Optional[_spanfetch.SpanFetcher] = None
        # I/O-shape counters (cumulative across epochs; io_stats())
        self.spans_read = 0
        self.seek_calls = 0
        self.bytes_read = 0
        self.records_emitted = 0
        self.gather_batches = 0
        self.gather_bytes = 0
        self.gather_fallback_batches = 0
        self._seed = seed
        self.epoch = epoch - 1  # before_first() increments into `epoch`
        self._skip_next = skip_records
        self.records_consumed = 0
        self._index_loaded = False
        # numpy index mirror: per-record file offsets and framed sizes
        # (vectorized span planning + arithmetic range reads; no
        # per-record tuple list — the parse is one C-speed conversion,
        # shared across sub-shard splitters via _load_index_cached)
        self._index_offs = np.empty(0, dtype=np.int64)
        self._index_sizes = np.empty(0, dtype=np.int64)
        # the sidecar's key column, record order (None until the index
        # loads) — the point-read path (io/lookup.py) resolves by it
        self._index_keys: Optional[np.ndarray] = None
        # compressed-block geometry (set by _read_index_file when the
        # sidecar carries block:in-offset pairs — docs/recordio.md)
        self._compressed = False
        self._rec_block = np.empty(0, dtype=np.int64)  # block id per record
        self._rec_inoff = np.empty(0, dtype=np.int64)  # offset in decoded blk
        self._rec_next = np.empty(0, dtype=np.int64)  # next rec's inoff | -1
        self._block_offs = np.empty(0, dtype=np.int64)  # block file offsets
        self._block_sizes = np.empty(0, dtype=np.int64)  # on-disk framed size
        self._cache_key: object = None
        self.decode_cache_hits = 0
        self.decode_cache_misses = 0
        self._index_uri = index_uri
        self.index_begin = 0
        self.index_end = 0
        self._current = 0
        self._n_overflow = 0
        self._permutation: List[int] = []
        super().__init__(
            uri, part_index, num_parts, filesys=filesys,
            decode_ctx=decode_ctx,
        )

    def _read_index_file(self) -> None:
        total = self.file_offset[-1]
        skey = _index_stat_key(self._index_uri, total)
        data = (
            _load_index_cached(skey)
            if skey is not None
            else _load_index_uri(self._index_uri, total)
        )
        self._index_loaded = True
        self._index_keys = data.get("keys")
        if "offs" in data:
            self._index_offs = data["offs"]
            self._index_sizes = data["sizes"]
            return
        self._compressed = True
        self._rec_block = data["rec_block"]
        self._rec_inoff = data["rec_inoff"]
        self._rec_next = data["rec_next"]
        self._block_offs = data["block_offs"]
        self._block_sizes = data["block_sizes"]
        # decoded-block cache identity: per-file (path, size, local
        # mtime_ns, backend etag) + total size + block-layout digest +
        # (per lookup) the block's file offset. The mtime term makes an
        # IN-PLACE rewrite of a local file a different cache identity
        # even when the new content reproduces the exact block
        # geometry; remote backends carry whatever change token their
        # stat surfaced (S3/GCS/HTTP ETag, WebHDFS modificationTime —
        # FileInfo.etag), so an in-place remote rewrite misses instead
        # of serving stale decoded bytes; backends with no token fall
        # back to path+size+layout identity. Every component is a plain
        # str/int and the layout term a sha1 digest (NOT Python's
        # seeded hash()), so the identity is stable ACROSS processes —
        # the shared host tier (io/blockcache.py) keys on it.
        sig = []
        for f in self.files:
            path = f.path
            local = (
                path[len("file://"):]
                if path.startswith("file://")
                else (None if "://" in path else path)
            )
            mtime = 0
            if local is not None:
                try:
                    mtime = os.stat(local).st_mtime_ns
                except OSError:
                    pass
            sig.append(
                (path, int(f.size), mtime, getattr(f, "etag", "") or "")
            )
        self._cache_key = (
            tuple(sig),
            int(total),
            hashlib.sha1(self._block_offs.tobytes()).hexdigest(),
        )
        # byte-offset anchors: a record 'sits at' its block's file
        # offset, which keeps reset_partition's offset_begin/offset_end
        # bookkeeping meaningful (sizes are a compressed-path no-op)
        self._index_offs = self._block_offs[self._rec_block]
        self._index_sizes = np.zeros(len(self._rec_block), dtype=np.int64)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Record-count range (reference indexed_recordio_split.cc:12-41)."""
        if not self._index_loaded:
            self._read_index_file()
        ntotal = len(self._index_offs)
        nstep = (ntotal + num_parts - 1) // num_parts
        if part_index * nstep >= ntotal:
            self.offset_begin = self.offset_end = self.offset_curr = 0
            self.index_begin = self.index_end = 0
            self._permutation = []
            self._current = 0
            self._n_overflow = 0
            self._overflow = b""
            self._rec_iter = None
            self._teardown_window_pipeline()
            self._close_fs()
            return
        self.index_begin = part_index * nstep
        self.offset_begin = int(self._index_offs[self.index_begin])
        self.index_end = min((part_index + 1) * nstep, ntotal)
        if self.index_end < ntotal:
            self.offset_end = int(self._index_offs[self.index_end])
        else:
            self.offset_end = self.file_offset[-1]
        self._n_overflow = 0
        self.before_first()

    def before_first(self) -> None:
        """Starts the next epoch: derives the permutation from
        (seed, epoch) — deterministic per epoch, so a resume can rebuild
        epoch N's exact read order directly (reference
        indexed_recordio_split.cc:221-233 reshuffles with persistent RNG
        state instead; divergence documented on __init__)."""
        if self.index_end <= self.index_begin:
            return
        self.epoch += 1
        rnd = random.Random(
            self.KRAND_MAGIC + self._seed + 1_000_003 * self.epoch
        )
        if self.shuffle_mode == "batch":
            # tear the previous epoch's readahead down FIRST: a live
            # producer slicing a half-built permutation would issue (and
            # count) span reads for a window that is about to be thrown
            # away
            self._teardown_window_pipeline()
            # permute span STARTS; each span is batch_size contiguous
            # records served in file order. Only FULL spans are
            # shuffled — the remainder span (ntotal % batch_size
            # records) always reads last, so every multiple of
            # batch_size is a span boundary and therefore a resumable
            # position (skip_records would otherwise land inside the
            # short span whenever the shuffle placed it early). The
            # span permutation is then expanded to a per-record
            # permutation so batch mode rides the same windowed
            # gather emission as record/window.
            total = self.index_end - self.index_begin
            full_end = self.index_begin + (total // self.batch_size) * (
                self.batch_size
            )
            span_starts = list(
                range(self.index_begin, full_end, self.batch_size)
            )
            rnd.shuffle(span_starts)
            if full_end < self.index_end:
                span_starts.append(full_end)
            starts = np.asarray(span_starts, dtype=np.int64)
            counts = np.minimum(starts + self.batch_size, self.index_end) - (
                starts
            )
            pos = np.arange(int(counts.sum()), dtype=np.int64)
            self._permutation = np.repeat(starts, counts) + (
                pos - np.repeat(np.cumsum(counts) - counts, counts)
            )
            self._current = 0
        elif self.shuffle_mode in ("record", "window"):
            self._teardown_window_pipeline()
            if self._legacy_record:
                self._permutation = list(
                    range(self.index_begin, self.index_end)
                )
                rnd.shuffle(self._permutation)
            else:
                # window mode emits the SAME (seed, epoch) permutation
                # as record mode — the window machinery only changes
                # how the bytes reach the buffer, never the order they
                # leave it. The native MT19937 kernel replays
                # random.Random's exact draw/swap sequence (parity
                # tested), so the permutation stays bit-identical to
                # the legacy loop's whichever path computes it.
                perm = np.arange(
                    self.index_begin, self.index_end, dtype=np.int64
                )
                if not _native_shuffle(rnd, perm):
                    rnd.shuffle(perm)  # same swaps, interpreter speed
                self._permutation = perm
            self._current = 0
        else:
            self._current = self.index_begin
        self._n_overflow = 0
        self.records_consumed = 0
        if self._skip_next:
            self._fast_forward(self._skip_next)
            self._skip_next = 0
        super().before_first()

    def _fast_forward(self, n: int) -> None:
        """Skip ``n`` records of the CURRENT epoch arithmetically."""
        total = self.index_end - self.index_begin
        check(
            0 <= n <= total,
            f"skip_records={n} outside this shard's {total} records",
        )
        if self.windowed:
            if self.shuffle_mode == "batch":
                # only FULL spans shuffle (the remainder span reads
                # last), so resumable positions are exactly the
                # batch_size multiples inside the full-span range, plus
                # end-of-shard
                full = (total // self.batch_size) * self.batch_size
                check(
                    (n % self.batch_size == 0 and n <= full) or n == total,
                    f"skip_records={n} lands inside a shuffled span "
                    f"(checkpoint at span boundaries — batch_size="
                    f"{self.batch_size} multiples)",
                )
            elif self.shuffle_mode == "window":
                check(
                    n % self.window == 0 or n == total,
                    f"skip_records={n} lands inside a shuffled window of "
                    f"{self.window} (checkpoint at window boundaries — "
                    f"window={self.window} multiples)",
                )
            # record mode: any position resumes (the first window is
            # simply sliced from n on, so skipped records are never read)
            W = self._eff_window()
            self._win_start = n // W
            self._win_skip = n - self._win_start * W
        elif self._legacy_record:
            self._current = n
        else:
            self._current = self.index_begin + n
        self.records_consumed = n

    def _read_at(self, offset: int, size: int) -> bytes:
        """Seek to an absolute dataset offset and read (the shuffle path's
        per-record random I/O, reference indexed_recordio_split.cc:163-191)."""
        fp = bisect.bisect_right(self.file_offset, offset) - 1
        if fp != self._file_ptr or self._fs is None:
            self._close_fs()
            self._file_ptr = fp
            self._fs = self._open(fp)
        self._fs.seek(offset - self.file_offset[fp])
        self.offset_curr = offset
        out: List[bytes] = []
        nleft = size
        while nleft > 0:
            data = self._fs.read(nleft)
            if not data:
                if self._file_ptr + 1 >= len(self.files):
                    break
                self._file_ptr += 1
                self._fs.close()
                self._fs = self._open(self._file_ptr)
                continue
            out.append(data)
            nleft -= len(data)
            self.offset_curr += len(data)
        self.seek_calls += 1
        self.spans_read += 1
        self.bytes_read += size - nleft
        _SEEKS.inc()
        _SPANS.inc()
        _BYTES_READ.inc(size - nleft)
        return b"".join(out)

    # -- compressed-block machinery ------------------------------------------
    def _block_key(self, bid: int) -> object:
        return (self._cache_key, int(self._block_offs[bid]))

    def _get_fetcher(self) -> Optional[_spanfetch.SpanFetcher]:
        """The concurrent ranged-read engine (io/spanfetch.py) for
        REMOTE files, or None: local files keep the zero-copy
        mmap/pread ``_SpanReader`` fast path untouched, and
        ``DMLC_FETCH_THREADS=1`` pins the serial baseline the
        ``rec_remote_latency`` bench config scores against."""
        if self._files_all_local() or _spanfetch.fetch_threads() <= 1:
            return None
        if self._span_fetcher is None:
            self._span_fetcher = _spanfetch.SpanFetcher(
                self.files, self.file_offset, self.filesys
            )
        return self._span_fetcher

    def _get_span_reader(self) -> _SpanReader:
        if self._span_reader is None:
            self._span_reader = _SpanReader(
                self.files, self.file_offset, self.filesys
            )
        return self._span_reader

    def _fetch_blocks(self, missing: List[int]) -> Dict[int, bytes]:
        """Read, decode and publish the given MISSING block ids — the
        one miss path under ``_load_window_compressed`` and
        ``_emit_range`` after the two-level lookup answered empty.

        The blocks' file ranges coalesce into spans at block
        granularity (``merge_gap`` waste bound). Remote files read them
        as parallel ranged fetches (span fetcher) delivered in
        COMPLETION order; local files read them serially off the
        mmap/pread span reader. Either way each span's blocks are
        submitted to the shared decode pool AS THE SPAN LANDS, so fetch
        and decompress overlap inside one window instead of decoding
        only after the whole window joined."""
        ctx = self._decode_ctx
        marr = np.asarray(missing, dtype=np.int64)
        offs = self._block_offs[marr]
        sizes = self._block_sizes[marr]
        order, starts, ends = _plan_span_bounds(
            offs, sizes, self.merge_gap
        )
        span_begin = offs[order][starts]
        run_end = np.maximum.accumulate(offs[order] + sizes[order])
        span_len = run_end[ends - 1] - span_begin
        spans = list(zip(span_begin.tolist(), span_len.tolist()))
        pending: List[Tuple[int, object]] = []  # (bid, decode Future)

        def on_span(si: int, data) -> None:
            nbytes = spans[si][1]
            check_eq(len(data), nbytes, "span read truncated")
            self.spans_read += 1
            self.bytes_read += nbytes
            _SPANS.inc()
            _BYTES_READ.inc(nbytes)
            mv = memoryview(data)
            begin = spans[si][0]
            for k in order[starts[si] : ends[si]].tolist():
                rel = int(offs[k]) - begin
                blob, _end = scan_compressed_blob(
                    mv[rel : rel + int(sizes[k])], 0
                )
                pending.append((int(marr[k]), ctx.submit_decode(blob)))

        fetcher = self._get_fetcher() if len(spans) > 1 else None
        if fetcher is not None:
            for si, data in fetcher.fetch_iter(spans):
                on_span(si, data)
        else:
            reader = self._get_span_reader()
            for si, (begin, nbytes) in enumerate(spans):
                on_span(si, reader.read(begin, nbytes))
        out: Dict[int, bytes] = {}
        for bid, fut in pending:
            raw, _n = fut.result()
            out[bid] = raw
            ctx.put_block(self._block_key(bid), raw)
        return out

    def _emit_range(self, lo: int, hi: int) -> bytes:
        """Framed v1 bytes of records [lo, hi) of a compressed file:
        decode each covered block (cache-served), slice by the index's
        in-block offsets. The range's blocks go through the decode
        context in ONE batched lookup (L1 then one shared-tier round
        trip), then misses ride the coalesced ``_fetch_blocks`` miss
        path (parallel ranged reads on remote files, decode overlapped
        span by span). Output is byte-identical to the uncompressed
        writer's framing for the same records."""
        runs: List[Tuple[int, int, int]] = []  # (bid, first, last) recs
        i = lo
        while i < hi:
            b = int(self._rec_block[i])
            j = i + 1
            while j < hi and int(self._rec_block[j]) == b:
                j += 1
            runs.append((b, i, j))
            i = j
        uniq = {b for b, _i, _j in runs}
        found = self._decode_ctx.get_blocks(
            [self._block_key(b) for b in uniq]
        )
        blocks: Dict[int, bytes] = {}
        for b in uniq:
            raw = found.get(self._block_key(b))
            if raw is not None:
                self.decode_cache_hits += 1
                blocks[b] = raw
        missing = sorted(b for b in uniq if b not in blocks)
        if missing:
            self.decode_cache_misses += len(missing)
            blocks.update(self._fetch_blocks(missing))
        views: Dict[int, memoryview] = {}
        out: List[memoryview] = []
        for b, i, j in runs:
            mv = views.get(b)
            if mv is None:
                mv = views[b] = memoryview(blocks[b])
            start = int(self._rec_inoff[i])
            end = int(self._rec_next[j - 1])
            # memoryview slices: the only copy is the final join (the
            # bytes-slice version copied every run twice)
            out.append(mv[start:] if end < 0 else mv[start:end])
        return b"".join(out)

    def _read_spans(
        self, span_begin: np.ndarray, span_len: np.ndarray
    ) -> np.ndarray:
        """A window's planned spans as ONE uint8 buffer, spans at their
        planned offsets. A single span stays a zero-copy wrap of the
        span reader's view (an mmap of the page cache on local files);
        multiple spans fill one PREALLOCATED buffer in place — readinto
        on the serial path, parallel ranged reads through the span
        fetcher on remote backends (``fetch_into`` writes each span at
        its base as it lands). Either way peak memory is the window
        buffer itself: no parts list + full-window join copy."""
        spans = list(zip(span_begin.tolist(), span_len.tolist()))
        total = int(span_len.sum())
        n_spans = len(spans)
        self.spans_read += n_spans
        self.bytes_read += total
        _SPANS.inc(n_spans)
        _BYTES_READ.inc(total)
        fetcher = self._get_fetcher() if n_spans > 1 else None
        if fetcher is not None:
            buf = np.empty(total, dtype=np.uint8)
            bases = np.concatenate(([0], np.cumsum(span_len)[:-1]))
            fetcher.fetch_into(spans, memoryview(buf), bases.tolist())
            return buf
        reader = self._get_span_reader()
        if n_spans == 1:
            begin, nbytes = spans[0]
            data = reader.read(begin, nbytes)
            check_eq(len(data), nbytes, "span read truncated")
            return np.frombuffer(data, dtype=np.uint8)
        buf = np.empty(total, dtype=np.uint8)
        mv = memoryview(buf)
        base = 0
        for begin, nbytes in spans:
            got = reader.readinto(begin, mv[base : base + nbytes])
            check_eq(got, nbytes, "span read truncated")
            base += nbytes
        return buf

    def _load_window_compressed(
        self, perm: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Window shuffle over compressed blocks: span coalescing is
        valid at BLOCK granularity — the window's unique blocks are
        read via coalesced file spans (merge_gap bytes of waste bound),
        decompressed in parallel on the shared codec pool (overlapped
        with the consumer by the window readahead thread), and served
        from the two-level decode context — the in-process LRU first,
        then the host daemon's shared tier (a colocated process already
        decoded the window? zero decode, zero remote bytes), then
        span-read + pool-decode + publish. The emission buffer
        concatenates decoded blocks; per-record (start, size) come from
        the index's in-block offsets, in permutation order."""
        bids = self._rec_block[perm]
        uniq = np.unique(bids)
        ctx = self._decode_ctx
        decoded: Dict[int, bytes] = {}
        missing: List[int] = []
        found = ctx.get_blocks(
            [self._block_key(b) for b in uniq.tolist()]
        )
        for b in uniq.tolist():
            data = found.get(self._block_key(b))
            if data is None:
                missing.append(b)
            else:
                self.decode_cache_hits += 1
                decoded[b] = data
        self.decode_cache_misses += len(missing)
        if missing:
            # timeline span with the miss count: a window served from
            # the caches skips this entirely, so the Perfetto row shows
            # exactly which windows paid a fetch+decode and how long
            with _tracing.span(
                "dmlc:window_span_decode", blocks=len(missing)
            ):
                decoded.update(self._fetch_blocks(missing))
        lens = np.asarray(
            [len(decoded[b]) for b in uniq.tolist()], dtype=np.int64
        )
        base = np.concatenate(([0], np.cumsum(lens)[:-1]))
        buf = np.frombuffer(
            b"".join(decoded[b] for b in uniq.tolist()), dtype=np.uint8
        )
        pos = np.searchsorted(uniq, bids)
        rec_start = base[pos] + self._rec_inoff[perm]
        nxt = self._rec_next[perm]
        rec_end = base[pos] + np.where(nxt >= 0, nxt, lens[pos])
        idt = np.int32 if len(buf) < (1 << 31) else np.int64
        return (
            buf,
            rec_start.astype(idt),
            (rec_end - rec_start).astype(idt),
        )

    # -- window-shuffle machinery -------------------------------------------
    @property
    def windowed(self) -> bool:
        """True when this split serves its shuffle through the unified
        window/gather machinery (record without the legacy escape
        hatch, batch, window) — i.e. ``next_gather_batch`` is live and
        the split prefetches internally (create() returns it bare)."""
        return (
            self.shuffle_mode in ("record", "batch", "window")
            and not self._legacy_record
        )

    def supports_gather(self) -> bool:
        """Whether ``next_gather_batch`` serves this configuration."""
        return self.windowed

    def _eff_window(self) -> int:
        """Records per shuffle window on the unified path: record mode
        is one window covering the shard — but ONLY where that window
        is a zero-copy mmap of local uncompressed files (resident =
        page cache, each byte read once). On compressed or remote
        sources a shard-wide window would MATERIALIZE the whole shard
        (decoded blocks / downloaded spans) in one buffer, so record
        mode bounds itself to ``self.window``-record windows there —
        the emitted order is IDENTICAL for any window size (the
        permutation is global; windows only cut it), memory stays
        bounded, and the cost is window-count read passes like window
        mode. batch/window modes always use ``self.window``."""
        if self.shuffle_mode == "record":
            if not self._compressed and self._files_all_local():
                return max(1, len(self._permutation))
            return max(1, self.window)
        return self.window

    def _files_all_local(self) -> bool:
        if self._all_local is None:
            self._all_local = all(
                f.path.startswith("file://") or "://" not in f.path
                for f in self.files
            )
        return self._all_local

    def _n_windows(self) -> int:
        return -(-len(self._permutation) // self._eff_window())

    def _teardown_window_pipeline(self) -> None:
        if self._win_iter is not None:
            self._win_iter.destroy()
            self._win_iter = None
        self._win_gen = None
        self._win_buf = None
        self._win_pos = 0
        self._win_start = 0
        self._win_skip = 0

    def _load_window(
        self, lo: int, hi: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Read the records at permutation positions [lo, hi) via
        coalesced spans. Returns the client-side shuffle buffer
        ``(buf, rel, size)``: one uint8 buffer of span bytes plus each
        record's start offset and length in PERMUTATION order — the
        emission path gathers records out with vectorized fancy
        indexing, no per-record Python.

        When the merged gaps more than double the buffer (aggressive
        ``merge_gap`` over a sparse window), the buffer is compacted to
        the records' own bytes with one extra gather, bounding resident
        memory at ~the window's record bytes."""
        with annotate("dmlc:window_load"):
            return self._load_window_inner(lo, hi)

    def _load_window_inner(
        self, lo: int, hi: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        perm = np.asarray(self._permutation[lo:hi], dtype=np.int64)
        if self._compressed:
            return self._load_window_compressed(perm)
        offs = self._index_offs[perm]
        sizes = self._index_sizes[perm]
        order, starts, ends = _plan_span_bounds(
            offs, sizes, self.merge_gap
        )
        soffs = offs[order]
        s_sorted = sizes[order]
        run_end = np.maximum.accumulate(soffs + s_sorted)
        span_begin = soffs[starts]
        span_len = run_end[ends - 1] - span_begin
        buf = self._read_spans(span_begin, span_len)
        # each sorted entry's start inside buf: offset within its span
        # + the span's base in the concatenation
        counts = ends - starts
        span_base = np.concatenate(([0], np.cumsum(span_len)[:-1]))
        rel_sorted = (
            soffs - np.repeat(span_begin, counts)
            + np.repeat(span_base, counts)
        )
        idt = np.int32 if len(buf) < (1 << 31) else np.int64
        rec_bytes = int(s_sorted.sum())
        if len(buf) > 2 * rec_bytes:
            base = np.cumsum(s_sorted) - s_sorted
            gather = np.arange(rec_bytes, dtype=idt) + np.repeat(
                (rel_sorted - base).astype(idt), s_sorted
            )
            buf = buf[gather]
            rel_sorted = base
        rel = np.empty(len(rel_sorted), dtype=idt)
        rel[order] = rel_sorted.astype(idt)  # sorted → permutation order
        stride = int(sizes[0]) if len(sizes) else 0
        if (
            stride
            and int(sizes.min()) == stride == int(sizes.max())
            and len(buf) % stride == 0
            and not (rel % stride).any()
        ):
            # uniform-stride window (fixed-size records, the common
            # RecordIO-shard shape): emit via 2D row gather — one fancy
            # index at memcpy speed, no per-byte index arrays
            return buf.reshape(-1, stride), rel // stride, None
        return buf, rel, sizes.astype(idt)

    def _window_stream(
        self,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        # _win_start/_win_skip were fixed by before_first/_fast_forward
        # before the first pull starts this generator; the skip applies
        # to the first window only (record-mode resume at any position)
        W = self._eff_window()
        n = len(self._permutation)
        skip = self._win_skip
        for k in range(self._win_start, self._n_windows()):
            lo = min(k * W + skip, n)
            hi = min((k + 1) * W, n)
            skip = 0
            if lo >= hi:
                continue
            yield self._load_window(lo, hi)

    def _refill_window(self) -> bool:
        """Pull the next loaded window into the emission buffer; False
        at end of epoch."""
        if self._readahead:
            if self._win_iter is None:
                # lazy start: before_first/_fast_forward have fixed
                # _win_start by the time the first record is pulled
                self._win_iter = ThreadedIter(
                    self._window_stream,
                    max_capacity=2,
                    name="split-window-readahead",
                )
            nxt = self._win_iter.next()
        else:
            if self._win_gen is None:
                self._win_gen = self._window_stream()
            nxt = next(self._win_gen, None)
        if nxt is None:
            return False
        self._win_buf = nxt
        self._win_pos = 0
        return True

    def _emit_from_window(self, n: int) -> Tuple[int, List[bytes]]:
        """Gather up to ``n`` records (in permutation order) out of the
        buffered windows; returns (count, chunks). One vectorized fancy
        index per window touched — no per-record Python."""
        got = 0
        chunks: List[bytes] = []
        while got < n:
            buf_state = self._win_buf
            if buf_state is None or self._win_pos >= len(buf_state[1]):
                with annotate("dmlc:gather_refill"):
                    refilled = self._refill_window()
                if not refilled:
                    break
                buf_state = self._win_buf
            buf, rel, size = buf_state  # type: ignore[misc]
            take = min(n - got, len(rel) - self._win_pos)
            r = rel[self._win_pos : self._win_pos + take]
            if size is None:
                # uniform-stride: r holds row indices into the 2D buffer
                chunks.append(buf[r].tobytes())
            else:
                s = size[self._win_pos : self._win_pos + take]
                total = int(s.sum())
                # output cursor per record, then shift each run to its
                # record's start in buf
                base = np.cumsum(s, dtype=r.dtype) - s
                gather = np.arange(total, dtype=r.dtype) + np.repeat(
                    r - base, s
                )
                chunks.append(buf[gather].tobytes())
            self._win_pos += take
            got += take
        return got, chunks

    def next_gather_batch(
        self, n_records: int
    ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Zero-copy batched emission for the unified shuffle path:
        returns ``(buf, starts, sizes)`` — a uint8 view of the current
        window's span bytes plus int64 byte offsets/lengths of up to
        ``n_records`` framed records IN PERMUTATION ORDER — or None at
        end of epoch. No record bytes are copied or re-framed; the
        caller parses straight out of the window buffer (the native
        gather kernel, staging/fused.py) and must finish with the views
        before pulling past the current window (the buffer is recycled
        when the window drains). A call never crosses a window
        boundary, so short returns are normal — keep calling until the
        batch is full or None arrives. Only valid when ``windowed``
        (``supports_gather()``)."""
        check(self.windowed, "next_gather_batch needs a windowed shuffle")
        buf_state = self._win_buf
        if buf_state is None or self._win_pos >= len(buf_state[1]):
            # the refill is the part worth a timeline span: it blocks on
            # the readahead thread (or loads inline) — a long one IS the
            # window pipeline starving the consumer. The in-window slice
            # below is a couple of numpy views; tracing it per batch
            # would cost more than it shows.
            with annotate("dmlc:gather_refill"):
                if not self._refill_window():
                    return None
            buf_state = self._win_buf
        buf, rel, size = buf_state  # type: ignore[misc]
        take = min(n_records, len(rel) - self._win_pos)
        r = rel[self._win_pos : self._win_pos + take]
        if size is None:
            # uniform-stride window: rel holds row indices into the 2D
            # buffer; flatten the view and expand to byte offsets
            stride = buf.shape[1]
            starts = r.astype(np.int64) * stride
            sizes = np.full(take, stride, dtype=np.int64)
            out = (buf.reshape(-1), starts, sizes)
        else:
            s = size[self._win_pos : self._win_pos + take]
            out = (buf, r.astype(np.int64), s.astype(np.int64))
        self._win_pos += take
        self.records_consumed += take
        self.records_emitted += take
        self.gather_batches += 1
        nbytes = int(out[2].sum())
        self.gather_bytes += nbytes
        _RECORDS.inc(take)
        _GATHER_BATCHES.inc()
        _GATHER_BYTES.inc(nbytes)
        return out

    def count_gather_fallback(self, n: int = 1) -> None:
        """Consumers that pulled ``next_gather_batch`` views but had to
        RE-FRAME them (native gather kernel absent in the loaded .so)
        report it here, so ``gather_fallback_batches`` keeps its
        meaning — 'emissions that paid the framed-bytes copy' — across
        layers, and a stale binary can't masquerade as the zero-copy
        fast path in io_stats/telemetry."""
        self.gather_fallback_batches += n
        _GATHER_FALLBACK.inc(n)

    def io_stats(self) -> Dict[str, object]:
        """I/O-shape counters, cumulative since construction: ``spans``
        positioned reads issued, ``seeks`` stream seek() calls (0 on
        the local pread fast path), ``bytes_read``, and ``records`` —
        records actually emitted (skip_records fast-forward excluded) —
        plus the robustness counters (``retries``/``backoff_secs``/
        ``faults_injected`` deltas, see InputSplitBase.io_stats).
        Coalescing shows up as spans ≪ records."""
        seeks = self.seek_calls
        if self._span_reader is not None:
            seeks += self._span_reader.seeks
        if self._span_fetcher is not None:
            seeks += self._span_fetcher.seeks
        out = {
            "mode": self.shuffle_mode or "sequential",
            "records": self.records_emitted,
            "spans": self.spans_read,
            "seeks": seeks,
            "bytes_read": self.bytes_read,
            "reopens": _spanfetch.reopens_total() - self._reopen_snap,
            **_retry.stats_delta(self._retry_snap),
        }
        if self._span_fetcher is not None:
            # concurrent-fetch shape (remote sources only): spans
            # actually fetched in parallel and the peak concurrency the
            # AIMD ramp reached — fetch_spans == spans with peak 1
            # means the ramp never engaged (contiguous plan or
            # DMLC_FETCH_THREADS=1 would not create a fetcher at all)
            out["fetch_spans"] = self._span_fetcher.spans
            out["fetch_bytes"] = self._span_fetcher.bytes
            out["fetch_concurrency_peak"] = (
                self._span_fetcher.concurrency_peak
            )
        if self.windowed:
            # gather-emission shape: batches/bytes handed out zero-copy
            # vs emissions that fell back to the framed-bytes gather
            # (generic parsers, native kernel absent) — docs/shuffle.md
            out["gather_batches"] = self.gather_batches
            out["gather_bytes"] = self.gather_bytes
            out["gather_fallback_batches"] = self.gather_fallback_batches
        if self._compressed:
            # decoded-block cache shape: hits ≫ misses on a second epoch
            # proves each block decompressed once (DMLC_DECODE_CACHE_MB)
            out["decode_cache_hits"] = self.decode_cache_hits
            out["decode_cache_misses"] = self.decode_cache_misses
        return out

    def next_batch_ex(self, n_records: int) -> Optional[bytes]:
        """Reference NextBatchEx (indexed_recordio_split.cc:159-212):
        every shuffle mode (record/batch/window) = coalesced spans
        refilling a client-side shuffle buffer (readahead thread) with
        one vectorized re-framing gather per emission — the NumPy
        fallback to ``next_gather_batch``; legacy record mode =
        per-record seeks (the reference's literal loop, kept for A/B);
        sequential = one span."""
        if self.windowed:
            n = self._n_overflow or n_records
            got, chunks = self._emit_from_window(n)
            if not got:
                return None
            self._n_overflow = n - got
            self.records_consumed += got
            self.records_emitted += got
            self.gather_fallback_batches += 1
            _RECORDS.inc(got)
            _GATHER_FALLBACK.inc()
            return chunks[0] if len(chunks) == 1 else b"".join(chunks)
        if self._legacy_record:
            n = self._n_overflow or n_records
            parts: List[bytes] = []
            while len(parts) < n and self._current < len(self._permutation):
                idx = self._permutation[self._current]
                if self._compressed:
                    parts.append(self._emit_range(idx, idx + 1))
                else:
                    parts.append(
                        self._read_at(
                            int(self._index_offs[idx]),
                            int(self._index_sizes[idx]),
                        )
                    )
                self._current += 1
            if not parts:
                return None
            self._n_overflow = n - len(parts)
            self.records_consumed += len(parts)
            self.records_emitted += len(parts)
            _RECORDS.inc(len(parts))
            return b"".join(parts)
        n = self._n_overflow or n_records
        last = min(self._current + n, self.index_end)
        self._n_overflow = self._current + n - last
        if last <= self._current:
            return None
        if self._compressed:
            chunk = self._emit_range(self._current, last)
        else:
            begin_off = int(self._index_offs[self._current])
            end_off = (
                int(self._index_offs[last])
                if last < len(self._index_offs)
                else self.file_offset[-1]
            )
            chunk = self._read_at(begin_off, end_off - begin_off)
        if chunk:
            self.records_consumed += last - self._current
            self.records_emitted += last - self._current
            _RECORDS.inc(last - self._current)
        self._current = last
        return chunk if chunk else None

    def close(self) -> None:
        self._teardown_window_pipeline()
        if self._span_reader is not None:
            self._span_reader.close()
            self._span_reader = None
        if self._span_fetcher is not None:
            self._span_fetcher.close()
            self._span_fetcher = None
        super().close()

    def next_chunk(self) -> Optional[bytes]:
        return self.next_batch_ex(self.batch_size)

    def next_batch(self, n_records: int) -> Optional[bytes]:
        return self.next_batch_ex(n_records)

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self.next_batch_ex(self.batch_size)
            if chunk is None:
                return None
            self._rec_iter = self.extract_records(chunk)


class SingleFileSplit(InputSplit):
    """stdin / single-file text split without sharding (reference
    src/io/single_file_split.h)."""

    def __init__(self, path: str = "-") -> None:
        self._path = path
        self._stream = None
        self._buffer = b""
        self._eof = False
        self._rec_iter: Optional[Iterator[bytes]] = None
        self._size = 0
        self.before_first()

    def _open(self):
        if self._path == "-":
            import sys

            return sys.stdin.buffer
        return open(self._path, "rb")

    def before_first(self) -> None:
        if self._path == "-" and self._stream is not None:
            raise Error("cannot rewind stdin")
        if self._stream is not None and self._path != "-":
            self._stream.close()
        self._stream = self._open()
        self._eof = False
        self._rec_iter = None
        self._overflow = b""

    def total_size(self) -> int:
        if self._path == "-":
            return 0
        import os

        return os.path.getsize(self._path)

    def next_chunk(self) -> Optional[bytes]:
        while not self._eof:
            data = self._stream.read(DEFAULT_BUFFER_BYTES)
            if not data:
                self._eof = True
                if self._overflow:
                    out, self._overflow = self._overflow + b"\n", b""
                    return out
                return None
            data = self._overflow + data
            cut = max(data.rfind(b"\n"), data.rfind(b"\r"))
            if cut <= 0:
                self._overflow = data
                continue
            self._overflow = data[cut + 1 :]
            return data[: cut + 1]
        return None

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        for line in chunk.replace(b"\r", b"\n").split(b"\n"):
            if line:
                yield line

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._rec_iter = self.extract_records(chunk)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check_eq(num_parts, 1, "SingleFileSplit does not shard")


class ThreadedInputSplit(InputSplit):
    """Read-ahead wrapper: prefetch chunks on a background thread with
    double buffering (reference src/io/threaded_input_split.h,
    set_max_capacity(2) at :33)."""

    def __init__(self, base: InputSplitBase, max_capacity: int = 2) -> None:
        self._base = base
        self._cap = max_capacity
        self._rec_iter: Optional[Iterator[bytes]] = None
        self._first_epoch = True
        self._iter: ThreadedIter[bytes] = ThreadedIter(
            self._produce, max_capacity=max_capacity, name="split-prefetch"
        )

    def _produce(self):
        if not self._first_epoch:
            self._base.before_first()
        self._first_epoch = False
        while True:
            chunk = self._base.next_chunk()
            if chunk is None:
                return
            yield chunk

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self._iter.next()
            if chunk is None:
                return None
            self._rec_iter = self._base.extract_records(chunk)

    def before_first(self) -> None:
        self._rec_iter = None
        self._iter.before_first()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._iter.destroy()
        self._base.reset_partition(part_index, num_parts)
        self._first_epoch = True
        self._rec_iter = None
        self._iter = ThreadedIter(
            self._produce, max_capacity=self._cap, name="split-prefetch"
        )

    def total_size(self) -> int:
        return self._base.total_size()

    def hint_chunk_size(self, nbytes: int) -> None:
        self._base.hint_chunk_size(nbytes)

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def io_stats(self) -> Dict[str, object]:
        """Forward the wrapped split's I/O-shape counters (indexed
        splits); empty dict when the base doesn't track them — every
        io_stats() implementation returns a dict (ISSUE 4 satellite:
        callers assume one)."""
        fn = getattr(self._base, "io_stats", None)
        out = fn() if fn is not None else None
        return out if out else {}

    def close(self) -> None:
        self._iter.destroy()
        self._base.close()


class CachedInputSplit(InputSplit):
    """First epoch streams chunks to a local cache file while serving them;
    later epochs replay the cache (reference src/io/cached_input_split.h:
    InitPreprocIter :148-164, InitCachedIter :166-189)."""

    def __init__(self, base: InputSplit, cache_file: str) -> None:
        self._base = base
        self._cache_file = cache_file
        self._cache_complete = False
        self._rec_iter: Optional[Iterator[bytes]] = None
        self._iter: ThreadedIter[bytes] = ThreadedIter(
            self._produce_preproc, name="split-cache-build"
        )

    def _produce_preproc(self):
        out = Stream.create(self._cache_file, "w")
        try:
            while True:
                chunk = self._base.next_chunk()
                if chunk is None:
                    break
                serializer.write_bytes(out, chunk)
                yield chunk
            self._cache_complete = True
        finally:
            out.close()

    def _produce_cached(self):
        stream = Stream.create(self._cache_file, "r")
        try:
            while True:
                n = serializer.try_read_scalar(stream, "uint64")
                if n is None:
                    return
                yield stream.read_exact(n)
        finally:
            stream.close()

    def before_first(self) -> None:
        self._rec_iter = None
        if self._cache_complete:
            self._iter.destroy()
            self._iter = ThreadedIter(self._produce_cached, name="split-cache-replay")
        else:
            # first pass didn't finish: rebuild the cache from scratch
            self._iter.destroy()
            self._base.before_first()
            self._iter = ThreadedIter(self._produce_preproc, name="split-cache-build")

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._rec_iter is not None:
                rec = next(self._rec_iter, None)
                if rec is not None:
                    return rec
            chunk = self._iter.next()
            if chunk is None:
                return None
            self._rec_iter = self._base.extract_records(chunk)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._cache_complete = False
        self._iter.destroy()
        self._base.reset_partition(part_index, num_parts)
        self._iter = ThreadedIter(self._produce_preproc, name="split-cache-build")
        self._rec_iter = None

    def total_size(self) -> int:
        return self._base.total_size()

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def io_stats(self) -> Dict[str, object]:
        fn = getattr(self._base, "io_stats", None)
        out = fn() if fn is not None else None
        return out if out else {}

    def close(self) -> None:
        self._iter.destroy()
        self._base.close()


class InputSplitShuffle(InputSplit):
    """Macro-shuffle: over-partition into num_parts * num_shuffle_parts
    sub-parts and visit this rank's sub-parts in a seeded shuffled order,
    reshuffled each epoch (reference include/dmlc/input_split_shuffle.h:
    24-33, 100-119; kRandMagic_=666 :151)."""

    KRAND_MAGIC = 666

    def __init__(
        self,
        base: InputSplit,
        part_index: int,
        num_parts: int,
        num_shuffle_parts: int,
        seed: int = 0,
    ) -> None:
        check(num_shuffle_parts > 0, "num_shuffle_parts must be positive")
        self._base = base
        self._num_total = num_parts * num_shuffle_parts
        self._sub_parts = [
            part_index * num_shuffle_parts + i for i in range(num_shuffle_parts)
        ]
        self._rnd = random.Random(self.KRAND_MAGIC + seed)
        self._order: List[int] = []
        self._cursor = 0
        self.before_first()

    def before_first(self) -> None:
        self._order = list(self._sub_parts)
        self._rnd.shuffle(self._order)
        self._cursor = 0
        self._base.reset_partition(self._order[0], self._num_total)

    def _advance(self) -> bool:
        self._cursor += 1
        if self._cursor >= len(self._order):
            return False
        self._base.reset_partition(self._order[self._cursor], self._num_total)
        return True

    def next_record(self) -> Optional[bytes]:
        while True:
            rec = self._base.next_record()
            if rec is not None:
                return rec
            if not self._advance():
                return None

    def next_chunk(self) -> Optional[bytes]:
        while True:
            chunk = self._base.next_chunk()
            if chunk is not None:
                return chunk
            if not self._advance():
                return None

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        nsp = len(self._sub_parts)
        self._sub_parts = [part_index * nsp + i for i in range(nsp)]
        self._num_total = num_parts * nsp
        self.before_first()

    def total_size(self) -> int:
        return self._base.total_size()

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def io_stats(self) -> Dict[str, object]:
        fn = getattr(self._base, "io_stats", None)
        out = fn() if fn is not None else None
        return out if out else {}

    def close(self) -> None:
        self._base.close()


class DynamicShardSource(InputSplit):
    """Tracker-leased dynamic sharding: an InputSplit whose shard →
    worker placement is decided at RUN time by the tracker's shard
    service (tracker/shardsvc.py, docs/sharding.md) instead of a
    ``part_index/num_parts`` fixed at open.

    The file set is oversharded into ``K x num_workers`` micro-shards;
    a micro-shard IS ``(part_index=i, num_parts=M)`` of the standard
    byte-range/magic-scan planner, so shard CONTENT — including the
    per-shard ``(seed, epoch)`` shuffle permutation — is bit-identical
    to a static run over the same ``M`` parts; only which worker drains
    which shard changes. The driver pulls a lease, opens the standard
    (windowed) splitter for that micro-shard via ``make_splitter``,
    drains it, reports ``shard_done``, and pulls the next — so a slow
    worker simply takes fewer shards and an idle worker steals the
    reclaimed ones. Waiting for a grantable shard is surfaced as the
    ``dmlc:shard_lease_wait`` stall stage on the flight recorder.

    Semantics: committed work is exactly-once (the ``on_shard_done``
    hook sees ``recorded`` exactly once per micro-shard, cluster-wide);
    record emission is at-least-once only if a LIVE worker outlives its
    lease TTL without renewing (renewal rides every pull and every
    tracker heartbeat). ``before_first()`` starts the next epoch — a
    fresh cluster-wide ledger — mirroring the static splitters'
    epoch-increment contract.

    ``make_splitter(shard, num_shards, epoch)`` must build the shard's
    splitter exactly as the static path would (``create`` wires this
    up; ``dynamic_shards=True`` / ``&dynamic_shards=1``).

    Hooks (settable attributes): ``on_lease(shard, num_shards)`` fires
    after a lease is granted, ``on_shard_done(shard, status)`` after
    the tracker acks a completed shard (status ``recorded`` |
    ``duplicate``) — tests and bench commit per-shard outputs on
    ``recorded`` for end-to-end exactly-once accounting.
    """

    def __init__(
        self,
        make_splitter,
        client=None,
        epoch: int = 0,
        fileset: Optional[str] = None,
        windowed_hint: bool = False,
        renew_frac: float = 3.0,
        make_probe=None,
    ) -> None:
        if client is None:
            # lazy import: the lease protocol (sockets) lives with the
            # tracker — io/ only drives it (lint L010 keeps raw sockets
            # out of this layer)
            from ..tracker.shardsvc import ShardLeaseClient

            client = ShardLeaseClient()
        self._client = client
        self._make_splitter = make_splitter
        # introspection-only builder (total_size before any lease):
        # must NOT start read-ahead, so callers whose make_splitter
        # wraps in ThreadedInputSplit pass the bare construction here
        self._make_probe = make_probe or make_splitter
        self._fileset = fileset
        self._windowed_hint = windowed_hint
        self._renew_frac = max(1.5, renew_frac)
        self.epoch = epoch
        self._started = False
        self._exhausted = False
        self._split: Optional[InputSplit] = None
        self._probe: Optional[InputSplit] = None
        self._total_size: Optional[int] = None
        self._chunk_hint: Optional[int] = None
        self._lease: Optional[Dict] = None
        self._last_renew = 0.0
        self.num_shards: Optional[int] = None
        self.current_shard: Optional[int] = None
        # worker-side shape counters (io_stats)
        self.leases = 0
        self.shards_recorded = 0
        self.shards_duplicate = 0
        self.lease_wait_secs = 0.0
        self.renews_lost = 0
        self._closed_stats: Dict[str, float] = {}
        self.on_lease = None
        self.on_shard_done = None

    # -- lease machinery -----------------------------------------------------
    def _ensure_split(self) -> bool:
        """Hold a live per-shard splitter; False at end of epoch."""
        while self._split is None:
            if self._exhausted:
                return False
            # the lease RPC (and any "come back later" backoff) IS the
            # wait: recording both under the stall span means every
            # shard_lease_wait slice encloses the request's flow-start,
            # so a merged timeline draws the arrow straight to the
            # tracker's shard_lease handler span (docs/observability.md)
            with annotate("dmlc:shard_lease_wait"):
                resp = self._client.lease(self.epoch, self._fileset)
                status = resp.get("status")
                if status == "wait":
                    # every micro-shard is leased out: park (visibly —
                    # this IS the straggler signal on a merged
                    # timeline) until one completes or a lease expires
                    # and is reclaimed
                    backoff = float(resp.get("backoff", 0.1))
                    time.sleep(min(1.0, max(0.01, backoff)))
                    self.lease_wait_secs += backoff
            if status == "lease":
                shard = int(resp["shard"])
                self.num_shards = int(resp["num_shards"])
                self._lease = resp
                self.current_shard = shard
                self.leases += 1
                self._last_renew = time.monotonic()
                split = self._make_splitter(
                    shard, self.num_shards, self.epoch
                )
                if self._chunk_hint:
                    split.hint_chunk_size(self._chunk_hint)
                self._split = split
                if self.on_lease is not None:
                    self.on_lease(shard, self.num_shards)
            elif status == "wait":
                pass  # already parked inside the stall span above
            elif status == "done":
                self._exhausted = True
                return False
            else:
                raise Error(
                    "shard lease request failed: "
                    f"{resp.get('error', resp)!r}"
                )
        return True

    def _maybe_renew(self) -> None:
        if self._lease is None:
            return
        now = time.monotonic()
        ttl = float(self._lease.get("ttl", 30.0))
        interval = ttl / self._renew_frac
        if now - self._last_renew < interval:
            return
        self._last_renew = now
        try:
            # short reconnect budget: a renew rides the READ path, so
            # it must not park the consumer for the full crash-recovery
            # window — this cadence (below) is the real retry loop
            resp = self._client.renew(self.epoch, retry_secs=2.0)
        except (OSError, ConnectionError):
            # transient: retry SOON (1s, not a full interval — two
            # hiccups in a row must not eat the whole TTL), but not on
            # every pull (each attempt can pay a connect timeout)
            self._last_renew = now - interval + min(1.0, interval / 2.0)
            return
        if resp.get("status") == "lost":
            # keep draining: shard_done dedupes (first finisher wins),
            # but count it — a nonzero renews_lost means the TTL is too
            # tight for this worker's stall profile
            self.renews_lost += 1

    @staticmethod
    def _merge_stats(dst: Dict[str, object], stats: Dict) -> None:
        """Numeric counters sum, first non-numeric value wins — ONE
        merge rule for drained and live shards."""
        for k, v in stats.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                dst[k] = dst.get(k, 0) + v
            elif k not in dst:
                dst[k] = v

    def _accumulate_stats(self, split: InputSplit) -> None:
        stats = getattr(split, "io_stats", lambda: None)() or {}
        self._merge_stats(self._closed_stats, stats)

    def _release_lease(self) -> None:
        """Hand an UNFINISHED lease back to the queue (close /
        mid-epoch restart). Best-effort on purpose — but not optional
        in spirit: a process whose rabit heartbeat outlives this source
        would renew the abandoned lease forever. A refused dial gets a
        SHORT reconnect budget (a tracker mid-relaunch comes back in
        seconds, and a dropped release otherwise waits out a TTL) —
        only a tracker that stays unreachable past it is left to the
        TTL / supervisor reclaim."""
        lease = self._lease
        self._lease = None
        if lease is None:
            return
        try:
            self._client.release(
                int(lease.get("epoch", self.epoch)), int(lease["shard"]),
                self._fileset, retry_secs=5.0,
            )
        except (OSError, ConnectionError, ValueError, KeyError):
            pass

    def _shard_finished(self) -> None:
        split, lease = self._split, self._lease
        self._split = None
        self._lease = None
        if split is not None:
            self._accumulate_stats(split)
            split.close()
        if lease is None:
            return
        shard = int(lease["shard"])
        # the signature rides along so a straggler's done from before a
        # dataset switch can't land on the new dataset's ledger
        resp = self._client.done(self.epoch, shard, self._fileset)
        status = resp.get("status", "error")
        if status == "recorded":
            self.shards_recorded += 1
        elif status == "duplicate":
            self.shards_duplicate += 1
        else:
            # a fully-drained shard the tracker refuses to account
            # (aged-out epoch, stale dataset signature) means this
            # worker's rows may double-count a peer's — stop loudly,
            # don't keep feeding the consumer as if the shard committed
            raise Error(
                f"tracker refused shard_done for micro-shard {shard} "
                f"(epoch {self.epoch}): {resp.get('error', resp)}"
            )
        if self.on_shard_done is not None:
            self.on_shard_done(shard, status)

    def _pull(self, op):
        """The one leased pull loop behind every emission method:
        ensure a leased shard is open, keep its lease renewed, delegate
        to the open splitter, and commit the shard when the delegate
        drains (None)."""
        while True:
            if not self._ensure_split():
                return None
            self._maybe_renew()
            out = op(self._split)
            if out is not None:
                self._started = True
                return out
            self._shard_finished()

    # -- InputSplit contract -------------------------------------------------
    def next_record(self) -> Optional[bytes]:
        return self._pull(lambda s: s.next_record())

    def next_chunk(self) -> Optional[bytes]:
        return self._pull(lambda s: s.next_chunk())

    def next_batch(self, n_records: int) -> Optional[bytes]:
        return self._pull(lambda s: s.next_batch(n_records))

    def next_gather_batch(self, n_records: int):
        """Zero-copy gather emission, delegated per micro-shard (the
        fused staging path). A call never crosses a shard boundary —
        short returns at shard edges are normal, like window edges."""
        check(
            self._windowed_hint,
            "next_gather_batch needs a windowed shuffle configuration",
        )
        return self._pull(lambda s: s.next_gather_batch(n_records))

    @property
    def windowed(self) -> bool:
        return self._windowed_hint

    def supports_gather(self) -> bool:
        return self._windowed_hint

    def count_gather_fallback(self, n: int = 1) -> None:
        if self._split is not None and hasattr(
            self._split, "count_gather_fallback"
        ):
            self._split.count_gather_fallback(n)

    def before_first(self) -> None:
        """Next epoch: a fresh cluster-wide ledger. Before anything was
        pulled this is a no-op (the constructor's ``epoch`` is the
        first epoch), mirroring the static splitters' increment-per-
        rewind contract. A live lease is released back to the queue
        (cmd=shard_release); normal flow drains to None first, so this
        only costs work on an explicit mid-epoch restart."""
        if not self._started and not self._exhausted:
            return
        if self._split is not None:
            self._accumulate_stats(self._split)
            self._split.close()
            self._split = None
        self._release_lease()
        self.epoch += 1
        self._exhausted = False
        self._started = False
        self.current_shard = None

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise Error(
            "DynamicShardSource has no static partition to reset: shard "
            "placement is leased from the tracker (docs/sharding.md); "
            "open a static split (part_index/num_parts) if you need "
            "pinned placement"
        )

    def _get_probe(self) -> InputSplit:
        """A (0, 1) splitter used only for whole-set introspection
        (total_size, extract_records before any lease) — never read."""
        if self._probe is None:
            self._probe = self._make_probe(0, 1, self.epoch)
        return self._probe

    def total_size(self) -> int:
        if self._total_size is None:
            src = self._split if self._split is not None else self._get_probe()
            self._total_size = src.total_size()
        return self._total_size

    def hint_chunk_size(self, nbytes: int) -> None:
        self._chunk_hint = nbytes
        if self._split is not None:
            self._split.hint_chunk_size(nbytes)

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        src = self._split if self._split is not None else self._get_probe()
        return src.extract_records(chunk)

    def io_stats(self) -> Dict[str, object]:
        """Numeric counters summed across every drained micro-shard's
        splitter plus the live one, with the lease shape on top
        (``leases``/``shards_recorded``/``shards_duplicate``/
        ``lease_wait_secs``/``renews_lost``) — docs/sharding.md."""
        out: Dict[str, object] = dict(self._closed_stats)
        if self._split is not None:
            live = getattr(self._split, "io_stats", lambda: None)() or {}
            self._merge_stats(out, live)
        inner_mode = out.get("mode", "sequential")
        out["mode"] = f"dynamic:{inner_mode}"
        out["leases"] = self.leases
        out["shards_recorded"] = self.shards_recorded
        out["shards_duplicate"] = self.shards_duplicate
        out["lease_wait_secs"] = round(self.lease_wait_secs, 4)
        out["renews_lost"] = self.renews_lost
        if self.num_shards is not None:
            out["num_shards"] = self.num_shards
        return out

    def close(self) -> None:
        # a live lease is released, not completed — the partially
        # drained shard goes back to the queue to be re-served in full
        # (TTL / supervisor reclaim only cover a tracker we can't reach)
        if self._split is not None:
            self._accumulate_stats(self._split)
            self._split.close()
            self._split = None
        self._release_lease()
        if self._probe is not None:
            self._probe.close()
            self._probe = None


def fileset_signature(
    data_uri: str, index_uri: Optional[str] = None, type: str = "recordio"
) -> str:
    """Canonical dataset identity for the shard-lease protocol
    (docs/sharding.md): mismatched workers (different URIs on the same
    tracker) must fail loudly, not drain different bytes. fault://
    wrappers are normalized away — a chaos-wrapped worker reads the
    SAME dataset as its clean peers — and local paths are canonicalized
    the way ``faults.wrap_uri`` canonicalizes them (strip ``file://``,
    lead with ``/``) so a clean ``file:///d/x.rec`` peer signs
    identically to a faulted ``/d/x.rec`` one. Shared by the dynamic
    create() path and the dsserve preprocessing tier (both lease and
    commit under this signature, so they can never disagree)."""
    from .faults import unwrap_uri as _unwrap

    def _sig_norm(u: str) -> str:
        u = _unwrap(u)
        if u.startswith("file://"):
            u = u[len("file://"):]
        if u and "://" not in u and not u.startswith("/"):
            u = "/" + u
        return u

    return hashlib.sha1(
        f"{_sig_norm(data_uri)}|{_sig_norm(index_uri or '')}|{type}"
        .encode()
    ).hexdigest()


def create(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type: str = "text",
    index_uri: Optional[str] = None,
    shuffle=None,  # None | bool | 'record' | 'batch'
    seed: int = 0,
    batch_size: Optional[int] = None,
    recurse_directories: bool = False,
    num_shuffle_parts: int = 0,
    threaded: bool = True,
    epoch: int = 0,
    skip_records: int = 0,
    window: Optional[int] = None,
    merge_gap: Optional[int] = None,
    dynamic_shards: Optional[bool] = None,
) -> InputSplit:
    """InputSplit factory (reference InputSplit::Create, src/io.cc:81-130).

    - ``uri`` may carry ``#cachefile`` sugar → CachedInputSplit
      (reference io.cc:120-124)
    - default wraps the split in a read-ahead thread (reference
      io.cc:119-122); ``shuffle='window'`` splits prefetch internally
      (their readahead thread loads coalesced spans) and are returned
      bare — cached OR threaded OR window-readahead, never stacked
    - ``type``: 'text' | 'recordio' | 'indexed_recordio'
    - ``window``/``merge_gap``: shuffle='window' knobs
      (``?shuffle=window&window=N&merge_gap=B`` as URI sugar)
    - ``dynamic_shards`` (``&dynamic_shards=1``): ignore the static
      ``part_index/num_parts`` placement and pull tracker-leased
      micro-shards instead (DynamicShardSource, docs/sharding.md) —
      each micro-shard opens the standard splitter with the same
      options, so per-shard order matches the static path bit-for-bit.
      Requires a running tracker (``DMLC_TRACKER_URI``/``PORT``).
      The driver is returned bare; each leased micro-shard's splitter
      gets the same wrapper a static drain would (windowed splitters
      prefetch internally, others ride ``ThreadedInputSplit`` when
      ``threaded``).
    """
    check(
        num_parts >= 1 and 0 <= part_index < num_parts,
        f"invalid shard ({part_index}, {num_parts}): need "
        "0 <= part_index < num_parts (reference io.cc CHECK)",
    )
    spec = URISpec(uri, part_index, num_parts)
    # streaming sugar: pointing at a stream's manifest (or &stream=1 /
    # type='stream' on the directory) follows the LIVE stream — a
    # tail-following StreamSource instead of a sealed-file splitter
    # (stream/source.py, docs/streaming.md). Lazy import: stream/
    # imports this module for the InputSplit contract.
    from ..stream.manifest import MANIFEST_NAME as _stream_manifest_name

    if (
        type == "stream"
        or bool(uri_int(spec.args, "stream", 0))
        or spec.uri.rstrip("/").endswith("/" + _stream_manifest_name)
    ):
        from ..stream.source import StreamSource

        check(
            not spec.cache_file,
            "a #cachefile would freeze a growing stream's first read; "
            "streams are followed live, not cached",
        )
        dir_uri = spec.uri.rstrip("/")
        if dir_uri.endswith("/" + _stream_manifest_name):
            dir_uri = dir_uri[: -(len(_stream_manifest_name) + 1)]
        if dynamic_shards is None:
            dynamic_shards = bool(uri_int(spec.args, "dynamic_shards", 0))
        check(
            dynamic_shards or (part_index == 0 and num_parts == 1),
            "a static stream follow drains everything (one reader); "
            "multi-worker streaming uses &dynamic_shards=1 leased "
            "micro-shards (docs/streaming.md)",
        )
        if shuffle is None:
            shuffle = spec.args.get("shuffle", "0")
        return StreamSource(
            dir_uri,
            shuffle=normalize_shuffle(shuffle),
            seed=seed if seed else uri_int(spec.args, "seed", 0),
            window=(
                window
                if window is not None
                else uri_int(spec.args, "window", 8192, minimum=1)
            ),
            batch_size=(
                batch_size
                if batch_size is not None
                else uri_int(spec.args, "batch_size", 256)
            ),
            dynamic=dynamic_shards,
            threaded=threaded,
        )
    # per-dataset options ride the URI (reference-style sugar); explicit
    # keyword args win when both are given:
    #   ?shuffle_parts=N&seed=S       macro-shuffle, any record type
    #   ?index=<uri>[&shuffle=1][&batch_size=N]   count-indexed recordio
    if num_shuffle_parts == 0:
        num_shuffle_parts = uri_int(spec.args, "shuffle_parts", 0)
    if type == "recordio" and (index_uri is not None or "index" in spec.args):
        if index_uri is None:
            index_uri = str(spec.args["index"])
        type = "indexed_recordio"
    if seed == 0:
        seed = uri_int(spec.args, "seed", 0)
    if type == "indexed_recordio":
        if shuffle is None:
            shuffle = spec.args.get("shuffle", "0")
        shuffle = normalize_shuffle(shuffle)
        if batch_size is None:
            batch_size = uri_int(spec.args, "batch_size", 256)
        if window is None:
            window = uri_int(spec.args, "window", 65536, minimum=1)
        if merge_gap is None:
            merge_gap = uri_int(spec.args, "merge_gap", 65536, minimum=0)
        # &legacy_shuffle=1: force the reference's per-record seek loop
        # for shuffle=record (A/B baseline against the gather fast path)
        legacy_shuffle = bool(uri_int(spec.args, "legacy_shuffle", 0))
        # data-position resume sugar (?epoch=E&skip_records=N): start at
        # epoch E's deterministic permutation, N records in (§5.4)
        if epoch == 0:
            epoch = uri_int(spec.args, "epoch", 0)
        if skip_records == 0:
            skip_records = uri_int(spec.args, "skip_records", 0)
        check(
            not (shuffle and spec.cache_file),
            "indexed shuffle with a #cachefile would freeze the first "
            "epoch's shuffle order into the cache; pick one",
        )
    else:
        shuffle = normalize_shuffle(shuffle)
        # position fast-forward needs count-indexed access; silently
        # starting at record 0 would make a resume retrain duplicate
        # data — refuse loudly (the check() idiom of the sugar below)
        check(
            epoch == 0
            and skip_records == 0
            and "epoch" not in spec.args
            and "skip_records" not in spec.args,
            f"epoch/skip_records require an indexed recordio source "
            f"(?index=<uri>), not type={type!r}",
        )
    batch_size = 256 if batch_size is None else batch_size
    if type == "text" and spec.uri == "-":
        return SingleFileSplit("-")
    if type not in ("text", "recordio", "indexed_recordio"):
        raise Error(f"unknown InputSplit type {type!r}")
    if type == "indexed_recordio":
        check(index_uri is not None, "indexed_recordio requires index_uri")
    legacy = legacy_shuffle if type == "indexed_recordio" else False

    def _build_base(pi: int, nparts: int, ep: int) -> InputSplitBase:
        """The one construction site for both placements: the static
        path calls it once with (part_index, num_parts, epoch); the
        dynamic driver calls it per leased micro-shard with
        (shard, K*num_workers, current_epoch) — identical options, so
        shard content and per-shard shuffle order never depend on who
        drains it."""
        if type == "text":
            return LineSplitter(
                spec.uri, pi, nparts,
                recurse_directories=recurse_directories,
            )
        if type == "recordio":
            return RecordIOSplitter(
                spec.uri, pi, nparts,
                recurse_directories=recurse_directories,
            )
        return IndexedRecordIOSplitter(
            spec.uri,
            index_uri,  # type: ignore[arg-type]
            pi,
            nparts,
            batch_size=batch_size,
            shuffle=shuffle,
            seed=seed,
            epoch=ep,
            skip_records=skip_records,
            # the indexed branch above resolved both (kwarg > URI >
            # default), so they are never None here
            window=window,  # type: ignore[arg-type]
            merge_gap=merge_gap,  # type: ignore[arg-type]
            legacy_shuffle=legacy,
        )

    if dynamic_shards is None:
        dynamic_shards = bool(uri_int(spec.args, "dynamic_shards", 0))
    if dynamic_shards:
        check(
            not spec.cache_file,
            "dynamic_shards with a #cachefile would freeze one worker's "
            "shard sequence into the cache; pick one",
        )
        check(
            num_shuffle_parts == 0,
            "dynamic_shards already shuffles placement; num_shuffle_parts "
            "composes only with static shards",
        )
        check(
            skip_records == 0,
            "skip_records requires static sharding: mid-epoch resume "
            "under dynamic shards is ledger-owned (completed micro-shards "
            "are simply not re-served — docs/sharding.md)",
        )
        windowed_hint = (
            type == "indexed_recordio"
            and shuffle in ("record", "batch", "window")
            and not legacy
        )
        sig = fileset_signature(spec.uri, index_uri, type)
        try:
            from ..tracker.shardsvc import ShardLeaseClient

            client = ShardLeaseClient()
        except KeyError as e:
            raise Error(
                "dynamic_shards needs a tracker: set DMLC_TRACKER_URI/"
                f"DMLC_TRACKER_PORT (missing {e}) — docs/sharding.md"
            ) from None

        def _make_leased(pi: int, nparts: int, ep: int) -> InputSplit:
            # same wrapper rule as the static tail below: windowed
            # splitters prefetch internally, everything else keeps the
            # read-ahead thread a static drain would have
            b = _build_base(pi, nparts, ep)
            if threaded and not (
                isinstance(b, IndexedRecordIOSplitter) and b.windowed
            ):
                return ThreadedInputSplit(b)
            return b

        return DynamicShardSource(
            _make_leased,
            client=client,
            epoch=epoch,
            fileset=sig,
            windowed_hint=windowed_hint,
            make_probe=_build_base,
        )
    base: InputSplitBase = _build_base(part_index, num_parts, epoch)
    split: InputSplit = base
    if num_shuffle_parts > 0:
        check(
            not spec.cache_file,
            "num_shuffle_parts with a #cachefile would freeze the first "
            "epoch's shuffle order into the cache; pick one",
        )
        shuffled = InputSplitShuffle(
            base, part_index, num_parts, num_shuffle_parts, seed
        )
        # shuffling must not cost the read-ahead thread the unshuffled
        # path gets
        return ThreadedInputSplit(shuffled) if threaded else shuffled
    if spec.cache_file:
        # cached OR threaded, never both: CachedInputSplit prefetches
        # internally (reference io.cc:119-124 chooses exactly one wrapper)
        return CachedInputSplit(base, spec.cache_file)
    if isinstance(base, IndexedRecordIOSplitter) and base.windowed:
        # every unified-path shuffle mode (record/batch/window) already
        # prefetches on its own readahead thread (coalesced spans for
        # window k+1 load while k drains); stacking a ThreadedInputSplit
        # would add a queue without overlap — and would hide
        # next_gather_batch from the fused consumer
        return base
    if threaded:
        return ThreadedInputSplit(base)
    return split
