"""Compression codec layer for RecordIO compressed blocks.

The reference RecordIO container (include/dmlc/recordio.h:16-45) frames
raw bytes only, so every epoch re-reads every payload byte over the
remote link. This module is the repo's SINGLE compression site (lint
L009 bans zlib/gzip/zstandard/lz4 imports anywhere else): a codec
registry with level control, streaming helpers, the compressed-block
wire header (codec id, raw length, crc32 content checksum), a parallel
decode pool sized from the usable-CPU count (utils/cpus.py), and a
bytes-bounded LRU cache of decoded blocks so windowed shuffle and
multi-epoch runs decode each block once.

Codecs: ``raw`` (identity, id 0) and ``zlib``/``gzip`` (ids 1/2) ride
the stdlib and are always available; ``zstd``/``lz4`` (ids 3/4) sit
behind import guards — ``get_codec`` raises a checked Error naming the
missing package, and ``available_codecs()`` lists only what this host
can actually decode (surfaced by ``tools info`` and the
``dryrun_multichip`` report so deploy targets can be checked remotely).

Block wire format (the payload of a cflag-4 RecordIO frame,
docs/recordio.md)::

    codec_id  u8     registry id (0 raw, 1 zlib, 2 gzip, 3 zstd, 4 lz4)
    version   u8     block-header version, currently 1
    reserved  u16    zero
    n_records u32    records framed inside the decoded bytes
    raw_len   u32    decoded byte count
    crc32     u32    crc32 of the DECODED bytes (content checksum:
                     catches corrupt blocks AND codec bugs)
    <compressed bytes>

Env knobs: ``DMLC_DECODE_CACHE_MB`` (decoded-block LRU budget, default
256), ``DMLC_DECODE_THREADS`` (decode pool size, default the
affinity/cgroup-aware usable-CPU count).

Telemetry (docs/observability.md): ``io.codec.bytes_raw`` /
``io.codec.bytes_compressed`` counters (both directions — their ratio
is the compression ratio bench.py reports), the
``io.codec.decode_seconds`` histogram, and
``io.codec.cache_hits``/``cache_misses``.
"""

from __future__ import annotations

import hashlib
import struct
import threading
import time as _time
import zlib as _zlib
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Iterable, Iterator, List, Optional, Tuple, Union

from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from ..utils.cpus import available_cpus
from ..utils.env import get_env
from ..utils.logging import Error, check

__all__ = [
    "BLOCK_HEADER",
    "Codec",
    "DecodeContext",
    "DecodedBlockCache",
    "available_codecs",
    "crc32",
    "decode_block",
    "decode_blocks",
    "decode_threads",
    "default_decode_cache",
    "default_decode_context",
    "default_decode_pool",
    "encode_block",
    "get_codec",
    "register_codec",
    "submit_decode",
    "wire_block_key",
]

# codec_id, version, reserved, n_records, raw_len, crc32
BLOCK_HEADER = struct.Struct("<BBHIII")
BLOCK_VERSION = 1

_REG = _default_registry()
_BYTES_RAW = _REG.counter(
    "io.codec.bytes_raw", help="uncompressed bytes through the codec layer"
)
_BYTES_COMPRESSED = _REG.counter(
    "io.codec.bytes_compressed", help="compressed bytes through the codec layer"
)
_DECODE_SECONDS = _REG.histogram(
    "io.codec.decode_seconds", help="per-block decompress wall time"
)
_CACHE_HITS = _REG.counter(
    "io.codec.cache_hits", help="decoded-block cache hits"
)
_CACHE_MISSES = _REG.counter(
    "io.codec.cache_misses", help="decoded-block cache misses"
)


def crc32(data) -> int:
    """crc32 content checksum (masked to u32 for the block header)."""
    return _zlib.crc32(data) & 0xFFFFFFFF


class Codec:
    """One compression algorithm: name, wire id, (de)compress, and
    incremental streaming helpers.

    ``compress``/``decompress`` are whole-buffer (blocks are bounded by
    the writer's ``block_bytes``, so buffering one is cheap);
    ``compress_stream``/``decompress_stream`` consume chunk iterators
    for callers converting data too large to hold (``tools
    recompress`` streams block by block on top of these semantics).
    Codec errors surface as checked ``Error``s, never raw codec
    exceptions.
    """

    name = "?"
    codec_id = -1
    default_level: Optional[int] = None

    def _compress(self, data: bytes, level: Optional[int]) -> bytes:
        raise NotImplementedError

    def _decompress(self, data: bytes, raw_len: Optional[int]) -> bytes:
        raise NotImplementedError

    def compress(self, data: bytes, level: Optional[int] = None) -> bytes:
        try:
            return self._compress(bytes(data), level)
        except Exception as e:  # codec internals differ per backend
            raise Error(f"codec {self.name!r}: compress failed: {e}") from e

    def decompress(
        self, data: bytes, raw_len: Optional[int] = None
    ) -> bytes:
        try:
            return self._decompress(bytes(data), raw_len)
        except Exception as e:
            raise Error(f"codec {self.name!r}: decompress failed: {e}") from e

    # -- streaming ------------------------------------------------------------
    def compress_stream(
        self, chunks: Iterable[bytes], level: Optional[int] = None
    ) -> Iterator[bytes]:
        """Incremental compress: yields output as input chunks arrive.
        The base implementation buffers (guarded codecs without an
        incremental API); zlib/gzip override with true streaming."""
        buf = b"".join(chunks)
        if buf:
            yield self.compress(buf, level)

    def decompress_stream(self, chunks: Iterable[bytes]) -> Iterator[bytes]:
        buf = b"".join(chunks)
        if buf:
            yield self.decompress(buf)


class RawCodec(Codec):
    """Identity codec (id 0): block framing + crc without compression —
    the cheapest way to get checksummed blocks, and the degenerate case
    every round-trip property test includes."""

    name = "raw"
    codec_id = 0

    def _compress(self, data: bytes, level: Optional[int]) -> bytes:
        return data

    def _decompress(self, data: bytes, raw_len: Optional[int]) -> bytes:
        return data

    def compress_stream(self, chunks, level=None):
        for c in chunks:
            if c:
                yield bytes(c)

    def decompress_stream(self, chunks):
        for c in chunks:
            if c:
                yield bytes(c)


class ZlibCodec(Codec):
    name = "zlib"
    codec_id = 1
    default_level = 6
    _wbits = 15  # zlib wrapper

    def _compress(self, data: bytes, level: Optional[int]) -> bytes:
        co = _zlib.compressobj(
            self.default_level if level is None else level, _zlib.DEFLATED,
            self._wbits,
        )
        return co.compress(data) + co.flush()

    def _decompress(self, data: bytes, raw_len: Optional[int]) -> bytes:
        return _zlib.decompress(data, self._wbits)

    def compress_stream(self, chunks, level=None):
        co = _zlib.compressobj(
            self.default_level if level is None else level, _zlib.DEFLATED,
            self._wbits,
        )
        for c in chunks:
            out = co.compress(bytes(c))
            if out:
                yield out
        out = co.flush()
        if out:
            yield out

    def decompress_stream(self, chunks):
        do = _zlib.decompressobj(self._wbits)
        for c in chunks:
            out = do.decompress(bytes(c))
            if out:
                yield out
        out = do.flush()
        if out:
            yield out


class GzipCodec(ZlibCodec):
    """zlib with the gzip wrapper (wbits 16+15) — same deflate stream,
    but the on-disk block payload is a valid .gz member, convenient for
    external tooling poking at extracted blobs."""

    name = "gzip"
    codec_id = 2
    _wbits = 16 + 15


class ZstdCodec(Codec):
    name = "zstd"
    codec_id = 3
    default_level = 3

    def __init__(self, mod) -> None:
        self._mod = mod

    def _compress(self, data: bytes, level: Optional[int]) -> bytes:
        level = self.default_level if level is None else level
        return self._mod.ZstdCompressor(level=level).compress(data)

    def _decompress(self, data: bytes, raw_len: Optional[int]) -> bytes:
        dctx = self._mod.ZstdDecompressor()
        if raw_len is not None:
            return dctx.decompress(data, max_output_size=raw_len)
        return dctx.decompress(data)


class Lz4Codec(Codec):
    name = "lz4"
    codec_id = 4
    default_level = 0

    def __init__(self, mod) -> None:
        self._mod = mod  # lz4.frame

    def _compress(self, data: bytes, level: Optional[int]) -> bytes:
        level = self.default_level if level is None else level
        return self._mod.compress(data, compression_level=level)

    def _decompress(self, data: bytes, raw_len: Optional[int]) -> bytes:
        return self._mod.decompress(data)


_CODECS: Dict[str, Codec] = {}
_BY_ID: Dict[int, Codec] = {}
_MISSING: Dict[str, str] = {}  # name -> reason (guarded import failed)


def register_codec(codec: Codec) -> None:
    _CODECS[codec.name] = codec
    _BY_ID[codec.codec_id] = codec


register_codec(RawCodec())
register_codec(ZlibCodec())
register_codec(GzipCodec())

try:  # optional, never a hard dependency
    import zstandard as _zstd_mod

    register_codec(ZstdCodec(_zstd_mod))
except ImportError:
    _MISSING["zstd"] = "python package 'zstandard' is not installed"

try:
    import lz4.frame as _lz4_frame

    register_codec(Lz4Codec(_lz4_frame))
except ImportError:
    _MISSING["lz4"] = "python package 'lz4' is not installed"


def available_codecs() -> List[str]:
    """Codec names this process can encode AND decode, id order."""
    return [c.name for c in sorted(_CODECS.values(), key=lambda c: c.codec_id)]


def get_codec(name: Union[str, int, Codec]) -> Codec:
    """Resolve a codec by name, wire id, or instance; checked Error for
    unknown names/ids and for guarded codecs whose package is missing
    (a compressed file must fail loudly on a host that cannot decode
    it, never produce garbage)."""
    if isinstance(name, Codec):
        return name
    if isinstance(name, int):
        codec = _BY_ID.get(name)
        if codec is None:
            known = {c.codec_id: c.name for c in _CODECS.values()}
            missing = [f"{k} ({v})" for k, v in sorted(_MISSING.items())]
            raise Error(
                f"unknown or unavailable codec id {name} (available: "
                f"{known}{'; missing: ' + ', '.join(missing) if missing else ''})"
            )
        return codec
    key = str(name).lower()
    codec = _CODECS.get(key)
    if codec is None:
        if key in _MISSING:
            raise Error(f"codec {key!r} unavailable: {_MISSING[key]}")
        raise Error(
            f"unknown codec {name!r} (available: {available_codecs()})"
        )
    return codec


# -- block encode/decode ------------------------------------------------------
def encode_block(
    raw: bytes,
    n_records: int,
    codec: Union[str, Codec],
    level: Optional[int] = None,
) -> bytes:
    """Raw framed record bytes → block blob (header + compressed)."""
    c = get_codec(codec)
    comp = c.compress(raw, level)
    _BYTES_RAW.inc(len(raw))
    _BYTES_COMPRESSED.inc(len(comp))
    return (
        BLOCK_HEADER.pack(
            c.codec_id, BLOCK_VERSION, 0, n_records, len(raw), crc32(raw)
        )
        + comp
    )


def decode_block(blob) -> Tuple[bytes, int]:
    """Block blob → (raw framed record bytes, n_records); verifies the
    declared raw length and the crc32 content checksum, raising a
    checked Error on any mismatch (corruption must never decode to
    garbage records)."""
    blob = bytes(blob)
    check(
        len(blob) >= BLOCK_HEADER.size,
        f"compressed block shorter than its {BLOCK_HEADER.size}-byte header",
    )
    codec_id, version, _res, n_records, raw_len, want_crc = (
        BLOCK_HEADER.unpack_from(blob)
    )
    check(
        version == BLOCK_VERSION,
        f"unsupported compressed-block version {version} "
        f"(this reader supports {BLOCK_VERSION})",
    )
    codec = get_codec(codec_id)
    # flight-recorder span per decode-pool job: the Perfetto timeline
    # shows each codec-decode worker's occupancy next to the window
    # loader waiting on it (the registry histogram keeps the aggregate)
    t0 = _time.perf_counter()
    with _tracing.span(
        "dmlc:decode_block", codec=codec.name, raw_len=raw_len
    ):
        raw = codec.decompress(blob[BLOCK_HEADER.size:], raw_len)
    _DECODE_SECONDS.observe(_time.perf_counter() - t0)
    check(
        len(raw) == raw_len,
        f"compressed block decoded to {len(raw)} bytes, header says "
        f"{raw_len} (truncated or corrupt block)",
    )
    got_crc = crc32(raw)
    check(
        got_crc == want_crc,
        f"compressed block crc mismatch: got {got_crc:#010x}, header says "
        f"{want_crc:#010x} (corrupt block)",
    )
    _BYTES_RAW.inc(raw_len)
    _BYTES_COMPRESSED.inc(len(blob) - BLOCK_HEADER.size)
    return raw, n_records


# -- parallel decode pool -----------------------------------------------------
def decode_threads() -> int:
    """Decode pool size: ``DMLC_DECODE_THREADS`` wins, else the
    affinity/cgroup-quota-aware usable-CPU count (utils/cpus.py) — the
    stdlib codecs release the GIL inside (de)compress, so the pool gets
    real parallelism."""
    env = get_env("DMLC_DECODE_THREADS", 0)
    if env > 0:
        return env
    return available_cpus()


_POOL: Optional[ThreadPoolExecutor] = None
_POOL_LOCK = threading.Lock()


def default_decode_pool() -> ThreadPoolExecutor:
    """Process-global decompress pool (lazy; shared by every reader so
    concurrent splits don't multiply thread counts)."""
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                _POOL = ThreadPoolExecutor(
                    max_workers=decode_threads(),
                    thread_name_prefix="codec-decode",
                )
    return _POOL


def decode_blocks(blobs: List[bytes]) -> List[Tuple[bytes, int]]:
    """Decode many block blobs, overlapping decompression on the shared
    pool when it helps; order preserved. Worker errors re-raise here."""
    if len(blobs) <= 1 or decode_threads() <= 1:
        return [decode_block(b) for b in blobs]
    return list(default_decode_pool().map(decode_block, blobs))


def submit_decode(blob) -> "Future":
    """Submit ONE block decode to the shared pool; returns its Future.

    The fetch→decode overlap seam: the concurrent span fetcher
    (io/spanfetch.py) hands each span's blocks here as the span LANDS,
    so decompression of early spans runs while later spans are still in
    flight. With a single-thread pool the decode runs inline and the
    Future comes back already resolved — same results, serial timing."""
    if decode_threads() <= 1:
        f: "Future" = Future()
        try:
            f.set_result(decode_block(blob))
        except Exception as e:  # surfaces at .result(), like a pool job
            f.set_exception(e)
        return f
    return default_decode_pool().submit(decode_block, blob)


# -- decoded-block LRU cache --------------------------------------------------
class DecodedBlockCache:
    """Bytes-bounded LRU of decoded block payloads.

    Keys are caller-chosen identities (the indexed splitter uses
    ``(file paths, total size, block file offset)``); values are the
    decoded raw framed bytes. Thread-safe — the window-shuffle
    readahead thread fills while the consumer thread reads. An entry
    larger than the whole budget is served but not retained.
    """

    def __init__(self, max_bytes: int) -> None:
        check(max_bytes >= 0, f"cache budget {max_bytes} must be >= 0")
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._items: "OrderedDict[object, bytes]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, key) -> Optional[bytes]:
        with self._lock:
            data = self._items.get(key)
            if data is None:
                self.misses += 1
                _CACHE_MISSES.inc()
                return None
            self._items.move_to_end(key)
            self.hits += 1
            _CACHE_HITS.inc()
            return data

    def put(self, key, data: bytes) -> None:
        n = len(data)
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            if n > self.max_bytes:
                return  # larger than the whole budget: serve, don't retain
            self._items[key] = data
            self._bytes += n
            while self._bytes > self.max_bytes and self._items:
                _k, evicted = self._items.popitem(last=False)
                self._bytes -= len(evicted)

    def clear(self) -> None:
        with self._lock:
            self._items.clear()
            self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._items)


_CACHE: Optional[DecodedBlockCache] = None
_CACHE_LOCK = threading.Lock()


def default_decode_cache() -> DecodedBlockCache:
    """Process-global decoded-block cache, budget
    ``DMLC_DECODE_CACHE_MB`` (default 256) — sized at first use."""
    global _CACHE
    if _CACHE is None:
        with _CACHE_LOCK:
            if _CACHE is None:
                _CACHE = DecodedBlockCache(
                    get_env("DMLC_DECODE_CACHE_MB", 256) * (1 << 20)
                )
    return _CACHE


# -- the decode seam: two-level cache + pool behind one object ----------------
def wire_block_key(key: object) -> str:
    """Flatten a structured block identity to the content-addressed
    string the host daemon keys on. The identity must be built from
    plain strings/ints/tuples (the splitter's file-set signature +
    layout digest + block offset) — ``repr`` of those is deterministic
    ACROSS processes, which Python's seeded ``hash()`` is not, and
    cross-process agreement is the whole point of the shared tier."""
    if isinstance(key, str):
        return key
    return hashlib.sha1(repr(key).encode()).hexdigest()


class DecodeContext:
    """The single seam every block-decode consumer rides: in-process
    LRU (L1), then the host-shared daemon tier (L2, io/blockcache.py),
    then decode — plus the shared decompress pool. The window loader,
    the splitter's ``_fetch_blocks`` miss path, and ``decode_chunk``
    all go through one of these instead of reaching into module
    globals, so tests can inject
    a fake daemon or a private LRU, and the two-level policy lives in
    exactly one place.

    ``shared='auto'`` (the default) resolves the process-wide daemon
    client lazily (one connect attempt, cached negative result —
    blockcache.default_client); ``shared=None`` pins the context to
    in-process-only behavior; any client-shaped object (``get``/
    ``publish``) is used as given.
    """

    _AUTO = "auto"

    def __init__(
        self,
        cache: Optional[DecodedBlockCache] = None,
        shared: object = "auto",
    ) -> None:
        self._cache = cache
        self._shared = shared

    def cache(self) -> DecodedBlockCache:
        return self._cache if self._cache is not None else (
            default_decode_cache()
        )

    def shared(self):
        """The L2 client, or None (disabled/absent daemon)."""
        if self._shared == self._AUTO:
            from .blockcache import default_client

            return default_client()
        return self._shared

    def get_block(self, key: object) -> Optional[bytes]:
        """L1 then L2; an L2 hit is promoted into L1 so repeats inside
        one process stay memory-local."""
        data = self.cache().get(key)
        if data is not None:
            return data
        shared = self.shared()
        if shared is not None:
            try:
                data = shared.get(wire_block_key(key))
            except Exception:  # the shared tier is best-effort, always
                data = None
            if data is not None:
                self.cache().put(key, data)
        return data

    def get_blocks(self, keys) -> Dict[object, bytes]:
        """Bulk ``get_block``: L1 each key, then ONE shared-tier round
        trip for all L1 misses (client.get_many) — the batched path the
        window loader and range emission ride so per-block IPC can't
        eat the decode win. Returns only the keys found; callers decode
        the rest."""
        cache = self.cache()
        out: Dict[object, bytes] = {}
        missing = []
        for key in keys:
            data = cache.get(key)
            if data is not None:
                out[key] = data
            else:
                missing.append(key)
        if missing:
            shared = self.shared()
            if shared is not None:
                by_wire = {wire_block_key(k): k for k in missing}
                try:
                    got = shared.get_many(list(by_wire))
                except Exception:
                    got = {}
                for wire, data in got.items():
                    key = by_wire[wire]
                    cache.put(key, data)
                    out[key] = data
        return out

    def put_block(self, key: object, raw: bytes) -> None:
        """Retain decoded bytes in L1 and offer them to the host tier
        (a lost publish race or absent daemon is a silent no-op)."""
        self.cache().put(key, raw)
        shared = self.shared()
        if shared is not None:
            try:
                shared.publish(wire_block_key(key), raw)
            except Exception:
                pass

    # pool access rides the context too, so a future per-context pool
    # (or a test's serial fake) needs no caller changes
    def decode_block(self, blob) -> Tuple[bytes, int]:
        return decode_block(blob)

    def decode_blocks(self, blobs: List[bytes]) -> List[Tuple[bytes, int]]:
        return decode_blocks(blobs)

    def submit_decode(self, blob) -> "Future":
        return submit_decode(blob)


_CTX: Optional[DecodeContext] = None
_CTX_LOCK = threading.Lock()


def default_decode_context() -> DecodeContext:
    """Process-global two-level decode context (L1 = the default LRU,
    L2 = the host daemon when reachable)."""
    global _CTX
    if _CTX is None:
        with _CTX_LOCK:
            if _CTX is None:
                _CTX = DecodeContext()
    return _CTX
