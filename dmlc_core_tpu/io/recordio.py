"""RecordIO: splittable binary record container, bit-compatible with the
reference format so existing ``.rec`` data loads unchanged.

Reference: include/dmlc/recordio.h:16-45 (format), src/recordio.cc (codec).

Frame layout (little-endian uint32 words)::

    [kMagic = 0xced7230a] [lrec] [data ...] [zero pad to 4-byte boundary]
    lrec = (cflag << 29) | length        # length < 2**29 (512 MB)

When the payload itself contains the magic word at a 4-byte-aligned offset,
the writer splits the record at each such occurrence into a multi-part chain
(the occurrence itself is elided and re-inserted by the reader):

    cflag 0: complete record    1: start   2: middle   3: end

Compressed blocks (this repo's extension; docs/recordio.md): a writer
given a ``codec`` buffers framed records into blocks and emits each
block as one magic-framed blob whose lrec carries bit 2 of the cflag
(``CFLAG_COMPRESSED``): cflag 4 = complete compressed blob, 5/6/7 =
start/middle/end of a magic-escaped blob chain (same part semantics as
v1, so the aligned-magic escape applies to compressed bytes too and the
byte-range magic scan stays sound). The blob payload is an
``io/codec.py`` block: 16-byte header (codec id, record count, raw
length, crc32 of the decoded bytes) + compressed bytes, and the decoded
bytes are themselves plain v1 frames — decode and every v1 consumer
works unchanged. v1 frames pass through untouched; v1-only readers
reject the reserved cflags loudly (checked error, never garbage).

TPU-first design departure: scanning for aligned magic words is the hot loop;
we vectorize it with one numpy view + compare over the whole payload instead
of a byte loop (reference scans per-word, src/recordio.cc:22-28). The hot
READ path has a native counterpart: native/fastparse.cc
``dmlc_parse_rowrec_ell`` walks frames (magic/lrec headers, multipart
chains) directly in C++ on the RecordIO→HBM staging path
(staging/fused.py); this Python codec remains the writer and the
reference implementation the native kernel's parity tests check against.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..utils.logging import Error, check, check_lt
from . import codec as _codec
from .stream import SeekStream, Stream

__all__ = [
    "KMAGIC",
    "CFLAG_COMPRESSED",
    "RecordIOWriter",
    "IndexedRecordIOWriter",
    "RecordIOReader",
    "RecordIOChunkReader",
    "encode_lrec",
    "decode_flag",
    "decode_length",
    "chunk_has_compressed",
    "decode_chunk",
    "scan_compressed_blob",
]

KMAGIC = 0xCED7230A  # reference recordio.h:43; (kMagic >> 29) & 7 > 3
_MAGIC_BYTES = struct.pack("<I", KMAGIC)
_MAX_LEN = 1 << 29

# cflag bit 2: the frame payload is (part of) an io/codec.py compressed
# block, not record bytes. The low two bits keep the v1 part semantics
# (0 complete, 1 start, 2 middle, 3 end), so 4=whole blob, 5/6/7 = a
# magic-escaped blob chain. The magic word itself decodes to cflag 6
# with a ~249 MB length — a compressed MIDDLE part, never a record
# head, so the head predicates below stay collision-free.
CFLAG_COMPRESSED = 4

# default raw bytes buffered per compressed block (writer side): large
# enough to amortize the per-block header/crc and give the codec
# context, small enough that the decoded-block cache holds many and a
# shuffled read decodes little it doesn't need
DEFAULT_BLOCK_BYTES = 1 << 18


def encode_lrec(cflag: int, length: int) -> int:
    """Reference recordio.h:52-54."""
    return ((cflag & 7) << 29) | length


def decode_flag(lrec: int) -> int:
    """Reference recordio.h:60-62."""
    return (lrec >> 29) & 7


def decode_length(lrec: int) -> int:
    """Reference recordio.h:68-70."""
    return lrec & (_MAX_LEN - 1)


def _aligned_magic_positions(payload: bytes) -> np.ndarray:
    """4-byte-aligned offsets where the payload equals the magic word.

    Vectorized equivalent of the writer's scan loop (reference
    src/recordio.cc:20-28): view the lower-aligned prefix as uint32 and
    compare against little-endian kMagic in one pass.
    """
    lower = len(payload) & ~3
    if lower == 0:
        return np.empty(0, dtype=np.int64)
    words = np.frombuffer(payload, dtype="<u4", count=lower // 4)
    return (np.nonzero(words == KMAGIC)[0] * 4).astype(np.int64)


def _fsync_stream(stream) -> None:
    """Best-effort fsync of a Stream's underlying fd: in-memory and
    pipe-like sinks simply have no durable fd to sync."""
    fp = getattr(stream, "_fp", stream)
    try:
        os.fsync(fp.fileno())
    except (AttributeError, OSError, ValueError):
        pass


class RecordIOWriter:
    """Reference RecordIOWriter (recordio.h:38-115, recordio.cc:11-51).

    With a ``codec`` (name or io/codec.py Codec), records are buffered
    and emitted as compressed blocks of ~``block_bytes`` raw framed
    bytes each; call ``flush()`` when done — the final partial block is
    only written then. Without a codec the output is bit-identical to
    the reference v1 format and ``flush()`` is a no-op on the framing.
    """

    def __init__(
        self,
        stream: Stream,
        codec=None,
        level: Optional[int] = None,
        block_bytes: int = DEFAULT_BLOCK_BYTES,
        fsync: bool = False,
    ) -> None:
        self.stream = stream
        self.except_counter = 0  # number of magic collisions escaped
        self.bytes_written = 0  # framed bytes emitted through this writer
        self.records_written = 0  # records EMITTED (durable framing), not
        #                           records merely buffered in a pending block
        self.fsync = fsync  # default commit() durability policy
        self.codec = (
            None if codec in (None, "", "none") else _codec.get_codec(codec)
        )
        self.level = level
        check(block_bytes >= 1, f"block_bytes={block_bytes} must be >= 1")
        self.block_bytes = block_bytes
        self.blocks_written = 0
        self._blk_parts: List[bytes] = []
        self._blk_len = 0
        self._blk_offs: List[int] = []  # frame starts inside the block
        self._blk_keys: List[Optional[int]] = []

    def _frame_payload(self, data: bytes, base_flag: int = 0) -> bytes:
        """Frame one payload with the aligned-magic multipart escape
        (reference recordio.cc:11-51). ``base_flag`` ORs into every
        part's cflag — 0 for v1 records, CFLAG_COMPRESSED for block
        blobs (same escape, reserved cflag space)."""
        check_lt(len(data), _MAX_LEN, "RecordIO only accepts records < 2^29 bytes")
        out: List[bytes] = []
        dptr = 0
        for pos in _aligned_magic_positions(data):
            pos = int(pos)
            cflag = (1 if dptr == 0 else 2) | base_flag
            out.append(_MAGIC_BYTES)
            out.append(struct.pack("<I", encode_lrec(cflag, pos - dptr)))
            out.append(data[dptr:pos])
            dptr = pos + 4
            self.except_counter += 1
        cflag = (3 if dptr != 0 else 0) | base_flag
        out.append(_MAGIC_BYTES)
        out.append(struct.pack("<I", encode_lrec(cflag, len(data) - dptr)))
        out.append(data[dptr:])
        # pad the FINAL part's data to a 4-byte boundary with zeros
        tail_len = len(data) - dptr
        pad = (4 - (tail_len & 3)) & 3
        if pad:
            out.append(b"\x00" * pad)
        return b"".join(out)

    def write_record(self, data: bytes) -> None:
        framed = self._frame_payload(data)
        if self.codec is not None:
            self._buffer_block(framed, (0,), (None,))
            return
        self.stream.write(framed)
        self.bytes_written += len(framed)
        self.records_written += 1

    def tell(self) -> int:
        check(isinstance(self.stream, SeekStream), "stream is not seekable")
        return self.stream.tell()  # type: ignore[union-attr]

    def write_framed_block(self, framed: bytes, offsets) -> None:
        """Bulk-write pre-framed records (data/rowrec.py
        encode_block_frames output). ``offsets`` are frame-start byte
        offsets relative to ``framed``; subclasses use them to keep
        per-record bookkeeping (the index sidecar) in one place."""
        if self.codec is not None:
            self._buffer_block(framed, offsets, (None,) * len(offsets))
            return
        base = self.bytes_written
        self.stream.write(framed)
        self.bytes_written += len(framed)
        self.records_written += len(offsets)
        self._note_framed_records(base, offsets)

    def _note_framed_records(self, base: int, offsets) -> None:
        pass  # the plain writer keeps no per-record state

    # -- compressed-block buffering ------------------------------------------
    def _buffer_block(self, framed: bytes, offsets, keys) -> None:
        """Buffer framed records (frame starts at ``offsets``) into the
        pending block, splitting bulk appends at record boundaries so
        block granularity honors ``block_bytes`` even when a caller
        (the vectorized rowrec framer, bulk recompression) hands a
        multi-record buffer larger than the budget in one call."""
        n = len(offsets)
        if n == 0:
            return
        bounds = [int(o) for o in offsets]
        check(
            bounds[0] == 0,
            f"write_framed_block: first frame must start at byte 0 of "
            f"the buffer (got {bounds[0]}); leading bytes would be lost",
        )
        bounds.append(len(framed))
        i = 0
        while i < n:
            # grow the run until the block reaches its budget; always
            # at least one record so an oversized record flushes alone
            j = i + 1
            while (
                j < n
                and self._blk_len + (bounds[j] - bounds[i]) < self.block_bytes
            ):
                j += 1
            seg = (
                framed
                if i == 0 and j == n and bounds[0] == 0
                else framed[bounds[i] : bounds[j]]
            )
            base = self._blk_len - bounds[i]
            for t in range(i, j):
                self._blk_offs.append(base + bounds[t])
                self._blk_keys.append(keys[t])
            self._blk_parts.append(seg)
            self._blk_len += len(seg)
            if self._blk_len >= self.block_bytes:
                self.flush_block()
            i = j

    def flush_block(self) -> None:
        """Emit the buffered records as one compressed block frame."""
        if not self._blk_offs:
            return
        raw = b"".join(self._blk_parts)
        blob = _codec.encode_block(
            raw, len(self._blk_offs), self.codec, self.level
        )
        framed = self._frame_payload(blob, base_flag=CFLAG_COMPRESSED)
        base = self.bytes_written
        self.stream.write(framed)
        self.bytes_written += len(framed)
        self.blocks_written += 1
        self.records_written += len(self._blk_offs)
        self._note_block_records(base, self._blk_offs, self._blk_keys)
        self._blk_parts, self._blk_len = [], 0
        self._blk_offs, self._blk_keys = [], []

    def _note_block_records(self, base: int, offsets, keys) -> None:
        pass  # the plain writer keeps no per-record state

    def flush(self) -> None:
        """Flush the pending compressed block (if any) and the stream.
        REQUIRED after the last record when writing with a codec."""
        self.flush_block()
        self.stream.flush()

    def commit(self, fsync: Optional[bool] = None) -> Tuple[int, int]:
        """Durable checkpoint: seal the pending block, flush data (and
        any sidecar), optionally fsync, and return the ``(byte, record)``
        watermark — the exact prefix a concurrent reader may consume.

        Because the pending block is sealed first, the watermark always
        lands on a frame boundary: the committed prefix decodes as whole
        records, never a torn tail. ``fsync=None`` follows the writer's
        constructor policy; ``True``/``False`` override per call.
        Streams without a durable fd (pipes, memory) skip the fsync
        silently — the flush is still the framing guarantee.
        """
        self.flush_block()
        self.stream.flush()
        do_sync = self.fsync if fsync is None else bool(fsync)
        self._commit_sidecar(do_sync)
        if do_sync:
            _fsync_stream(self.stream)
        return (self.bytes_written, self.records_written)

    def _commit_sidecar(self, do_sync: bool) -> None:
        pass  # the plain writer has no sidecar

    def close(self) -> None:
        """flush(); the stream itself stays caller-owned."""
        self.flush()


class IndexedRecordIOWriter(RecordIOWriter):
    """RecordIO writer that also emits the external index file an
    IndexedRecordIOSplitter shards by.

    Index format: whitespace-separated ``key offset`` pairs, one record
    per line (reference ReadIndexFile,
    src/io/indexed_recordio_split.cc:43-62). Keys default to the record
    ordinal. Offsets are the writer's own running byte count, so any
    Stream works (pipes, remote sinks) — but they are only valid index
    offsets when the writer starts at byte 0 of the destination file.

    With a ``codec``, the offset column becomes ``<block>:<in>`` —
    the block frame's file offset and the record's frame-start offset
    inside the DECODED block bytes (docs/recordio.md). A v1 index
    parser fails loudly on the ``:`` (checked, not garbage), and the
    compressed-aware IndexedRecordIOSplitter keys its whole block/
    record geometry off this sidecar.
    """

    def __init__(
        self,
        stream: Stream,
        index_stream: Stream,
        codec=None,
        level: Optional[int] = None,
        block_bytes: int = DEFAULT_BLOCK_BYTES,
        fsync: bool = False,
    ) -> None:
        super().__init__(
            stream, codec=codec, level=level, block_bytes=block_bytes,
            fsync=fsync,
        )
        # enforce the byte-0 contract instead of documenting it: an
        # append-positioned seekable stream would silently emit a corrupt
        # index (ADVICE r3). Non-seekable sinks (pipes) stay permitted.
        try:
            pos = stream.tell()
        except (OSError, AttributeError, Error):
            pos = 0
        check(
            pos == 0,
            f"IndexedRecordIOWriter must start at byte 0 of the "
            f"destination (stream is at {pos}); offsets would be wrong",
        )
        self.index_stream = index_stream
        self._count = 0

    def write_record(self, data: bytes, key: Optional[int] = None) -> None:
        if self.codec is not None:
            framed = self._frame_payload(data)
            self._buffer_block(framed, (0,), (key,))
            return
        offset = self.bytes_written
        super().write_record(data)
        k = self._count if key is None else key
        self.index_stream.write(f"{k}\t{offset}\n".encode())
        self._count += 1

    def _note_framed_records(self, base: int, offsets) -> None:
        if len(offsets) == 0:
            return
        lines = "".join(
            f"{self._count + i}\t{base + int(o)}\n"
            for i, o in enumerate(offsets)
        )
        self.index_stream.write(lines.encode())
        self._count += len(offsets)

    def _note_block_records(self, base: int, offsets, keys) -> None:
        lines: List[str] = []
        for o, k in zip(offsets, keys):
            kk = self._count if k is None else k
            lines.append(f"{kk}\t{base}:{int(o)}\n")
            self._count += 1
        self.index_stream.write("".join(lines).encode())

    def _commit_sidecar(self, do_sync: bool) -> None:
        # the sidecar commits WITH the data: a reader that trusts a
        # committed watermark must find every committed record's index
        # line already flushed
        self.index_stream.flush()
        if do_sync:
            _fsync_stream(self.index_stream)


class RecordIOReader:
    """Reference RecordIOReader (recordio.h:118-158, recordio.cc:53-82).

    Transparently decodes compressed blocks (cflag 4-7): the blob is
    reassembled, verified (codec id, raw length, crc32) and decoded via
    io/codec.py, and its inner v1 frames are served one record at a
    time. ``allow_compressed=False`` makes this a v1-only reader that
    REJECTS compressed blocks with a checked error — the behavior of a
    reader predating the block format, made explicit."""

    def __init__(self, stream: Stream, allow_compressed: bool = True) -> None:
        self.stream = stream
        self._eof = False
        self._allow_compressed = allow_compressed
        self._pending: Optional[Iterator[memoryview]] = None

    def _read_chain(self, cflag: int, length: int) -> bytes:
        """Read a (possibly multipart) frame chain starting at an
        already-consumed header; returns the reassembled payload with
        elided magics re-inserted. ``cflag`` bit 2 (compressed) must be
        uniform across the chain."""
        want_compressed = cflag & CFLAG_COMPRESSED
        parts: List[bytes] = []
        while True:
            upper = (length + 3) & ~3
            data = self.stream.read_exact(upper)
            parts.append(data[:length])
            if (cflag & 3) in (0, 3):
                break
            parts.append(_MAGIC_BYTES)  # re-insert elided magic between parts
            head = self.stream.read(8)
            if len(head) != 8:
                raise Error("Invalid RecordIO file: truncated header")
            magic, lrec = struct.unpack("<II", head)
            if magic != KMAGIC:
                raise Error(f"Invalid RecordIO file: bad magic {magic:#x}")
            cflag = decode_flag(lrec)
            length = decode_length(lrec)
            if (cflag & CFLAG_COMPRESSED) != want_compressed or (
                cflag & 3
            ) not in (2, 3):
                raise Error(
                    f"Invalid RecordIO file: corrupt multipart chain "
                    f"(continuation cflag {cflag})"
                )
        return b"".join(parts)

    def next_record(self) -> Optional[bytes]:
        """Next logical record (multi-part chains reassembled with the elided
        magic words re-inserted, compressed blocks decoded), or None at
        end of stream."""
        while True:
            if self._pending is not None:
                rec = next(self._pending, None)
                if rec is not None:
                    return bytes(rec)
                self._pending = None
            if self._eof:
                return None
            head = self.stream.read(8)
            if len(head) == 0:
                self._eof = True
                return None
            if len(head) != 8:
                raise Error("Invalid RecordIO file: truncated header")
            magic, lrec = struct.unpack("<II", head)
            if magic != KMAGIC:
                raise Error(f"Invalid RecordIO file: bad magic {magic:#x}")
            cflag = decode_flag(lrec)
            length = decode_length(lrec)
            if cflag & CFLAG_COMPRESSED:
                if not self._allow_compressed:
                    raise Error(
                        f"compressed RecordIO block (cflag {cflag}) in a "
                        f"v1-only reader; re-open with allow_compressed=True "
                        f"or convert with `tools recompress --codec none`"
                    )
                blob = self._read_chain(cflag, length)
                raw, _n = _codec.decode_block(blob)
                self._pending = iter(RecordIOChunkReader(raw, 0, 1))
                continue
            return self._read_chain(cflag, length)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


_SCAN_BLOCK_WORDS = 1 << 18  # 1 MB of uint32 words per scan block


def first_head_in_words(words: np.ndarray) -> int:
    """Word index of the first record-START header (magic word followed
    by an lrec whose PART flag is 0 or 1 — cflag 0/1 for v1 records,
    4/5 for compressed blocks) in a little-endian uint32 view, or -1.

    The single vectorized implementation of the head predicate used by the
    chunk reader, the RecordIO splitter, and the native-core fallback
    (reference FindNextRecordIOHead, src/recordio.cc:85-100). The magic
    word itself decodes to cflag 6 (a middle part), so a [magic][magic]
    byte pair is still never a head.
    """
    if len(words) < 2:
        return -1
    hits = np.nonzero((words[:-1] == KMAGIC) & (((words[1:] >> 29) & 3) <= 1))[0]
    return int(hits[0]) if len(hits) else -1


def last_head_in_words(words: np.ndarray) -> int:
    """Word index of the last record-START header, or -1 (reference
    backward scan, src/io/recordio_split.cc:26-42)."""
    if len(words) < 2:
        return -1
    hits = np.nonzero((words[:-1] == KMAGIC) & (((words[1:] >> 29) & 3) <= 1))[0]
    return int(hits[-1]) if len(hits) else -1


def _find_next_record_head(buf: memoryview, start: int) -> int:
    """First aligned offset >= start that looks like a record START header
    (magic followed by lrec with cflag 0 or 1), or len(buf) if none.

    Vectorized FindNextRecordIOHead (reference src/recordio.cc:85-100) —
    scans forward in 1MB blocks with early exit, so per-part cost matches
    the reference's scan-to-first-head instead of a full-chunk pass.
    """
    n = len(buf) & ~3
    start = (start + 3) & ~3
    nwords = n // 4
    w0 = start // 4
    while w0 + 1 < nwords:
        w1 = min(w0 + _SCAN_BLOCK_WORDS, nwords)
        # include one word of overlap so a head at the block boundary is seen
        words = np.frombuffer(buf[w0 * 4 : min(w1 * 4 + 4, n)], dtype="<u4")
        hit = first_head_in_words(words)
        if hit >= 0:
            return (w0 + hit) * 4
        w0 = w1
    return len(buf)


class RecordIOChunkReader:
    """Split one InputSplit chunk among threads and iterate its records as
    zero-copy memoryviews.

    Reference RecordIOChunkReader (recordio.h:160-196, recordio.cc:101-156):
    divide the chunk into ``num_parts`` aligned byte ranges, then snap each
    boundary forward to the next record head.
    """

    def __init__(self, chunk: bytes, part_index: int = 0, num_parts: int = 1) -> None:
        view = memoryview(chunk)
        size = len(view)
        nstep = (size + num_parts - 1) // num_parts
        nstep = (nstep + 3) & ~3
        begin = min(size, nstep * part_index)
        end = min(size, nstep * (part_index + 1))
        self._view = view
        self._pos = _find_next_record_head(view, begin)
        self._end = _find_next_record_head(view, end) if end < size else size

    def next_record(self) -> Optional[memoryview]:
        """Reference recordio.cc:114-156: reassembles multi-part records; a
        single-part record is returned as a zero-copy view."""
        if self._pos >= self._end:
            return None
        view = self._view
        parts: List[bytes] = []
        while True:
            head = view[self._pos : self._pos + 8]
            if len(head) != 8:
                raise Error("RecordIO chunk: truncated header")
            magic, lrec = struct.unpack("<II", head)
            check(magic == KMAGIC, "RecordIO chunk: bad magic")
            cflag = decode_flag(lrec)
            check(
                cflag & CFLAG_COMPRESSED == 0,
                "compressed RecordIO block in a v1 chunk reader "
                "(run the chunk through decode_chunk first)",
            )
            length = decode_length(lrec)
            upper = (length + 3) & ~3
            start = self._pos + 8
            self._pos = start + upper
            if cflag == 0:
                return view[start : start + length]
            parts.append(bytes(view[start : start + length]))
            if cflag == 3:
                return memoryview(b"".join(parts))
            parts.append(_MAGIC_BYTES)

    def __iter__(self) -> Iterator[memoryview]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


# -- compressed-chunk decode --------------------------------------------------
def chunk_has_compressed(chunk) -> bool:
    """One vectorized pass: does this chunk contain any compressed-block
    frame? In a well-formed file every ALIGNED magic word is a frame
    header (the writer escapes aligned payload magics), so a magic
    followed by a word with cflag bit 2 set can only be a compressed
    frame — zero false positives, and v1 chunks pay one numpy compare
    instead of a per-frame Python walk."""
    usable = len(chunk) & ~3
    if usable < 8:
        return False
    words = np.frombuffer(chunk, dtype="<u4", count=usable // 4)
    return bool(
        np.any((words[:-1] == KMAGIC) & ((words[1:] >> np.uint32(29)) >= 4))
    )


def scan_compressed_blob(view: memoryview, pos: int) -> Tuple[bytes, int]:
    """Reassemble one compressed-blob frame chain starting at ``pos``
    (which must be a cflag-4/5 head); returns (blob bytes, end offset).
    The in-buffer analogue of RecordIOReader._read_chain."""
    parts: List[bytes] = []
    first = True
    while True:
        head = view[pos : pos + 8]
        check(len(head) == 8, "RecordIO chunk: truncated compressed header")
        magic, lrec = struct.unpack("<II", head)
        check(magic == KMAGIC, "RecordIO chunk: bad magic in compressed chain")
        cflag = decode_flag(lrec)
        check(
            cflag & CFLAG_COMPRESSED
            and ((cflag & 3) in ((0, 1) if first else (2, 3))),
            f"RecordIO chunk: corrupt compressed chain (cflag {cflag})",
        )
        length = decode_length(lrec)
        start = pos + 8
        pos = start + ((length + 3) & ~3)
        if not first:
            parts.append(_MAGIC_BYTES)
        parts.append(bytes(view[start : start + length]))
        check(
            len(parts[-1]) == length,
            "RecordIO chunk: truncated compressed block",
        )
        if (cflag & 3) in (0, 3):
            return b"".join(parts), pos
        first = False


def decode_chunk(chunk: bytes, ctx=None) -> bytes:
    """Decode every compressed block in a chunk of whole frames,
    passing v1 frames through untouched; returns pure v1 framed bytes
    (byte-identical to what an uncompressed writer emits for the same
    records). Chunks without compressed frames return unchanged (same
    object) after one vectorized scan. Blocks decode in parallel
    through ``ctx`` (a codec.DecodeContext; default the process-global
    one), so a prefetch thread pulling chunks overlaps network reads
    with decompression and tests can inject a serial/fake context."""
    if not chunk_has_compressed(chunk):
        return chunk
    view = memoryview(chunk)
    n = len(chunk)
    out: List[object] = []  # bytes/memoryview, or int blob ordinal
    blobs: List[bytes] = []
    pos = 0
    run_start = 0
    while pos + 8 <= n:
        magic, lrec = struct.unpack("<II", view[pos : pos + 8])
        check(magic == KMAGIC, "RecordIO chunk: bad magic")
        cflag = decode_flag(lrec)
        if cflag & CFLAG_COMPRESSED:
            if run_start < pos:
                out.append(view[run_start:pos])
            blob, pos = scan_compressed_blob(view, pos)
            out.append(len(blobs))
            blobs.append(blob)
            run_start = pos
        else:
            pos += 8 + ((decode_length(lrec) + 3) & ~3)
    check(pos == n, "RecordIO chunk: trailing partial frame")
    if run_start < n:
        out.append(view[run_start:n])
    if ctx is None:
        ctx = _codec.default_decode_context()
    decoded = ctx.decode_blocks(blobs)
    return b"".join(
        decoded[p][0] if isinstance(p, int) else p for p in out
    )
