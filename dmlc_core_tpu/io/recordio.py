"""RecordIO: splittable binary record container, bit-compatible with the
reference format so existing ``.rec`` data loads unchanged.

Reference: include/dmlc/recordio.h:16-45 (format), src/recordio.cc (codec).

Frame layout (little-endian uint32 words)::

    [kMagic = 0xced7230a] [lrec] [data ...] [zero pad to 4-byte boundary]
    lrec = (cflag << 29) | length        # length < 2**29 (512 MB)

When the payload itself contains the magic word at a 4-byte-aligned offset,
the writer splits the record at each such occurrence into a multi-part chain
(the occurrence itself is elided and re-inserted by the reader):

    cflag 0: complete record    1: start   2: middle   3: end

TPU-first design departure: scanning for aligned magic words is the hot loop;
we vectorize it with one numpy view + compare over the whole payload instead
of a byte loop (reference scans per-word, src/recordio.cc:22-28). The hot
READ path has a native counterpart: native/fastparse.cc
``dmlc_parse_rowrec_ell`` walks frames (magic/lrec headers, multipart
chains) directly in C++ on the RecordIO→HBM staging path
(staging/fused.py); this Python codec remains the writer and the
reference implementation the native kernel's parity tests check against.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional

import numpy as np

from ..utils.logging import Error, check, check_lt
from .stream import SeekStream, Stream

__all__ = [
    "KMAGIC",
    "RecordIOWriter",
    "IndexedRecordIOWriter",
    "RecordIOReader",
    "RecordIOChunkReader",
    "encode_lrec",
    "decode_flag",
    "decode_length",
]

KMAGIC = 0xCED7230A  # reference recordio.h:43; (kMagic >> 29) & 7 > 3
_MAGIC_BYTES = struct.pack("<I", KMAGIC)
_MAX_LEN = 1 << 29


def encode_lrec(cflag: int, length: int) -> int:
    """Reference recordio.h:52-54."""
    return ((cflag & 7) << 29) | length


def decode_flag(lrec: int) -> int:
    """Reference recordio.h:60-62."""
    return (lrec >> 29) & 7


def decode_length(lrec: int) -> int:
    """Reference recordio.h:68-70."""
    return lrec & (_MAX_LEN - 1)


def _aligned_magic_positions(payload: bytes) -> np.ndarray:
    """4-byte-aligned offsets where the payload equals the magic word.

    Vectorized equivalent of the writer's scan loop (reference
    src/recordio.cc:20-28): view the lower-aligned prefix as uint32 and
    compare against little-endian kMagic in one pass.
    """
    lower = len(payload) & ~3
    if lower == 0:
        return np.empty(0, dtype=np.int64)
    words = np.frombuffer(payload, dtype="<u4", count=lower // 4)
    return (np.nonzero(words == KMAGIC)[0] * 4).astype(np.int64)


class RecordIOWriter:
    """Reference RecordIOWriter (recordio.h:38-115, recordio.cc:11-51)."""

    def __init__(self, stream: Stream) -> None:
        self.stream = stream
        self.except_counter = 0  # number of magic collisions escaped
        self.bytes_written = 0  # framed bytes emitted through this writer

    def write_record(self, data: bytes) -> None:
        check_lt(len(data), _MAX_LEN, "RecordIO only accepts records < 2^29 bytes")
        out: List[bytes] = []
        dptr = 0
        for pos in _aligned_magic_positions(data):
            pos = int(pos)
            cflag = 1 if dptr == 0 else 2
            out.append(_MAGIC_BYTES)
            out.append(struct.pack("<I", encode_lrec(cflag, pos - dptr)))
            out.append(data[dptr:pos])
            dptr = pos + 4
            self.except_counter += 1
        cflag = 3 if dptr != 0 else 0
        out.append(_MAGIC_BYTES)
        out.append(struct.pack("<I", encode_lrec(cflag, len(data) - dptr)))
        out.append(data[dptr:])
        # pad the FINAL part's data to a 4-byte boundary with zeros
        tail_len = len(data) - dptr
        pad = (4 - (tail_len & 3)) & 3
        if pad:
            out.append(b"\x00" * pad)
        framed = b"".join(out)
        self.stream.write(framed)
        self.bytes_written += len(framed)

    def tell(self) -> int:
        check(isinstance(self.stream, SeekStream), "stream is not seekable")
        return self.stream.tell()  # type: ignore[union-attr]

    def write_framed_block(self, framed: bytes, offsets) -> None:
        """Bulk-write pre-framed records (data/rowrec.py
        encode_block_frames output). ``offsets`` are frame-start byte
        offsets relative to ``framed``; subclasses use them to keep
        per-record bookkeeping (the index sidecar) in one place."""
        base = self.bytes_written
        self.stream.write(framed)
        self.bytes_written += len(framed)
        self._note_framed_records(base, offsets)

    def _note_framed_records(self, base: int, offsets) -> None:
        pass  # the plain writer keeps no per-record state


class IndexedRecordIOWriter(RecordIOWriter):
    """RecordIO writer that also emits the external index file an
    IndexedRecordIOSplitter shards by.

    Index format: whitespace-separated ``key offset`` pairs, one record
    per line (reference ReadIndexFile,
    src/io/indexed_recordio_split.cc:43-62). Keys default to the record
    ordinal. Offsets are the writer's own running byte count, so any
    Stream works (pipes, remote sinks) — but they are only valid index
    offsets when the writer starts at byte 0 of the destination file.
    """

    def __init__(self, stream: Stream, index_stream: Stream) -> None:
        super().__init__(stream)
        # enforce the byte-0 contract instead of documenting it: an
        # append-positioned seekable stream would silently emit a corrupt
        # index (ADVICE r3). Non-seekable sinks (pipes) stay permitted.
        try:
            pos = stream.tell()
        except (OSError, AttributeError, Error):
            pos = 0
        check(
            pos == 0,
            f"IndexedRecordIOWriter must start at byte 0 of the "
            f"destination (stream is at {pos}); offsets would be wrong",
        )
        self.index_stream = index_stream
        self._count = 0

    def write_record(self, data: bytes, key: Optional[int] = None) -> None:
        offset = self.bytes_written
        super().write_record(data)
        k = self._count if key is None else key
        self.index_stream.write(f"{k}\t{offset}\n".encode())
        self._count += 1

    def _note_framed_records(self, base: int, offsets) -> None:
        if len(offsets) == 0:
            return
        lines = "".join(
            f"{self._count + i}\t{base + int(o)}\n"
            for i, o in enumerate(offsets)
        )
        self.index_stream.write(lines.encode())
        self._count += len(offsets)


class RecordIOReader:
    """Reference RecordIOReader (recordio.h:118-158, recordio.cc:53-82)."""

    def __init__(self, stream: Stream) -> None:
        self.stream = stream
        self._eof = False

    def next_record(self) -> Optional[bytes]:
        """Next logical record (multi-part chains reassembled with the elided
        magic words re-inserted), or None at end of stream."""
        if self._eof:
            return None
        parts: List[bytes] = []
        while True:
            head = self.stream.read(8)
            if len(head) == 0 and not parts:
                self._eof = True
                return None
            if len(head) != 8:
                raise Error("Invalid RecordIO file: truncated header")
            magic, lrec = struct.unpack("<II", head)
            if magic != KMAGIC:
                raise Error(f"Invalid RecordIO file: bad magic {magic:#x}")
            cflag = decode_flag(lrec)
            length = decode_length(lrec)
            upper = (length + 3) & ~3
            data = self.stream.read_exact(upper)
            parts.append(data[:length])
            if cflag in (0, 3):
                break
            parts.append(_MAGIC_BYTES)  # re-insert elided magic between parts
        return b"".join(parts)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


_SCAN_BLOCK_WORDS = 1 << 18  # 1 MB of uint32 words per scan block


def first_head_in_words(words: np.ndarray) -> int:
    """Word index of the first record-START header (magic word followed by an
    lrec with cflag 0 or 1) in a little-endian uint32 view, or -1.

    The single vectorized implementation of the head predicate used by the
    chunk reader, the RecordIO splitter, and the native-core fallback
    (reference FindNextRecordIOHead, src/recordio.cc:85-100).
    """
    if len(words) < 2:
        return -1
    hits = np.nonzero((words[:-1] == KMAGIC) & (((words[1:] >> 29) & 7) <= 1))[0]
    return int(hits[0]) if len(hits) else -1


def last_head_in_words(words: np.ndarray) -> int:
    """Word index of the last record-START header, or -1 (reference
    backward scan, src/io/recordio_split.cc:26-42)."""
    if len(words) < 2:
        return -1
    hits = np.nonzero((words[:-1] == KMAGIC) & (((words[1:] >> 29) & 7) <= 1))[0]
    return int(hits[-1]) if len(hits) else -1


def _find_next_record_head(buf: memoryview, start: int) -> int:
    """First aligned offset >= start that looks like a record START header
    (magic followed by lrec with cflag 0 or 1), or len(buf) if none.

    Vectorized FindNextRecordIOHead (reference src/recordio.cc:85-100) —
    scans forward in 1MB blocks with early exit, so per-part cost matches
    the reference's scan-to-first-head instead of a full-chunk pass.
    """
    n = len(buf) & ~3
    start = (start + 3) & ~3
    nwords = n // 4
    w0 = start // 4
    while w0 + 1 < nwords:
        w1 = min(w0 + _SCAN_BLOCK_WORDS, nwords)
        # include one word of overlap so a head at the block boundary is seen
        words = np.frombuffer(buf[w0 * 4 : min(w1 * 4 + 4, n)], dtype="<u4")
        hit = first_head_in_words(words)
        if hit >= 0:
            return (w0 + hit) * 4
        w0 = w1
    return len(buf)


class RecordIOChunkReader:
    """Split one InputSplit chunk among threads and iterate its records as
    zero-copy memoryviews.

    Reference RecordIOChunkReader (recordio.h:160-196, recordio.cc:101-156):
    divide the chunk into ``num_parts`` aligned byte ranges, then snap each
    boundary forward to the next record head.
    """

    def __init__(self, chunk: bytes, part_index: int = 0, num_parts: int = 1) -> None:
        view = memoryview(chunk)
        size = len(view)
        nstep = (size + num_parts - 1) // num_parts
        nstep = (nstep + 3) & ~3
        begin = min(size, nstep * part_index)
        end = min(size, nstep * (part_index + 1))
        self._view = view
        self._pos = _find_next_record_head(view, begin)
        self._end = _find_next_record_head(view, end) if end < size else size

    def next_record(self) -> Optional[memoryview]:
        """Reference recordio.cc:114-156: reassembles multi-part records; a
        single-part record is returned as a zero-copy view."""
        if self._pos >= self._end:
            return None
        view = self._view
        parts: List[bytes] = []
        while True:
            head = view[self._pos : self._pos + 8]
            if len(head) != 8:
                raise Error("RecordIO chunk: truncated header")
            magic, lrec = struct.unpack("<II", head)
            check(magic == KMAGIC, "RecordIO chunk: bad magic")
            cflag = decode_flag(lrec)
            length = decode_length(lrec)
            upper = (length + 3) & ~3
            start = self._pos + 8
            self._pos = start + upper
            if cflag == 0:
                return view[start : start + length]
            parts.append(bytes(view[start : start + length]))
            if cflag == 3:
                return memoryview(b"".join(parts))
            parts.append(_MAGIC_BYTES)

    def __iter__(self) -> Iterator[memoryview]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec
