"""Low-latency batched point reads over indexed RecordIO — the online
feature-store scenario (ROADMAP item 5, docs/serving.md).

Every other consumer in this repo drains epochs; this module composes
the already-built random-access substrate into a read-few-records-NOW
hot path:

- **Key resolution** rides the shared cached sidecar index
  (``io/split.py _load_index_cached`` — one parse per (uri, mtime),
  shared across handles): the key column is kept in record order, and a
  batch of keys resolves to record positions in ONE vectorized
  ``searchsorted`` pass. Missing keys are explicit ``None`` results,
  never an exception and never a wrong record.
- **Hot blocks come from the caches**: the whole batch's unique blocks
  go through the two-level ``codec.DecodeContext`` — the in-process L1
  LRU, then the per-host block-cache daemon (``io/blockcache.py``) in
  ONE ``get_many`` round trip. A dead or absent daemon degrades to L1
  silently, exactly like the epoch path.
- **Residual misses are coalesced parallel ranged reads**: the missing
  blocks' file spans merge at ``merge_gap`` granularity and ride the
  splitter's one miss path (``_fetch_blocks``) — the concurrent span
  fetcher (``io/spanfetch.py``) on remote files with fetch→decode
  overlap, mmap/pread locally — and every decoded block is published
  back through the daemon's admission/quota machinery.
- **Records leave decoded blocks via the frame walk**: per block, one
  native ``dmlc_walk_record_spans`` call (or one vectorized numpy
  header pass) turns index slices into payload spans; only the rare
  multi-part chain (payload containing the aligned magic) is
  reassembled in Python.

``RecordLookup`` is the library handle; ``LookupServer``/
``LookupClient`` are the ``tools serve`` daemon mode — a
length-prefixed-JSON request loop (the framing idiom of
``blockcache.py``/``dsserve/wire.py``; record payloads follow the JSON
header as one raw blob, so values never pay base64) with p50/p99
latency histograms and QPS on the telemetry registry (``io.lookup.*``,
``/metrics`` via telemetry/export.py) and a ``lookup_wait`` stall stage
in the flight recorder.

Warming: ``RecordLookup.warm`` prefetches the blocks covering a key set
(hottest blocks first, optionally capped) and publishes them through
the block-cache daemon's EXISTING admission control and per-tenant
quotas — run the serve tier under its own ``DMLC_BLOCK_CACHE_TENANT``
with a ``DMLC_BLOCK_CACHE_TENANT_MB`` quota and warming can never evict
an epoch tenant's working set (docs/serving.md).

Lint: L016 confines socket-serving request loops inside
``dmlc_core_tpu/io/`` to ``blockcache.py`` and this module (and L010's
socket-import rule exempts both).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data import native as _native
from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from ..utils.logging import Error, check
from . import codec as _codec
from . import recordio as _recordio
from . import split as _split
from .blockcache import MAX_FRAME, _recv_all, _recv_frame

__all__ = [
    "LookupClient",
    "LookupServer",
    "RecordLookup",
]

logger = logging.getLogger("dmlc_core_tpu.io.lookup")

_REG = _default_registry()
_BATCHES = _REG.counter(
    "io.lookup.batches", help="batched lookup() calls served"
)
_KEYS = _REG.counter("io.lookup.keys", help="keys resolved by lookup()")
_HITS = _REG.counter(
    "io.lookup.hits", help="keys that resolved to a record"
)
_NEGATIVES = _REG.counter(
    "io.lookup.negatives", help="keys absent from the index (None results)"
)
_BYTES = _REG.counter(
    "io.lookup.bytes", help="record payload bytes returned by lookup()"
)
_BLOCK_HITS = _REG.counter(
    "io.lookup.block_hits", help="blocks served from the L1/L2 caches"
)
_BLOCK_MISSES = _REG.counter(
    "io.lookup.block_misses", help="blocks fetched+decoded on the miss path"
)
_WARMED = _REG.counter(
    "io.lookup.warm_blocks", help="blocks prefetched by warm()"
)
_BATCH_SECONDS = _REG.histogram(
    "io.lookup.batch_seconds", help="library-level lookup() wall time"
)
_REQUEST_SECONDS = _REG.histogram(
    "io.lookup.request_seconds",
    help="serve-daemon per-request wall time (p50/p99 on /metrics)",
)
_CLIENTS = _REG.gauge(
    "io.lookup.clients", help="serve-daemon connections currently open"
)

_MAGIC_MASK = np.uint32((1 << 29) - 1)


# -- frame walk: index slices -> payload bytes --------------------------------
def _extract_payloads(
    buf: np.ndarray, starts: np.ndarray, sizes: np.ndarray, what: str
) -> List[bytes]:
    """Payload bytes of the framed records at ``(starts[i], sizes[i])``
    slices of ``buf`` (uint8). One native ``dmlc_walk_record_spans``
    call — or one vectorized numpy header pass — resolves every
    single-frame record; only multi-part chains fall back to a Python
    reassembly. A slice that holds no valid record head means the index
    and the data disagree: checked Error, never a wrong payload."""
    n = len(starts)
    if n == 0:
        return []
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    sizes = np.ascontiguousarray(sizes, dtype=np.int64)
    check(
        int(starts.min(initial=0)) >= 0
        and int((starts + sizes).max(initial=0)) <= len(buf)
        and bool((sizes >= 8).all()),
        f"{what}: record slices fall outside the decoded bytes "
        f"(corrupt index or data)",
    )
    res = _native.walk_record_spans(buf, starts, sizes)
    if res is not None:
        offs, lens, _nm, nc = res
        check(
            nc == 0,
            f"{what}: {nc} record slices hold no valid record frame "
            f"(index and data disagree)",
        )
    else:
        # one vectorized pass: gather every record's 8 header bytes,
        # check magic + cflag, compute payload spans in place
        hdr = buf[starts[:, None] + np.arange(8)]
        words = hdr.view("<u4")
        magic_ok = words[:, 0] == np.uint32(_recordio.KMAGIC)
        lrec = words[:, 1]
        cflag = lrec >> np.uint32(29)
        plen = (lrec & _MAGIC_MASK).astype(np.int64)
        single = magic_ok & (cflag == 0)
        fits = (8 + ((plen + 3) & ~np.int64(3))) <= sizes
        bad = (~magic_ok) | (magic_ok & (cflag > 1)) | (single & ~fits)
        check(
            not bool(bad.any()),
            f"{what}: {int(bad.sum())} record slices hold no valid "
            f"record frame (index and data disagree)",
        )
        offs = np.where(single, starts + 8, np.int64(-2))
        lens = np.where(single, plen, np.int64(0))
    out: List[bytes] = []
    for i in range(n):
        o = int(offs[i])
        if o >= 0:
            out.append(bytes(buf[o : o + int(lens[i])]))
            continue
        # multi-part chain (payload contains the aligned magic word):
        # reassemble through the reference chunk reader — rare by
        # construction, so per-record Python here costs nothing
        s = int(starts[i])
        rec = _recordio.RecordIOChunkReader(
            memoryview(buf[s : s + int(sizes[i])]), 0, 1
        ).next_record()
        check(
            rec is not None,
            f"{what}: truncated multi-part record (index and data "
            f"disagree)",
        )
        out.append(bytes(rec))
    return out


class RecordLookup:
    """Batched multi-key point reads over one indexed ``.rec`` shard
    (any codec).

    ``lookup(keys) -> [bytes | None, ...]`` — results align with the
    input keys; a key absent from the index is an explicit ``None``
    (negative lookup), a corrupt block is a checked Error. Bytes are
    bit-identical whether a block arrived from the in-process L1, the
    host daemon, or a fresh fetch+decode — and across codecs, since
    decoded blocks carry plain v1 frames.

    Thread-safe: one handle serves a multi-threaded daemon (batches
    serialize on an internal lock — batching, not concurrency, is the
    throughput lever on this path).
    """

    def __init__(
        self,
        uri: str,
        index_uri: Optional[str] = None,
        decode_ctx: Optional[_codec.DecodeContext] = None,
        merge_gap: int = 65536,
        filesys=None,
    ) -> None:
        self.uri = uri
        self.index_uri = index_uri or uri + ".idx"
        self.merge_gap = merge_gap
        # the splitter IS the substrate: file table, cached index
        # arrays, cross-process cache identity, span reader/fetcher and
        # the coalesced block miss path all come from it — lookup adds
        # key resolution and payload extraction, not a second I/O stack
        self._sp = _split.IndexedRecordIOSplitter(
            uri,
            self.index_uri,
            0,
            1,
            shuffle=False,
            readahead=False,
            merge_gap=merge_gap,
            filesys=filesys,
            decode_ctx=decode_ctx,
        )
        keys = self._sp._index_keys
        check(
            keys is not None and len(keys) == len(self._sp._index_offs),
            f"index file {self.index_uri!r} carries no usable key column",
        )
        # sorted-key view for one-searchsorted-per-batch resolution;
        # computed once per handle (the parsed index itself is shared
        # through the process-wide LRU)
        self._key_order = np.argsort(keys, kind="stable")
        self._keys_sorted = keys[self._key_order]
        self._lock = threading.Lock()
        self._codec_memo: Optional[str] = None
        self.lookups = 0
        self.keys_resolved = 0
        self.negatives = 0
        self.bytes_out = 0
        self.block_cache_hits = 0
        self.block_cache_misses = 0

    # -- introspection --------------------------------------------------------
    @property
    def n_records(self) -> int:
        return len(self._keys_sorted)

    @property
    def compressed(self) -> bool:
        return bool(self._sp._compressed)

    def describe(self) -> dict:
        """Index key count + block geometry — what an operator needs to
        size a serve tier (``tools info <uri>``) without opening the
        sidecar by hand. Takes the handle lock: the codec probe shares
        the span reader (and, remotely, its stream cursors) with
        in-flight lookups."""
        with self._lock:
            return self._describe_locked()

    def _describe_locked(self) -> dict:
        sp = self._sp
        out = {
            "records": int(len(sp._index_offs)),
            "keys": int(len(self._keys_sorted)),
            "key_dtype": str(self._keys_sorted.dtype),
            "total_bytes": int(sp.file_offset[-1]),
            "compressed": bool(sp._compressed),
        }
        if sp._compressed:
            bs = sp._block_sizes
            out.update(
                blocks=int(len(bs)),
                block_bytes={
                    "min": int(bs.min()),
                    "mean": int(bs.mean()),
                    "max": int(bs.max()),
                },
                records_per_block=round(len(sp._index_offs) / len(bs), 1),
                codec=self._codec_name(),
            )
        else:
            out["codec"] = "none"
        return out

    def _codec_name(self) -> str:
        """Codec of the first block (28 bytes read: frame + block
        headers, memoized — one probe per handle) — shards are
        single-codec by construction of the writer, and 'unknown'
        degrades instead of failing an info call."""
        if self._codec_memo is not None:
            return self._codec_memo
        self._codec_memo = self._probe_codec()
        return self._codec_memo

    def _probe_codec(self) -> str:
        sp = self._sp
        try:
            head = bytes(
                sp._get_span_reader().read(int(sp._block_offs[0]), 28)
            )
            magic, lrec = struct.unpack("<II", head[:8])
            if magic != _recordio.KMAGIC:
                return "unknown"
            codec_id = head[8]
            return _codec.get_codec(int(codec_id)).name
        except Exception:
            return "unknown"

    # -- key resolution -------------------------------------------------------
    @staticmethod
    def _int_key(k) -> int:
        """Exact integer coercion: ints (and integer strings, the wire
        form) pass; a float truncating to a DIFFERENT key would return
        the wrong record, which this path must never do."""
        if isinstance(k, bool):  # bool IS int: True would read key 1
            raise TypeError(f"non-integer key {k!r}")
        if isinstance(k, (int, np.integer)):
            return int(k)
        if isinstance(k, (str, bytes)):
            return int(k)  # ValueError on '3.7' — no silent truncation
        raise TypeError(f"non-integer key {k!r}")

    @staticmethod
    def _str_key(k) -> str:
        """Exact string coercion: str passes, bytes decode (the sidecar
        is text, so its keys are utf-8), ints render exactly. Anything
        else — a float, an arbitrary object — would str() into a key
        that can never match and masquerade as an honest negative."""
        if isinstance(k, str):
            return k
        if isinstance(k, bytes):
            return k.decode()
        if isinstance(k, (int, np.integer)) and not isinstance(k, bool):
            return str(int(k))
        raise TypeError(f"non-string key {k!r}")

    def _as_key_array(self, keys: Sequence) -> np.ndarray:
        if self._keys_sorted.dtype == np.int64:
            try:
                return np.asarray(
                    [self._int_key(k) for k in keys], dtype=np.int64
                )
            except (ValueError, TypeError, OverflowError):
                raise Error(
                    f"lookup keys must be integers for this index "
                    f"({self.index_uri!r} has integer keys)"
                ) from None
        try:
            return np.asarray([self._str_key(k) for k in keys])
        except (TypeError, UnicodeDecodeError):
            raise Error(
                f"lookup keys must be strings for this index "
                f"({self.index_uri!r} has string keys)"
            ) from None

    def _resolve(self, keys: Sequence) -> Tuple[np.ndarray, np.ndarray]:
        """(query hit mask, record positions of the hits) — one
        vectorized searchsorted pass over the sorted key view."""
        q = self._as_key_array(keys)
        if len(q) == 0:
            return np.zeros(0, dtype=bool), np.zeros(0, dtype=np.int64)
        n = len(self._keys_sorted)
        pos = np.searchsorted(self._keys_sorted, q)
        pos_c = np.minimum(pos, max(n - 1, 0))
        hit = (
            (pos < n) & (self._keys_sorted[pos_c] == q)
            if n
            else np.zeros(len(q), dtype=bool)
        )
        recs = self._key_order[pos_c[hit]]
        return hit, recs.astype(np.int64)

    # -- the batched read -----------------------------------------------------
    def lookup(self, keys: Sequence) -> List[Optional[bytes]]:
        """Record payload bytes for every key, ``None`` for keys absent
        from the index; results align with the input order (duplicate
        query keys each get the record)."""
        t0 = _time.perf_counter()
        with self._lock:
            out = self._lookup_locked(keys)
        _BATCH_SECONDS.observe(_time.perf_counter() - t0)
        return out

    def _lookup_locked(self, keys: Sequence) -> List[Optional[bytes]]:
        hit, recs = self._resolve(keys)
        results: List[Optional[bytes]] = [None] * len(hit)
        n_hit = int(hit.sum())
        self.lookups += 1
        self.keys_resolved += len(hit)
        self.negatives += len(hit) - n_hit
        _BATCHES.inc()
        _KEYS.inc(len(hit))
        _HITS.inc(n_hit)
        _NEGATIVES.inc(len(hit) - n_hit)
        if n_hit == 0:
            return results
        # duplicates collapse before any I/O planning
        recs_u, inv = np.unique(recs, return_inverse=True)
        if self._sp._compressed:
            payloads_u = self._read_compressed(recs_u)
        else:
            payloads_u = self._read_v1(recs_u)
        nbytes = 0
        j = 0
        for i in np.nonzero(hit)[0].tolist():
            p = payloads_u[int(inv[j])]
            results[i] = p
            nbytes += len(p)
            j += 1
        self.bytes_out += nbytes
        _BYTES.inc(nbytes)
        return results

    def _read_compressed(self, recs: np.ndarray) -> List[bytes]:
        """Payloads for UNIQUE record positions of a block shard: the
        batch's unique blocks resolve through the two-level decode
        context in ONE batched lookup (L1, then one daemon
        ``get_many`` round trip), misses ride the splitter's coalesced
        parallel miss path, and each block's records leave via one
        frame-walk call."""
        sp = self._sp
        bids = sp._rec_block[recs]
        uniq = np.unique(bids)
        keymap = {int(b): sp._block_key(int(b)) for b in uniq.tolist()}
        found = sp._decode_ctx.get_blocks(list(keymap.values()))
        blocks: Dict[int, bytes] = {}
        missing: List[int] = []
        for b, k in keymap.items():
            raw = found.get(k)
            if raw is None:
                missing.append(b)
            else:
                blocks[b] = raw
        self.block_cache_hits += len(blocks)
        self.block_cache_misses += len(missing)
        sp.decode_cache_hits += len(blocks)
        sp.decode_cache_misses += len(missing)
        _BLOCK_HITS.inc(len(blocks))
        if missing:
            _BLOCK_MISSES.inc(len(missing))
            # named span: a cold batch's whole fetch+decode shows as one
            # region on the timeline, with per-span/per-decode children
            with _tracing.span(
                "dmlc:lookup_block_fetch", blocks=len(missing)
            ):
                blocks.update(sp._fetch_blocks(sorted(missing)))
        out: List[bytes] = [b""] * len(recs)
        order = np.argsort(bids, kind="stable")
        ob = bids[order]
        i = 0
        while i < len(order):
            b = int(ob[i])
            j = i
            while j < len(order) and int(ob[j]) == b:
                j += 1
            sel = order[i:j]
            raw = blocks[b]
            buf = np.frombuffer(raw, dtype=np.uint8)
            starts = sp._rec_inoff[recs[sel]]
            nxt = sp._rec_next[recs[sel]]
            ends = np.where(nxt >= 0, nxt, len(raw))
            payloads = _extract_payloads(
                buf, starts, ends - starts, f"lookup {self.uri!r}"
            )
            for k, p in zip(sel.tolist(), payloads):
                out[int(k)] = p
            i = j
        return out

    def _read_v1(self, recs: np.ndarray) -> List[bytes]:
        """Payloads for UNIQUE record positions of an uncompressed
        shard: the records' framed byte ranges coalesce into spans at
        ``merge_gap`` granularity and read through the splitter's span
        machinery (zero-copy mmap locally, parallel ranged reads via
        the span fetcher on remote files), then one frame-walk pass
        slices payloads out of the span buffer."""
        sp = self._sp
        offs = sp._index_offs[recs]
        sizes = sp._index_sizes[recs]
        order, s_starts, s_ends = _split._plan_span_bounds(
            offs, sizes, self.merge_gap
        )
        span_begin = offs[order][s_starts]
        run_end = np.maximum.accumulate(offs[order] + sizes[order])
        span_len = run_end[s_ends - 1] - span_begin
        buf = sp._read_spans(span_begin, span_len)
        span_of = np.repeat(np.arange(len(s_starts)), s_ends - s_starts)
        base = np.concatenate(([0], np.cumsum(span_len)[:-1]))
        rel = offs[order] - span_begin[span_of] + base[span_of]
        sorted_payloads = _extract_payloads(
            np.ascontiguousarray(buf),
            rel,
            sizes[order],
            f"lookup {self.uri!r}",
        )
        out: List[bytes] = [b""] * len(recs)
        for j, k in enumerate(order.tolist()):
            out[int(k)] = sorted_payloads[j]
        return out

    # -- warming --------------------------------------------------------------
    def warm(
        self,
        keys: Optional[Sequence] = None,
        max_blocks: Optional[int] = None,
    ) -> int:
        """Prefetch the decoded blocks covering ``keys`` (``None`` = the
        whole shard), hottest blocks — the ones covering the most
        requested keys — first, optionally capped at ``max_blocks``.
        Fetched blocks publish through the block-cache daemon's EXISTING
        admission control and per-tenant quota machinery (a quota'd
        serve tenant can never evict an epoch tenant's working set —
        docs/serving.md). Returns the number of blocks actually
        fetched+decoded (already-cached blocks cost nothing).
        Uncompressed shards have no decoded-block tier: no-op."""
        if not self._sp._compressed:
            return 0
        with self._lock:
            sp = self._sp
            if keys is None:
                bids = sp._rec_block
            else:
                _hit, recs = self._resolve(keys)
                if len(recs) == 0:
                    return 0
                bids = sp._rec_block[recs]
            uniq, counts = np.unique(bids, return_counts=True)
            hot = uniq[np.argsort(-counts, kind="stable")]
            if max_blocks is not None:
                hot = hot[: max(int(max_blocks), 0)]
            keymap = {int(b): sp._block_key(int(b)) for b in hot.tolist()}
            found = sp._decode_ctx.get_blocks(list(keymap.values()))
            missing = sorted(
                b for b, k in keymap.items() if k not in found
            )
            if missing:
                with _tracing.span(
                    "dmlc:lookup_warm", blocks=len(missing)
                ):
                    sp._fetch_blocks(missing)
                _WARMED.inc(len(missing))
            return len(missing)

    def io_stats(self) -> Dict[str, object]:
        base = self._sp.io_stats()
        base.update(
            lookups=self.lookups,
            keys_resolved=self.keys_resolved,
            negatives=self.negatives,
            lookup_bytes=self.bytes_out,
            block_cache_hits=self.block_cache_hits,
            block_cache_misses=self.block_cache_misses,
        )
        return base

    def close(self) -> None:
        self._sp.close()


# -- wire framing (blockcache idiom + a raw payload blob) ---------------------
def _send_frame(sock: socket.socket, obj: dict, payload: bytes = b"") -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    # reject at the SENDER: the receiver drops an oversized frame's
    # connection, and the failure would masquerade as a dead daemon
    # (the collective.py oversized-payload lesson). Record payloads are
    # not capped — only the JSON header is a control frame.
    if len(data) > MAX_FRAME:
        raise Error(
            f"lookup control frame of {len(data)} bytes exceeds the "
            f"{MAX_FRAME}-byte cap — split the key batch"
        )
    sock.sendall(struct.pack("<I", len(data)) + data + payload)
# frame RECEIVE hygiene (length cap, close semantics) is shared with
# blockcache._recv_frame — one implementation per the L016 rationale;
# only the send side differs here (the appended raw payload blob)


class LookupServer:
    """The ``tools serve`` daemon: batched point lookups over one
    indexed shard on a TCP request loop.

    Protocol (one request frame in, one response frame out, per the
    blockcache framing idiom): 4-byte LE length + compact JSON. A
    ``lookup`` response's JSON header carries ``sizes`` (-1 = negative
    lookup) and the record payloads follow the header as ONE raw blob
    in key order — values never pay base64 or JSON escaping. Ops:
    ``lookup`` (keys), ``warm`` (keys/max_blocks), ``stats``, ``ping``.

    Telemetry: every request ticks ``io.lookup.requests{op=...}`` and
    observes ``io.lookup.request_seconds`` (the p50/p99 the acceptance
    bench pins); ``metrics_port`` serves the process registry on
    ``/metrics`` (telemetry/export.py).
    """

    def __init__(
        self,
        handle: RecordLookup,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_port: int = 0,
    ) -> None:
        self.handle = handle
        self.host = host
        self._sock = socket.create_server((host, port), backlog=64)
        self.port = self._sock.getsockname()[1]
        self._closed = threading.Event()
        self._conns: set = set()
        self._lock = threading.Lock()
        self._t0 = _time.perf_counter()
        self.requests = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="lookup-accept"
        )
        self._accept_thread.start()
        self._metrics_server = None
        if metrics_port:
            from ..telemetry.export import serve_metrics_http

            self._metrics_server = serve_metrics_http(
                metrics_port, registry=_REG, json_provider=self.stats,
                name="lookup-metrics-http",
            )
        logger.info(
            "lookup daemon serving %s:%d over %s",
            host, self.port, handle.uri,
        )

    def serve_forever(self) -> None:
        """Block until ``close()`` (foreground CLI mode)."""
        self._closed.wait()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._metrics_server is not None:
            try:
                self._metrics_server.shutdown()
                self._metrics_server.server_close()
            except Exception:
                pass

    def _accept_loop(self) -> None:
        # a timed accept keeps close() prompt: closing a listening
        # socket from another thread does not reliably unblock a
        # blocked accept(), so the loop polls the closed flag instead
        # (the dsserve server idiom)
        self._sock.settimeout(0.25)
        while not self._closed.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # closed
            conn.settimeout(None)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="lookup-conn",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.add(conn)
        _CLIENTS.inc(1)
        try:
            while True:
                try:
                    req = _recv_frame(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                t0 = _time.perf_counter()
                op = str(req.get("op"))
                try:
                    resp, payload = self._handle(op, req)
                except Error as e:  # checked: answer, keep the conn
                    resp, payload = {"ok": False, "error": str(e)}, b""
                except Exception as e:  # one bad request, not the daemon
                    logger.exception("lookup request failed")
                    resp, payload = {"ok": False, "error": repr(e)}, b""
                self.requests += 1
                # clamp the label to the known vocabulary — a hostile
                # op string must not mint unbounded metric series any
                # more than unbounded span names
                _REG.counter(
                    "io.lookup.requests",
                    labels={"op": op if op in self._OPS else "unknown"},
                ).inc()
                try:
                    _send_frame(conn, resp, payload)
                except Error as e:
                    # the RESPONSE header outgrew the frame cap (a huge
                    # sizes array): answer with a compact refusal so the
                    # client sees a checked error, not a dead daemon
                    try:
                        _send_frame(conn, {"ok": False, "error": str(e)})
                    except (Error, OSError):
                        return
                except OSError:
                    return
                _REQUEST_SECONDS.observe(_time.perf_counter() - t0)
        finally:
            with self._lock:
                self._conns.discard(conn)
            _CLIENTS.inc(-1)
            try:
                conn.close()
            except OSError:
                pass

    #: known ops — also the trace-span vocabulary (a hostile op string
    #: must not mint unbounded span names on the ring)
    _OPS = frozenset({"ping", "lookup", "warm", "stats"})

    def _handle(self, op: str, req: dict) -> Tuple[dict, bytes]:
        span = f"dmlc:lookup_{op if op in self._OPS else 'unknown'}"
        # handler span carrying the client's trace context: the flow
        # arrow from the caller's lookup_wait lands here
        with _tracing.handler_span(span, req.get("tc")):
            if op == "ping":
                return {"ok": True, "pid": os.getpid()}, b""
            if op == "lookup":
                keys = req.get("keys", [])
                # a scalar here is a client serialization bug: a JSON
                # string would iterate char-by-char into VALID keys and
                # answer wrong records with ok:true
                check(
                    isinstance(keys, (list, tuple)),
                    f"lookup keys must be a JSON array, got "
                    f"{type(keys).__name__}",
                )
                vals = self.handle.lookup(keys)
                sizes = [
                    -1 if v is None else len(v) for v in vals
                ]
                payload = b"".join(v for v in vals if v is not None)
                return {"ok": True, "sizes": sizes}, payload
            if op == "warm":
                keys = req.get("keys")
                check(
                    keys is None or isinstance(keys, (list, tuple)),
                    f"warm keys must be a JSON array, got "
                    f"{type(keys).__name__}",
                )
                n = self.handle.warm(keys, req.get("max_blocks"))
                return {"ok": True, "warmed_blocks": n}, b""
            if op == "stats":
                return {"ok": True, "stats": self.stats()}, b""
            return {"ok": False, "error": f"unknown op {op!r}"}, b""

    def stats(self) -> dict:
        """Request counts/QPS/uptime are per-server; p50/p99 come from
        the PROCESS-global ``io.lookup.request_seconds`` histogram (the
        repo-wide registry convention) — a process hosting several
        servers reads blended percentiles."""
        h = self.handle
        uptime = _time.perf_counter() - self._t0
        hist = _REG.snapshot().get("histograms", {}).get(
            "io.lookup.request_seconds", {}
        )
        return {
            "pid": os.getpid(),
            "host": self.host,
            "port": self.port,
            "uri": h.uri,
            "uptime_secs": round(uptime, 3),
            "requests": self.requests,
            "qps": round(self.requests / max(uptime, 1e-9), 2),
            "p50_ms": round(hist.get("p50", 0.0) * 1e3, 3),
            "p99_ms": round(hist.get("p99", 0.0) * 1e3, 3),
            "lookups": h.lookups,
            "keys_resolved": h.keys_resolved,
            "negatives": h.negatives,
            "bytes": h.bytes_out,
            "block_cache_hits": h.block_cache_hits,
            "block_cache_misses": h.block_cache_misses,
            "shard": h.describe(),
        }


def _wire_keys(keys: Sequence) -> list:
    """JSON-able key list with the handle's coercion strictness: ints
    and strings pass, bytes decode (the sidecar is text); anything else
    would str() into a never-matching key and fake a negative."""
    out = []
    for k in keys:
        if isinstance(k, (int, np.integer)):
            out.append(int(k))
        elif isinstance(k, str):
            out.append(k)
        elif isinstance(k, bytes):
            try:
                out.append(k.decode())
            except UnicodeDecodeError:
                raise Error(f"undecodable bytes lookup key {k!r}") from None
        else:
            raise Error(
                f"lookup keys must be ints or strings, got {k!r}"
            )
    return out


class LookupClient:
    """One connection to a ``LookupServer``; the RTT wait is a
    ``lookup_wait`` stall stage on the flight recorder (a slow serve
    tier shows up in the stall report by name, docs/observability.md).
    Thread-safe behind a lock (one in-flight request per connection)."""

    def __init__(
        self, host: str, port: int, timeout: float = 30.0
    ) -> None:
        self.host = host
        self.port = int(port)
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _connect_locked(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(
                (self.host, self.port), timeout=self._timeout
            )
            self._sock = s
        return self._sock

    def _request(
        self, obj: dict, want_payload: bool = False
    ) -> Tuple[dict, bytes]:
        with self._lock:
            sock = self._connect_locked()
            try:
                # the wait span encloses the SEND too so the request's
                # flow-start lands inside it: every lookup_wait slice
                # gets its causal arrow to the daemon's handler span
                with _tracing.span("dmlc:lookup_wait"):
                    tc = _tracing.rpc_context()
                    if tc:
                        obj = {**obj, "tc": tc}
                    _send_frame(sock, obj)
                    resp = _recv_frame(sock)
                    payload = b""
                    if want_payload and resp.get("ok"):
                        total = sum(
                            s for s in resp.get("sizes", ()) if s > 0
                        )
                        if total:
                            payload = _recv_all(sock, total)
            except (OSError, ConnectionError, ValueError) as e:
                self._close_locked()
                raise Error(
                    f"lookup daemon {self.host}:{self.port} "
                    f"unreachable: {e}"
                ) from e
        if not resp.get("ok"):
            raise Error(
                f"lookup daemon {self.host}:{self.port} refused "
                f"{obj.get('op')!r}: {resp.get('error')}"
            )
        return resp, payload

    def lookup(self, keys: Sequence) -> List[Optional[bytes]]:
        keys = _wire_keys(keys)
        resp, payload = self._request(
            {"op": "lookup", "keys": keys}, want_payload=True
        )
        sizes = resp.get("sizes", [])
        check(
            len(sizes) == len(keys),
            "lookup daemon answered the wrong key count",
        )
        out: List[Optional[bytes]] = []
        at = 0
        for s in sizes:
            if s < 0:
                out.append(None)
            else:
                out.append(payload[at : at + s])
                at += s
        check(
            at == len(payload),
            "lookup daemon payload length disagrees with its sizes",
        )
        return out

    def warm(
        self,
        keys: Optional[Sequence] = None,
        max_blocks: Optional[int] = None,
    ) -> int:
        req: dict = {"op": "warm", "max_blocks": max_blocks}
        if keys is not None:
            req["keys"] = _wire_keys(keys)
        resp, _ = self._request(req)
        return int(resp.get("warmed_blocks", 0))

    def stats(self) -> dict:
        resp, _ = self._request({"op": "stats"})
        return resp["stats"]

    def ping(self) -> bool:
        try:
            self._request({"op": "ping"})
            return True
        except Error:
            return False

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()
