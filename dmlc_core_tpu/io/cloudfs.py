"""Remote filesystems over stdlib HTTP: http(s)://, s3://, gs://, hdfs://,
azure://.

Reference: src/io/s3_filesys.cc (self-contained S3 client: SigV4 signing,
ranged-GET seekable reads, multipart-upload writes, XML listings — behavior
re-implemented fresh against the public AWS spec), src/io/hdfs_filesys.cc
(libhdfs JNI wrapper) and src/io/azure_filesys.cc (partial).

TPU-native choices:
- pure stdlib (urllib/hmac/hashlib/xml.etree) instead of libcurl+OpenSSL —
  no native deps on the hot path (reads stream into the parser's chunk
  buffer; the signing cost is per-connection, not per-byte)
- ``hdfs://`` speaks WebHDFS REST instead of the JVM-bound libhdfs
  (hadoop clusters expose it by default; no JVM in the TPU host image)
- ``gs://`` uses the GCS XML interop API with Application Default
  Credentials — GCE/TPU-VM metadata-server OAuth tokens (the standard
  auth on the target platform) or a GOOGLE_APPLICATION_CREDENTIALS
  service-account JWT exchange — with HMAC interop keys
  (GS_ACCESS_KEY_ID) as an explicit override, all over the same request
  skeleton as S3
- ``azure://`` supports SAS-token/public access (read+list); the reference
  itself ships Azure as a partial backend (azure_filesys.h:22-32)

Endpoints are overridable via env (S3_ENDPOINT etc.), which is also how the
hermetic tests point these clients at in-process fake servers.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import os
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.logging import Error, check
from .filesystem import FS_REGISTRY, FileInfo, FileSystem
from .retry import HttpError, RetryPolicy, is_transient
from .retry import request as _retry_request
from .spanfetch import count_stream_reopen as _count_stream_reopen
from .stream import SeekStream, Stream
from .uri import URI

__all__ = [
    "HttpReadStream",
    "HttpFileSystem",
    "SigV4Signer",
    "S3FileSystem",
    "OAuthTokenProvider",
    "MetadataServerToken",
    "ServiceAccountToken",
    "GCSFileSystem",
    "WebHdfsFileSystem",
    "AzureBlobFileSystem",
]

_CHUNK = 1 << 16


def _request(
    url: str,
    method: str = "GET",
    headers: Optional[Dict[str, str]] = None,
    data: Optional[bytes] = None,
    timeout: float = 60.0,
    policy: Optional[RetryPolicy] = None,
):
    """One HTTP round trip with transient-failure retry (io/retry.py
    owns the policy and the single urlopen call site); returns the open
    response (caller reads/closes). Raises HttpError (status attached)
    on HTTP errors, Error on connection failures."""
    return _retry_request(url, method, headers, data, timeout, policy=policy)


class HttpReadStream(SeekStream):
    """Seekable read stream over HTTP ranged GETs.

    Seek is a cheap restart: drop the connection, re-issue a ranged request
    at the new offset on the next read (reference CURLReadStreamBase::Seek,
    s3_filesys.cc:550-593). ``prepare`` customizes each restart (signing,
    offset query params).

    Transient failures — a 5xx on the (re)connect, a socket reset or
    IncompleteRead mid-body, a silently short body — reconnect with a
    Range header at the exact resume offset, so the fault is invisible
    to callers. One ``RetryPolicy`` spans the stream's lifetime: its
    cumulative backoff budget bounds a stream limping through repeated
    faults, and the per-operation attempt cap bounds consecutive
    no-progress reconnects.
    """

    def __init__(
        self,
        url: str,
        size: Optional[int] = None,
        prepare: Optional[
            Callable[[int, Dict[str, str]], Tuple[str, Dict[str, str]]]
        ] = None,
        policy: Optional[RetryPolicy] = None,
    ) -> None:
        self.url = url
        self._size = size
        self._prepare = prepare
        self._policy = policy or RetryPolicy()
        self._stalls = 0  # consecutive reconnects without progress
        self._pos = 0
        self._resp = None

    def _restart(self) -> None:
        self._drop()
        headers: Dict[str, str] = {}
        url = self.url
        if self._prepare is not None:
            url, headers = self._prepare(self._pos, headers)
        elif self._pos:
            headers["Range"] = f"bytes={self._pos}-"
        if self._size is not None and self._pos >= self._size:
            self._resp = None
            return
        try:
            self._resp = _request(url, "GET", headers, policy=self._policy)
        except HttpError as e:
            if e.status == 416:  # range beyond EOF
                self._resp = None
                return
            raise
        if self._pos and "Range" in headers:
            # a server/proxy that ignores Range would silently serve byte 0
            # as if it were byte _pos — corrupt shards, no error. Demand
            # proof the range was honored.
            status = getattr(self._resp, "status", 206)
            crange = self._resp.headers.get("Content-Range", "")
            start = None
            if crange.startswith("bytes "):
                try:
                    start = int(crange[6:].split("-")[0])
                except ValueError:
                    start = None
            if status != 206 or start != self._pos:
                self._drop()
                raise Error(
                    f"server ignored Range request at offset {self._pos} "
                    f"for {url} (status {status}, Content-Range {crange!r})"
                )
        if self._size is None:
            total = _total_from_response(self._resp)
            if total is not None:
                self._size = total

    def _drop(self) -> None:
        if self._resp is not None:
            try:
                self._resp.close()
            except OSError:
                pass
            self._resp = None

    def _reconnect_pause(self, cause: Optional[BaseException]) -> None:
        """Account one mid-body reconnect: raise past the no-progress
        attempt cap or the policy's cumulative budget, else backoff."""
        self._stalls += 1
        if self._stalls >= self._policy.max_attempts:
            err = Error(
                f"read of {self.url} failed after {self._stalls} "
                f"reconnects without progress at offset {self._pos}"
            )
            if cause is not None:
                raise err from cause
            raise err
        self._policy.pause(cause=cause, what=f"read {self.url} @{self._pos}")

    def read(self, n: int = -1) -> bytes:
        if n == 0:
            return b""
        while True:
            if self._resp is None:
                if self._size is not None and self._pos >= self._size:
                    return b""
                self._restart()
                if self._resp is None:
                    return b""
            try:
                out = self._resp.read(None if n < 0 else n)
            except Exception as e:
                # socket reset / IncompleteRead mid-body: resume the
                # ranged GET at the exact offset instead of failing
                self._drop()
                if not is_transient(e):
                    raise
                self._reconnect_pause(e)
                continue
            if out:
                self._pos += len(out)
                self._stalls = 0
                return out
            self._drop()
            # empty read with bytes still expected = the server dropped the
            # connection mid-transfer; resume the ranged GET instead of
            # reporting a silently-truncated EOF
            if self._size is not None and self._pos < self._size:
                self._reconnect_pause(None)
                continue
            return b""

    def seek(self, pos: int) -> None:
        if pos != self._pos:
            if self._resp is not None:
                # a live connection torn down by repositioning: the next
                # read pays a full reconnect (ranged GET). Counted as
                # io.fetch.reopens so serial-fallback seek storms are
                # visible in io_stats/bench/`tools trace report`.
                _count_stream_reopen()
            self._drop()
            self._pos = pos

    def tell(self) -> int:
        return self._pos

    def write(self, data) -> int:
        raise Error("HttpReadStream is read-only")

    def close(self) -> None:
        self._drop()


def _total_from_response(resp) -> Optional[int]:
    crange = resp.headers.get("Content-Range")
    if crange and "/" in crange:
        try:
            return int(crange.rsplit("/", 1)[1])
        except ValueError:
            return None
    clen = resp.headers.get("Content-Length")
    return int(clen) if clen else None


class HttpFileSystem(FileSystem):
    """Plain http(s) reads (reference HttpReadStream, s3_filesys.cc:750)."""

    def open(self, uri: str, mode: str = "r") -> Stream:
        check(mode in ("r", "rb"), "http(s) filesystem is read-only")
        return HttpReadStream(uri)

    def get_path_info(self, uri: str) -> FileInfo:
        resp = _request(uri, "HEAD")
        size = int(resp.headers.get("Content-Length") or 0)
        # change token for the decoded-block cache identity: the ETag
        # when the origin sends one, else Last-Modified, else none
        etag = (
            resp.headers.get("ETag")
            or resp.headers.get("Last-Modified")
            or ""
        )
        resp.close()
        return FileInfo(uri, size, "file", etag)

    def list_directory(self, uri: str) -> List[FileInfo]:
        raise Error("http(s) filesystem cannot list directories")


# -- AWS Signature Version 4 -------------------------------------------------


class SigV4Signer:
    """AWS SigV4 request signing (public spec; reference implements the
    same scheme in C++, s3_filesys.cc:72-200)."""

    def __init__(
        self,
        access_key: str,
        secret_key: str,
        region: str,
        service: str = "s3",
        session_token: Optional[str] = None,
    ) -> None:
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = service
        self.session_token = session_token

    @staticmethod
    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    def sign(
        self,
        method: str,
        url: str,
        headers: Dict[str, str],
        payload_hash: Optional[str] = None,
        now: Optional[datetime.datetime] = None,
    ) -> Dict[str, str]:
        """Returns headers with Authorization/x-amz-* added."""
        parsed = urllib.parse.urlsplit(url)
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        payload_hash = payload_hash or hashlib.sha256(b"").hexdigest()
        out = dict(headers)
        out["host"] = parsed.netloc
        out["x-amz-date"] = amz_date
        out["x-amz-content-sha256"] = payload_hash
        if self.session_token:
            out["x-amz-security-token"] = self.session_token
        signed_names = sorted(k.lower() for k in out)
        canonical_headers = "".join(
            f"{k}:{out[_orig_key(out, k)].strip()}\n" for k in signed_names
        )
        signed_headers = ";".join(signed_names)
        # canonical URI/query must match the wire form byte-for-byte: the
        # path and query are already percent-encoded by the caller, so use
        # them as sent (re-quoting would double-encode, and decoding the
        # query loses the original escapes -> SignatureDoesNotMatch)
        query = (
            "&".join(
                sorted(
                    p if "=" in p else p + "="
                    for p in parsed.query.split("&")
                )
            )
            if parsed.query
            else ""
        )
        canonical = "\n".join(
            [
                method,
                parsed.path or "/",
                query,
                canonical_headers,
                signed_headers,
                payload_hash,
            ]
        )
        scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
        to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )
        key = self._hmac(
            self._hmac(
                self._hmac(
                    self._hmac(
                        ("AWS4" + self.secret_key).encode(), datestamp
                    ),
                    self.region,
                ),
                self.service,
            ),
            "aws4_request",
        )
        signature = hmac.new(key, to_sign.encode(), hashlib.sha256).hexdigest()
        out["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        )
        return out


def _orig_key(d: Dict[str, str], lower: str) -> str:
    for k in d:
        if k.lower() == lower:
            return k
    raise KeyError(lower)


# -- S3 ----------------------------------------------------------------------


class S3WriteStream(Stream):
    """Buffered multipart-upload writer (reference WriteStream,
    s3_filesys.cc:768-1016). Small objects go up as one PUT; larger ones
    initiate a multipart upload per ``part_bytes``
    (DMLC_S3_WRITE_BUFFER_MB, min 5MB per the S3 API)."""

    def __init__(self, fs: "S3FileSystem", bucket: str, key: str) -> None:
        self.fs = fs
        self.bucket = bucket
        self.key = key
        if "DMLC_S3_WRITE_BUFFER_BYTES" in os.environ:  # test hook
            self.part_bytes = int(os.environ["DMLC_S3_WRITE_BUFFER_BYTES"])
        else:
            mb = int(os.environ.get("DMLC_S3_WRITE_BUFFER_MB", "16"))
            self.part_bytes = max(mb, 5) << 20  # S3 minimum part size 5MB
        self._buf = bytearray()
        self._upload_id: Optional[str] = None
        self._etags: List[str] = []
        self._closed = False

    def write(self, data) -> int:
        self._buf.extend(data)
        while len(self._buf) >= self.part_bytes:
            self._flush_part(bytes(self._buf[: self.part_bytes]))
            del self._buf[: self.part_bytes]
        return len(data)

    def _flush_part(self, payload: bytes) -> None:
        if self._upload_id is None:
            url = self.fs.object_url(self.bucket, self.key) + "?uploads="
            resp = self.fs.request("POST", url, b"")
            root = ET.fromstring(resp)
            self._upload_id = _xml_find(root, "UploadId")
        n = len(self._etags) + 1
        url = (
            self.fs.object_url(self.bucket, self.key)
            + f"?partNumber={n}&uploadId={self._upload_id}"
        )
        headers = self.fs.request("PUT", url, payload, want_headers=True)
        self._etags.append(headers.get("ETag", f'"part{n}"'))

    def flush(self) -> None:
        pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._upload_id is None:
            # single-shot PUT
            url = self.fs.object_url(self.bucket, self.key)
            self.fs.request("PUT", url, bytes(self._buf))
            return
        if self._buf:
            self._flush_part(bytes(self._buf))
            self._buf.clear()
        parts = "".join(
            f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{etag}</ETag></Part>"
            for i, etag in enumerate(self._etags)
        )
        body = (
            "<CompleteMultipartUpload>" + parts + "</CompleteMultipartUpload>"
        ).encode()
        url = (
            self.fs.object_url(self.bucket, self.key)
            + f"?uploadId={self._upload_id}"
        )
        self.fs.request("POST", url, body)


def _xml_find(root, tag: str) -> str:
    for el in root.iter():
        if el.tag.endswith(tag):
            return el.text or ""
    raise Error(f"missing <{tag}> in response")


class S3FileSystem(FileSystem):
    """Self-contained S3 client (reference S3FileSystem,
    src/io/s3_filesys.cc). Credentials/region/endpoint from env:
    AWS_ACCESS_KEY_ID / S3_ACCESS_KEY, AWS_SECRET_ACCESS_KEY /
    S3_SECRET_KEY, AWS_REGION / S3_REGION, S3_ENDPOINT (path-style;
    also the hermetic-test hook), AWS_SESSION_TOKEN
    (reference env handling, s3_filesys.cc:1151-1169)."""

    protocol = "s3://"

    def __init__(self) -> None:
        self.access_key = os.environ.get(
            "S3_ACCESS_KEY", os.environ.get("AWS_ACCESS_KEY_ID", "")
        )
        self.secret_key = os.environ.get(
            "S3_SECRET_KEY", os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        )
        self.region = os.environ.get(
            "S3_REGION", os.environ.get("AWS_REGION", "us-east-1")
        )
        self.session_token = os.environ.get("AWS_SESSION_TOKEN")
        self.endpoint = os.environ.get("S3_ENDPOINT")  # implies path-style
        self.verify_ssl = os.environ.get("S3_VERIFY_SSL", "1") != "0"
        self.signer = (
            SigV4Signer(
                self.access_key,
                self.secret_key,
                self.region,
                "s3",
                self.session_token,
            )
            if self.access_key
            else None
        )

    # -- plumbing ------------------------------------------------------------
    def split_uri(self, uri: str) -> Tuple[str, str]:
        u = URI(uri)
        check(u.protocol == self.protocol, f"not a {self.protocol} uri: {uri}")
        return u.host, u.path.lstrip("/")

    def object_url(self, bucket: str, key: str) -> str:
        key_q = urllib.parse.quote(key, safe="/-_.~")
        if self.endpoint:
            return f"{self.endpoint}/{bucket}/{key_q}"
        return f"https://{bucket}.s3.{self.region}.amazonaws.com/{key_q}"

    def _signed_headers(
        self, method: str, url: str, headers: Dict[str, str], payload: bytes
    ) -> Dict[str, str]:
        if self.signer is None:
            return headers
        payload_hash = hashlib.sha256(payload).hexdigest()
        return self.signer.sign(method, url, headers, payload_hash)

    def request(
        self, method: str, url: str, payload: bytes = b"", want_headers=False
    ):
        headers = self._signed_headers(method, url, {}, payload)
        resp = _request(url, method, headers, payload or None)
        try:
            if want_headers:
                return dict(resp.headers)
            return resp.read()
        finally:
            resp.close()

    # -- FileSystem interface ------------------------------------------------
    def open(self, uri: str, mode: str = "r") -> Stream:
        bucket, key = self.split_uri(uri)
        if mode in ("r", "rb"):
            url = self.object_url(bucket, key)

            def prepare(pos: int, headers: Dict[str, str]):
                h = dict(headers)
                if pos:
                    h["Range"] = f"bytes={pos}-"
                return url, self._signed_headers("GET", url, h, b"")

            info = self.get_path_info(uri)
            return HttpReadStream(url, size=info.size, prepare=prepare)
        if mode in ("w", "wb"):
            return S3WriteStream(self, bucket, key)
        raise Error(f"unsupported mode {mode!r} for s3")

    def get_path_info(self, uri: str) -> FileInfo:
        bucket, key = self.split_uri(uri)
        url = self.object_url(bucket, key)
        headers = self._signed_headers("HEAD", url, {}, b"")
        try:
            resp = _request(url, "HEAD", headers)
        except HttpError as e:
            if e.status == 404:
                # maybe a "directory" (key prefix)
                if self.list_directory(uri):
                    return FileInfo(uri.rstrip("/") + "/", 0, "directory")
            raise
        size = int(resp.headers.get("Content-Length") or 0)
        # the object's ETag (S3 and the GCS XML API both send one on
        # HEAD): an in-place rewrite changes it even at identical size
        etag = resp.headers.get("ETag") or ""
        resp.close()
        return FileInfo(uri, size, "file", etag)

    def delete(self, uri: str, recursive: bool = False) -> None:
        """DELETE object; with ``recursive``, every object under the
        prefix (object stores have no directories — a 'directory' delete
        is a listed prefix sweep). Powers remote checkpoint retention.

        Prefix sweeps use the batch DeleteObjects POST (up to 1000 keys
        per request): pruning one sharded pod checkpoint is one LIST +
        one POST instead of nprocs+1 sequential round trips."""
        if recursive:
            infos = self.list_directory_recursive(uri)
            if infos:
                bucket = self.split_uri(uri)[0]
                keys = [self.split_uri(i.path)[1] for i in infos]
                for i in range(0, len(keys), 1000):
                    self._delete_batch(bucket, keys[i:i + 1000])
                return
        bucket, key = self.split_uri(uri)
        self.request("DELETE", self.object_url(bucket, key))

    def _delete_batch(self, bucket: str, keys: List[str]) -> None:
        """POST /?delete (DeleteObjects). Content-MD5 is mandatory."""
        from xml.sax.saxutils import escape

        body = (
            "<Delete><Quiet>true</Quiet>"
            + "".join(f"<Object><Key>{escape(k)}</Key></Object>" for k in keys)
            + "</Delete>"
        ).encode()
        base = (
            f"{self.endpoint}/{bucket}"
            if self.endpoint
            else f"https://{bucket}.s3.{self.region}.amazonaws.com"
        )
        url = base + "/?delete"
        headers = {
            "Content-MD5": base64.b64encode(
                hashlib.md5(body).digest()
            ).decode(),
        }
        headers = self._signed_headers("POST", url, headers, body)
        resp = _request(url, "POST", headers, body)
        try:
            out = resp.read()
        finally:
            resp.close()
        # Quiet mode returns only failures; any <Error> means keys remain
        if b"<Error>" in out:
            raise Error(
                f"DeleteObjects reported failures: {out[:500].decode(errors='replace')}"
            )

    # header name differs per store (GCS XML interop: x-goog-copy-source)
    _COPY_SOURCE_HEADER = "x-amz-copy-source"

    def copy(self, src_uri: str, dst_uri: str) -> None:
        """Server-side object copy (PUT + copy-source header): no bytes
        transit this process — the checkpoint tmp-key → final-key rename
        costs one metadata round trip, not a re-upload."""
        sbucket, skey = self.split_uri(src_uri)
        dbucket, dkey = self.split_uri(dst_uri)
        url = self.object_url(dbucket, dkey)
        headers = {
            self._COPY_SOURCE_HEADER: (
                f"/{sbucket}/{urllib.parse.quote(skey, safe='/-_.~')}"
            )
        }
        headers = self._signed_headers("PUT", url, headers, b"")
        resp = _request(url, "PUT", headers)
        try:
            body = resp.read()
        finally:
            resp.close()
        # S3 copy reports some failures inside a 200 body (API quirk)
        if b"<Error>" in body:
            raise Error(
                f"copy {src_uri} -> {dst_uri} failed: "
                f"{body[:300].decode(errors='replace')}"
            )

    def list_directory(self, uri: str) -> List[FileInfo]:
        """ListObjectsV2 with '/' delimiter (reference ListObjects,
        s3_filesys.cc:1018)."""
        bucket, key = self.split_uri(uri)
        prefix = key.rstrip("/")
        if prefix:
            prefix += "/"
        base = (
            f"{self.endpoint}/{bucket}"
            if self.endpoint
            else f"https://{bucket}.s3.{self.region}.amazonaws.com"
        )
        out: List[FileInfo] = []
        token = None
        while True:
            q = {
                "list-type": "2",
                "prefix": prefix,
                "delimiter": "/",
            }
            if token:
                q["continuation-token"] = token
            # quote_via=quote: S3 canonicalizes spaces as %20, and '+' in
            # the wire query would be decoded as a space server-side
            url = base + "/?" + urllib.parse.urlencode(
                sorted(q.items()), quote_via=urllib.parse.quote
            )
            body = self.request("GET", url)
            root = ET.fromstring(body)
            for el in root.iter():
                tag = el.tag.rsplit("}", 1)[-1]
                if tag == "Contents":
                    k = s = None
                    etag = ""
                    for child in el:
                        ctag = child.tag.rsplit("}", 1)[-1]
                        if ctag == "Key":
                            k = child.text
                        elif ctag == "Size":
                            s = int(child.text or 0)
                        elif ctag == "ETag":
                            etag = child.text or ""
                    if k and k != prefix:
                        out.append(
                            FileInfo(
                                f"{self.protocol}{bucket}/{k}",
                                s or 0,
                                "file",
                                etag,
                            )
                        )
                elif tag == "CommonPrefixes":
                    for child in el:
                        if child.tag.endswith("Prefix") and child.text:
                            out.append(
                                FileInfo(
                                    f"{self.protocol}{bucket}/{child.text}",
                                    0,
                                    "directory",
                                )
                            )
            nxt = [
                el.text
                for el in root.iter()
                if el.tag.endswith("NextContinuationToken")
            ]
            truncated = [
                el.text
                for el in root.iter()
                if el.tag.endswith("IsTruncated")
            ]
            if truncated and truncated[0] == "true" and nxt and nxt[0]:
                token = nxt[0]
            else:
                return out


# -- GCS OAuth (Application Default Credentials) -----------------------------


class OAuthTokenProvider:
    """Cached OAuth2 access token, refreshed ahead of expiry.

    Thread-safe: fused producers fan out over threads and all share the
    singleton filesystem instance."""

    _MARGIN = 120.0  # refresh this many seconds before expiry

    def __init__(self) -> None:
        self._token: Optional[str] = None
        self._refresh_at = 0.0  # soft deadline: refresh past this
        self._expiry = 0.0      # hard deadline: token invalid past this
        self._lock = threading.Lock()

    def token(self) -> str:
        with self._lock:
            now = time.time()  # noqa: L008 (token refresh/expiry deadlines are wall-clock)
            if self._token is not None and now < self._refresh_at:
                return self._token
            # the fetch runs under the lock, stalling every signing
            # thread: with a still-valid cached token to fall back on,
            # take ONE attempt (the early refresh retries on the next
            # request anyway); only a token-less fetch earns the full
            # retry schedule
            have_fallback = self._token is not None and now < self._expiry
            try:
                tok, ttl = self._fetch(
                    RetryPolicy(max_attempts=1) if have_fallback else None
                )
            except (OSError, Error, KeyError, ValueError):
                # transient fetch failure: a still-valid token (we refresh
                # _MARGIN early) must keep the job alive rather than
                # downgrading a mid-run refresh hiccup into hard failure
                if have_fallback:
                    return self._token
                raise
            ttl = max(float(ttl), 0.0)
            self._token = tok
            now = time.time()  # noqa: L008 (token refresh/expiry deadlines are wall-clock)
            # short-lived answers (metadata servers count expires_in
            # down) are still reused for half their life instead of
            # refetching per request once ttl < margin
            soft = ttl - self._MARGIN if ttl > 2 * self._MARGIN else ttl / 2
            self._refresh_at = now + soft
            self._expiry = now + ttl
            return self._token

    def _fetch(
        self, policy: Optional[RetryPolicy] = None
    ) -> Tuple[str, float]:
        raise NotImplementedError


class MetadataServerToken(OAuthTokenProvider):
    """GCE/TPU-VM instance token from the metadata server — the default
    credential on the platform this framework targets (HMAC interop keys,
    the r3 approach, are a legacy opt-in most orgs disable). Host
    overridable via GCE_METADATA_HOST (also the hermetic-test hook)."""

    def __init__(self) -> None:
        super().__init__()
        host = os.environ.get("GCE_METADATA_HOST", "metadata.google.internal")
        self.url = (
            f"http://{host}/computeMetadata/v1/instance/"
            "service-accounts/default/token"
        )

    def _fetch(
        self, policy: Optional[RetryPolicy] = None
    ) -> Tuple[str, float]:
        resp = _request(
            self.url, headers={"Metadata-Flavor": "Google"}, timeout=2.0,
            policy=policy,
        )
        try:
            body = json.loads(resp.read())
        finally:
            resp.close()
        return body["access_token"], float(body.get("expires_in", 300))


class ServiceAccountToken(OAuthTokenProvider):
    """GOOGLE_APPLICATION_CREDENTIALS service-account key → RS256 JWT →
    access token (the OAuth2 jwt-bearer grant). Token endpoint
    overridable for tests via GCS_TOKEN_URI."""

    SCOPE = "https://www.googleapis.com/auth/devstorage.read_write"

    def __init__(self, path: str) -> None:
        super().__init__()
        try:
            with open(path) as f:
                info = json.load(f)
        except (OSError, ValueError) as e:
            raise Error(
                f"bad GOOGLE_APPLICATION_CREDENTIALS file {path!r}: {e}"
            ) from e
        check(
            info.get("type") == "service_account",
            f"{path}: not a service_account key (type={info.get('type')!r})",
        )
        check(
            "client_email" in info and "private_key" in info,
            f"{path}: service_account key missing client_email/private_key",
        )
        self.email = info["client_email"]
        self.private_key_pem = info["private_key"].encode()
        self.token_uri = os.environ.get(
            "GCS_TOKEN_URI", info.get("token_uri",
                                      "https://oauth2.googleapis.com/token")
        )

    @staticmethod
    def _b64(data: bytes) -> bytes:
        return base64.urlsafe_b64encode(data).rstrip(b"=")

    def _jwt(self, now: float) -> bytes:
        try:
            from cryptography.hazmat.primitives import hashes, serialization
            from cryptography.hazmat.primitives.asymmetric import padding
        except ImportError as e:  # pragma: no cover - baked into the image
            raise Error(
                "service-account gs:// auth needs the 'cryptography' "
                "package for RS256 signing; use HMAC interop keys "
                "(GS_ACCESS_KEY_ID) or metadata-server credentials instead"
            ) from e
        header = self._b64(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
        claims = self._b64(json.dumps({
            "iss": self.email,
            "scope": self.SCOPE,
            "aud": self.token_uri,
            "iat": int(now),
            "exp": int(now) + 3600,
        }).encode())
        signing_input = header + b"." + claims
        key = serialization.load_pem_private_key(
            self.private_key_pem, password=None
        )
        sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
        return signing_input + b"." + self._b64(sig)

    def _fetch(
        self, policy: Optional[RetryPolicy] = None
    ) -> Tuple[str, float]:
        payload = urllib.parse.urlencode({
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": self._jwt(time.time()).decode(),  # noqa: L008 (JWT iat/exp claims are wall-clock by spec)
        }).encode()
        resp = _request(
            self.token_uri, "POST", {
                "Content-Type": "application/x-www-form-urlencoded",
            }, payload, policy=policy,
        )
        try:
            body = json.loads(resp.read())
        finally:
            resp.close()
        return body["access_token"], float(body.get("expires_in", 3600))


class GCSFileSystem(S3FileSystem):
    """gs:// via the GCS XML API with Application Default Credentials.

    Credential resolution (the ADC order, on the stdlib HTTP client):

    1. HMAC interop keys (GS_ACCESS_KEY_ID / GS_SECRET_ACCESS_KEY) →
       SigV4, the S3-compatible legacy path — explicit override;
    2. GOOGLE_APPLICATION_CREDENTIALS service-account JSON → RS256 JWT
       exchanged for an OAuth token;
    3. GCE/TPU-VM metadata server → instance OAuth token (the default
       on the target platform); probed lazily, failure cached, so
       non-GCE hosts fall through to
    4. anonymous (public buckets).

    Endpoint override GCS_ENDPOINT (also the hermetic-test hook).
    NO_GCE_CHECK=1 skips the metadata probe (google-auth convention).
    """

    protocol = "gs://"

    _PROBE_RETRY = 60.0  # seconds between metadata probes after a failure

    def __init__(self) -> None:
        super().__init__()
        # GS_* ONLY — inheriting the AWS/S3 env creds here would SigV4-
        # sign gs:// requests with AWS keys on any host that also talks
        # to s3://, shadowing working ADC credentials with guaranteed
        # 403s
        self.access_key = os.environ.get("GS_ACCESS_KEY_ID", "")
        self.secret_key = os.environ.get("GS_SECRET_ACCESS_KEY", "")
        # GCS_ENDPOINT only — falling back to S3_ENDPOINT would silently
        # route gs:// traffic to an S3-targeting override
        self.endpoint = os.environ.get(
            "GCS_ENDPOINT", "https://storage.googleapis.com"
        )
        self.signer = (
            SigV4Signer(
                self.access_key, self.secret_key, self.region, "s3",
                self.session_token,
            )
            if self.access_key
            else None
        )
        self._oauth: Optional[OAuthTokenProvider] = None
        self._probe_fail_until = 0.0
        if self.signer is None:
            sa_path = os.environ.get("GOOGLE_APPLICATION_CREDENTIALS")
            if sa_path:
                self._oauth = ServiceAccountToken(sa_path)
            elif os.environ.get("NO_GCE_CHECK", "0") != "1":
                self._oauth = MetadataServerToken()

    @property
    def _oauth_failed(self) -> bool:
        """True while inside the post-failure probe backoff window."""
        return time.time() < self._probe_fail_until  # noqa: L008 (probe backoff window is wall-clock)

    _COPY_SOURCE_HEADER = "x-goog-copy-source"  # GCS XML interop spelling

    def _delete_batch(self, bucket: str, keys: List[str]) -> None:
        """GCS's XML interop API has no DeleteObjects POST — per-object
        DELETEs (the JSON batch API is a different protocol stack)."""
        for k in keys:
            self.request("DELETE", self.object_url(bucket, k))

    def _signed_headers(
        self, method: str, url: str, headers: Dict[str, str], payload: bytes
    ) -> Dict[str, str]:
        if self.signer is not None:
            return super()._signed_headers(method, url, headers, payload)
        if self._oauth is not None and not self._oauth_failed:
            try:
                token = self._oauth.token()
            except (OSError, Error, KeyError, ValueError):
                if isinstance(self._oauth, MetadataServerToken):
                    # no reachable metadata server: back off to anonymous
                    # for a window, then re-probe — NOT latched forever,
                    # or one transient timeout on a real TPU VM would
                    # silently downgrade a private-bucket job to 401s
                    self._probe_fail_until = time.time() + self._PROBE_RETRY  # noqa: L008 (probe backoff window is wall-clock)
                    return headers
                raise  # explicit service-account config must fail loudly
            out = dict(headers)
            out["Authorization"] = f"Bearer {token}"
            return out
        return headers


# -- WebHDFS -----------------------------------------------------------------


class WebHdfsWriteStream(Stream):
    """Buffered WebHDFS writer.

    WebHDFS writes are a two-step dance: the namenode answers the
    ``CREATE``/``APPEND`` operation with a 307 redirect naming the
    datanode, and the payload goes to that Location (urllib refuses to
    auto-follow redirects for PUT/POST, which is exactly right here —
    the first request must carry no body). The first flushed part runs
    ``CREATE`` (PUT), later parts ``APPEND`` (POST), so large files
    stream in bounded memory. Part size via DMLC_WEBHDFS_WRITE_BUFFER_MB
    (default 16; DMLC_WEBHDFS_WRITE_BUFFER_BYTES is the test hook).
    """

    def __init__(
        self, fs: "WebHdfsFileSystem", uri: str, append: bool = False
    ) -> None:
        self.fs = fs
        self.uri = uri
        if "DMLC_WEBHDFS_WRITE_BUFFER_BYTES" in os.environ:  # test hook
            self.part_bytes = int(os.environ["DMLC_WEBHDFS_WRITE_BUFFER_BYTES"])
        else:
            mb = int(os.environ.get("DMLC_WEBHDFS_WRITE_BUFFER_MB", "16"))
            self.part_bytes = max(1, mb) << 20
        self._buf = bytearray()
        # append mode continues an existing file; a missing one is created
        self._created = append and fs.exists(uri)
        self._closed = False

    def read(self, n: int = -1) -> bytes:
        raise Error("WebHdfsWriteStream is write-only")

    def write(self, data) -> int:
        self._buf.extend(data)
        while len(self._buf) >= self.part_bytes:
            self._flush_part(bytes(self._buf[: self.part_bytes]))
            del self._buf[: self.part_bytes]
        return len(data)

    def _flush_part(self, payload: bytes) -> None:
        if not self._created:
            # CREATE with overwrite=true is idempotent: a retried upload
            # rewrites the same first part
            url = self.fs._url(self.uri, "CREATE", overwrite="true")
            self.fs._two_step(url, "PUT", payload)
            self._created = True
            return
        # APPEND is NOT idempotent (a lost response after the commit
        # would duplicate the part on retry) — fail loudly instead
        url = self.fs._url(self.uri, "APPEND")
        self.fs._two_step(url, "POST", payload, idempotent=False)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # an empty buffer still CREATEs in 'w' mode (empty file lands)
        if self._buf or not self._created:
            self._flush_part(bytes(self._buf))
            self._buf.clear()


class WebHdfsFileSystem(FileSystem):
    """hdfs:// via the WebHDFS REST API (op=OPEN/GETFILESTATUS/LISTSTATUS/
    CREATE/APPEND/RENAME/DELETE).

    The reference wraps libhdfs over JNI (src/io/hdfs_filesys.cc); REST
    needs no JVM on the TPU host. Namenode HTTP port from
    DMLC_WEBHDFS_PORT (default 9870); user from DMLC_HDFS_USER/$USER.
    """

    protocol = "hdfs://"

    def __init__(self) -> None:
        self.http_port = int(os.environ.get("DMLC_WEBHDFS_PORT", "9870"))
        self.user = os.environ.get(
            "DMLC_HDFS_USER", os.environ.get("USER", "root")
        )
        self.scheme = os.environ.get("DMLC_WEBHDFS_SCHEME", "http")

    def _base(self, uri: str) -> Tuple[str, str]:
        u = URI(uri)
        host = u.host
        port = self.http_port
        if ":" in host:
            host, hdfs_port = host.rsplit(":", 1)
            # hdfs:// rpc port in the URI; WebHDFS port still applies
            _ = hdfs_port
        path = u.path if u.path.startswith("/") else "/" + u.path
        return f"{self.scheme}://{host}:{port}/webhdfs/v1", path

    def _url(self, uri: str, op: str, **params) -> str:
        base, path = self._base(uri)
        q = {"op": op, "user.name": self.user, **params}
        return base + urllib.parse.quote(path) + "?" + urllib.parse.urlencode(q)

    def _two_step(
        self,
        op_url: str,
        method: str,
        payload: bytes,
        idempotent: bool = True,
    ) -> None:
        """Namenode op → 307 Location → datanode payload upload. Also
        accepts ``noredirect``-style servers that answer 200 with a JSON
        ``Location`` instead of redirecting.

        ``idempotent=False`` disables retry on the DATANODE leg (the
        namenode leg carries no body and always retries): APPEND is not
        idempotent — a response lost after the server committed the
        bytes would duplicate the part on re-POST, silently corrupting
        the file. Better the loud failure."""
        location: Optional[str] = None
        try:
            resp = _request(op_url, method)
        except HttpError as e:
            if e.status not in (301, 302, 307):
                raise
            location = e.header("Location")
            check(
                bool(location),
                f"webhdfs {method} redirect for {op_url} carries no Location",
            )
        else:
            try:
                body = resp.read()
            finally:
                resp.close()
            if body:
                try:
                    location = json.loads(body).get("Location")
                except ValueError:
                    location = None
            check(
                bool(location),
                f"webhdfs {method} {op_url}: expected a datanode redirect "
                "or a JSON Location",
            )
        resp = _request(
            location,  # type: ignore[arg-type]
            method,
            {"Content-Type": "application/octet-stream"},
            payload,
            policy=None if idempotent else RetryPolicy(max_attempts=1),
        )
        resp.close()

    def open(self, uri: str, mode: str = "r") -> Stream:
        if mode in ("w", "wb", "a"):
            return WebHdfsWriteStream(self, uri, append=(mode == "a"))
        check(mode in ("r", "rb"), f"invalid webhdfs mode {mode!r}")
        info = self.get_path_info(uri)

        def prepare(pos: int, headers: Dict[str, str]):
            params = {"offset": pos} if pos else {}
            return self._url(uri, "OPEN", **params), headers

        return HttpReadStream(
            self._url(uri, "OPEN"), size=info.size, prepare=prepare
        )

    def rename(self, src_uri: str, dst_uri: str) -> None:
        """op=RENAME — atomic within HDFS (the namenode metadata swap),
        which makes hdfs:// checkpoints genuinely atomic-rename like
        local files. HDFS refuses to rename over an existing file, so a
        present destination is deleted first (re-save into the same
        step)."""
        _, dst_path = self._base(dst_uri)
        for attempt in range(2):
            url = self._url(src_uri, "RENAME", destination=dst_path)
            resp = _request(url, "PUT")
            try:
                ok = json.loads(resp.read() or b"{}").get("boolean", False)
            finally:
                resp.close()
            if ok:
                return
            if attempt == 0 and self.exists(dst_uri):
                self.delete(dst_uri)
                continue
            raise Error(f"webhdfs rename {src_uri} -> {dst_uri} refused")

    def get_path_info(self, uri: str) -> FileInfo:
        body = _read_all(self._url(uri, "GETFILESTATUS"))
        st = json.loads(body)["FileStatus"]
        ftype = "directory" if st["type"] == "DIRECTORY" else "file"
        # HDFS has no ETag; modificationTime (epoch millis) is the
        # namenode's change token and serves the same cache-identity job
        mtime = st.get("modificationTime")
        return FileInfo(
            uri, int(st.get("length", 0)), ftype,
            str(mtime) if mtime else "",
        )

    def delete(self, uri: str, recursive: bool = False) -> None:
        url = self._url(
            uri, "DELETE", recursive="true" if recursive else "false"
        )
        resp = _request(url, "DELETE")
        try:
            ok = json.loads(resp.read() or b"{}").get("boolean", False)
        finally:
            resp.close()
        check(ok, f"webhdfs delete refused for {uri}")

    def list_directory(self, uri: str) -> List[FileInfo]:
        body = _read_all(self._url(uri, "LISTSTATUS"))
        statuses = json.loads(body)["FileStatuses"]["FileStatus"]
        out = []
        base = uri.rstrip("/")
        for st in statuses:
            ftype = "directory" if st["type"] == "DIRECTORY" else "file"
            mtime = st.get("modificationTime")
            out.append(
                FileInfo(
                    f"{base}/{st['pathSuffix']}",
                    int(st.get("length", 0)),
                    ftype,
                    str(mtime) if mtime else "",
                )
            )
        return out


def _read_all(url: str) -> bytes:
    resp = _request(url)
    try:
        return resp.read()
    finally:
        resp.close()


# -- Azure Blob --------------------------------------------------------------


class AzureBlobFileSystem(FileSystem):
    """azure://container/blob for SAS-token or public containers.

    Account from AZURE_STORAGE_ACCOUNT, optional SAS from
    AZURE_STORAGE_SAS_TOKEN, endpoint override AZURE_ENDPOINT. Read +
    list; the reference's Azure backend is itself partial (list-only,
    open stubbed — azure_filesys.h:22-32), so this is a superset.
    """

    protocol = "azure://"

    def __init__(self) -> None:
        self.account = os.environ.get("AZURE_STORAGE_ACCOUNT", "")
        self.sas = os.environ.get("AZURE_STORAGE_SAS_TOKEN", "").lstrip("?")
        self.endpoint = os.environ.get(
            "AZURE_ENDPOINT",
            f"https://{self.account}.blob.core.windows.net",
        )

    def _url(self, uri: str, **params) -> str:
        u = URI(uri)
        path = f"{u.host}{u.path}"
        url = f"{self.endpoint}/{urllib.parse.quote(path)}"
        q = urllib.parse.urlencode(params)
        extras = "&".join(x for x in (q, self.sas) if x)
        return url + ("?" + extras if extras else "")

    def open(self, uri: str, mode: str = "r") -> Stream:
        check(mode in ("r", "rb"), "azure backend is read-only")
        info = self.get_path_info(uri)

        def prepare(pos: int, headers: Dict[str, str]):
            h = dict(headers)
            if pos:
                h["Range"] = f"bytes={pos}-"
            return self._url(uri), h

        return HttpReadStream(self._url(uri), size=info.size, prepare=prepare)

    def get_path_info(self, uri: str) -> FileInfo:
        resp = _request(self._url(uri), "HEAD")
        size = int(resp.headers.get("Content-Length") or 0)
        etag = resp.headers.get("ETag") or ""
        resp.close()
        return FileInfo(uri, size, "file", etag)

    def delete(self, uri: str, recursive: bool = False) -> None:
        if recursive:
            infos = self.list_directory(uri)
            if infos:
                for info in infos:
                    _request(self._url(info.path), "DELETE").close()
                return
        _request(self._url(uri), "DELETE").close()

    def list_directory(self, uri: str) -> List[FileInfo]:
        u = URI(uri)
        container = u.host
        prefix = u.path.lstrip("/")
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        out: List[FileInfo] = []
        marker = ""
        while True:  # List Blobs pages at 5000 entries via NextMarker
            url = (
                f"{self.endpoint}/{container}?restype=container&comp=list"
                + (f"&prefix={urllib.parse.quote(prefix)}" if prefix else "")
                + (f"&marker={urllib.parse.quote(marker)}" if marker else "")
                + (f"&{self.sas}" if self.sas else "")
            )
            root = ET.fromstring(_read_all(url))
            for blob in root.iter("Blob"):
                name = blob.findtext("Name") or ""
                size = int(blob.findtext("Properties/Content-Length") or 0)
                etag = blob.findtext("Properties/Etag") or ""
                out.append(
                    FileInfo(
                        f"{self.protocol}{container}/{name}", size, "file",
                        etag,
                    )
                )
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return out


# -- registration ------------------------------------------------------------

_SINGLETONS: Dict[str, FileSystem] = {}


def _singleton(cls):
    def make() -> FileSystem:
        inst = _SINGLETONS.get(cls.__name__)
        if inst is None:
            inst = cls()
            _SINGLETONS[cls.__name__] = inst
        return inst

    return make


def reset_singletons() -> None:
    """Drop cached instances (tests change env between cases)."""
    _SINGLETONS.clear()


for _proto, _cls in [
    ("http://", HttpFileSystem),
    ("https://", HttpFileSystem),
    ("s3://", S3FileSystem),
    ("gs://", GCSFileSystem),
    ("hdfs://", WebHdfsFileSystem),
    ("viewfs://", WebHdfsFileSystem),
    ("azure://", AzureBlobFileSystem),
]:
    if FS_REGISTRY.find(_proto) is None:
        FS_REGISTRY.add(_proto, _singleton(_cls))
