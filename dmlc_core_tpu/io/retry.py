"""Unified transient-failure retry layer for remote I/O.

One transient S3 500, a socket reset mid-body, or a flaky metadata
server must cost a backoff sleep, not the epoch. This module owns the
policy every remote touchpoint shares:

- ``RetryPolicy``: exponential backoff with decorrelated jitter
  (sleep = min(cap, uniform(base, 3*prev)) — the schedule that avoids
  retry convoys), a per-operation attempt cap, and a per-stream
  CUMULATIVE backoff budget: a stream that keeps hitting faults burns
  one budget across all its operations instead of multiplying
  per-operation caps.
- ``is_transient``: the classifier — HTTP 408/429/5xx, ``URLError``
  with socket causes, ``IncompleteRead``/short bodies, connection
  resets/aborts, timeouts. Everything else re-raises immediately.
- ``request``: the ONE ``urllib.request.urlopen`` call site in the
  repo (lint rule L006 keeps it that way); every remote HTTP round
  trip — S3/GCS/WebHDFS/Azure object ops, GCS token fetches, the YARN
  RM REST client — goes through it and inherits the policy.
- ``RetryingReadStream``: generic reopen-and-seek read wrapper for
  SeekStream backends (the ``fault://`` filesystem wraps its injected
  streams in one, so chaos tests exercise exactly this code path).
- process-global ``retries`` / ``backoff_secs`` / ``faults_injected``
  counters — telemetry-registry series (``io.retry.retries``,
  ``io.retry.backoff_seconds``, ``io.faults.injected``; see
  docs/observability.md) surfaced through the ``io_stats()`` plumbing
  (split → fused staging → pipeline → bench) as a bit-compatible view.
  Counters are process-global; per-split ``io_stats`` reports the delta
  since the split was constructed, so concurrent splits in one process
  see overlapping attributions.

Env knobs (read at policy construction): DMLC_RETRY_ATTEMPTS (4),
DMLC_RETRY_BASE_SECS (0.1), DMLC_RETRY_CAP_SECS (5.0),
DMLC_RETRY_BUDGET_SECS (60.0).
"""

from __future__ import annotations

import http.client
import os
import random
import socket  # noqa: L010 (exception classification only, no sockets made)
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional

from ..telemetry import default_registry as _default_registry
from ..telemetry import tracing as _tracing
from ..utils.logging import Error
from .stream import SeekStream

_registry = _default_registry()

__all__ = [
    "HttpError",
    "RetryPolicy",
    "RetryingReadStream",
    "is_transient",
    "request",
    "stats",
    "stats_delta",
    "reset_stats",
    "count_fault_injected",
]

# HTTP statuses worth retrying besides the 5xx band
_TRANSIENT_HTTP = frozenset({408, 429})


class HttpError(Error):
    """HTTP-level failure carrying the status and response headers, so
    callers branch on ``.status`` instead of string-parsing the message
    (the message keeps the legacy ``... -> HTTP <code>: <body>`` form
    for existing matchers). Header lookup via ``header()`` is
    case-insensitive (RFC 9110 — a proxy may emit ``location:``)."""

    def __init__(self, message: str, status: int, headers=None) -> None:
        super().__init__(message)
        self.status = status
        self.headers: Dict[str, str] = dict(headers or {})

    def header(self, name: str, default: Optional[str] = None) -> Optional[str]:
        want = name.lower()
        for k, v in self.headers.items():
            if k.lower() == want:
                return v
        return default


def is_transient(exc: BaseException) -> bool:
    """Would a retry plausibly succeed? HTTP 408/429/5xx, socket-caused
    URLErrors, short/incomplete bodies, resets and timeouts — yes;
    everything else (4xx, auth failures, parse errors) — no."""
    if isinstance(exc, HttpError):
        return exc.status in _TRANSIENT_HTTP or 500 <= exc.status <= 599
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in _TRANSIENT_HTTP or 500 <= exc.code <= 599
    if isinstance(exc, urllib.error.URLError):
        # reason is an exception for socket-level failures (reset,
        # refused, timeout, DNS) and a string for protocol-level ones
        return isinstance(exc.reason, (OSError, TimeoutError))
    return isinstance(
        exc,
        (
            http.client.IncompleteRead,
            http.client.BadStatusLine,  # includes RemoteDisconnected
            ConnectionError,  # reset / aborted / refused / broken pipe
            TimeoutError,
            socket.timeout,
        ),
    )


# -- process-global counters (io_stats plumbing) ------------------------------
# Backed by the telemetry registry since ISSUE 4: the same series a
# Prometheus scrape or tracker heartbeat reports. stats()/stats_delta()
# remain the bit-compatible io_stats() view over those counters — a
# registry ScopedView over the three series; the registry counters stay
# monotonic (exporters need that), so reset_stats() rebases the view
# instead of zeroing them.

_RETRIES = _registry.counter(
    "io.retry.retries", help="transient-failure retries healed"
)
_BACKOFF = _registry.counter(
    "io.retry.backoff_seconds", help="total retry backoff slept (secs)"
)
_FAULTS = _registry.counter(
    "io.faults.injected", help="faults fired by the fault:// layer"
)

_SERIES = ("io.retry.retries", "io.retry.backoff_seconds", "io.faults.injected")
_VIEW_LOCK = threading.Lock()  # guards the shared view's baseline swap
_VIEW = _registry.scoped(names=_SERIES)


def _count_retry(backoff: float) -> None:
    _RETRIES.inc()
    _BACKOFF.inc(backoff)


def count_fault_injected(n: int = 1) -> None:
    """Called by the fault-injection layer (io/faults.py) per fired
    fault, so injected chaos is observable next to the healed retries."""
    _FAULTS.inc(n)


def stats() -> Dict[str, float]:
    """Snapshot of the process-global counters (registry values minus
    the reset_stats() baseline — a ScopedView delta, remapped to the
    golden io_stats() keys).

    The three counters are read without a joint lock (each is
    independently thread-sharded), so a retry completing mid-read can
    skew one field against another by one increment — reporting-only
    jitter; read after quiescing for exact triples (as the chaos tests
    do). The old single-lock dict guaranteed a consistent triple; the
    trade buys lock-free hot-path increments."""
    with _VIEW_LOCK:
        d = _VIEW.delta()
    return {
        "retries": int(d.get("io.retry.retries", 0)),
        "backoff_secs": round(float(d.get("io.retry.backoff_seconds", 0.0)), 6),
        "faults_injected": int(d.get("io.faults.injected", 0)),
    }


def stats_delta(snapshot: Dict[str, float]) -> Dict[str, float]:
    """Counters accumulated since ``snapshot`` (an earlier stats())."""
    now = stats()
    return {
        "retries": int(now["retries"] - snapshot.get("retries", 0)),
        "backoff_secs": round(
            float(now["backoff_secs"] - snapshot.get("backoff_secs", 0.0)), 6
        ),
        "faults_injected": int(
            now["faults_injected"] - snapshot.get("faults_injected", 0)
        ),
    }


def reset_stats() -> None:
    """Zero the stats() view (test isolation). The underlying registry
    counters stay monotonic — only the view's baseline moves."""
    with _VIEW_LOCK:
        _VIEW.rebase()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class RetryPolicy:
    """Backoff schedule + budgets for one logical stream/operation.

    - ``max_attempts``: per-OPERATION cap — one request/read is tried at
      most this many times before the last error re-raises.
    - ``budget_secs``: per-STREAM cumulative cap — total backoff sleep
      across every operation sharing this policy instance; once spent,
      the next would-be retry re-raises instead of sleeping. A stream
      limping through faults terminates in bounded time.
    - backoff: exponential with decorrelated jitter,
      ``min(cap, uniform(base, 3*prev))``, seeded from ``rng`` when
      given (deterministic tests).

    Instances track their own ``retries``/``backoff_secs`` and mirror
    every retry into the process-global counters (io_stats plumbing).
    """

    def __init__(
        self,
        max_attempts: Optional[int] = None,
        base_secs: Optional[float] = None,
        cap_secs: Optional[float] = None,
        budget_secs: Optional[float] = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.max_attempts = max(
            1,
            int(max_attempts)
            if max_attempts is not None
            else int(_env_float("DMLC_RETRY_ATTEMPTS", 4)),
        )
        self.base_secs = (
            base_secs
            if base_secs is not None
            else _env_float("DMLC_RETRY_BASE_SECS", 0.1)
        )
        self.cap_secs = (
            cap_secs
            if cap_secs is not None
            else _env_float("DMLC_RETRY_CAP_SECS", 5.0)
        )
        self.budget_secs = (
            budget_secs
            if budget_secs is not None
            else _env_float("DMLC_RETRY_BUDGET_SECS", 60.0)
        )
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._prev = self.base_secs
        self.retries = 0
        self.backoff_secs = 0.0

    def next_backoff(self) -> float:
        """Next decorrelated-jitter delay (does not sleep or count)."""
        hi = max(self.base_secs, self._prev * 3.0)
        delay = min(self.cap_secs, self._rng.uniform(self.base_secs, hi))
        self._prev = delay
        return delay

    def pause(self, cause: Optional[BaseException] = None, what: str = "") -> None:
        """One retry pause: backoff-sleep within the cumulative budget,
        or — budget exhausted — re-raise ``cause`` (the last error)."""
        delay = self.next_backoff()
        if self.backoff_secs + delay > self.budget_secs:
            err = Error(
                f"retry budget exhausted ({self.backoff_secs:.2f}s of "
                f"{self.budget_secs:.2f}s spent){': ' + what if what else ''}"
            )
            if cause is not None:
                raise cause from err
            raise err
        self.retries += 1
        self.backoff_secs += delay
        _count_retry(delay)
        # the backoff sleep is a STALL on the trace timeline: a window
        # load gated on remote IO healing shows up here, attributable
        # next to the host_pull gap it causes downstream
        with _tracing.span(
            "dmlc:retry_backoff",
            what=what or None,
            delay_ms=round(delay * 1000.0, 3),
        ):
            self._sleep(delay)

    def run(self, fn: Callable[[], "object"], what: str = ""):
        """Call ``fn`` with transient-failure retry: non-transient errors
        and exhaustion (attempts or budget) re-raise the LAST error."""
        attempt = 1
        while True:
            try:
                return fn()
            except Exception as exc:
                if not is_transient(exc) or attempt >= self.max_attempts:
                    raise
                self.pause(cause=exc, what=what)
                attempt += 1


def request(
    url: str,
    method: str = "GET",
    headers: Optional[Dict[str, str]] = None,
    data: Optional[bytes] = None,
    timeout: float = 60.0,
    policy: Optional[RetryPolicy] = None,
):
    """One HTTP round trip with transient-failure retry; returns the
    open response (caller reads/closes). The repo's single urlopen call
    site: all remote HTTP — object stores, token fetches, REST clients —
    rides this and the shared policy. Raises ``HttpError`` (status +
    headers attached) on HTTP errors, ``Error`` on connection failures.
    """
    policy = policy or RetryPolicy()

    def once():
        req = urllib.request.Request(
            url, data=data, headers=headers or {}, method=method
        )
        return urllib.request.urlopen(req, timeout=timeout)

    try:
        return policy.run(once, what=f"{method} {url}")
    except urllib.error.HTTPError as e:
        body = e.read(4096).decode(errors="replace")
        raise HttpError(
            f"{method} {url} -> HTTP {e.code}: {body[:500]}",
            status=e.code,
            headers=e.headers,
        ) from e
    except urllib.error.URLError as e:
        raise Error(f"{method} {url} failed: {e.reason}") from e


class RetryingReadStream(SeekStream):
    """Reopen-and-seek retry wrapper over any seekable read stream.

    ``open_fn`` returns a FRESH inner SeekStream (each call is one
    connection/open attempt — itself retried under the policy, so N
    consecutive open-time 5xx before success are invisible). A
    transient error mid-read drops the inner stream, backs off, reopens
    and seeks to the exact resume offset — callers never observe the
    fault. Progress resets the consecutive-failure count, so the
    attempt cap bounds *stuck* retries, not total faults healed; the
    policy's cumulative budget bounds the total backoff either way.
    """

    def __init__(
        self,
        open_fn: Callable[[], SeekStream],
        policy: Optional[RetryPolicy] = None,
    ) -> None:
        self._open_fn = open_fn
        self._policy = policy or RetryPolicy()
        self._inner: Optional[SeekStream] = None
        self._pos = 0
        self._stalls = 0

    def _drop(self) -> None:
        if self._inner is not None:
            try:
                self._inner.close()
            except (OSError, Error):
                pass
            self._inner = None

    def _ensure(self) -> SeekStream:
        if self._inner is None:
            self._inner = self._policy.run(self._open_fn, what="open")
            if self._pos:
                self._inner.seek(self._pos)
        return self._inner

    def _read_once(self, n: int) -> Optional[bytes]:
        """One guarded inner read; None means 'faulted, retry'."""
        try:
            out = self._ensure().read(n)
        except Exception as exc:
            if not is_transient(exc):
                raise
            self._drop()
            self._stalls += 1
            if self._stalls >= self._policy.max_attempts:
                raise
            self._policy.pause(cause=exc, what=f"read at {self._pos}")
            return None
        self._stalls = 0
        if out:
            self._pos += len(out)
        return out

    def read(self, n: int = -1) -> bytes:
        if n == 0:
            return b""
        if n < 0:
            # read-to-EOF must not silently truncate at a healed fault:
            # accumulate until the inner stream reports a true EOF
            parts = []
            while True:
                out = self._read_once(1 << 20)
                if out is None:
                    continue
                if not out:
                    return b"".join(parts)
                parts.append(out)
        while True:
            out = self._read_once(n)
            if out is not None:
                return out

    def seek(self, pos: int) -> None:
        if pos == self._pos:
            return
        self._pos = pos
        if self._inner is not None:
            try:
                self._inner.seek(pos)
            except Exception as exc:
                if not is_transient(exc):
                    raise
                self._drop()  # reopen lazily at _pos on the next read

    def tell(self) -> int:
        return self._pos

    def write(self, data) -> int:
        raise Error("RetryingReadStream is read-only")

    def close(self) -> None:
        self._drop()
