"""URI-addressed streams, filesystems, RecordIO, and sharded input splits.

Reference: include/dmlc/io.h, recordio.h, src/io/ (SURVEY §2.3).
"""

from .uri import URI, URISpec  # noqa: F401
from .stream import (  # noqa: F401
    Stream,
    SeekStream,
    MemoryStream,
    FileStream,
    Serializable,
    StreamIO,
    wrap_text,
)
from .filesystem import (  # noqa: F401
    FileSystem,
    FileInfo,
    LocalFileSystem,
    MemoryFileSystem,
    TemporaryDirectory,
    FS_REGISTRY,
)
from . import codec  # noqa: F401 — the single compression site (L009)
from .codec import (  # noqa: F401
    DecodeContext,
    DecodedBlockCache,
    available_codecs,
    default_decode_cache,
    default_decode_context,
    get_codec,
)
from . import blockcache  # noqa: F401 — the shm/socket site (L010)
from .blockcache import (  # noqa: F401
    BlockCacheClient,
    BlockCacheDaemon,
)
from .recordio import (  # noqa: F401
    KMAGIC,
    CFLAG_COMPRESSED,
    RecordIOWriter,
    IndexedRecordIOWriter,
    RecordIOReader,
    RecordIOChunkReader,
    decode_chunk,
)
from . import serializer  # noqa: F401
from . import retry  # noqa: F401
from . import faults  # noqa: F401 — registers the fault:// scheme
from .retry import RetryPolicy, RetryingReadStream  # noqa: F401
from .faults import FaultInjectingFileSystem  # noqa: F401
from . import lookup  # noqa: F401 — the point-read hot path (L016)
from .lookup import (  # noqa: F401
    LookupClient,
    LookupServer,
    RecordLookup,
)
from .split import (  # noqa: F401
    InputSplit,
    InputSplitBase,
    LineSplitter,
    RecordIOSplitter,
    IndexedRecordIOSplitter,
    SingleFileSplit,
    ThreadedInputSplit,
    CachedInputSplit,
    InputSplitShuffle,
)
from .split import create as create_input_split  # noqa: F401
