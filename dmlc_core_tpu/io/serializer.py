"""Binary serialization over Streams.

Reference: include/dmlc/serializer.h + Stream::Write<T>/Read<T>
(include/dmlc/io.h:450-472). Wire format kept compatible with the reference's
canonical little-endian encoding so data written by dmlc-core loads here:

- arithmetic scalars: raw little-endian bytes of the C type
  (reference ArithmeticHandler, serializer.h:83-100; big-endian hosts swap,
  endian.h:51-62 — we always emit/read little-endian explicitly)
- string/bytes: uint64 length + raw bytes (serializer.h:176-190)
- vector<T>: uint64 size + elements (serializer.h:130-170)
- pair/map/set/list: composed from the above (serializer.h:300-380)

On top of that, numpy arrays serialize as dtype-tagged vectors — the
TPU-native extension used by RowBlockContainer page caches.
"""

from __future__ import annotations

import struct
from typing import Any, Union

import numpy as np

from ..utils.logging import Error
from .stream import Stream

__all__ = [
    "write_scalar",
    "read_scalar",
    "write_bytes",
    "read_bytes",
    "write_str",
    "read_str",
    "write_ndarray",
    "read_ndarray",
    "save",
    "load",
]

_FMT = {
    "int8": "<b",
    "uint8": "<B",
    "int32": "<i",
    "uint32": "<I",
    "int64": "<q",
    "uint64": "<Q",
    "float32": "<f",
    "float64": "<d",
    "bool": "<B",
}


def write_scalar(stream: Stream, value: Union[int, float, bool], ctype: str) -> None:
    """Write one scalar as its little-endian C representation."""
    fmt = _FMT.get(ctype)
    if fmt is None:
        raise Error(f"unknown scalar ctype {ctype!r}")
    stream.write(struct.pack(fmt, value))


def read_scalar(stream: Stream, ctype: str):
    fmt = _FMT.get(ctype)
    if fmt is None:
        raise Error(f"unknown scalar ctype {ctype!r}")
    size = struct.calcsize(fmt)
    data = stream.read_exact(size)
    return struct.unpack(fmt, data)[0]


def try_read_scalar(stream: Stream, ctype: str):
    """Read-or-None at EOF (reference Read<T> returns bool)."""
    fmt = _FMT[ctype]
    size = struct.calcsize(fmt)
    data = stream.read(size)
    if len(data) == 0:
        return None
    if len(data) != size:
        raise Error("Serializer: truncated scalar")
    return struct.unpack(fmt, data)[0]


def write_bytes(stream: Stream, data: bytes) -> None:
    """uint64 length + raw (reference serializer.h:176-190)."""
    stream.write(struct.pack("<Q", len(data)))
    if data:
        stream.write(data)


def read_bytes(stream: Stream) -> bytes:
    n = read_scalar(stream, "uint64")
    return stream.read_exact(n) if n else b""


def write_str(stream: Stream, s: str) -> None:
    write_bytes(stream, s.encode("utf-8"))


def read_str(stream: Stream) -> str:
    return read_bytes(stream).decode("utf-8")


# numpy dtype tag ↔ dtype; the on-wire tag is the dtype's string name.
def write_ndarray(stream: Stream, arr: np.ndarray) -> None:
    """dtype-tagged, shape-prefixed contiguous array.

    Layout: str(dtype) | uint32 ndim | uint64 shape[ndim] | raw LE data.
    This is the TPU-native extension backing RowBlock page caches; the
    reference serializes vector<T> (serializer.h:130-147) — a 1-D special
    case of this.
    """
    # NOT ascontiguousarray: it promotes 0-d arrays to 1-d, silently
    # changing the shape on the wire (scalars in checkpoint pytrees)
    arr = np.asarray(arr, order="C")
    write_str(stream, str(arr.dtype))
    write_scalar(stream, arr.ndim, "uint32")
    for d in arr.shape:
        write_scalar(stream, d, "uint64")
    data = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
    stream.write(data.tobytes())


def read_ndarray(stream: Stream) -> np.ndarray:
    dtype = np.dtype(read_str(stream))
    ndim = read_scalar(stream, "uint32")
    shape = tuple(read_scalar(stream, "uint64") for _ in range(ndim))
    count = int(np.prod(shape)) if shape else 1
    raw = stream.read_exact(count * dtype.itemsize)
    arr = np.frombuffer(raw, dtype=dtype.newbyteorder("<"), count=count)
    arr = arr.astype(dtype, copy=False).reshape(shape)
    if not arr.flags.writeable:
        arr = arr.copy()  # frombuffer views are read-only; consumers mutate
    return arr


# -- generic typed save/load -------------------------------------------------
# Type tags for the dynamic save/load path (reference has static C++ types;
# Python needs a tag byte). Kept stable: they are written into cache files.
_TAG_NONE = 0
_TAG_BOOL = 1
_TAG_INT = 2
_TAG_FLOAT = 3
_TAG_STR = 4
_TAG_BYTES = 5
_TAG_LIST = 6
_TAG_DICT = 7
_TAG_TUPLE = 8
_TAG_NDARRAY = 9


def save(stream: Stream, obj: Any) -> None:
    """Serialize a composite of scalars/str/bytes/list/dict/tuple/ndarray.

    The Python analogue of Stream::Write<T> over arbitrary STL graphs
    (reference io.h:60-106, serializer.h:300-380).
    """
    if obj is None:
        write_scalar(stream, _TAG_NONE, "uint8")
    elif isinstance(obj, bool):
        write_scalar(stream, _TAG_BOOL, "uint8")
        write_scalar(stream, obj, "bool")
    elif isinstance(obj, int):
        if not (-(1 << 63) <= obj < (1 << 63)):
            raise Error(f"cannot serialize int outside int64 range: {obj}")
        write_scalar(stream, _TAG_INT, "uint8")
        write_scalar(stream, obj, "int64")
    elif isinstance(obj, float):
        write_scalar(stream, _TAG_FLOAT, "uint8")
        write_scalar(stream, obj, "float64")
    elif isinstance(obj, str):
        write_scalar(stream, _TAG_STR, "uint8")
        write_str(stream, obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        write_scalar(stream, _TAG_BYTES, "uint8")
        write_bytes(stream, bytes(obj))
    elif isinstance(obj, list):
        write_scalar(stream, _TAG_LIST, "uint8")
        write_scalar(stream, len(obj), "uint64")
        for item in obj:
            save(stream, item)
    elif isinstance(obj, tuple):
        write_scalar(stream, _TAG_TUPLE, "uint8")
        write_scalar(stream, len(obj), "uint64")
        for item in obj:
            save(stream, item)
    elif isinstance(obj, dict):
        write_scalar(stream, _TAG_DICT, "uint8")
        write_scalar(stream, len(obj), "uint64")
        for k, v in obj.items():
            save(stream, k)
            save(stream, v)
    elif isinstance(obj, np.ndarray):
        write_scalar(stream, _TAG_NDARRAY, "uint8")
        write_ndarray(stream, obj)
    elif isinstance(obj, (np.integer,)):
        save(stream, int(obj))
    elif isinstance(obj, (np.floating,)):
        save(stream, float(obj))
    else:
        raise Error(f"cannot serialize object of type {type(obj).__name__}")


def load(stream: Stream) -> Any:
    tag = read_scalar(stream, "uint8")
    if tag == _TAG_NONE:
        return None
    if tag == _TAG_BOOL:
        return bool(read_scalar(stream, "bool"))
    if tag == _TAG_INT:
        return read_scalar(stream, "int64")
    if tag == _TAG_FLOAT:
        return read_scalar(stream, "float64")
    if tag == _TAG_STR:
        return read_str(stream)
    if tag == _TAG_BYTES:
        return read_bytes(stream)
    if tag in (_TAG_LIST, _TAG_TUPLE):
        n = read_scalar(stream, "uint64")
        items = [load(stream) for _ in range(n)]
        return tuple(items) if tag == _TAG_TUPLE else items
    if tag == _TAG_DICT:
        n = read_scalar(stream, "uint64")
        out = {}
        for _ in range(n):
            k = load(stream)
            out[k] = load(stream)
        return out
    if tag == _TAG_NDARRAY:
        return read_ndarray(stream)
    raise Error(f"Serializer: unknown tag {tag}")
