"""Named POSIX shared-memory segments — the ONE place in the library
that touches ``_posixshmem`` (lint L019).

PR 7 introduced the primitive inside io/blockcache.py for the per-host
decoded-block cache; the dsserve same-host transport (docs/dsserve.md,
data plane) needs the identical lifecycle, so the class lives here and
both services import it. Lint L019 confines ``_posixshmem`` /
``multiprocessing.shared_memory`` construction to this module the same
way L009 confines compression to io/codec.py — one site owns the
create/attach/unlink semantics, everyone else shares its trade-offs
instead of re-deriving them.
"""

from __future__ import annotations

import mmap
import os

try:  # CPython's POSIX shared-memory primitive (what the stdlib's
    # multiprocessing.shared_memory wraps); absent on non-POSIX builds
    import _posixshmem
except ImportError:  # pragma: no cover - non-POSIX platform
    _posixshmem = None

__all__ = ["ShmSegment", "shm_available", "shm_transport_enabled"]


def shm_available() -> bool:
    """True when this interpreter can open POSIX shared memory."""
    return _posixshmem is not None


def shm_transport_enabled() -> bool:
    """``DMLC_DSSERVE_SHM`` gate (default on), read by BOTH ends of the
    dsserve same-host transport. The transport negotiates per
    connection and silently degrades to TCP on any failure, so the knob
    exists for pinning a transport (benches, A/B drills), not for
    safety."""
    return os.environ.get("DMLC_DSSERVE_SHM", "on").strip().lower() not in (
        "off", "0", "false", "no",
    )


class ShmSegment:
    """Named POSIX shared-memory segment with EXPLICIT lifecycle —
    deliberately built on ``_posixshmem`` + ``mmap`` rather than
    ``multiprocessing.shared_memory``: the stdlib's resource tracker
    registers every open (create AND attach, bpo-39959; opt-out only
    lands in 3.13) for unlink-at-process-exit, which would tear
    daemon-owned segments down the moment ONE client exits, its
    set-based bookkeeping double-removes when daemon and client share
    a process, and suppressing it means mutating process-global tracker
    hooks under unrelated threads. Same syscalls, zero tracker
    interaction; lifecycle here is explicit — the owner unlinks on
    eviction/flush/close, a losing publisher unlinks its own copy. The
    cost is that a SIGKILL'd owner leaks its segments until `cached
    flush`/reboot — the standard trade for any shm service."""

    __slots__ = ("name", "buf", "_mmap")

    def __init__(self, name: str, create: bool = False,
                 size: int = 0) -> None:
        if _posixshmem is None:  # pragma: no cover - non-POSIX
            raise OSError("POSIX shared memory unavailable on this host")
        flags = os.O_RDWR | ((os.O_CREAT | os.O_EXCL) if create else 0)
        fd = _posixshmem.shm_open("/" + name, flags, mode=0o600)
        try:
            if create and size:
                os.ftruncate(fd, size)
            self._mmap = mmap.mmap(fd, os.fstat(fd).st_size)
        finally:
            os.close(fd)
        self.name = name
        self.buf: memoryview = memoryview(self._mmap)

    def close(self) -> None:
        """Unmap; raises BufferError while exported views are alive
        (callers guard — the mapping then lives until those views go)."""
        self.buf.release()
        self._mmap.close()

    def unlink(self) -> None:
        _posixshmem.shm_unlink("/" + self.name)
