"""Deterministic fault injection for any registered filesystem
(``fault://`` scheme).

``FaultInjectingFileSystem`` wraps an inner backend and injects a
*seeded, deterministic* fault schedule — short reads, mid-read
``ConnectionResetError``, N consecutive 5xx before an open succeeds,
latency spikes, truncated writes — so tests, ``bench.py`` and
``benchmarks/diag_starve.py`` can all prove the retry layer heals real
failure shapes (a clean read and a chaos read must be byte-identical).

URI grammar (both forms compose; the host form survives the split
factory, which strips query args into dataset options):

  fault://[spec]/<path>[?spec]

``spec`` is comma- (host segment) or &-separated (query) ``k=v`` pairs:

  inner=<proto>   inner backend protocol (default: local file)
  seed=N          schedule seed (default 0)
  resets=N        N mid-read ConnectionResetErrors at seeded points
  short=N         N seeded short reads (a fraction of the ask returned)
  errors=N        N consecutive HTTP-503 open failures before success
  latency_ms=M    latency spikes of M milliseconds (count: spikes=N)
  spikes=N        number of latency spikes (default 2 when latency_ms)
  wresets=N       N truncated writes: half the payload lands, then reset
  cap=BYTES       max bytes served per read call (default 8192; small
                  caps create many read ordinals for the schedule)

Examples::

  fault://resets=2,errors=3,seed=7/data/train.rec?index=...&shuffle=window
  fault:///tmp/x.rec?resets=1&seed=5
  fault://inner=s3,resets=1/bucket/key.bin

Every fired fault increments the global ``faults_injected`` counter
(io/retry.py), visible next to the healed ``retries`` in ``io_stats()``.
Read streams come back wrapped in ``RetryingReadStream``, so injected
faults exercise exactly the production retry path.
"""

from __future__ import annotations

import time
from random import Random
from typing import Dict, List, Optional, Tuple

from ..utils.logging import Error, check
from .filesystem import FS_REGISTRY, FileInfo, FileSystem
from .retry import (
    HttpError,
    RetryingReadStream,
    RetryPolicy,
    count_fault_injected,
)
from .stream import SeekStream, Stream
from .uri import URI

__all__ = ["FaultInjectingFileSystem", "FaultSpec", "wrap_uri", "unwrap_uri"]

_SPEC_KEYS = (
    "inner",
    "seed",
    "resets",
    "short",
    "errors",
    "latency_ms",
    "spikes",
    "wresets",
    "cap",
)


class FaultSpec:
    """Parsed fault schedule parameters (see module grammar)."""

    def __init__(self, args: Dict[str, str]) -> None:
        unknown = sorted(set(args) - set(_SPEC_KEYS))
        check(not unknown, f"unknown fault:// option(s) {unknown}")

        def num(key: str, default: int) -> int:
            raw = args.get(key)
            if raw is None:
                return default
            try:
                return int(raw)
            except ValueError:
                raise Error(
                    f"fault:// option {key}={raw!r} is not an integer"
                ) from None

        self.inner = args.get("inner", "file")
        self.seed = num("seed", 0)
        self.resets = num("resets", 0)
        self.short = num("short", 0)
        self.errors = num("errors", 0)
        self.latency_ms = num("latency_ms", 0)
        self.spikes = num("spikes", 2 if self.latency_ms else 0)
        self.wresets = num("wresets", 0)
        self.cap = num("cap", 8192)
        check(self.cap >= 1, f"fault:// cap={self.cap} must be >= 1")


def wrap_uri(uri: str, spec: str) -> str:
    """Prefix a plain local path / file:// URI with a fault:// host-form
    spec (``wrap_uri('/d/x.rec', 'resets=2,seed=7')`` →
    ``fault://resets=2,seed=7/d/x.rec``) — the helper bench.py and
    diag_starve use so a chaos run is one flag/env away."""
    if not spec:
        return uri
    path = uri[len("file://"):] if uri.startswith("file://") else uri
    check(
        "://" not in path,
        f"wrap_uri only wraps local paths; name the backend in the spec "
        f"(inner=...) for {uri!r}",
    )
    if not path.startswith("/"):
        path = "/" + path
    return f"fault://{spec}{path}"


def unwrap_uri(uri: str) -> str:
    """Inverse of :func:`wrap_uri` for IDENTITY purposes: the inner URI
    a host-form ``fault://`` wrapper points at, unchanged for every
    other scheme. Consumers that need a stable *dataset* identity (the
    dynamic shard service's fileset signature — one chaos-wrapped
    worker must not look like it reads different data than its clean
    peers) normalize through this; it does not parse query-form specs
    (those never reach a URI used as an identity — the split factory
    strips query args into options first)."""
    if not uri.startswith("fault://"):
        return uri
    rest = uri[len("fault://"):]
    slash = rest.find("/")
    if slash < 0:
        return uri
    spec_seg, path = rest[:slash], rest[slash:]
    args = dict(
        kv.split("=", 1) for kv in spec_seg.split(",") if "=" in kv
    )
    inner = args.get("inner", "file")
    if inner == "file":
        return path
    return f"{inner}://{path.lstrip('/')}"


class _Schedule:
    """Seeded, deterministic event schedule shared by every (re)open of
    one logical stream — consumed faults do not re-fire after the retry
    layer reopens.

    Events key on the READ ORDINAL (the k-th read call over the
    stream's lifetime), spaced every ~3 reads with seeded jitter, so
    they fire regardless of chunk sizes or seek patterns. Kinds:
    ``reset`` raises before serving bytes, ``short`` serves a third of
    the ask, ``latency`` sleeps then serves normally.
    """

    def __init__(self, spec: FaultSpec, key: str, incarnation: int) -> None:
        self.spec = spec
        rng = Random((spec.seed, key, incarnation).__repr__())
        kinds = (
            ["reset"] * spec.resets
            + ["short"] * spec.short
            + ["latency"] * spec.spikes
        )
        rng.shuffle(kinds)
        self.events: Dict[int, str] = {}
        ordinal = 0
        for kind in kinds:
            ordinal += 1 + rng.randint(1, 2)  # every 2-3 reads
            self.events[ordinal] = kind
        self.reads = 0
        self.open_errors_left = spec.errors
        self.write_resets_left = spec.wresets
        self.writes = 0

    def on_open(self) -> None:
        if self.open_errors_left > 0:
            self.open_errors_left -= 1
            count_fault_injected()
            raise HttpError(
                "GET (injected) -> HTTP 503: fault:// open error",
                status=503,
            )

    def on_read(self, n: int) -> Tuple[int, bool]:
        """Returns (bytes to serve, raise_reset_after_truncation)."""
        self.reads += 1
        kind = self.events.pop(self.reads, None)
        if kind is None:
            return n, False
        count_fault_injected()
        if kind == "reset":
            return 0, True
        if kind == "short":
            return max(1, n // 3), False
        time.sleep(self.spec.latency_ms / 1000.0)  # latency spike
        return n, False

    def on_write(self, n: int) -> Tuple[int, bool]:
        """Returns (bytes to land, raise_reset_after)."""
        self.writes += 1
        if self.write_resets_left > 0 and self.writes >= 2:
            # let the first write land so truncation is mid-object
            self.write_resets_left -= 1
            count_fault_injected()
            return max(0, n // 2), True
        return n, False


class _FaultyReadStream(SeekStream):
    """One incarnation of an injected read stream: serves the inner
    stream's bytes capped per call, firing the shared schedule."""

    def __init__(self, inner: SeekStream, sched: _Schedule) -> None:
        self._inner = inner
        self._sched = sched

    def read(self, n: int = -1) -> bytes:
        ask = self._sched.spec.cap if n < 0 else min(n, self._sched.spec.cap)
        serve, reset = self._sched.on_read(ask)
        if reset:
            raise ConnectionResetError("fault://: injected mid-read reset")
        return self._inner.read(serve)

    def seek(self, pos: int) -> None:
        self._inner.seek(pos)

    def tell(self) -> int:
        return self._inner.tell()

    def write(self, data) -> int:
        raise Error("fault:// read stream is read-only")

    def close(self) -> None:
        self._inner.close()


class _FaultyWriteStream(Stream):
    """Write wrapper injecting truncated writes: part of the payload
    lands, then the connection 'resets' — the crash shape
    checkpoint._write_atomic's verify-then-rename contract must catch."""

    def __init__(self, inner: Stream, sched: _Schedule) -> None:
        self._inner = inner
        self._sched = sched

    def read(self, n: int = -1) -> bytes:
        raise Error("fault:// write stream is write-only")

    def write(self, data) -> int:
        buf = bytes(data)
        land, reset = self._sched.on_write(len(buf))
        if land:
            self._inner.write(buf[:land])
        if reset:
            self._inner.flush()
            raise ConnectionResetError("fault://: injected truncated write")
        return len(buf)

    def flush(self) -> None:
        self._inner.flush()

    def close(self) -> None:
        self._inner.close()


class FaultInjectingFileSystem(FileSystem):
    """``fault://`` — wrap any inner filesystem with seeded faults."""

    protocol = "fault://"

    def __init__(self) -> None:
        # (uri) -> number of independent open() calls seen, so each
        # logical stream gets its own deterministic schedule incarnation
        self._opens: Dict[str, int] = {}

    # -- uri plumbing --------------------------------------------------------
    def _parse(self, uri: str) -> Tuple[str, FaultSpec, str]:
        """→ (inner_uri, spec, host_token). Host-form args and query-form
        args merge; query wins on collision."""
        base, _, query = uri.partition("?")
        u = URI(base)
        check(u.protocol == self.protocol, f"not a fault:// uri: {uri}")
        args: Dict[str, str] = {}
        host_token = u.host
        if host_token:
            for kv in host_token.split(","):
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                args[k] = v
        for kv in query.split("&"):
            if not kv:
                continue
            k, _, v = kv.partition("=")
            args[k] = v
        spec = FaultSpec(args)
        if spec.inner == "file":
            inner = u.path
        else:
            # first path segment is the inner host (bucket/namenode)
            inner = f"{spec.inner}://{u.path.lstrip('/')}"
        return inner, spec, host_token

    def _inner_fs(self, inner_uri: str) -> FileSystem:
        return FileSystem.get_instance(inner_uri)

    def _refault(self, host_token: str, inner_path: str, spec: FaultSpec) -> str:
        """Re-prefix an inner listing path back into fault:// form."""
        if spec.inner != "file":
            proto = spec.inner + "://"
            check(
                inner_path.startswith(proto),
                f"inner listing returned non-{proto} path {inner_path!r}",
            )
            inner_path = "/" + inner_path[len(proto):]
        return f"{self.protocol}{host_token}{inner_path}"

    # -- FileSystem interface ------------------------------------------------
    def open(self, uri: str, mode: str = "r") -> Stream:
        inner_uri, spec, _host = self._parse(uri)
        fs = self._inner_fs(inner_uri)
        incarnation = self._opens.get(uri, 0)
        self._opens[uri] = incarnation + 1
        sched = _Schedule(spec, inner_uri, incarnation)
        if mode in ("r", "rb"):

            def open_inner() -> SeekStream:
                sched.on_open()
                s = fs.open(inner_uri, "r")
                check(
                    isinstance(s, SeekStream),
                    f"fault:// needs a seekable inner stream for {inner_uri}",
                )
                return _FaultyReadStream(s, sched)  # type: ignore[arg-type]

            return RetryingReadStream(open_inner, policy=RetryPolicy())
        if mode in ("w", "wb", "a"):
            sched.on_open()
            return _FaultyWriteStream(fs.open(inner_uri, mode[0]), sched)
        raise Error(f"invalid fault:// mode {mode!r}")

    def get_path_info(self, uri: str) -> FileInfo:
        inner_uri, spec, host = self._parse(uri)
        info = self._inner_fs(inner_uri).get_path_info(inner_uri)
        return FileInfo(
            self._refault(host, info.path, spec), info.size, info.type,
            info.etag,
        )

    def list_directory(self, uri: str) -> List[FileInfo]:
        inner_uri, spec, host = self._parse(uri)
        return [
            FileInfo(
                self._refault(host, f.path, spec), f.size, f.type, f.etag
            )
            for f in self._inner_fs(inner_uri).list_directory(inner_uri)
        ]

    def delete(self, uri: str, recursive: bool = False) -> None:
        inner_uri, _spec, _host = self._parse(uri)
        self._inner_fs(inner_uri).delete(inner_uri, recursive=recursive)


_SINGLETON: Optional[FaultInjectingFileSystem] = None


def _singleton() -> FaultInjectingFileSystem:
    global _SINGLETON
    if _SINGLETON is None:
        _SINGLETON = FaultInjectingFileSystem()
    return _SINGLETON


if FS_REGISTRY.find("fault://") is None:
    FS_REGISTRY.add("fault://", _singleton)
