"""URI-addressed command-line tools: ``python -m dmlc_core_tpu.tools``.

Reference: the Tier-2 standalone CLI test programs under test/*.cc —
``filesys_test`` (mini ls/cat/cp against any URI, filesys_test.cc:8-40),
``split_test``/``split_read_test`` (stream one part of a sharded URI,
split_test.cc:8-24), ``recordio_test`` (pack/unpack roundtrip). Rebuilt
as one argparse CLI over the same URI machinery users get from the
library, plus ``rowrec pack``: text (libsvm/csv/libfm) → .rec [+ index]
conversion for the RecordIO→HBM staging path (BASELINE.md north star
#2), which the reference leaves to downstream projects.

Every subcommand accepts any registered URI scheme (file, s3, gs, hdfs,
azure, http, mem) — the point of the reference tools.
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
from typing import List, Optional

from ..data import create_parser
from ..data.rowrec import write_rowrec
from ..io import split as io_split
from ..io.filesystem import FileSystem
from ..io.stream import Stream
from ..utils.logging import Error

__all__ = ["main"]

_COPY_CHUNK = 4 << 20


def _cmd_ls(args) -> int:
    fs = FileSystem.get_instance(args.uri)
    lister = (
        fs.list_directory_recursive if args.recursive else fs.list_directory
    )
    for info in lister(args.uri):
        print(f"{info.size:>12}  {info.path}")
    return 0


def _cmd_cat(args) -> int:
    with Stream.create(args.uri, "r") as s:
        while True:
            buf = s.read(_COPY_CHUNK)
            if not buf:
                break
            sys.stdout.buffer.write(buf)
    sys.stdout.buffer.flush()
    return 0


def _cmd_cp(args) -> int:
    with Stream.create(args.src, "r") as src, Stream.create(
        args.dst, "w"
    ) as dst:
        n = 0
        while True:
            buf = src.read(_COPY_CHUNK)
            if not buf:
                break
            dst.write(buf)
            n += len(buf)
    print(f"copied {n} bytes {args.src} -> {args.dst}", file=sys.stderr)
    return 0


def _cmd_split(args) -> int:
    """Stream one shard of a URI — record counts/bytes like
    split_test.cc, with --dump echoing the records themselves."""
    sp = io_split.create(
        args.uri, args.part, args.num_parts, type=args.type, threaded=False
    )
    records = 0
    nbytes = 0
    try:
        while True:
            rec = sp.next_record()
            if rec is None:
                break
            records += 1
            nbytes += len(rec)
            if args.dump:
                sys.stdout.buffer.write(bytes(rec))
                if args.type == "text":
                    sys.stdout.buffer.write(b"\n")
    finally:
        sp.close()
    print(
        f"part {args.part}/{args.num_parts}: {records} records, "
        f"{nbytes} bytes",
        file=sys.stderr,
    )
    return 0


def _cmd_recordio(args) -> int:
    """pack: one record per input line; unpack: records back to lines
    (recordio_test.cc roundtrip, binary-safe via the frame format)."""
    from ..io.recordio import (
        IndexedRecordIOWriter,
        RecordIOReader,
        RecordIOWriter,
    )

    if args.action == "pack":
        if not args.dst:
            print("error: recordio pack needs a dst URI", file=sys.stderr)
            return 2
        with contextlib.ExitStack() as stack:
            dst = stack.enter_context(Stream.create(args.dst, "w"))
            codec = _codec_arg(args)
            writer = (
                IndexedRecordIOWriter(
                    dst,
                    stack.enter_context(Stream.create(args.index, "w")),
                    codec=codec,
                    level=args.level,
                )
                if args.index
                else RecordIOWriter(dst, codec=codec, level=args.level)
            )
            n = _pack_lines(args.src, writer)
            writer.flush_block()
        print(f"packed {n} records", file=sys.stderr)
    else:
        with Stream.create(args.src, "r") as src:
            n = 0
            for rec in RecordIOReader(src):
                sys.stdout.buffer.write(rec)
                sys.stdout.buffer.write(b"\n")
                n += 1
        print(f"unpacked {n} records", file=sys.stderr)
    return 0


def _pack_lines(src_uri: str, writer) -> int:
    """One record per line, streamed through the text splitter. Blank
    lines are NOT records: reference LineSplitter collapses runs of
    \\n/\\r (line_split.cc:42-44), and this CLI keeps its semantics —
    byte-faithful payloads belong in RecordIO directly, not line form."""
    sp = io_split.create(src_uri, 0, 1, type="text", threaded=False)
    n = 0
    try:
        while True:
            line = sp.next_record()
            if line is None:
                return n
            writer.write_record(bytes(line))
            n += 1
    finally:
        sp.close()


def _codec_arg(args):
    """CLI codec option → writer codec argument (``none`` = v1)."""
    codec = getattr(args, "codec", "none")
    return None if codec in ("", "none") else codec


def _cmd_rowrec(args) -> int:
    """Text dataset → rowrec .rec shards (+ optional count index) for
    the fused RecordIO→HBM staging path. ``--part/--num-parts`` convert
    one record-aligned shard so a large dataset converts in parallel
    (e.g. one part per dmlc-submit worker); ``--codec`` packs rows into
    compressed blocks (docs/recordio.md)."""
    parser = create_parser(
        args.src, args.part, args.num_parts, type=args.format, threaded=False
    )
    try:
        with contextlib.ExitStack() as stack:
            dst = stack.enter_context(Stream.create(args.dst, "w"))
            idx = (
                stack.enter_context(Stream.create(args.index, "w"))
                if args.index
                else None
            )
            n = write_rowrec(
                dst,
                iter(parser),
                index_stream=idx,
                codec=_codec_arg(args),
                level=args.level,
            )
    finally:
        parser.close()
    print(f"wrote {n} rows to {args.dst}", file=sys.stderr)
    return 0


def _cmd_recompress(args) -> int:
    """Convert a ``.rec`` (+``.idx``) between codecs in ONE stream pass:
    read records through RecordIOReader (decodes v1 frames and any
    compressed blocks alike), re-emit through a writer with the target
    codec — ``--codec none`` decompresses back to the reference v1
    format. The output round-trips byte-identically at the record
    level; with ``--index`` a fresh sidecar is written in the format
    matching the target codec (v1 byte offsets or block:in-offset
    pairs)."""
    from ..io.recordio import (
        DEFAULT_BLOCK_BYTES,
        IndexedRecordIOWriter,
        RecordIOReader,
        RecordIOWriter,
    )

    codec = _codec_arg(args)
    block_bytes = args.block_bytes or DEFAULT_BLOCK_BYTES
    n = 0
    with contextlib.ExitStack() as stack:
        src = stack.enter_context(Stream.create(args.src, "r"))
        dst = stack.enter_context(Stream.create(args.dst, "w"))
        writer = (
            IndexedRecordIOWriter(
                dst,
                stack.enter_context(Stream.create(args.index, "w")),
                codec=codec,
                level=args.level,
                block_bytes=block_bytes,
            )
            if args.index
            else RecordIOWriter(
                dst, codec=codec, level=args.level, block_bytes=block_bytes
            )
        )
        for rec in RecordIOReader(src):
            writer.write_record(rec)
            n += 1
        writer.flush_block()
        out_bytes = writer.bytes_written
    print(
        f"recompressed {n} records -> {args.dst} "
        f"(codec={codec or 'none'}, {out_bytes} bytes)",
        file=sys.stderr,
    )
    return 0


def _cmd_dump(args) -> int:
    """Parsed rows → text on stdout (default: rowrec .rec → libsvm; any
    ``?format=`` source works). ``%.9g`` keeps f32 labels/weights/values
    exact; qid and libfm fields are emitted when present, bare indices
    for binary features — the dump is a faithful inverse, streamed block
    by block (``--limit`` on a huge file reads only what it prints)."""
    from ..data import create_parser
    from ..io.uri import URISpec

    uspec = URISpec(args.src, args.part, args.num_parts)
    uri = args.src
    if "format" not in uspec.args:
        head, sep, frag = uri.partition("#")
        head += ("&" if "?" in head else "?") + "format=rowrec"
        uri = head + sep + frag
    parser = create_parser(uri, args.part, args.num_parts, threaded=False)
    rows = 0
    out = sys.stdout
    try:
        for blk in iter(parser):
            weights, qid, field, vals = (
                blk.weight, blk.qid, blk.field, blk.value
            )
            for i in range(blk.size):
                b, e = int(blk.offset[i]), int(blk.offset[i + 1])
                label = f"{float(blk.label[i]):.9g}"
                if weights is not None and float(weights[i]) != 1.0:
                    label += f":{float(weights[i]):.9g}"
                toks = [label]
                if qid is not None:
                    toks.append(f"qid:{int(qid[i])}")
                for j in range(b, e):
                    idx = int(blk.index[j])
                    if field is not None:
                        v = 1.0 if vals is None else float(vals[j])
                        toks.append(f"{int(field[j])}:{idx}:{v:.9g}")
                    elif vals is None:
                        toks.append(str(idx))  # binary feature
                    else:
                        toks.append(f"{idx}:{float(vals[j]):.9g}")
                out.write(" ".join(toks) + "\n")
                rows += 1
                if args.limit and rows >= args.limit:
                    print(f"dumped {rows} rows (limit)", file=sys.stderr)
                    return 0
    finally:
        parser.close()
    print(f"dumped {rows} rows", file=sys.stderr)
    return 0


def _cmd_info(args) -> int:
    """Runtime feature report (build_info): native kernels, env flags,
    accelerator runtime — the base.h feature macros as runtime facts.
    With a shard URI, also the indexed shard's key count and block
    geometry (io/lookup.py ``describe``) — what an operator needs to
    size a serve tier without opening the sidecar by hand."""
    import json

    from .. import build_info

    report = build_info()
    if getattr(args, "uri", None):
        from ..io.lookup import RecordLookup
        from ..utils.logging import Error as _Err

        handle = None
        try:
            handle = RecordLookup(args.uri, args.index or None)
            report["shard"] = handle.describe()
        except (_Err, OSError, ValueError):
            # a GROWING shard (stream/writer.py live generation): the
            # sidecar tail or final block may be mid-write — walk the
            # whole-frame prefix instead and report the in-flight tail
            # as uncommitted, not as corruption
            from ..stream import manifest as _stream_manifest

            scan = _stream_manifest.scan_committed_prefix(args.uri)
            scan["status"] = (
                f"growing (tail_bytes={scan['tail_bytes']} uncommitted)"
            )
            report["shard"] = scan
        finally:
            if handle is not None:
                handle.close()
    print(json.dumps(report, indent=2))
    return 0


def _cmd_serve(args) -> int:
    """The point-read serve daemon (io/lookup.py, docs/serving.md):
    batched ``lookup(keys)`` over one indexed shard on a TCP request
    loop, with p50/p99 latency histograms and QPS on ``/metrics``.
    ``--warm N`` prefetches the shard's N hottest blocks through the
    block-cache daemon's admission/quota machinery before serving;
    ``--port-file`` writes a JSON readiness signal for launchers."""
    import json
    import signal

    from ..io.lookup import LookupServer, RecordLookup
    from ..telemetry import tracing

    tracing.set_process_label("lookup-daemon")
    handle = RecordLookup(args.uri, args.index or None)
    if args.warm:
        n = handle.warm(max_blocks=args.warm)
        print(f"warmed {n} blocks", file=sys.stderr)
    server = LookupServer(
        handle, host=args.host, port=args.port,
        metrics_port=args.metrics_port,
    )
    if args.port_file:
        from ..dsserve.server import write_port_file

        write_port_file(args.port_file, args.host, server.port)
    signal.signal(signal.SIGTERM, lambda *_a: server.close())
    print(
        f"lookup daemon pid {os.getpid()} serving "
        f"{args.host}:{server.port} over {args.uri}"
        + (
            f" (/metrics on 127.0.0.1:{args.metrics_port})"
            if args.metrics_port
            else ""
        ),
        file=sys.stderr,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        # stats before handle.close(): the shard-geometry section probes
        # through the handle's span reader, which a closed handle would
        # lazily (and wrongly) reconstruct
        stats = server.stats()
        handle.close()
        print(json.dumps(stats), file=sys.stderr)
    return 0


def _cmd_cached(args) -> int:
    """Operator surface for the host-level shared decoded-block cache
    (io/blockcache.py, docs/recordio.md):

    - ``serve``: run the per-host daemon in the foreground (what
      ``dmlc-submit --block-cache`` launches once per host) until
      SIGINT/SIGTERM; owned shared-memory segments are unlinked on the
      way out.
    - ``stats``: one JSON snapshot of the daemon's store — entries,
      resident bytes, hit/miss/publish/eviction counts, per-tenant
      breakdown.
    - ``flush``: evict every unleased block (leased segments stay —
      a mapped view is never unlinked under a reader).
    """
    import json
    import signal

    from ..io import blockcache
    from ..telemetry import tracing

    sock = args.socket or blockcache.default_sock_path()
    if args.action == "serve":
        # the serve process IS the daemon: name it on the merged
        # flight-recorder timeline next to worker/tracker rows
        tracing.set_process_label("blockcache-daemon")
        daemon = blockcache.BlockCacheDaemon(
            sock,
            max_bytes=(args.budget_mb << 20) if args.budget_mb else None,
            tenant_max_bytes=(
                (args.tenant_mb << 20) if args.tenant_mb else None
            ),
            metrics_port=args.metrics_port,
        )
        daemon.start()
        signal.signal(signal.SIGTERM, lambda *_a: daemon.close())
        print(
            f"block-cache daemon pid {daemon.stats()['pid']} serving "
            f"{sock} (budget {daemon.max_bytes >> 20} MB"
            + (
                f", /metrics on 127.0.0.1:{args.metrics_port}"
                if args.metrics_port
                else ""
            )
            + ")",
            file=sys.stderr,
        )
        try:
            daemon.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            daemon.close()
        return 0
    client = blockcache.BlockCacheClient(sock)
    if args.action == "stats":
        stats = client.stats()
        if stats is None:
            print(f"error: no block-cache daemon at {sock}", file=sys.stderr)
            return 1
        print(json.dumps(stats, indent=2))
        return 0
    evicted = client.flush()
    if evicted is None:
        print(f"error: no block-cache daemon at {sock}", file=sys.stderr)
        return 1
    print(json.dumps({"evicted": evicted}))
    return 0


def _cmd_dsserve(args) -> int:
    """Operator surface for the disaggregated preprocessing tier
    (dmlc_core_tpu/dsserve/, docs/dsserve.md):

    - ``serve``: run one preprocessing worker in the foreground (what
      ``dmlc-submit --dsserve N`` launches N of, next to the tracker)
      until SIGINT/SIGTERM. With a tracker in the environment
      (``DMLC_TRACKER_URI``/``PORT``) the server leases micro-shards;
      ``--port-file`` writes the bound endpoint as a JSON readiness
      signal for launchers; ``--port 0`` binds any free port.
      SIGTERM is the GRACEFUL retire signal (docs/autoscale.md): the
      server finishes the shard it is producing, EPOCH_ENDs its
      streams, releases every held lease, then exits zero — so an
      autoscale scale-down (or operator drain) never strands a lease
      to its TTL.
    """
    import json
    import signal

    from ..dsserve.server import DsServeServer, write_port_file
    from ..telemetry import tracing

    tracing.set_process_label("dsserve-worker")
    server = DsServeServer(args.host, args.port, rank=args.rank)
    if args.port_file:
        write_port_file(args.port_file, args.host, server.port)
    signal.signal(signal.SIGTERM, lambda *_a: server.retire())
    print(
        f"dsserve worker pid {os.getpid()} rank {server.rank} serving "
        f"{args.host}:{server.port}"
        + (" (tracker-leased shards)"
           if os.environ.get("DMLC_TRACKER_URI") else " (static stripes)"),
        file=sys.stderr,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        print(json.dumps(server.stats()), file=sys.stderr)
    return 0


def _cmd_trace(args) -> int:
    """Operator surface for the flight recorder (telemetry/tracing.py,
    docs/observability.md):

    - ``dump <pid>``: SIGUSR2 the process — its installed handler
      writes the span rings to ``DMLC_TRACE_DIR`` (or the temp dir)
      without stopping it.
    - ``merge -o out.json in...json``: join per-process trace files
      from a ``dmlc-submit`` run (workers + cache daemon + tracker)
      into ONE Perfetto-loadable timeline keyed by rank/pid.
    - ``report trace.json``: stall attribution — per-stage busy/stall
      seconds, ring-starvation gaps over ``--gap-ms``, critical-path
      estimate per process.
    """
    import json
    import signal as _signal

    from ..telemetry import tracing

    if args.action == "dump":
        # `trace dump 1234` and `trace dump --pid 1234` both work — a
        # positional pid lands in the inputs list
        pid = args.pid
        if not pid and len(args.inputs) == 1 and args.inputs[0].isdigit():
            pid = int(args.inputs[0])
        if not pid:
            print("error: trace dump needs a pid", file=sys.stderr)
            return 2
        try:
            os.kill(pid, _signal.SIGUSR2)
        except (OSError, AttributeError) as e:
            print(f"error: cannot signal pid {pid}: {e}",
                  file=sys.stderr)
            return 1
        where = os.environ.get("DMLC_TRACE_DIR") or "its temp dir"
        print(
            f"SIGUSR2 sent to {pid}; it dumps "
            f"dmlc-trace-<label>-{pid}.json into its own "
            f"DMLC_TRACE_DIR (here: {where})",
            file=sys.stderr,
        )
        return 0
    if args.action == "merge":
        if not args.out or len(args.inputs) < 1:
            print("error: trace merge needs -o OUT and >=1 input",
                  file=sys.stderr)
            return 2
        merged = tracing.merge_traces(
            args.inputs, align_clocks=args.align_clocks
        )
        tracing.write_trace(merged, args.out)
        print(
            f"merged {merged['otherData']['merged']} trace(s), "
            f"{len(merged['traceEvents'])} events -> {args.out} "
            f"(load in https://ui.perfetto.dev)",
            file=sys.stderr,
        )
        return 0
    # report
    if len(args.inputs) != 1:
        print("error: trace report takes exactly one trace file",
              file=sys.stderr)
        return 2
    report = tracing.stall_report(
        tracing.load_trace(args.inputs[0]), gap_ms=args.gap_ms
    )
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    print(f"stall attribution for {args.inputs[0]} "
          f"(gap threshold {args.gap_ms} ms)")
    print("\nper-stage time (busy = work, stall = waiting):")
    stages = sorted(
        set(report["busy_seconds_by_stage"])
        | set(report["stall_seconds_by_stage"])
    )
    for s in stages:
        b = report["busy_seconds_by_stage"].get(s)
        w = report["stall_seconds_by_stage"].get(s)
        kind = "stall" if w is not None else "busy"
        secs = w if w is not None else b
        n = report["span_counts_by_stage"].get(s, 0)
        print(f"  {s:<24} {kind:<5} {secs:>10.4f}s  ({n} spans)")
    print("\nthreads (busy/idle inside each thread's span extent):")
    for name, t in sorted(report["threads"].items()):
        print(f"  {name:<40} busy {t['busy_seconds']:.4f}s  "
              f"idle {t['idle_seconds']:.4f}s  "
              f"wall {t['wall_seconds']:.4f}s")
    gaps = report["starvation_gaps"]
    print(f"\nstarvation gaps >= {args.gap_ms} ms: {len(gaps)}")
    for g in gaps[:10]:
        print(f"  {g['duration_ms']:>10.2f} ms  {g['stage']:<20} "
              f"{g['process']} / {g['thread']}")
    print("\ncritical-path estimate per process:")
    for proc, c in report["critical_path"].items():
        top = list(c["attributed_seconds"].items())[:3]
        attr = ", ".join(f"{k} {v:.3f}s" for k, v in top)
        print(f"  {proc}: wall {c['wall_seconds']:.3f}s, bottleneck "
              f"thread {c['bottleneck_thread']} ({attr}; "
              f"unattributed {c['unattributed_seconds']:.3f}s)")
    return 0


def _cmd_ckpt(args) -> int:
    """Operator surface for checkpoint directories: list steps with
    layout/size, inspect a step's tree shapes, prune to a retention
    count — over any URI backend (the reference leaves this to shell
    scripts against local disk). Uses only Checkpointer's public API
    (steps_info/restore/restore_meta/prune)."""
    import json

    from ..checkpoint import Checkpointer
    from ..utils.logging import Error as DmlcError

    ck = Checkpointer(args.base)
    if args.action == "ls":
        print(json.dumps(ck.steps_info(), indent=2))
        return 0
    if args.action == "show":
        try:
            step, tree = ck.restore(args.step)
        except (DmlcError, OSError) as e:
            sys.stderr.write(
                f"error: no readable checkpoint "
                f"{'step %s ' % args.step if args.step is not None else ''}"
                f"under {args.base}: {e}\n"
            )
            return 1

        def describe(t):
            if isinstance(t, dict):
                return {k: describe(v) for k, v in t.items()}
            if isinstance(t, (list, tuple)):
                return [describe(v) for v in t]
            if hasattr(t, "shape") and hasattr(t, "dtype"):
                return f"{t.dtype}{list(t.shape)}"
            return repr(t)

        out = {"step": step, "tree": describe(tree)}
        # the §5.4 data position (epoch, records consumed) — an operator
        # diagnosing a resume wants to see where the saved run was in
        # its input stream. Degraded-but-working: a corrupt/unreadable
        # sidecar must not cost the tree output the restore already
        # produced. (Costs a second base scan — fine for a CLI inspect.)
        try:
            meta = ck.restore_meta(step)
        except (DmlcError, OSError) as e:
            meta = None
            sys.stderr.write(f"warning: unreadable checkpoint meta: {e}\n")
        if meta is not None:
            out["meta"] = meta
        # default=str: meta is a user dict and may hold non-JSON-native
        # leaves (numpy scalars round-trip as 0-d arrays)
        print(json.dumps(out, indent=2, default=str))
        return 0
    # prune: --keep passes through VERBATIM — keep <= 0 means retention
    # disabled (Checkpointer semantics), never a silent default
    removed = ck.prune(keep=args.keep)
    print(json.dumps({"kept": ck.steps(), "removed": removed}))
    return 0


def _cmd_journal(args) -> int:
    """Operator surface for the tracker's control-plane journal
    (tracker/journal.py): dump the snapshot and every WAL record (seq,
    kind, CRC status), flag a torn tail, and say what a strict replay
    would recover — the thing to run when a supervised tracker's
    recovery looks wrong, BEFORE anyone deletes the directory."""
    import json

    from ..tracker import journal as _journal

    dump = _journal.inspect_journal(args.dir)
    if args.json:
        print(json.dumps(dump, indent=2, default=str))
        return 1 if (dump["crc_failures"] or
                     not os.path.isdir(args.dir)) else 0
    snap = dump["snapshot"]
    if snap is None:
        print("snapshot: none")
    elif "error" in snap:
        print(f"snapshot: CORRUPT ({snap['error']})")
    else:
        st = snap.get("state") or {}
        shards = st.get("shards") or {}
        print(
            f"snapshot: seq={snap['seq']} "
            f"fileset={shards.get('fileset')!r} "
            f"epochs={len(shards.get('epochs') or {})} "
            f"ranks={len(st.get('ranks') or {})}"
        )
    for r in dump["records"]:
        status = "ok" if r["crc_ok"] else "CRC-FAIL"
        print(
            f"wal @{r['offset']:<8d} seq={r['seq']} "
            f"kind={r['kind']} [{status}]"
        )
    if dump["torn_tail_at"] is not None:
        print(
            f"torn tail at byte {dump['torn_tail_at']} "
            "(truncated on next writable open — an interrupted append, "
            "not corruption)"
        )
    n_bad = dump["crc_failures"]
    print(
        f"{len(dump['records'])} WAL record(s), {n_bad} CRC failure(s)"
    )
    if n_bad:
        print("strict replay would REFUSE this journal (CRC damage)")
        return 1
    return 0


def _top_endpoint(raw: str) -> str:
    """Normalize the endpoint argument: full URL, host:port, or a bare
    port (loopback — the tracker binds 127.0.0.1)."""
    raw = (raw or "").strip()
    if not raw:
        raw = os.environ.get("DMLC_METRICS_PORT", "")
    if not raw:
        raise Error(
            "tools top needs the tracker metrics endpoint (a URL, "
            "host:port or port — the tracker logs 'telemetry endpoint "
            "on 127.0.0.1:PORT/metrics' at start, or pin it with "
            "DMLC_METRICS_PORT)"
        )
    if raw.isdigit():
        raw = f"127.0.0.1:{raw}"
    if not raw.startswith(("http://", "https://")):
        raw = f"http://{raw}"
    return raw.rstrip("/")


def _top_model(report: dict, window: float) -> dict:
    """Flatten a ``/metrics.json?window=`` report into the dashboard's
    model (also the ``--once --json`` output): per-rank and cluster
    rows/s, stall fractions, queue depth, cache hit rates, service
    QPS/p99. Pure — unit-testable without a tracker."""
    win = report.get("windowed") or {}
    per_rank = win.get("per_rank") or {}
    cluster = win.get("cluster") or {}
    def rank_order(kv):
        # tracker row first, then ranks NUMERICALLY (string sort puts
        # rank 10 before rank 2 on a 12-worker job)
        rank = kv[0]
        if rank == "tracker":
            return (0, 0, rank)
        try:
            return (1, int(rank), rank)
        except ValueError:
            return (2, 0, rank)

    ranks = {}
    for rank, view in sorted(per_rank.items(), key=rank_order):
        d = view.get("derived") or {}
        ranks[rank] = {
            "rows_per_sec": d.get("rows_per_sec", 0.0),
            "stall_fraction": d.get("stall_fraction", {}),
            "samples": view.get("samples", 0),
            **{
                k: d[k]
                for k in (
                    "block_cache_hit_rate",
                    "decode_cache_hit_rate",
                    "lookup_qps",
                    "lookup_p99_ms",
                    "dsserve_slots_per_sec",
                    "dsserve_wire_ratio",
                    "dsserve_shm_frac",
                    "shard_queue_depth",
                    "stream_lag_seconds",
                    "stream_lag_records",
                    "stream_watermark_records",
                )
                if k in d
            },
        }
    cd = cluster.get("derived") or {}
    # the shard queue depth lives on the tracker pseudo-rank's gauges
    qd = (
        (per_rank.get("tracker") or {})
        .get("gauges", {})
        .get("tracker.shards.queue_depth")
    )
    model = {
        "window_secs": window,
        "n_ranks": cluster.get("n_ranks", 0),
        "ranks": ranks,
        "cluster": cd,
    }
    if qd is not None:
        model["shard_queue_depth"] = qd
    # autoscale controller status (tracker/autoscale.py registers it as
    # a report section; absent on fixed-fleet jobs)
    if isinstance(report.get("autoscale"), dict):
        model["autoscale"] = report["autoscale"]
    return model


def _bar(frac: float, width: int = 10) -> str:
    frac = max(0.0, min(1.0, float(frac)))
    n = int(round(frac * width))
    return "█" * n + "·" * (width - n)


def _fmt_rate(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.2f}M"
    if v >= 1e3:
        return f"{v / 1e3:.1f}k"
    return f"{v:.1f}"


def _render_top(model: dict, endpoint: str) -> str:
    lines = [
        f"dmlc top — {endpoint}  window={model['window_secs']:g}s  "
        f"ranks={model['n_ranks']}"
    ]
    cd = model.get("cluster") or {}
    summary = [f"cluster rows/s {_fmt_rate(cd.get('rows_per_sec', 0.0))}"]
    if "shard_queue_depth" in model:
        summary.append(
            f"shard queue {model['shard_queue_depth'].get('last', 0):g}"
        )
    for key, label in (
        ("block_cache_hit_rate", "blockcache hit"),
        ("decode_cache_hit_rate", "decode hit"),
    ):
        if key in cd:
            summary.append(f"{label} {cd[key] * 100:.0f}%")
    if "lookup_qps" in cd:
        p99 = cd.get("lookup_p99_ms")
        summary.append(
            f"lookup {cd['lookup_qps']:g} qps"
            + (f" p99 {p99:g}ms" if p99 is not None else "")
        )
    if "dsserve_slots_per_sec" in cd:
        dss = f"dsserve {cd['dsserve_slots_per_sec']:g} slots/s"
        if "dsserve_wire_ratio" in cd:
            dss += f" wire {cd['dsserve_wire_ratio'] * 100:.0f}%"
        if "dsserve_shm_frac" in cd:
            dss += f" shm {cd['dsserve_shm_frac'] * 100:.0f}%"
        summary.append(dss)
    if "stream_lag_seconds" in cd:
        # slowest follower across the fleet (merge_windows takes max)
        summary.append(
            f"stream lag {cd['stream_lag_seconds']:.2f}s"
            f"/{cd.get('stream_lag_records', 0):g} recs"
        )
    lines.append("  ".join(summary))
    asc = model.get("autoscale")
    if asc:
        parts = [
            f"autoscale fleet {asc.get('actual', 0)}→"
            f"{asc.get('target', 0)} "
            f"(bounds {asc.get('min_workers', 0)}:"
            f"{asc.get('max_workers', 0)})"
        ]
        last = asc.get("last") or {}
        if last:
            parts.append(
                f"last {last.get('kind', '?')} ({last.get('reason', '?')})"
            )
        ceiling = asc.get("cost_ceiling") or 0
        parts.append(
            f"cost {asc.get('cost_spent', 0.0):.0f}"
            + (f"/{ceiling:g} ws" if ceiling else " ws")
        )
        if asc.get("direction_changes"):
            parts.append(f"flaps {asc['direction_changes']}")
        lines.append("  ".join(parts))
    lines.append("")
    # the lag column only appears on streaming jobs — a sealed-corpus
    # top keeps its exact layout
    has_lag = any(
        "stream_lag_seconds" in r
        for r in (model.get("ranks") or {}).values()
    )
    lag_head = f"{'lag':>8}  " if has_lag else ""
    lines.append(f"{'rank':>8}  {'rows/s':>10}  {lag_head}stall by stage")
    for rank, r in (model.get("ranks") or {}).items():
        stalls = sorted(
            (r.get("stall_fraction") or {}).items(),
            key=lambda kv: -kv[1],
        )[:3]
        stall_txt = "  ".join(
            f"{stage} {_bar(frac)} {frac * 100:.0f}%"
            for stage, frac in stalls
            if frac > 0
        )
        # data-plane mix for ranks draining dsserve: wire bytes per raw
        # byte (codec win when < 100%) and the shm/tcp slot split
        extras = []
        if "dsserve_wire_ratio" in r:
            extras.append(f"wire {r['dsserve_wire_ratio'] * 100:.0f}%")
        if "dsserve_shm_frac" in r:
            extras.append(f"shm {r['dsserve_shm_frac'] * 100:.0f}%")
        if extras:
            stall_txt = "  ".join(filter(None, [stall_txt, *extras]))
        lag_txt = ""
        if has_lag:
            if "stream_lag_seconds" in r:
                lag_txt = f"{r['stream_lag_seconds']:.2f}s".rjust(8) + "  "
            else:
                lag_txt = f"{'-':>8}  "
        lines.append(
            f"{rank:>8}  {_fmt_rate(r.get('rows_per_sec', 0.0)):>10}  "
            f"{lag_txt}{stall_txt}"
        )
    return "\n".join(lines)


def _cmd_top(args) -> int:
    """Live terminal dashboard over the tracker's windowed telemetry
    (docs/observability.md "Time series"): polls
    ``/metrics.json?window=N`` and renders per-rank rows/s, the top
    stall stages as bars, shard queue depth, cache hit rates and
    service QPS/p99. ``--once`` renders one frame (``--json`` for the
    machine-readable model) — the scripted/tier-1 mode."""
    import json as _json
    import time as _time

    from ..io import retry as _retry

    endpoint = _top_endpoint(args.endpoint)
    url = f"{endpoint}/metrics.json?window={args.window:g}"

    def fetch() -> dict:
        with _retry.request(url, timeout=10.0) as resp:
            return _json.loads(resp.read().decode())

    if args.once:
        model = _top_model(fetch(), args.window)
        if args.json:
            print(_json.dumps(model, indent=1))
        else:
            print(_render_top(model, endpoint))
        return 0
    try:
        while True:
            frame = _render_top(_top_model(fetch(), args.window), endpoint)
            # clear + home, then the frame (plain ANSI — no curses dep)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            _time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


def _cmd_autoscale(args) -> int:
    """Offline surface for the elastic controller (tracker/autoscale.py,
    docs/autoscale.md):

    - ``replay <metrics-report.json>``: run the PURE decision function
      over the time series recorded in an end-of-job report
      (``DMLC_METRICS_REPORT``) and print the decisions it would have
      made — deterministic and offline, so thresholds/dwell/ceiling can
      be tuned against yesterday's job without rerunning it. The
      simulated fleet tracks the decisions, so the printed cost is the
      plan's worker×seconds spend.
    """
    import json as _json

    from ..tracker import autoscale as _as

    with open(args.report) as f:
        report = _json.load(f)
    ts = report.get("timeseries")
    if not isinstance(ts, dict) or not ts.get("per_rank"):
        print(
            "error: report has no retained time series — need the "
            "end-of-job DMLC_METRICS_REPORT shape (a run with DMLC_TS "
            "sampling on)",
            file=sys.stderr,
        )
        return 1
    lo, sep, hi = str(args.fleet).partition(":")
    try:
        cfg = _as.AutoscaleConfig(
            min_workers=int(lo),
            max_workers=int(hi if sep else lo),
            up_threshold=args.up,
            down_threshold=args.down,
            dwell_secs=args.dwell,
            cost_ceiling=args.cost_ceiling,
            interval=max(0.1, args.interval),
            window=max(0.5, args.window),
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    decisions = _as.replay(ts, cfg, include_holds=not args.actions_only)
    if args.json:
        print(_json.dumps(decisions, indent=1))
        return 0
    for d in decisions:
        print(
            f"t+{d['t']:8.1f}s  {d['kind']:<10} {d['reason']:<14} "
            f"target={d['target']}  input {d.get('input_stall', 0.0):.2f}  "
            f"compute {d.get('compute_stall', 0.0):.2f}  "
            f"queue {d.get('queue_depth', 0.0):g}  "
            f"cost {d['cost_spent']:.0f}ws"
        )
    kinds = {}
    for d in decisions:
        kinds[d["kind"]] = kinds.get(d["kind"], 0) + 1
    total = decisions[-1]["cost_spent"] if decisions else 0.0
    print(
        f"# {len(decisions)} decisions "
        f"({', '.join(f'{k} {n}' for k, n in sorted(kinds.items()))}); "
        f"plan cost {total:.0f} worker-seconds"
    )
    return 0


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.tools",
        description=__doc__.splitlines()[0],
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    ls = sub.add_parser("ls", help="list a directory URI")
    ls.add_argument("uri")
    ls.add_argument("-r", "--recursive", action="store_true")
    ls.set_defaults(fn=_cmd_ls)

    cat = sub.add_parser("cat", help="print a URI's bytes to stdout")
    cat.add_argument("uri")
    cat.set_defaults(fn=_cmd_cat)

    cp = sub.add_parser("cp", help="copy src URI to dst URI")
    cp.add_argument("src")
    cp.add_argument("dst")
    cp.set_defaults(fn=_cmd_cp)

    spl = sub.add_parser("split", help="stream one shard of a URI")
    spl.add_argument("uri")
    spl.add_argument("part", type=int)
    spl.add_argument("num_parts", type=int)
    spl.add_argument(
        "--type", default="text",
        choices=("text", "recordio", "indexed_recordio"),
    )
    spl.add_argument("--dump", action="store_true",
                     help="echo records to stdout")
    spl.set_defaults(fn=_cmd_split)

    def add_codec_opts(sp) -> None:
        from ..io.codec import available_codecs

        sp.add_argument(
            "--codec", default="none",
            choices=["none"] + available_codecs(),
            help="compress records into blocks (none = v1 frames)",
        )
        sp.add_argument("--level", default=None, type=int,
                        help="codec compression level (codec default)")

    rio = sub.add_parser("recordio", help="pack/unpack line records")
    rio.add_argument("action", choices=("pack", "unpack"))
    rio.add_argument("src")
    rio.add_argument("dst", nargs="?", default="",
                     help="output URI (pack); unpack prints to stdout")
    rio.add_argument("--index", default="",
                     help="also write a count index (pack only)")
    add_codec_opts(rio)
    rio.set_defaults(fn=_cmd_recordio)

    rr = sub.add_parser(
        "rowrec", help="convert a text dataset to rowrec .rec"
    )
    rr.add_argument("src", help="source URI (?format= honored)")
    rr.add_argument("dst", help="output .rec URI")
    rr.add_argument("--format", default="auto",
                    choices=("auto", "libsvm", "csv", "libfm"))
    rr.add_argument("--index", default="",
                    help="also write a count index")
    rr.add_argument("--part", default=0, type=int,
                    help="convert only this shard of src")
    rr.add_argument("--num-parts", default=1, type=int)
    add_codec_opts(rr)
    rr.set_defaults(fn=_cmd_rowrec)

    rcx = sub.add_parser(
        "recompress",
        help="convert a .rec between codecs in one stream pass",
    )
    rcx.add_argument("src", help="source .rec URI (v1 or compressed)")
    rcx.add_argument("dst", help="output .rec URI")
    rcx.add_argument("--index", default="",
                     help="write a fresh index sidecar for dst")
    rcx.add_argument(
        "--block-bytes", default=None, type=int,
        help="raw bytes buffered per compressed block",
    )
    add_codec_opts(rcx)
    # recompress compresses unless told otherwise; --codec none converts
    # a compressed file back to reference v1 frames
    rcx.set_defaults(fn=_cmd_recompress, codec="zlib")

    dp = sub.add_parser(
        "dump", help="decode a rowrec .rec back to libsvm text"
    )
    dp.add_argument("src", help=".rec URI (shardable)")
    dp.add_argument("--part", default=0, type=int)
    dp.add_argument("--num-parts", default=1, type=int)
    dp.add_argument("--limit", default=0, type=int,
                    help="stop after N rows (0 = all)")
    dp.set_defaults(fn=_cmd_dump)

    info = sub.add_parser(
        "info",
        help="runtime feature report (JSON); with a shard URI, also "
             "its index key count + block geometry",
    )
    info.add_argument(
        "uri", nargs="?", default="",
        help="optional indexed .rec URI to describe",
    )
    info.add_argument(
        "--index", default="",
        help="index sidecar URI (default <uri>.idx)",
    )
    info.set_defaults(fn=_cmd_info)

    sv = sub.add_parser(
        "serve", help="low-latency point-read daemon over an indexed shard"
    )
    sv.add_argument("uri", help="indexed .rec URI to serve")
    sv.add_argument(
        "--index", default="", help="index sidecar URI (default <uri>.idx)"
    )
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument(
        "--port", default=0, type=int, help="TCP port (0 = any free)"
    )
    sv.add_argument(
        "--port-file", default="",
        help="write a JSON readiness file naming the bound endpoint",
    )
    sv.add_argument(
        "--metrics-port", default=0, type=int,
        help="loopback /metrics port (0 = off)",
    )
    sv.add_argument(
        "--warm", default=0, type=int,
        help="prefetch the N hottest blocks before serving (0 = off)",
    )
    sv.set_defaults(fn=_cmd_serve)

    cd = sub.add_parser(
        "cached", help="host-level shared decoded-block cache daemon"
    )
    cd.add_argument("action", choices=["serve", "stats", "flush"])
    cd.add_argument(
        "--socket", default="",
        help="UNIX socket path (default: $DMLC_BLOCK_CACHE_SOCK or the "
             "uid-scoped temp-dir path)",
    )
    cd.add_argument(
        "--budget-mb", default=0, type=int,
        help="serve: total resident budget (default "
             "$DMLC_BLOCK_CACHE_MB or 1024)",
    )
    cd.add_argument(
        "--tenant-mb", default=0, type=int,
        help="serve: per-tenant byte quota (default the whole budget)",
    )
    cd.add_argument(
        "--metrics-port", default=0, type=int,
        help="serve: loopback /metrics port (0 = off)",
    )
    cd.set_defaults(fn=_cmd_cached)

    ds = sub.add_parser(
        "dsserve", help="disaggregated preprocessing worker (dsserve://)"
    )
    ds.add_argument("action", choices=["serve"])
    ds.add_argument("--host", default="127.0.0.1")
    ds.add_argument(
        "--port", default=0, type=int,
        help="listen port (0 = any free port; see --port-file)",
    )
    ds.add_argument(
        "--port-file", default="",
        help="write the bound endpoint here as JSON once listening "
             "(the dmlc-submit launcher's readiness signal)",
    )
    ds.add_argument(
        "--rank", default=None, type=int,
        help="shard-lease identity (default $DMLC_TASK_ID)",
    )
    ds.set_defaults(fn=_cmd_dsserve)

    tr = sub.add_parser(
        "trace", help="flight-recorder dump/merge/report (Perfetto)"
    )
    tr.add_argument("action", choices=["dump", "merge", "report"])
    tr.add_argument(
        "inputs", nargs="*",
        help="trace JSON files (merge: many; report: one)",
    )
    tr.add_argument(
        "--pid", default=0, type=int,
        help="dump: process to SIGUSR2 (it writes its own rings)",
    )
    tr.add_argument(
        "-o", "--out", default="",
        help="merge: output trace JSON path",
    )
    tr.add_argument(
        "--gap-ms", default=10.0, type=float,
        help="report: minimum wait-span duration counted as a "
             "starvation gap (default 10)",
    )
    tr.add_argument(
        "--json", action="store_true",
        help="report: emit the full report as JSON",
    )
    tr.add_argument(
        "--align-clocks", action="store_true",
        help="merge: shift each file's timestamps by its recorded "
             "heartbeat-RTT clock offset (multi-HOST runs; same-host "
             "files already share a wall clock)",
    )
    tr.set_defaults(fn=_cmd_trace)

    top = sub.add_parser(
        "top",
        help="live cluster dashboard over the tracker's "
             "/metrics.json?window= endpoint",
    )
    top.add_argument(
        "endpoint", nargs="?", default="",
        help="tracker metrics endpoint: URL, host:port or bare port "
             "(the tracker logs 'telemetry endpoint on ...' at start)",
    )
    top.add_argument(
        "--window", default=30.0, type=float,
        help="rate window in seconds (default 30)",
    )
    top.add_argument(
        "--interval", default=2.0, type=float,
        help="refresh interval in seconds (default 2)",
    )
    top.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (scripts/tests)",
    )
    top.add_argument(
        "--json", action="store_true",
        help="with --once: print the derived model as JSON",
    )
    top.set_defaults(fn=_cmd_top)

    asc = sub.add_parser(
        "autoscale",
        help="offline elastic-controller tools (replay recorded runs)",
    )
    asc.add_argument("action", choices=["replay"])
    asc.add_argument(
        "report",
        help="end-of-job metrics report JSON (DMLC_METRICS_REPORT)",
    )
    asc.add_argument(
        "--fleet", default="1:4", metavar="MIN:MAX",
        help="fleet bounds to simulate (default 1:4)",
    )
    asc.add_argument(
        "--up", default=0.40, type=float,
        help="input-stall fraction that triggers scale-up (default 0.40)",
    )
    asc.add_argument(
        "--down", default=0.10, type=float,
        help="input-stall fraction that triggers retire (default 0.10)",
    )
    asc.add_argument(
        "--dwell", default=10.0, type=float,
        help="minimum seconds between scale actions (default 10)",
    )
    asc.add_argument(
        "--cost-ceiling", default=0.0, type=float,
        help="worker-seconds budget (0 = unlimited)",
    )
    asc.add_argument(
        "--interval", default=2.0, type=float,
        help="controller tick to simulate (default 2)",
    )
    asc.add_argument(
        "--window", default=10.0, type=float,
        help="windowed-view width per decision (default 10)",
    )
    asc.add_argument(
        "--actions-only", action="store_true",
        help="print only scale actions, not holds",
    )
    asc.add_argument(
        "--json", action="store_true",
        help="emit the decision list as JSON",
    )
    asc.set_defaults(fn=_cmd_autoscale)

    ck = sub.add_parser(
        "ckpt", help="inspect/prune checkpoint directories (any URI)"
    )
    ck.add_argument("action", choices=["ls", "show", "prune"])
    ck.add_argument("base", help="checkpoint base URI")
    ck.add_argument("--step", type=int, default=None,
                    help="step for 'show' (default: newest)")
    ck.add_argument("--keep", type=int, default=3,
                    help="retention count for 'prune'")
    ck.set_defaults(fn=_cmd_ckpt)

    jr = sub.add_parser(
        "journal",
        help="inspect a tracker control-plane journal directory",
    )
    jr.add_argument("action", choices=["inspect"])
    jr.add_argument("dir", help="journal directory (--tracker-journal)")
    jr.add_argument("--json", action="store_true", default=False,
                    help="machine-readable dump")
    jr.set_defaults(fn=_cmd_journal)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (Error, OSError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
