#!/usr/bin/env python
"""AST lint gate for the repo (reference scripts/lint.py via
travis_script.sh:19-23 — the reference runs a pylint-style pass per
commit; this is the dependency-free equivalent for this tree).

Checks (each finding is `path:line: code message`, exit 1 on any):
  L001 unused import            (name imported but never referenced;
                                 `__all__` strings and re-export aliases
                                 like `import x as x` count as uses)
  L002 bare except              (`except:` hides SystemExit/KeyboardInterrupt;
                                 use `except Exception:` at minimum)
  L003 mutable default argument (def f(x=[]) shares state across calls)
  L004 f-string without placeholders (usually a forgotten format arg)
  L005 duplicate dict key       (silently drops the earlier value)
  L006 direct urlopen           (all remote HTTP must ride the transient-
                                 failure retry layer; io/retry.py owns the
                                 single urlopen call site and is exempt)
  L007 direct jax.device_put    (all host→device transfers must ride the
                                 coalesced staging layer; dmlc_core_tpu/
                                 staging/ owns the call sites and is
                                 exempt, tests/ may build device fixtures,
                                 and link probes opt out per line with
                                 `# noqa: L007`. Non-batch placements go
                                 through staging.device_put.)
  L008 time.time() in dmlc_core_tpu/ (durations measured with the wall
                                 clock go backwards under NTP slew; use
                                 time.perf_counter()/monotonic() — the
                                 telemetry histograms assume it. Genuine
                                 wall-clock sites — token/JWT expiry in
                                 io/cloudfs.py, job timestamps in
                                 tracker/tracker.py — opt out per line
                                 with `# noqa: L008`.)
  L009 direct compression import (zlib/gzip/zstandard/lz4 belong to the
                                 codec layer: io/codec.py owns the one
                                 compression site — registry, levels,
                                 block header/crc, decode pool, decoded-
                                 block cache — and is exempt; everything
                                 else compresses through it so telemetry
                                 and import guards can't be bypassed)
  L010 raw socket import in dmlc_core_tpu/io/ (the two sanctioned wire
                                 services own the socket sites:
                                 io/blockcache.py — control-plane
                                 framing, lease bookkeeping — and
                                 io/lookup.py — the point-read serve
                                 daemon — are exempt; everything else
                                 in io/ rides their clients so the
                                 fallback semantics and io.blockcache.*
                                 telemetry can't be bypassed. Genuine
                                 non-cache uses — retry.py's socket
                                 exception classification — opt out per
                                 line with `# noqa: L010`.)
  L019 shared-memory segment construction outside io/shm.py (imports
                                 of _posixshmem or multiprocessing.
                                 shared_memory, and alias-aware
                                 shm_open/shm_unlink/SharedMemory
                                 calls, anywhere in dmlc_core_tpu/:
                                 ShmSegment in io/shm.py is the one
                                 construction site — it owns the
                                 no-resource-tracker rationale
                                 (bpo-39959), explicit unlink
                                 lifecycle and the SIGKILL leak
                                 trade-off; blockcache and the dsserve
                                 same-host transport both ride it.
                                 File-backed mmap — io/split.py,
                                 staging/fused.py — is out of scope.)
  L011 Chrome trace-event literal in dmlc_core_tpu/ (the flight
                                 recorder owns trace-event emission and
                                 the trace-file format:
                                 telemetry/tracing.py — event schema,
                                 clock rebasing, drop accounting, the
                                 traceEvents container — and is exempt;
                                 everything else records through its
                                 span/instant/counter API so per-thread
                                 ordering and drop counters can't be
                                 bypassed. Flags dict literals shaped
                                 like an event ({"ph": ..., "ts": ...})
                                 or like the file ({"traceEvents": ...});
                                 reading those keys from a loaded trace
                                 is fine.)
  L013 rendezvous cmd string literal in dmlc_core_tpu/tracker/ (the
                                 wire protocol's command strings —
                                 start/recover/shutdown/print/metrics/
                                 shard_lease/shard_renew/shard_done/
                                 shard_release/watch —
                                 are spelled out in exactly one place:
                                 tracker/protocol.py's CMD_* constants.
                                 A literal elsewhere in tracker/ can
                                 typo into an unknown-cmd drop the
                                 protocol check never catches; compare
                                 and send the constants. Tests crafting
                                 raw frames live outside the scope.)
  L014 raw socket construction in dmlc_core_tpu/tracker/ (the wire
                                 layer owns TCP plumbing: protocol.py —
                                 listeners via make_listener /
                                 bind_first_free / find_free_port,
                                 dials via connect_worker /
                                 connect_peer — and collective.py (the
                                 peer-link data plane) are exempt; a
                                 socket.socket( / create_connection(
                                 elsewhere in tracker/ forks timeout
                                 and error-handling policy per call
                                 site. Genuine non-wire uses — the UDP
                                 route probe in get_host_ip — opt out
                                 per line with `# noqa: L014`.)
  L015 struct frame pack/unpack in dmlc_core_tpu/dsserve/ and
                                 dmlc_core_tpu/tracker/ (binary wire
                                 framing is a single-site concern: the
                                 dsserve slot-frame header lives in
                                 dsserve/wire.py, the rendezvous int/
                                 string frames in tracker/protocol.py,
                                 the collective's peer-link header in
                                 tracker/collective.py — those three
                                 are exempt. A struct.pack/unpack/
                                 Struct call elsewhere in either tree
                                 hand-rolls a frame that can drift
                                 field order or endianness against the
                                 sanctioned sites and corrupt every
                                 frame after it.)
  L012 thread-pool creation in dmlc_core_tpu/io/ (exactly two pools are
                                 sanctioned: codec.py's decode pool —
                                 sized by the cgroup/affinity-aware
                                 usable-CPU count, DMLC_DECODE_THREADS —
                                 and spanfetch.py's ranged-fetch pool —
                                 DMLC_FETCH_THREADS + the in-flight
                                 byte budget. An ad-hoc
                                 ThreadPoolExecutor/ThreadPool anywhere
                                 else in io/ bypasses the cgroup-aware
                                 sizing and the budget; route decode
                                 work through codec's pool and remote
                                 reads through SpanFetcher.)
  L017 trace-context encode/decode outside telemetry/tracing.py (the
                                 causal RPC trace context — 16-hex-
                                 digit trace/span ids, "trace-span" on
                                 the wire — is encoded and decoded in
                                 exactly one module: tracing.py's
                                 encode_context/decode_context. A
                                 hand-rolled 016x format or base-16
                                 int parse elsewhere in the wire-
                                 speaking trees (telemetry/, tracker/,
                                 dsserve/, io/, tools/, staging/) can
                                 drift the format and silently break
                                 every flow arrow; carry the context
                                 as the opaque string tracing hands
                                 out.)
  L018 journal CRC record framing outside tracker/journal.py (the
                                 tracker's crash-recovery WAL — CRC-
                                 framed, torn-tail-truncating — is
                                 written and verified in exactly one
                                 module: journal.py. A binascii.crc32/
                                 zlib.crc32 call elsewhere in
                                 dmlc_core_tpu/tracker/ starts a
                                 second checksum site whose framing
                                 can drift against the replay path and
                                 turn a recoverable journal into one
                                 strict replay refuses.)
  L016 socket-serving request loops in dmlc_core_tpu/io/ (exactly two
                                 modules are sanctioned servers there:
                                 blockcache.py — the shared-cache
                                 control plane — and lookup.py — the
                                 point-read serve daemon. A listen/
                                 accept/create_server elsewhere in io/
                                 forks connection lifecycle, frame
                                 hygiene and teardown policy per call
                                 site; serve through those two or live
                                 outside io/.)
  L020 stream manifest literal / tail-frame walk in dmlc_core_tpu/
                                 (the streaming commit point is a
                                 single-site concern: stream/
                                 manifest.py owns the "manifest.json"
                                 filename (MANIFEST_NAME), the atomic-
                                 rename read/write pair, and the
                                 decode_length-driven frame walks —
                                 whole_record_prefix / walk_frames /
                                 scan_committed_prefix / count_records
                                 — that decide where a growing shard's
                                 committed prefix ends. A filename
                                 literal elsewhere can drift against
                                 the publisher; a second frame walk
                                 can disagree about where the torn
                                 tail starts and read uncommitted
                                 bytes. Spell the name via
                                 MANIFEST_NAME and walk frames through
                                 manifest.py's helpers; docstrings
                                 mentioning the filename are fine.)

Run: python tools/lint.py [paths...]   (default: the repo's source roots)
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Iterator, List, Tuple

REPO = Path(__file__).resolve().parent.parent
DEFAULT_PATHS = [
    "dmlc_core_tpu",
    "tests",
    "benchmarks",
    "tools",
    "examples",
    "bench.py",
    "__graft_entry__.py",
]

Finding = Tuple[str, int, str, str]  # path, line, code, message


def _py_files(paths: List[str]) -> Iterator[Path]:
    for p in paths:
        path = (REPO / p) if not Path(p).is_absolute() else Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def _names_loaded(tree: ast.AST) -> set:
    """Every identifier the module references outside import statements,
    plus attribute roots (`os.path` uses `os`) and `__all__` strings."""
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                    for elt in ast.walk(node.value):
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            used.add(elt.value)
    return used


def _check_unused_imports(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    used = _names_loaded(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.partition(".")[0]
                if alias.asname == alias.name:
                    continue  # `import x as x` is a deliberate re-export
                if bound not in used:
                    yield node.lineno, f"unused import '{alias.name}'"
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                if alias.asname == alias.name:
                    continue  # PEP 484 re-export idiom
                bound = alias.asname or alias.name
                if bound not in used:
                    yield node.lineno, f"unused import '{alias.name}'"


def _check_bare_except(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield node.lineno, "bare 'except:' (catch Exception instead)"


def _check_mutable_defaults(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    yield default.lineno, (
                        f"mutable default argument in '{node.name}()'"
                    )


def _check_fstring_no_placeholder(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    # a FormattedValue's format_spec is itself a JoinedStr (usually all
    # constants, e.g. the ".4f" in f"{x:.4f}") — not a reportable f-string
    specs = {
        id(node.format_spec)
        for node in ast.walk(tree)
        if isinstance(node, ast.FormattedValue) and node.format_spec is not None
    }
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.JoinedStr)
            and id(node) not in specs
            and not any(isinstance(v, ast.FormattedValue) for v in node.values)
        ):
            yield node.lineno, "f-string without placeholders"


def _check_duplicate_dict_keys(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            seen = set()
            for key in node.keys:
                if isinstance(key, ast.Constant):
                    try:
                        hash(key.value)
                    except TypeError:
                        continue
                    if key.value in seen:
                        yield key.lineno, f"duplicate dict key {key.value!r}"
                    seen.add(key.value)


def _check_direct_urlopen(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call whose target is (or resolves to) urllib.request.urlopen.
    Catches ``urllib.request.urlopen(...)``, ``request.urlopen(...)``
    and a bare ``urlopen(...)`` bound by ``from urllib.request import
    urlopen`` (with or without an alias)."""
    aliases = {"urlopen"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "urllib.request":
            for alias in node.names:
                if alias.name == "urlopen":
                    aliases.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in aliases) or (
            isinstance(f, ast.Attribute) and f.attr == "urlopen"
        )
        if hit:
            yield node.lineno, (
                "direct urlopen call (route remote HTTP through the "
                "retry layer, io/retry.py)"
            )


def _check_direct_device_put(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call whose target resolves to jax's device_put: the staging
    layer (dmlc_core_tpu/staging/) owns every host→device transfer so
    batches always ride the coalesced single-DMA / packed-shard paths.
    Catches ``jax.device_put(...)``, any ``X.device_put(...)`` attribute
    call, and a bare ``device_put(...)`` bound by ``from jax import
    device_put`` (with or without an alias). The staging layer's own
    ``device_put`` wrapper imported as a bare name is NOT flagged — that
    wrapper is the sanctioned escape hatch for non-batch placements."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "device_put":
                    aliases.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in aliases) or (
            isinstance(f, ast.Attribute) and f.attr == "device_put"
        )
        if hit:
            yield node.lineno, (
                "direct device_put call (host→device transfers belong to "
                "the staging layer; import staging.device_put for "
                "non-batch placements)"
            )


def _check_wall_clock_time(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call resolving to ``time.time``: the ``<mod>.time(...)``
    attribute call where ``<mod>`` is the time module under any name
    (``import time`` / ``import time as t``) and a bare ``time(...)``
    bound by ``from time import time`` (with or without an alias).
    Scoped to dmlc_core_tpu/ (see lint_file): library code measuring
    durations must use perf_counter/monotonic; legitimate wall-clock
    reads opt out per line with ``# noqa: L008``."""
    fn_aliases = set()
    mod_aliases = {"time"}  # names the time MODULE is bound to
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    fn_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mod_aliases.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in fn_aliases) or (
            isinstance(f, ast.Attribute)
            and f.attr == "time"
            and isinstance(f.value, ast.Name)
            and f.value.id in mod_aliases
        )
        if hit:
            yield node.lineno, (
                "time.time() for measurement (use time.perf_counter()/"
                "monotonic(); wall-clock sites opt out with noqa: L008)"
            )


_CODEC_MODULES = ("zlib", "gzip", "zstandard", "lz4")


def _check_codec_imports(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any import binding a compression module (zlib/gzip/zstandard/lz4,
    incl. submodules like lz4.frame): compression is one layer
    (io/codec.py — codec registry, block header + crc, decode pool,
    decoded-block cache, telemetry), mirroring the L006 (urlopen) and
    L008 (time.time) single-site pattern."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.partition(".")[0]
                if root in _CODEC_MODULES:
                    yield node.lineno, (
                        f"direct import of {alias.name!r} (compression "
                        f"belongs to the codec layer, io/codec.py)"
                    )
        elif isinstance(node, ast.ImportFrom):
            root = (node.module or "").partition(".")[0]
            if node.level == 0 and root in _CODEC_MODULES:
                yield node.lineno, (
                    f"direct import from {node.module!r} (compression "
                    f"belongs to the codec layer, io/codec.py)"
                )


# files allowed to call urlopen directly: the retry layer itself (the
# leading '/' anchors the path segment — audio/retry.py is NOT exempt)
_L006_EXEMPT = ("/io/retry.py",)
# files allowed to import compression modules directly: the codec layer
_L009_EXEMPT = ("/io/codec.py",)
# L010 is SCOPED to dmlc_core_tpu/io/ and exempts the two sanctioned
# wire services: the block-cache daemon (UNIX-socket control plane) and
# the point-read serve daemon (TCP request loop, io/lookup.py)
_L010_SCOPE_DIRS = ("dmlc_core_tpu/io/",)
_L010_EXEMPT = ("/io/blockcache.py", "/io/lookup.py")
# L019 is scoped to the WHOLE library (a shm segment could plausibly be
# minted anywhere) and exempts the one sanctioned construction site
_L019_SCOPE_DIRS = ("dmlc_core_tpu/",)
_L019_EXEMPT = ("/io/shm.py",)
# L020 is scoped to the WHOLE library (a manifest path could be spelled
# anywhere a stream is opened) and exempts the one sanctioned site for
# the filename, the atomic read/write pair and the tail-frame walks.
# recordio.py DEFINES decode_length — definitions aren't imports, so it
# needs no exemption.
_L020_SCOPE_DIRS = ("dmlc_core_tpu/",)
_L020_EXEMPT = ("/stream/manifest.py",)
# L016 is scoped to dmlc_core_tpu/io/ and exempts the same two files —
# the only modules allowed to RUN a socket-serving request loop there
_L016_SCOPE_DIRS = ("dmlc_core_tpu/io/",)
_L016_EXEMPT = ("/io/blockcache.py", "/io/lookup.py")
# trees allowed to call jax.device_put directly: the staging layer owns
# the transfer call sites; tests build device-resident fixtures.
# Anchored against the REPO-RELATIVE path (a checkout living under e.g.
# /home/ci/tests/ must not exempt the whole repo); files outside the
# repo (lint_file called on scratch dirs, as the lint's own tests do)
# fall back to an absolute-path segment match.
_L007_EXEMPT_DIRS = ("dmlc_core_tpu/staging/", "tests/")
# L008 is SCOPED (not exempted): it only applies to library code under
# dmlc_core_tpu/ — benches and tests measure with perf_counter already,
# and scripts outside the library may legitimately want wall-clock
_L008_SCOPE_DIRS = ("dmlc_core_tpu/",)
# L011 is scoped to dmlc_core_tpu/ and exempts the flight recorder,
# which owns trace-event emission and the trace-file format
_L011_SCOPE_DIRS = ("dmlc_core_tpu/",)
_L011_EXEMPT = ("/telemetry/tracing.py",)
# L012 is scoped to dmlc_core_tpu/io/ and exempts the two sanctioned
# pool owners: the codec decode pool and the span-fetch pool
_L012_SCOPE_DIRS = ("dmlc_core_tpu/io/",)
_L012_EXEMPT = ("/io/codec.py", "/io/spanfetch.py")
# L013 is scoped to dmlc_core_tpu/tracker/ and exempts the protocol
# module, which owns the CMD_* constants. Kept in sync with
# protocol.RENDEZVOUS_CMDS by a test (tests/test_lint.py).
_L013_SCOPE_DIRS = ("dmlc_core_tpu/tracker/",)
_L013_EXEMPT = ("/tracker/protocol.py",)
# L014 is scoped to dmlc_core_tpu/tracker/ and exempts the two
# sanctioned wire modules: protocol.py (listeners + dials) and
# collective.py (the peer-link data plane)
_L014_SCOPE_DIRS = ("dmlc_core_tpu/tracker/",)
_L014_EXEMPT = ("/tracker/protocol.py", "/tracker/collective.py")
# L015 is scoped to the two trees that own binary wire protocols and
# exempts their sanctioned frame sites (the dsserve slot framing, the
# rendezvous int/string framing, the collective peer-link header)
_L015_SCOPE_DIRS = ("dmlc_core_tpu/dsserve/", "dmlc_core_tpu/tracker/")
_L015_EXEMPT = (
    "/dsserve/wire.py",
    "/tracker/protocol.py",
    "/tracker/collective.py",
    "/tracker/journal.py",
)
# L018 is scoped to dmlc_core_tpu/tracker/ and exempts the journal,
# which owns the WAL's CRC record framing (write AND verify sides)
_L018_SCOPE_DIRS = ("dmlc_core_tpu/tracker/",)
_L018_EXEMPT = ("/tracker/journal.py",)
# L017 is scoped to the wire-speaking trees (everywhere a trace
# context could plausibly be hand-rolled onto a protocol) and exempts
# the flight recorder, which owns the context encoding
_L017_SCOPE_DIRS = (
    "dmlc_core_tpu/telemetry/",
    "dmlc_core_tpu/tracker/",
    "dmlc_core_tpu/dsserve/",
    "dmlc_core_tpu/io/",
    "dmlc_core_tpu/tools/",
    "dmlc_core_tpu/staging/",
)
_L017_EXEMPT = ("/telemetry/tracing.py",)
_L013_CMDS = frozenset(
    {
        "start",
        "recover",
        "shutdown",
        "print",
        "metrics",
        "shard_lease",
        "shard_renew",
        "shard_done",
        "shard_release",
        "watch",
    }
)


def _check_rendezvous_cmd_literals(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any string constant spelling a rendezvous command: inside
    dmlc_core_tpu/tracker/ the wire command vocabulary lives in
    protocol.py's ``CMD_*`` constants (single-site pattern of
    L006/L008-L012) — a literal comparison or send elsewhere can typo
    into a silently-dropped unknown command. Scoped in lint_file;
    docstrings match only if the ENTIRE docstring is a command word,
    which no real docstring is."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value in _L013_CMDS
        ):
            yield node.lineno, (
                f"rendezvous cmd literal {node.value!r} (compare/send the "
                "CMD_* constants from tracker/protocol.py)"
            )

def _check_shm_socket_imports(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any import binding the ``socket`` module: inside
    dmlc_core_tpu/io/ the two sanctioned wire services (io/blockcache.py
    — UNIX-socket control plane — and io/lookup.py — the point-read
    serve daemon) own cross-process traffic, mirroring the
    L006/L008/L009 single-site pattern. Shared-memory construction is
    L019's business (io/shm.py). Scoped in lint_file."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.partition(".")[0] == "socket":
                    yield node.lineno, (
                        "direct socket import in io/ (cross-process "
                        "traffic belongs to io/blockcache.py and "
                        "io/lookup.py)"
                    )
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mod = node.module or ""
            if mod.partition(".")[0] == "socket":
                yield node.lineno, (
                    "direct socket import in io/ (cross-process "
                    "traffic belongs to io/blockcache.py and "
                    "io/lookup.py)"
                )


_SHM_CTORS = ("shm_open", "shm_unlink", "SharedMemory")


def _check_shm_segment_construction(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any import binding ``_posixshmem`` or ``multiprocessing.
    shared_memory`` (incl. ``from multiprocessing import
    shared_memory`` / ``from multiprocessing.shared_memory import
    SharedMemory``), and any call resolving to ``shm_open`` /
    ``shm_unlink`` / ``SharedMemory`` under any alias: inside
    dmlc_core_tpu/ shared-memory segment construction is one module —
    io/shm.py's ShmSegment, which owns the no-resource-tracker
    rationale (bpo-39959), the explicit create/attach/unlink lifecycle
    and the leak trade-off — mirroring the L006/L008-L018 single-site
    pattern. A second construction site forks segment naming and
    lifecycle policy; blockcache leases and the dsserve same-host
    transport both ride ShmSegment. File-backed ``mmap`` (io/split.py,
    staging/fused.py) is NOT this rule's business. Scoped in
    lint_file."""
    fn_aliases = set()
    mod_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.partition(".")[0] == "_posixshmem":
                    yield node.lineno, (
                        "direct _posixshmem import (segment construction "
                        "belongs to io/shm.py's ShmSegment)"
                    )
                    mod_aliases.add(alias.asname or "_posixshmem")
                elif alias.name.startswith("multiprocessing.shared_memory"):
                    yield node.lineno, (
                        "direct shared_memory import (shared segments "
                        "belong to io/shm.py's ShmSegment)"
                    )
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mod = node.module or ""
            if mod.partition(".")[0] == "_posixshmem":
                yield node.lineno, (
                    "direct _posixshmem import (segment construction "
                    "belongs to io/shm.py's ShmSegment)"
                )
                for alias in node.names:
                    if alias.name in _SHM_CTORS:
                        fn_aliases.add(alias.asname or alias.name)
            elif mod.startswith("multiprocessing.shared_memory") or (
                mod == "multiprocessing"
                and any(a.name == "shared_memory" for a in node.names)
            ):
                yield node.lineno, (
                    "direct shared_memory import (shared segments "
                    "belong to io/shm.py's ShmSegment)"
                )
                for alias in node.names:
                    if alias.name in _SHM_CTORS:
                        fn_aliases.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (
            isinstance(f, ast.Name)
            and (f.id in fn_aliases or f.id in ("shm_open", "shm_unlink"))
        ) or (
            isinstance(f, ast.Attribute)
            and (
                f.attr in ("shm_open", "shm_unlink")
                or (
                    f.attr == "SharedMemory"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in mod_aliases | {"shared_memory"}
                )
            )
        )
        if hit:
            yield node.lineno, (
                "shared-memory segment construction outside io/shm.py "
                "(shm_open/shm_unlink/SharedMemory belong to ShmSegment "
                "— a second site forks naming and lifecycle policy)"
            )


def _check_trace_event_literals(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Dict literals shaped like a Chrome trace event (both ``"ph"``
    and ``"ts"`` constant keys) or like the trace-file container (a
    ``"traceEvents"`` constant key): the flight recorder
    (telemetry/tracing.py) owns the event schema and the file format,
    mirroring the L006/L008-L010 single-site pattern — ad-hoc event
    dicts would fork the clock rebasing and dodge the ring's drop
    accounting. Scoped to dmlc_core_tpu/ in lint_file; reading those
    keys from a loaded trace (subscripts, .get) is not flagged."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        keys = {
            k.value
            for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        if "traceEvents" in keys:
            yield node.lineno, (
                "trace-file dict literal (the traceEvents container "
                "belongs to telemetry/tracing.py)"
            )
        elif "ph" in keys and "ts" in keys:
            yield node.lineno, (
                "Chrome trace-event dict literal (record through the "
                "flight-recorder API, telemetry/tracing.py)"
            )


_POOL_TYPES = ("ThreadPoolExecutor", "ProcessPoolExecutor", "ThreadPool")


def _check_thread_pool_creation(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call instantiating an executor/pool type (``ThreadPool
    Executor(...)``, ``futures.ThreadPoolExecutor(...)``,
    ``multiprocessing.pool.ThreadPool(...)`` — with or without an
    import alias): inside dmlc_core_tpu/io/ exactly two pools are
    sanctioned — codec.py's decode pool and spanfetch.py's ranged-fetch
    pool, both sized from the cgroup/affinity-aware usable-CPU count
    with documented env overrides. Scoped in lint_file; everything else
    in io/ must ride those so the sizing policy and the span fetcher's
    in-flight byte budget cannot be bypassed."""
    aliases = set(_POOL_TYPES)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in _POOL_TYPES and alias.asname:
                    aliases.add(alias.asname)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in aliases) or (
            isinstance(f, ast.Attribute) and f.attr in _POOL_TYPES
        )
        if hit:
            yield node.lineno, (
                "thread-pool creation in io/ (the decode pool in "
                "io/codec.py and the span-fetch pool in io/spanfetch.py "
                "are the sanctioned executors — ad-hoc pools bypass the "
                "cgroup-aware sizing and the in-flight byte budget)"
            )


_SOCKET_CTORS = ("socket", "create_connection")


def _check_socket_construction(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call constructing a TCP socket — ``socket.socket(...)`` /
    ``socket.create_connection(...)`` under any module alias, or the
    bare names bound by ``from socket import socket/create_connection``
    (with or without an alias): inside dmlc_core_tpu/tracker/ the wire
    layer is one place (protocol.py's make_listener / bind_first_free /
    find_free_port / connect_worker / connect_peer, and collective.py's
    peer-link data plane), mirroring the L006/L008-L013 single-site
    pattern — an ad-hoc socket forks connect/IO-timeout policy and
    error handling per call site. Scoped in lint_file; the UDP route
    probe opts out per line with ``# noqa: L014``."""
    fn_aliases = set()
    mod_aliases = {"socket"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "socket":
            for alias in node.names:
                if alias.name in _SOCKET_CTORS:
                    fn_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "socket":
                    mod_aliases.add(alias.asname or "socket")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in fn_aliases) or (
            isinstance(f, ast.Attribute)
            and f.attr in _SOCKET_CTORS
            and isinstance(f.value, ast.Name)
            and f.value.id in mod_aliases
        )
        if hit:
            yield node.lineno, (
                "raw socket construction in tracker/ (listeners/dials "
                "belong to tracker/protocol.py — make_listener, "
                "bind_first_free, find_free_port, connect_worker, "
                "connect_peer)"
            )


_STRUCT_FNS = ("pack", "unpack", "pack_into", "unpack_from", "Struct")


def _check_struct_framing(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call resolving to the struct module's pack/unpack/Struct —
    ``struct.pack(...)`` under any module alias, or the bare names
    bound by ``from struct import pack/Struct`` (with or without an
    alias): inside dmlc_core_tpu/dsserve/ and dmlc_core_tpu/tracker/
    the wire framing is a single-site concern (dsserve/wire.py's slot
    frames, protocol.py's int/string frames, collective.py's peer-link
    header), mirroring the L006/L008-L014 pattern — a second
    hand-rolled frame site can drift field order or endianness and
    corrupt every frame after it. Scoped in lint_file."""
    fn_aliases = set()
    mod_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "struct":
            for alias in node.names:
                if alias.name in _STRUCT_FNS:
                    fn_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "struct":
                    mod_aliases.add(alias.asname or "struct")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in fn_aliases) or (
            isinstance(f, ast.Attribute)
            and f.attr in _STRUCT_FNS
            and isinstance(f.value, ast.Name)
            and f.value.id in mod_aliases
        )
        if hit:
            yield node.lineno, (
                "struct frame pack/unpack outside the sanctioned wire "
                "modules (dsserve frames belong to dsserve/wire.py; "
                "tracker frames to protocol.py/collective.py)"
            )


def _check_socket_serving_loops(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call that makes a module a socket SERVER — ``.listen(...)``
    or ``.accept(...)`` on any object, or ``socket.create_server(...)``
    under any module alias (incl. the bare name bound by ``from socket
    import create_server``): inside dmlc_core_tpu/io/ exactly two
    request loops are sanctioned — the block-cache control plane
    (io/blockcache.py) and the point-read serve daemon (io/lookup.py),
    the L006/L008-L015 single-site pattern. Scoped in lint_file.
    Dialing out (connect/create_connection) is L010's business, not
    this rule's."""
    fn_aliases = set()
    mod_aliases = {"socket"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "socket":
            for alias in node.names:
                if alias.name == "create_server":
                    fn_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "socket":
                    mod_aliases.add(alias.asname or "socket")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in fn_aliases) or (
            isinstance(f, ast.Attribute)
            and (
                f.attr in ("accept", "listen")
                or (
                    f.attr == "create_server"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in mod_aliases
                )
            )
        )
        if hit:
            yield node.lineno, (
                "socket-serving request loop in io/ (servers there are "
                "confined to io/blockcache.py and io/lookup.py — a "
                "third loop forks connection lifecycle and frame "
                "hygiene per site)"
            )


def _check_trace_context_codec(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Trace-context encode/decode primitives outside their owner:
    the 16-hex-digit id format spec (any string literal containing the
    marker, covering f-strings, %-format and str.format alike — the
    spec constant of an f-string IS a string literal in the AST) and
    base-16 ``int(x, 16)`` parsing. Both are how a module would
    hand-roll telemetry/tracing.py's encode_context/decode_context;
    alias games don't apply (``int`` is a builtin, the format marker is
    a literal), so the two patterns are the whole surface. Scoped in
    lint_file; tracing.py itself is exempt."""
    hex16 = "016" + "x"  # not spelled whole, or this file flags itself
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and hex16 in node.value
        ):
            yield node.lineno, (
                "16-hex-digit trace-id formatting outside "
                "telemetry/tracing.py (use tracing.encode_context / "
                "rpc_context and carry the string opaquely)"
            )
        elif isinstance(node, ast.Call) and (
            isinstance(node.func, ast.Name) and node.func.id == "int"
        ):
            base = None
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                base = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "base" and isinstance(kw.value, ast.Constant):
                    base = kw.value.value
            if base == 16:
                yield node.lineno, (
                    "base-16 id parsing outside telemetry/tracing.py "
                    "(use tracing.decode_context; a second parser can "
                    "drift the wire format and silently break every "
                    "flow arrow)"
                )


_CRC_MODULES = ("binascii", "zlib")


def _check_journal_crc_framing(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Any call resolving to a crc32 — ``binascii.crc32(...)`` /
    ``zlib.crc32(...)`` under any module alias, or the bare name bound
    by ``from binascii import crc32`` (with or without an alias):
    inside dmlc_core_tpu/tracker/ the crash-recovery WAL's CRC record
    framing is a single-site concern (tracker/journal.py — the writer
    AND the strict/lenient readers), mirroring the L006/L008-L017
    pattern. A second checksum site can frame records the replay
    cannot verify — corruption indistinguishable from a real torn
    tail. Scoped in lint_file."""
    fn_aliases = set()
    mod_aliases = set(_CRC_MODULES)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in _CRC_MODULES:
            for alias in node.names:
                if alias.name == "crc32":
                    fn_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in _CRC_MODULES:
                    mod_aliases.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Name) and f.id in fn_aliases) or (
            isinstance(f, ast.Attribute)
            and f.attr == "crc32"
            and isinstance(f.value, ast.Name)
            and f.value.id in mod_aliases
        )
        if hit:
            yield node.lineno, (
                "journal CRC record framing outside tracker/journal.py "
                "(the WAL's checksum write/verify is confined there — "
                "a second crc32 site can drift the frame format "
                "against the replay path)"
            )


_MANIFEST_NAME = "manifest.json"


def _docstring_consts(tree: ast.Module) -> set:
    """id()s of the Constant nodes that are module/class/function
    docstrings — prose ABOUT the manifest is not a second spelling of
    its path."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _check_stream_manifest_framing(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Two spellings of the streaming commit point outside stream/
    manifest.py, mirroring the L006/L008-L019 single-site pattern:
    (a) a ``"manifest.json"`` string literal (incl. f-string parts) —
    hand-rolled manifest paths bypass the atomic-rename publisher and
    can drift the filename; import ``MANIFEST_NAME`` instead (the
    imported constant is the sanctioned alias and never flags); and
    (b) any import or alias-aware use of ``decode_length`` from the
    recordio module — the lrec length accessor only matters to a frame
    WALK (advance = 8 + pad4(length)), and tail-frame walks that
    decide where a growing shard's committed prefix ends live in
    manifest.py (whole_record_prefix / walk_frames /
    scan_committed_prefix / count_records). Sniffing a frame's FLAG
    (staging/fused.py's compression probe) doesn't need the length and
    stays quiet. Docstrings are ignored. Scoped in lint_file."""
    doc_ids = _docstring_consts(tree)
    lit_msg = (
        'stream manifest filename literal (the commit-point path is '
        "spelled once, stream/manifest.py's MANIFEST_NAME — a second "
        "spelling can drift against the atomic-rename publisher)"
    )
    walk_msg = (
        "RecordIO tail-frame walking outside stream/manifest.py "
        "(decode_length-driven walks decide where the committed "
        "prefix ends; a second walk can disagree about the torn "
        "tail and read uncommitted bytes — use manifest.py's "
        "whole_record_prefix/walk_frames/count_records)"
    )
    fn_aliases = set()
    mod_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.rpartition(".")[2] == "recordio":
                for alias in node.names:
                    if alias.name == "decode_length":
                        yield node.lineno, walk_msg
                        fn_aliases.add(alias.asname or alias.name)
            elif any(a.name == "recordio" for a in node.names):
                for alias in node.names:
                    if alias.name == "recordio":
                        mod_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.rpartition(".")[2] == "recordio":
                    mod_aliases.add(
                        alias.asname or alias.name.partition(".")[0]
                    )
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant):
            if (
                isinstance(node.value, str)
                and _MANIFEST_NAME in node.value
                and id(node) not in doc_ids
            ):
                yield node.lineno, lit_msg
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Name) and f.id in fn_aliases) or (
                isinstance(f, ast.Attribute)
                and f.attr == "decode_length"
                and isinstance(f.value, ast.Name)
                and f.value.id in mod_aliases
            ):
                yield node.lineno, walk_msg


CHECKS = [
    ("L001", _check_unused_imports),
    ("L002", _check_bare_except),
    ("L003", _check_mutable_defaults),
    ("L004", _check_fstring_no_placeholder),
    ("L005", _check_duplicate_dict_keys),
    ("L006", _check_direct_urlopen),
    ("L007", _check_direct_device_put),
    ("L008", _check_wall_clock_time),
    ("L009", _check_codec_imports),
    ("L010", _check_shm_socket_imports),
    ("L011", _check_trace_event_literals),
    ("L012", _check_thread_pool_creation),
    ("L013", _check_rendezvous_cmd_literals),
    ("L014", _check_socket_construction),
    ("L015", _check_struct_framing),
    ("L016", _check_socket_serving_loops),
    ("L017", _check_trace_context_codec),
    ("L018", _check_journal_crc_framing),
    ("L019", _check_shm_segment_construction),
    ("L020", _check_stream_manifest_framing),
]


def lint_file(path: Path) -> List[Finding]:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as exc:  # compileall also catches this; belt+braces
        return [(str(path), exc.lineno or 0, "L000", f"syntax error: {exc.msg}")]
    # `# noqa` on a statement's first line suppresses its findings
    # (flake8 convention; re-export blocks carry `# noqa: F401`)
    noqa_lines = {
        i
        for i, text in enumerate(src.splitlines(), start=1)
        if "# noqa" in text
    }
    out: List[Finding] = []
    rel = str(path.relative_to(REPO)) if path.is_relative_to(REPO) else str(path)
    posix = path.as_posix()
    in_repo = path.is_relative_to(REPO)
    rel_posix = rel.replace("\\", "/") if in_repo else None
    for code, fn in CHECKS:
        if code == "L006" and posix.endswith(_L006_EXEMPT):
            continue
        if code == "L009" and posix.endswith(_L009_EXEMPT):
            continue
        if code == "L007" and (
            rel_posix.startswith(_L007_EXEMPT_DIRS)
            if in_repo
            else any("/" + d in posix for d in _L007_EXEMPT_DIRS)
        ):
            continue
        if code == "L008" and not (
            rel_posix.startswith(_L008_SCOPE_DIRS)
            if in_repo
            else any("/" + d in posix for d in _L008_SCOPE_DIRS)
        ):
            continue
        if code == "L010":
            if posix.endswith(_L010_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L010_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L010_SCOPE_DIRS)
            ):
                continue
        if code == "L011":
            if posix.endswith(_L011_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L011_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L011_SCOPE_DIRS)
            ):
                continue
        if code == "L012":
            if posix.endswith(_L012_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L012_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L012_SCOPE_DIRS)
            ):
                continue
        if code == "L013":
            if posix.endswith(_L013_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L013_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L013_SCOPE_DIRS)
            ):
                continue
        if code == "L014":
            if posix.endswith(_L014_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L014_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L014_SCOPE_DIRS)
            ):
                continue
        if code == "L015":
            if posix.endswith(_L015_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L015_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L015_SCOPE_DIRS)
            ):
                continue
        if code == "L016":
            if posix.endswith(_L016_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L016_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L016_SCOPE_DIRS)
            ):
                continue
        if code == "L017":
            if posix.endswith(_L017_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L017_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L017_SCOPE_DIRS)
            ):
                continue
        if code == "L018":
            if posix.endswith(_L018_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L018_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L018_SCOPE_DIRS)
            ):
                continue
        if code == "L019":
            if posix.endswith(_L019_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L019_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L019_SCOPE_DIRS)
            ):
                continue
        if code == "L020":
            if posix.endswith(_L020_EXEMPT):
                continue
            if not (
                rel_posix.startswith(_L020_SCOPE_DIRS)
                if in_repo
                else any("/" + d in posix for d in _L020_SCOPE_DIRS)
            ):
                continue
        for line, msg in fn(tree):
            if line not in noqa_lines:
                out.append((rel, line, code, msg))
    return out


def main(argv: List[str]) -> int:
    paths = argv or DEFAULT_PATHS
    findings: List[Finding] = []
    n_files = 0
    for f in _py_files(paths):
        if "__pycache__" in f.parts:
            continue
        n_files += 1
        findings.extend(lint_file(f))
    findings.sort()
    for path, line, code, msg in findings:
        print(f"{path}:{line}: {code} {msg}")
    print(
        f"lint: {n_files} files, {len(findings)} finding(s)",
        file=sys.stderr,
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
