"""Online learning: paced generator → live RecordIO stream → jitted FM.

The writer appends fixed-K rows to a growing stream directory
(stream/writer.py: codec blocks, durable watermark commits, size
rotation); the trainer follows the manifest LIVE through the same
``create()`` factory every sealed dataset uses — windowed shuffle
inside the committed watermark, rotation as an epoch boundary, clean
EOS (docs/streaming.md).

Single process (demo):  python examples/train_online_fm.py
    spawns the generator as a thread and trains while it writes.

Two terminals (real deployment shape):
    python examples/train_online_fm.py --produce /tmp/fm_stream
    python examples/train_online_fm.py /tmp/fm_stream

Multi-worker trainers (tracker-leased micro-shards, exactly-once):
    ./dmlc-submit --cluster local --num-workers 2 \
        python examples/train_online_fm.py /tmp/fm_stream

Env knobs: DMLC_STREAM_MAX_LAG caps how far the writer may run ahead
of the slowest acked reader (docs/streaming.md); DMLC_STREAM_POLL sets
the tail poll cadence.
"""

import os
import struct
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_FEATURES = 1 << 12
K = 8  # nnz per row, fixed — rows pack to one flat struct
B = 256
_ROW = struct.Struct("<f" + "I" * K + "f" * K)  # label, idx[K], val[K]


def make_row(rng: np.random.Generator, w: np.ndarray) -> bytes:
    idx = rng.integers(0, N_FEATURES, K, dtype=np.uint32)
    val = rng.uniform(0, 1, K).astype(np.float32)
    label = float((w[idx] * val).sum() > 0)
    return _ROW.pack(label, *idx.tolist(), *val.tolist())


def produce(dir_path: str, rows: int = 8000, rows_per_sec: float = 4000.0):
    """The generator: paced appends with periodic durable commits and
    size rotation — each sealed shard is an ordinary indexed RecordIO
    file any offline job can read."""
    from dmlc_core_tpu.stream import StreamWriter

    rng = np.random.default_rng(0)
    w = rng.normal(size=N_FEATURES) / np.sqrt(K)
    chunk = max(1, int(rows_per_sec * 0.01))
    with StreamWriter(
        dir_path, codec="zlib", rotate_bytes=64 << 10, commit_records=200
    ) as writer:
        for i in range(rows):
            writer.append(make_row(rng, w))
            if i % chunk == chunk - 1:
                time.sleep(0.01)
    print(f"producer: {rows} rows appended, stream sealed (EOS)")


def to_batch(rows: list) -> dict:
    """Pack parsed rows into one fixed-shape ELL batch; short tails pad
    with weight 0 (weighted_mean ignores padding)."""
    import jax.numpy as jnp

    n = len(rows)
    idx = np.zeros((B, K), np.int32)
    val = np.zeros((B, K), np.float32)
    lab = np.zeros(B, np.float32)
    wgt = np.zeros(B, np.float32)
    for r, rec in enumerate(rows):
        f = _ROW.unpack(rec)
        lab[r] = f[0]
        idx[r] = f[1 : 1 + K]
        val[r] = f[1 + K :]
        wgt[r] = 1.0
    return {
        "indices": jnp.asarray(idx),
        "values": jnp.asarray(val),
        "labels": jnp.asarray(lab),
        "weights": jnp.asarray(wgt),
    }


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--produce":
        produce(sys.argv[2] if len(sys.argv) > 2 else "/tmp/fm_stream")
        return

    import jax

    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.models import FactorizationMachine

    dir_path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/fm_stream_demo"

    # under dmlc-submit, rendezvous like any dmlc worker; the stream
    # itself is shared — workers pull tracker-leased micro-shards
    worker = None
    if os.environ.get("DMLC_TRACKER_URI"):
        from dmlc_core_tpu.tracker.client import RabitWorker

        worker = RabitWorker()
        rank = worker.start()
    else:
        rank = 0
        if len(sys.argv) < 2:
            # demo mode: nobody is writing yet — spawn the generator
            import shutil
            import threading

            shutil.rmtree(dir_path, ignore_errors=True)
            os.makedirs(dir_path, exist_ok=True)
            threading.Thread(
                target=produce, args=(dir_path,), daemon=True
            ).start()

    model = FactorizationMachine(N_FEATURES, embed_dim=8)
    params = model.init(jax.random.PRNGKey(0))
    step = jax.jit(lambda p, b: model.sgd_step(p, b, lr=0.1))

    # the manifest URI routes create() to a live StreamSource: shuffle
    # happens in aligned windows WITHIN the committed watermark, so the
    # drain is deterministic given (seed, rotation history). Multi-
    # worker follows add &dynamic_shards=1 (leased micro-shards).
    uri = dir_path + "/manifest.json?shuffle=window&window=1024&seed=7"
    if worker is not None:
        uri += "&dynamic_shards=1"
    src = io_split.create(uri, threaded=False)

    seen, gstep, loss = 0, 0, None
    last_gen = 0
    t0 = time.monotonic()
    while True:
        chunk = src.next_batch(B)
        if chunk is None:
            break  # EOS: writer closed and every committed row drained
        rows = list(src.extract_records(chunk))
        params, loss = step(params, to_batch(rows))
        seen += len(rows)
        gstep += 1
        gen = getattr(src, "generation", 0)
        if gen != last_gen:
            # rotation = dataset switch: the sealed shard is final
            print(f"rank {rank}: rotated into generation {gen}")
            last_gen = gen
        if gstep % 10 == 0:
            print(
                f"rank {rank} step {gstep}: loss={float(loss):.4f} "
                f"rows={seen} lag={src.lag_seconds():.2f}s"
            )
    dt = time.monotonic() - t0
    loss_str = "n/a" if loss is None else f"{float(loss):.4f}"
    print(
        f"rank {rank}: stream drained — {seen} rows in {dt:.1f}s "
        f"({seen / max(dt, 1e-9):,.0f} rows/s), final loss={loss_str}"
    )
    src.close()
    if worker is not None:
        worker.shutdown()


if __name__ == "__main__":
    main()
