"""End-to-end Criteo-style path: rowrec RecordIO → fused ELL staging →
jitted factorization machine, with checkpoint resume.

This is the RecordIO north-star pipeline (BASELINE.md): rows are stored
pre-parsed in reference-bit-compatible RecordIO frames (data/rowrec.py),
the fused native kernel scans frames straight into packed ELL batch
rings, and each batch rides one DMA into HBM.

Single host:   python examples/train_criteo_rec.py [/path/to/data.rec]
Multi-process: ./dmlc-submit --cluster local --num-workers 2 \
                   python examples/train_criteo_rec.py /path/to/data.rec

Under dmlc-submit with >1 worker this is TRUE multi-host SGD
(docs/collectives.md): each rank computes gradients over its own shard,
the per-step gradients are summed across ranks by the tracker-topology
collective engine (tracker/collective.py allreduce) together with a
contributor count (so uneven shard tails average over the ranks that
still have data), and every rank applies the identical shared update —
params stay bit-identical across ranks by construction. Fault
tolerance is rabit-style: the model is lazily checkpointed IN MEMORY
every SAVE_EVERY steps (``Collective.checkpoint``); a worker the
supervisor relaunches bootstraps params + position from a live peer
(``load_checkpoint``), replays the missed rounds through the
survivors' result caches, and rejoins the live round — final model
equal to a run with no kills (the chaos drill in tests/ pins this).

Env knobs (collective mode): DMLC_SGD_PATH=tree|ring pins the
allreduce path (default: size-based; pin ``tree`` when a chaos run
must be bit-identical to a clean one — faulted ring rounds retry over
the tree, whose float-sum fold order differs by rounding),
DMLC_SGD_OUT=<path> writes each rank's final params to
``<path>.rank<N>.npz`` (what the drill compares), DMLC_SGD_EPOCHS.

Generates a small synthetic shard when no path is given.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_FEATURES = 1 << 14
K = 39  # 13 dense + 26 categorical, Criteo-shaped


def synth(path: str, rows: int = 20000) -> None:
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec
    from dmlc_core_tpu.io.stream import FileStream

    rng = np.random.default_rng(0)
    idx = np.empty((rows, K), dtype=np.uint32)
    idx[:, :13] = np.arange(13)
    idx[:, 13:] = rng.integers(13, N_FEATURES, (rows, 26))
    val = np.ones((rows, K), dtype=np.float32)
    val[:, :13] = rng.uniform(0, 1, (rows, 13))
    w = rng.normal(size=N_FEATURES) / np.sqrt(K)
    logits = (w[idx] * val).sum(axis=1)
    labels = (logits > 0).astype(np.float32)
    blk = RowBlock(
        offset=np.arange(rows + 1, dtype=np.int64) * K,
        label=labels,
        index=idx.reshape(-1),
        value=val.reshape(-1),
    )
    # the sidecar index enables count-exact sharding + shuffled epochs
    # via `?index=<uri>&shuffle=1` (reference indexed_recordio semantics).
    # multi-worker launches race through synth: write to per-process temp
    # names, then atomically publish the index FIRST, so a worker that
    # sees the data file always sees a complete index (content is
    # deterministic, so concurrent publishers agree)
    tmp, itmp = f"{path}.tmp{os.getpid()}", f"{path}.idx.tmp{os.getpid()}"
    with FileStream(tmp, "w") as f, FileStream(itmp, "w") as fi:
        write_rowrec(f, [blk], index_stream=fi)
    os.replace(itmp, path + ".idx")
    os.replace(tmp, path)


def shard_sizes(n_total: int, world: int) -> list:
    """Per-rank record counts under the splitter's ceil-division count
    sharding (io/split.py reset_partition): when ``n_total % world !=
    0`` the tail ranks own fewer records, so one rank's consumed count
    is NOT a valid resume position for another."""
    nstep = -(-n_total // world)
    return [
        max(0, min((r + 1) * nstep, n_total) - min(r * nstep, n_total))
        for r in range(world)
    ]


def index_count(idx_path: str) -> int:
    with open(idx_path) as f:
        return sum(1 for line in f if line.strip())


def pack_state(params, gstep: int, epoch: int, consumed: int) -> bytes:
    """Serialize (params, data position) for the in-memory peer
    checkpoint (``Collective.checkpoint`` — rabit lazy_checkpoint): one
    npz blob a bootstrapping peer can adopt wholesale."""
    import io

    buf = io.BytesIO()
    np.savez(
        buf, gstep=gstep, epoch=epoch, consumed=consumed,
        **{"p_" + k: np.asarray(v) for k, v in params.items()},
    )
    return buf.getvalue()


def unpack_state(state: bytes):
    import io

    import jax.numpy as jnp

    z = np.load(io.BytesIO(state))
    params = {
        k[2:]: jnp.asarray(z[k]) for k in z.files if k.startswith("p_")
    }
    return params, int(z["gstep"]), int(z["epoch"]), int(z["consumed"])


def main() -> None:
    import jax

    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.models import FactorizationMachine, sgd_update
    from dmlc_core_tpu.staging import (
        BatchSpec,
        StagingPipeline,
        drain_close,
        ell_batches,
    )

    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/criteo_demo.rec"
    if not os.path.exists(path):
        print(f"generating synthetic rowrec shard at {path}")
        synth(path)

    # under dmlc-submit, join the tracker rendezvous like any dmlc
    # worker: the tracker assigns the rank we shard by (and relaunched
    # workers reclaim theirs); standalone runs shard by env/defaults
    worker = None
    if os.environ.get("DMLC_TRACKER_URI"):
        from dmlc_core_tpu.tracker.client import RabitWorker

        worker = RabitWorker()
        rank = worker.start()
        world = worker.world_size
    else:
        rank = int(os.environ.get("DMLC_TASK_ID", 0))
        world = int(os.environ.get("DMLC_NUM_WORKER", 1))
    model = FactorizationMachine(N_FEATURES, embed_dim=8)
    params = model.init(jax.random.PRNGKey(0))
    lr = 0.1
    step = jax.jit(lambda p, b: model.sgd_step(p, b, lr=lr))

    # multi-worker under the tracker: true multi-host SGD — per-rank
    # gradients summed across ranks by the collective engine, one
    # shared update per step (docs/collectives.md)
    coll = None
    if worker is not None and world > 1:
        from dmlc_core_tpu.tracker.collective import Collective

        coll = Collective(worker)
        sgd_path = os.environ.get("DMLC_SGD_PATH") or None
        grad_fn = jax.jit(model.loss_and_grads)
        apply_fn = jax.jit(lambda p, g: sgd_update(p, g, lr))

    gstep, start_epoch, skip = 0, 0, 0
    ck = None
    if coll is None:
        # v2: steps are global BATCH counts with (epoch, records)
        # metadata — a fresh directory, so checkpoints from the older
        # epoch-numbered layout can't be misread as positions
        ck = Checkpointer(
            "/tmp/criteo_ckpts_v2", keep=2, process_index=rank
        )
        # resume: params + the DATA POSITION (epoch, records consumed)
        # the save recorded — a mid-epoch preemption fast-forwards into
        # the same shuffled epoch instead of replaying or skipping rows
        # (§5.4)
        start = ck.latest_step()
        if start is not None:
            gstep, params = ck.restore(start)
            pos = ck.restore_meta(start)
            if pos is not None:
                start_epoch = int(pos["epoch"])
                rec = pos["records"]
                # per-rank dict (current layout) or a bare count (older
                # checkpoints: rank 0's count — only exact when every
                # shard has the same size)
                if isinstance(rec, dict):
                    skip = int(rec.get(str(rank), 0))
                else:
                    skip = int(rec)
                print(
                    f"rank {rank}: resumed step {gstep} at epoch "
                    f"{start_epoch}, {skip} records in"
                )
            else:
                # no position recorded (crash before the sidecar
                # landed): conservative fallback — keep the params,
                # replay from epoch 0 rather than risk skipping data
                print(
                    f"rank {rank}: resumed step {gstep}; no data "
                    f"position recorded, replaying from epoch 0"
                )
    elif int(os.environ.get("DMLC_NUM_ATTEMPT", "0") or 0) > 0:
        # rabit-style relaunch: no disk restore — bootstrap params AND
        # the data position from a live peer's in-memory checkpoint,
        # then replay the missed rounds through the survivors' result
        # caches (the engine fast-forwarded its round clock to the
        # checkpoint). A fresh job (attempt 0) skips the ask: nobody
        # has state yet, and peers may not be pumping frames.
        version, state = coll.load_checkpoint()
        if state:
            params, gstep, start_epoch, skip = unpack_state(state)
            print(
                f"rank {rank}: bootstrapped from peer at version "
                f"{version} (step {gstep}, epoch {start_epoch}, "
                f"{skip} records in)"
            )

    B = 2048
    SAVE_EVERY = 4  # batches between mid-epoch position checkpoints
    spec = BatchSpec(batch_size=B, layout="ell", max_nnz=K)
    # DMLC_DYNAMIC_SHARDS=1: tracker-leased micro-shard placement
    # (docs/sharding.md) — a straggling host drains fewer shards
    # instead of gating the epoch. Needs the tracker rendezvous, and
    # resume-by-position is static-only (mid-epoch resume under
    # leasing is ledger-owned: completed micro-shards are simply not
    # re-served), so the skip fast-forward and the per-rank position
    # sidecars are skipped in this mode.
    dynamic = (
        os.environ.get("DMLC_DYNAMIC_SHARDS", "0") not in ("", "0")
        and worker is not None
    )
    # with a sidecar index, shards are count-exact and each epoch reads
    # in a fresh shuffled order (URI sugar → IndexedRecordIOSplitter);
    # without one, fall back to sequential byte-sharded reads
    has_index = os.path.exists(path + ".idx")
    sizes = shard_sizes(index_count(path + ".idx"), world) if has_index else []
    epochs = int(os.environ.get("DMLC_SGD_EPOCHS", "3"))
    for epoch in range(start_epoch, epochs):
        # shuffle=batch: permuted SPANS of batch_size records, one
        # coalesced seek per span — sequential-read throughput at
        # shuffle granularity batch_size (shuffle=1 would be the
        # reference's per-record-seek full permutation). The permutation
        # derives from (seed, epoch), so `epoch=`/`skip_records=` land a
        # resume on the exact record the crash interrupted.
        uri = (
            f"{path}?index={path}.idx&shuffle=batch&batch_size={B}"
            f"&seed=1&epoch={epoch}"
            + (
                "&dynamic_shards=1"
                if dynamic
                else (f"&skip_records={skip}" if skip else "")
            )
            if has_index
            else path
        )
        stream = ell_batches(uri, spec, part_index=rank, num_parts=world)
        pipe = StagingPipeline(stream)
        loss = None
        consumed, skip = skip, 0
        if coll is not None:
            # distributed step: allreduce [grads, have-data flag] as ONE
            # round; the flag sum says how many ranks contributed, so
            # uneven shard tails average over the ranks still holding
            # data and the epoch ends when the sum hits zero — every
            # rank agrees on both, because every rank sees the same
            # reduced vector. Ranks with exhausted shards keep calling
            # with zeros: allreduce is collective, a rank that stopped
            # calling would wedge the others.
            import jax.numpy as jnp
            from jax.flatten_util import ravel_pytree

            flat0, unravel = ravel_pytree(params)
            dim = flat0.size
            it = iter(pipe)
            while True:
                batch = next(it, None)
                if batch is not None:
                    loss, grads = grad_fn(params, batch)
                    vec = np.concatenate([
                        np.asarray(ravel_pytree(grads)[0], np.float32),
                        np.ones(1, np.float32),
                    ])
                else:
                    vec = np.zeros(dim + 1, np.float32)
                summed = coll.allreduce(vec, "sum", path=sgd_path)
                n_contrib = float(summed[-1])
                if n_contrib == 0:
                    break  # every rank drained its shard: epoch done
                params = apply_fn(
                    params, unravel(jnp.asarray(summed[:-1] / n_contrib))
                )
                gstep += 1
                if batch is not None:
                    consumed += int(
                        (np.asarray(batch["weights"]) > 0).sum()
                    )
                # in-memory peer checkpoint at span-aligned positions:
                # the replay window a relaunched peer needs is bounded
                # by SAVE_EVERY, which must stay <= DMLC_COLLECTIVE_CACHE
                if (
                    has_index and not dynamic
                    and gstep % SAVE_EVERY == 0 and consumed % B == 0
                ):
                    coll.checkpoint(
                        pack_state(params, gstep, epoch, consumed),
                        version=gstep,
                    )
        else:
            for batch in pipe:
                params, loss = step(params, batch)
                consumed += int((np.asarray(batch["weights"]) > 0).sum())
                gstep += 1
                # mid-epoch position checkpoint: only at span-aligned
                # positions (a padded tail batch is not resumable-into;
                # the epoch-end save right below covers it). Rank 0
                # writes the positions of EVERY rank, keyed by rank:
                # when ntotal % world != 0 the tail ranks' shards are
                # smaller, so rank 0's count clamped to each shard's
                # size is that rank's position (a B-multiple is never
                # strictly inside a smaller shard's tail span, and a
                # rank whose shard is already exhausted resumes at its
                # total = skip-everything).
                if (
                    has_index and not dynamic and gstep % SAVE_EVERY == 0
                    and consumed % B == 0
                ):
                    ck.save_async(
                        gstep, params,
                        meta={
                            "epoch": epoch,
                            "records": {
                                str(r): min(consumed, sizes[r])
                                for r in range(world)
                            },
                        },
                    )
        stats = pipe.throughput()
        loss_str = "n/a (empty shard)" if loss is None else f"{float(loss):.4f}"
        print(
            f"rank {rank} epoch {epoch}: loss={loss_str} "
            f"({stats['rows_per_sec']:,.0f} rows/s, "
            f"{stats['mb_per_sec']:,.0f} MB/s into device)"
        )
        # pipeline first, source second, honoring close_timed_out
        drain_close(pipe, stream)
        # epoch boundary: next resume starts the following epoch clean.
        if coll is not None:
            coll.checkpoint(
                pack_state(params, gstep, epoch + 1, 0), version=gstep
            )
        else:
            # async: the write overlaps the next epoch's training;
            # ck.save/restore/wait all drain it, and the final wait()
            # below surfaces any background write failure before we
            # declare success
            ck.save_async(
                gstep, params, meta={"epoch": epoch + 1, "records": 0}
            )
    if ck is not None:
        ck.wait()
        print("latest checkpoint step:", ck.latest_step())
    out = os.environ.get("DMLC_SGD_OUT")
    if out:
        # per-rank final params (atomic publish) — in collective mode
        # every rank's file holds the SAME bytes (the chaos drill pins
        # cross-rank AND kill-vs-clean equality on these)
        tmp = f"{out}.rank{rank}.npz.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(
                f, gstep=gstep,
                **{k: np.asarray(v) for k, v in params.items()},
            )
        os.replace(tmp, f"{out}.rank{rank}.npz")
    if coll is not None:
        coll.close()
    if worker is not None:
        worker.shutdown()


if __name__ == "__main__":
    main()
