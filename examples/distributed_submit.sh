#!/bin/bash
# Launch patterns for dmlc-submit (reference tracker/dmlc-submit usage).
set -e
cd "$(dirname "$0")/.."

# 2 local workers with rendezvous (each reads its shard of the data)
./dmlc-submit --cluster local --num-workers 2 --host-ip 127.0.0.1 \
    python examples/train_higgs.py /tmp/higgs_demo.libsvm

# what a TPU pod launch would run (printed, not executed):
./dmlc-submit --cluster tpu-pod --num-workers 4 --dry-run \
    --tpu-name my-pod --tpu-zone us-central2-b \
    python examples/train_higgs.py gs://bucket/higgs.libsvm
