"""Parameter module walkthrough (reference example/parameter.cc).

Run: python examples/parameter_demo.py learning_rate=0.1 name=demo
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dmlc_core_tpu.params.parameter import Parameter, field


class TrainParam(Parameter):
    learning_rate = field(float, default=0.01, lower=0.0, help="Step size.")
    num_hidden = field(int, default=128, lower=1, upper=4096, help="Hidden units.")
    activation = field(
        str,
        default="relu",
        enum={"relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh"},
        help="Nonlinearity.",
    )
    name = field(str, required=True, help="Run name.")
    silent = field(bool, default=False, aliases=("quiet",), help="Mute logs.")


def main() -> None:
    kwargs = dict(kv.split("=", 1) for kv in sys.argv[1:])
    p = TrainParam()
    p.init(kwargs)
    print("initialized:", p.to_dict())
    print("\ngenerated docs:\n" + TrainParam.doc())
    print("json round-trip:", p.save_json())


if __name__ == "__main__":
    main()
