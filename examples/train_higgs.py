"""End-to-end: libsvm file → sharded parse → fixed-shape batches → TPU →
jitted logistic regression, with checkpointing.

Single host:   python examples/train_higgs.py /path/to/data.libsvm
Multi-process: launch via dmlc-submit (each rank reads its shard):
    ./dmlc-submit --cluster local --num-workers 2 \
        python examples/train_higgs.py /path/to/data.libsvm

Generates a small synthetic file when no path is given.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def synth(path: str, rows: int = 20000, d: int = 28) -> None:
    rng = np.random.default_rng(0)
    w = rng.normal(size=d)
    with open(path, "w") as f:
        for _ in range(rows):
            x = rng.normal(size=d)
            y = int(x @ w > 0)
            feats = " ".join(f"{j}:{x[j]:.5f}" for j in range(d))
            f.write(f"{y} {feats}\n")


def main() -> None:
    import jax

    from dmlc_core_tpu import data as D
    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.models import LogisticRegression
    from dmlc_core_tpu.staging import (
        BatchSpec,
        FixedShapeBatcher,
        StagingPipeline,
        drain_close,
    )

    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/higgs_demo.libsvm"
    if not os.path.exists(path):
        print(f"generating synthetic data at {path}")
        synth(path)

    # shard by worker rank when launched through dmlc-submit
    rank = int(os.environ.get("DMLC_TASK_ID", 0))
    world = int(os.environ.get("DMLC_NUM_WORKER", 1))
    d = 29
    model = LogisticRegression(num_features=d)
    params = model.init(jax.random.PRNGKey(0))
    step = jax.jit(lambda p, b: model.sgd_step(p, b, lr=0.5))
    ck = Checkpointer("/tmp/higgs_ckpts", keep=2, process_index=rank)

    spec = BatchSpec(batch_size=1024, layout="dense", num_features=d)
    for epoch in range(3):
        parser = D.create_parser(path, rank, world, type="libsvm")
        pipe = StagingPipeline(
            FixedShapeBatcher(spec).batches(iter(parser))
        )
        loss = None
        for batch in pipe:
            params, loss = step(params, batch)
        stats = pipe.throughput()
        loss_str = "n/a (empty shard)" if loss is None else f"{float(loss):.4f}"
        print(
            f"rank {rank} epoch {epoch}: loss={loss_str} "
            f"({stats['rows_per_sec']:,.0f} rows/s into device)"
        )
        # pipeline first, source second — and only when the teardown
        # join completed (close_timed_out): an orphaned producer thread
        # may still be reading the parser's buffers
        drain_close(pipe, parser)
        ck.save(epoch, params)
    print("latest checkpoint step:", ck.latest_step())


if __name__ == "__main__":
    main()
