"""Host-level shared decoded-block cache (io/blockcache.py): protocol
round trips, publish races, lease-gated eviction, per-tenant quotas,
daemon-death fallback, stale-key safety, and the two-process
decode-once-per-host acceptance path.

Every test here runs the daemon in-process on a private socket — the
control plane is a real UNIX socket and the data plane real shared
memory either way, so cross-process behavior is exercised by the
subprocess tests at the bottom. The module is gated by the conftest
``blockcache`` capability probe (skips with a visible reason where
/dev/shm or UNIX sockets are unavailable)."""

import json
import os
import subprocess
import sys
import threading

import pytest

from dmlc_core_tpu.io import blockcache
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.blockcache import BlockCacheClient, BlockCacheDaemon
from dmlc_core_tpu.io.codec import (
    DecodeContext,
    DecodedBlockCache,
    wire_block_key,
)
from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
from dmlc_core_tpu.io.stream import FileStream

pytestmark = pytest.mark.blockcache


@pytest.fixture
def daemon(tmp_path):
    d = BlockCacheDaemon(
        str(tmp_path / "cache.sock"), max_bytes=16 << 20
    ).start()
    yield d
    d.close()


def client(d, tenant="t"):
    return BlockCacheClient(d.sock_path, tenant=tenant)


# -- protocol basics ----------------------------------------------------------
def test_publish_then_get_roundtrip(daemon):
    a, b = client(daemon, "a"), client(daemon, "b")
    assert a.ping()
    assert a.get("k") is None
    assert a.publish("k", b"payload-bytes")
    assert b.get("k") == b"payload-bytes"
    st = daemon.stats()
    assert st["entries"] == 1 and st["publishes"] == 1
    assert st["hits"] == 1 and st["misses"] == 1
    assert st["tenants"]["a"]["bytes"] == len(b"payload-bytes")


def _wait_leases(daemon, want, tries=100):
    # lease releases are oneway frames — give the daemon a beat
    while daemon.stats()["active_leases"] != want and tries:
        tries -= 1
        threading.Event().wait(0.01)
    return daemon.stats()["active_leases"]


def test_get_view_is_shared_memory(daemon):
    a = client(daemon)
    a.publish("k", b"0123456789")
    v = a.get_view("k")
    assert bytes(v.view) == b"0123456789" and len(v) == 10
    assert daemon.stats()["active_leases"] == 1
    v.close()
    assert _wait_leases(daemon, 0) == 0


def test_publish_race_single_winner(daemon):
    """Two processes decode the same block and publish concurrently:
    exactly one copy is adopted, the loser unlinks its segment and its
    next lookup hits the winner's bytes."""
    a, b = client(daemon, "a"), client(daemon, "b")
    data = b"x" * 4096
    assert a.publish("blk", data)
    assert not b.publish("blk", data)  # duplicate -> loser
    assert b.get("blk") == data  # ...and the loser now hits
    st = daemon.stats()
    assert st["entries"] == 1 and st["bytes"] == len(data)

    # a genuinely concurrent race from two connections stays clean:
    # every key ends with exactly one resident copy
    daemon_bytes = st["bytes"]
    wins = []

    def racer(c):
        got = [c.publish(f"race-{i}", bytes([i]) * 512) for i in range(8)]
        wins.append(got)

    t1 = threading.Thread(target=racer, args=(client(daemon, "r1"),))
    t2 = threading.Thread(target=racer, args=(client(daemon, "r2"),))
    t1.start(); t2.start(); t1.join(); t2.join()
    st = daemon.stats()
    assert st["entries"] == 9  # blk + 8 race keys, each exactly once
    assert st["bytes"] == daemon_bytes + 8 * 512
    for i, (w1, w2) in enumerate(zip(*wins)):
        assert w1 != w2 or not (w1 and w2), f"race-{i} adopted twice"
        assert client(daemon).get(f"race-{i}") == bytes([i]) * 512


def test_eviction_never_unlinks_leased(tmp_path):
    """A reader holding a leased view keeps its segment alive through
    arbitrary eviction pressure; the lease's release makes it evictable
    again."""
    d = BlockCacheDaemon(str(tmp_path / "c.sock"), max_bytes=25).start()
    try:
        a = client(d)
        assert a.publish("x1", b"0123456789")
        v = a.get_view("x1")  # lease held across the pressure below
        assert a.publish("x2", b"0123456789")
        assert a.publish("x3", b"0123456789")  # over budget: must evict
        st = d.stats()
        assert st["evictions"] == 1
        assert a.get("x2") is None  # the LRU *unleased* entry went
        assert a.get("x1") == b"0123456789"  # leased entry survived
        assert bytes(v.view) == b"0123456789"  # mapping still valid
        v.close()
        assert a.publish("x4", b"0123456789")  # x1 now evictable
        st = d.stats()
        assert st["bytes"] <= 25
    finally:
        d.close()


def test_oversized_and_tenant_quota_rejected(tmp_path):
    d = BlockCacheDaemon(
        str(tmp_path / "c.sock"), max_bytes=1 << 20, tenant_max_bytes=64
    ).start()
    try:
        a, b = client(d, "a"), client(d, "b")
        assert not a.publish("big", b"z" * 128)  # > tenant quota: rejected
        assert a.publish("a1", b"z" * 48)
        assert a.publish("a2", b"z" * 48)  # evicts a1 WITHIN tenant a
        assert b.publish("b1", b"y" * 48)  # b's quota untouched by a
        st = d.stats()
        assert st["rejected"] == 1
        assert st["tenants"]["a"]["bytes"] == 48
        assert st["tenants"]["b"]["bytes"] == 48
        assert a.get("a1") is None and a.get("a2") is not None
    finally:
        d.close()


def test_connection_drop_releases_leases(daemon):
    a = client(daemon)
    a.publish("k", b"data-here")
    v = a.get_view("k")
    assert daemon.stats()["active_leases"] == 1
    a.close()  # connection gone WITHOUT releasing
    deadline = 50
    while daemon.stats()["active_leases"] and deadline:
        deadline -= 1
        threading.Event().wait(0.02)
    assert daemon.stats()["active_leases"] == 0
    del v


def test_flush_keeps_leased(daemon):
    a = client(daemon)
    a.publish("k1", b"one")
    a.publish("k2", b"two")
    v = a.get_view("k1")
    assert a.flush() == 1  # k2 only; k1 is leased
    assert a.get("k1") == b"one"
    v.close()
    assert a.flush() == 1


# -- client fallback behavior -------------------------------------------------
def test_default_client_negative_cache(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "DMLC_BLOCK_CACHE_SOCK", str(tmp_path / "nothing-here.sock")
    )
    blockcache.reset_default_client()
    try:
        assert blockcache.default_client() is None
        assert blockcache.default_client() is None  # cached, no re-probe
    finally:
        blockcache.reset_default_client()


def test_env_off_disables_even_with_live_daemon(daemon, monkeypatch):
    monkeypatch.setenv("DMLC_BLOCK_CACHE_SOCK", daemon.sock_path)
    monkeypatch.setenv("DMLC_BLOCK_CACHE", "off")
    blockcache.reset_default_client()
    try:
        assert blockcache.default_client() is None
    finally:
        blockcache.reset_default_client()


# -- splitter integration -----------------------------------------------------
def _write_zlib_rec(tmp_path, n=1200, rewrite_tag=b""):
    rec = str(tmp_path / "data.rec")
    idx = rec + ".idx"
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi, codec="zlib", block_bytes=1 << 12)
        for i in range(n):
            w.write_record((rewrite_tag or b"A") + (b"%06d" % i) * 18)
        w.flush_block()
    return rec, idx


def _drain(rec, idx, ctx, kill_daemon_after=None):
    sp = io_split.IndexedRecordIOSplitter(
        rec, idx, 0, 1, shuffle="window", window=200, seed=5,
        decode_ctx=ctx,
        # no readahead when a mid-read daemon kill is staged: window
        # loads must interleave with the consumer's pulls so some
        # happen strictly AFTER the kill
        readahead=kill_daemon_after is None,
    )
    out = []
    pulls = 0
    while True:
        chunk = sp.next_batch_ex(256)
        if chunk is None:
            break
        out.append(chunk)
        pulls += 1
        if kill_daemon_after is not None and pulls == 2:
            kill_daemon_after.close()
    stats = sp.io_stats()
    sp.close()
    return b"".join(out), stats


def test_second_context_decodes_nothing(daemon, tmp_path):
    """The acceptance shape in-process: reader 2 (fresh L1, same
    daemon) serves every block from the shared tier — its own decode
    count stays flat and the bytes are identical."""
    from dmlc_core_tpu.telemetry import default_registry

    rec, idx = _write_zlib_rec(tmp_path)
    c1, c2 = client(daemon, "p1"), client(daemon, "p2")
    b1, st1 = _drain(rec, idx, DecodeContext(
        cache=DecodedBlockCache(1 << 24), shared=c1))
    assert st1["decode_cache_misses"] > 0 and c1.publishes > 0

    hist = default_registry().histogram("io.codec.decode_seconds")
    decodes_before = hist.snapshot()["count"]
    b2, st2 = _drain(rec, idx, DecodeContext(
        cache=DecodedBlockCache(1 << 24), shared=c2))
    assert b2 == b1
    assert hist.snapshot()["count"] == decodes_before  # zero decodes
    assert c2.hits > 0 and c2.misses == 0
    assert st2["decode_cache_hits"] > 0 and st2["decode_cache_misses"] == 0


def test_daemon_killed_mid_read_degrades_silently(tmp_path):
    """Killing the daemon between windows costs only the shared tier:
    the iterator finishes byte-identical via in-process decode, no
    error surfaces."""
    rec, idx = _write_zlib_rec(tmp_path)
    clean, _ = _drain(rec, idx, DecodeContext(
        cache=DecodedBlockCache(1 << 24), shared=None))
    d = BlockCacheDaemon(str(tmp_path / "kill.sock"), max_bytes=16 << 20)
    d.start()
    c = client(d)
    got, _ = _drain(
        rec, idx,
        # zero-budget L1: EVERY window consults the shared tier, so
        # some lookups land strictly after the kill
        DecodeContext(cache=DecodedBlockCache(0), shared=c),
        kill_daemon_after=d,
    )
    assert got == clean
    assert not c.alive  # marked dead, later calls are cheap no-ops


def test_stale_mtime_misses_not_serves(daemon, tmp_path):
    """An in-place rewrite (same path, same size, same block geometry)
    changes the cache identity: the second reader MISSES the daemon and
    decodes the new bytes instead of being served the old ones."""
    rec, idx = _write_zlib_rec(tmp_path, rewrite_tag=b"A")
    c1 = client(daemon, "p1")
    b1, _ = _drain(rec, idx, DecodeContext(
        cache=DecodedBlockCache(1 << 24), shared=c1))

    rec2, idx2 = _write_zlib_rec(tmp_path, rewrite_tag=b"B")
    assert rec2 == rec and os.path.getsize(rec) == os.path.getsize(rec2)
    os.utime(rec, ns=(1, 1))  # force a distinct mtime_ns either way

    c2 = client(daemon, "p2")
    b2, _ = _drain(rec, idx, DecodeContext(
        cache=DecodedBlockCache(1 << 24), shared=c2))
    assert b2 != b1  # new content came through
    assert c2.hits == 0 and c2.misses > 0  # old identity never matched


def test_wire_key_stable_across_processes(tmp_path):
    """The daemon key must be identical from two distinct interpreters
    (Python's hash() is seed-randomized; the sha1 identity is not)."""
    key = (("file.rec", 123, 456, "etag-x"), 789, "aa" * 20)
    script = (
        "from dmlc_core_tpu.io.codec import wire_block_key;"
        f"print(wire_block_key({key!r}))"
    )
    outs = {
        subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, check=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip()
        for _ in range(2)
    }
    assert len(outs) == 1 and outs.pop() == wire_block_key(key)


_DRAIN_SCRIPT = """
import json, sys
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.telemetry import default_registry
rec, idx = sys.argv[1], sys.argv[2]
sp = io_split.IndexedRecordIOSplitter(rec, idx, 0, 1, shuffle="window",
                                      window=200, seed=5)
n = 0
while True:
    c = sp.next_batch_ex(256)
    if c is None:
        break
    n += len(c)
sp.close()
reg = default_registry()
hits = sum(v for k, v in reg.counter_values("io.blockcache.hits").items())
print(json.dumps({
    "bytes": n,
    "decodes": reg.histogram("io.codec.decode_seconds").snapshot()["count"],
    "blockcache_hits": hits,
}))
"""


def test_two_real_processes_decode_once_per_host(daemon, tmp_path):
    """The acceptance criterion proper: a SECOND process over the same
    compressed shard shows io.blockcache.hits > 0 and decodes zero
    blocks itself, through the default (env-resolved) client path."""
    rec, idx = _write_zlib_rec(tmp_path)
    env = dict(os.environ, DMLC_BLOCK_CACHE_SOCK=daemon.sock_path,
               JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run():
        out = subprocess.run(
            [sys.executable, "-c", _DRAIN_SCRIPT, rec, idx],
            capture_output=True, text=True, env=env, cwd=repo, check=True,
        )
        return json.loads(out.stdout)

    first = run()
    assert first["decodes"] > 0 and first["blockcache_hits"] == 0
    second = run()
    assert second["bytes"] == first["bytes"]
    assert second["blockcache_hits"] > 0
    assert second["decodes"] == 0  # decode-once-per-host
    assert daemon.stats()["publishes"] > 0


# -- CLI ----------------------------------------------------------------------
def test_tools_cached_stats_and_flush(daemon, capsys):
    from dmlc_core_tpu.tools import main as tools_main

    client(daemon).publish("k", b"some-bytes")
    assert tools_main(["cached", "stats", "--socket", daemon.sock_path]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["entries"] == 1 and stats["publishes"] == 1
    assert tools_main(["cached", "flush", "--socket", daemon.sock_path]) == 0
    assert json.loads(capsys.readouterr().out) == {"evicted": 1}
    assert daemon.stats()["entries"] == 0


def test_tools_cached_no_daemon(tmp_path, capsys):
    from dmlc_core_tpu.tools import main as tools_main

    rc = tools_main(
        ["cached", "stats", "--socket", str(tmp_path / "absent.sock")]
    )
    assert rc == 1
    assert "no block-cache daemon" in capsys.readouterr().err
