"""Concurrent ranged span fetcher (ISSUE 9 tentpole, io/spanfetch.py).

The contract under test: parallel remote reads change WHEN bytes
arrive, never what they are — ``fetch_into`` assembles the exact serial
buffer, ``fetch_iter`` delivers every span once in completion order,
the in-flight byte budget only throttles (never drops or deadlocks),
contiguous plans collapse to one connection, and the splitter engages
the engine for remote-shaped sources only (local files keep the
zero-copy ``_SpanReader`` fast path; ``DMLC_FETCH_THREADS=1`` pins the
serial baseline). Byte-identity under chaos lives in test_faults.py /
test_split_gather.py; this file covers the engine itself.
"""

import numpy as np
import pytest

from dmlc_core_tpu.io import spanfetch
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.filesystem import FileSystem
from dmlc_core_tpu.io.spanfetch import SpanFetcher
from dmlc_core_tpu.telemetry import default_registry
from dmlc_core_tpu.utils import Error


def _make_file(tmp_path, n_bytes=1 << 16, name="spans.bin", seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 255, n_bytes, dtype=np.uint8).tobytes()
    p = str(tmp_path / name)
    with open(p, "wb") as f:
        f.write(data)
    return p, data


def _fetcher_for(uri, threads=4, inflight=None):
    fs = FileSystem.get_instance(uri)
    info = fs.get_path_info(uri)
    return (
        SpanFetcher(
            [info], [0, info.size], fs,
            threads=threads, inflight_bytes=inflight,
        ),
        info.size,
    )


def _scattered_spans(total, n=37, size=700, seed=3):
    rng = np.random.default_rng(seed)
    starts = np.sort(
        rng.choice(total - size, size=n, replace=False)
    ).tolist()
    # drop accidental overlaps: keep spans disjoint and non-contiguous
    spans = []
    last_end = -1
    for s in starts:
        if s > last_end:
            spans.append((int(s), size))
            last_end = s + size
    return spans


def test_fetch_into_assembles_exact_bytes(tmp_path):
    p, data = _make_file(tmp_path)
    # fault:// with no faults = a remote-shaped seekable backend over
    # the local file (the same wrapper the chaos suites use)
    f, total = _fetcher_for(f"fault://seed=1{p}")
    spans = _scattered_spans(total)
    sizes = [n for _b, n in spans]
    out = np.empty(sum(sizes), dtype=np.uint8)
    bases = [0]
    for n in sizes[:-1]:
        bases.append(bases[-1] + n)
    f.fetch_into(spans, memoryview(out), bases)
    f.close()
    want = b"".join(data[b : b + n] for b, n in spans)
    assert out.tobytes() == want
    assert f.spans == len(spans)
    assert f.bytes == sum(sizes)
    assert f.concurrency_peak >= 2  # the ramp actually went parallel


def test_fetch_iter_delivers_every_span_once(tmp_path):
    p, data = _make_file(tmp_path)
    f, total = _fetcher_for(f"fault://seed=2{p}")
    spans = _scattered_spans(total, n=23)
    seen = {}
    for si, view in f.fetch_iter(spans):
        assert si not in seen
        seen[si] = bytes(view)
    f.close()
    assert sorted(seen) == list(range(len(spans)))
    for si, (b, n) in enumerate(spans):
        assert seen[si] == data[b : b + n], si


def test_tiny_inflight_budget_still_completes(tmp_path):
    """A budget smaller than any span degrades to one-span-at-a-time —
    it must never drop or deadlock (the inflight==0 escape)."""
    p, data = _make_file(tmp_path)
    f, total = _fetcher_for(f"fault://seed=3{p}", inflight=1)
    spans = _scattered_spans(total, n=9)
    got = dict(
        (si, bytes(v)) for si, v in f.fetch_iter(spans)
    )
    f.close()
    assert len(got) == len(spans)
    assert f.concurrency_peak == 1  # budget serialized the flight
    for si, (b, n) in enumerate(spans):
        assert got[si] == data[b : b + n]


def test_contiguous_spans_collapse_to_one_connection(tmp_path):
    p, data = _make_file(tmp_path, n_bytes=8192)
    f, total = _fetcher_for(f"fault://seed=4{p}")
    spans = [(i * 1024, 1024) for i in range(8)]  # byte-adjacent
    got = dict((si, bytes(v)) for si, v in f.fetch_iter(spans))
    f.close()
    assert bytes(b"".join(got[i] for i in range(8))) == data
    assert f.concurrency_peak == 1  # sequential stream, no ranged race


def test_span_past_eof_raises_checked_error(tmp_path):
    p, _data = _make_file(tmp_path, n_bytes=4096)
    f, total = _fetcher_for(f"fault://seed=5{p}")
    with pytest.raises(Error, match="span read truncated"):
        for _ in f.fetch_iter([(0, 1024), (total - 512, 1024)]):
            pass
    f.close()


def test_fetch_telemetry_series_tick(tmp_path):
    reg = default_registry()
    spans_before = reg.counter("io.fetch.spans").value()
    bytes_before = reg.counter("io.fetch.bytes").value()
    wait_before = reg.histogram("io.fetch.span_wait_seconds").snapshot()[
        "count"
    ]
    p, _data = _make_file(tmp_path)
    f, total = _fetcher_for(f"fault://seed=6{p}")
    spans = _scattered_spans(total, n=19)
    for _ in f.fetch_iter(spans):
        pass
    f.close()
    assert (
        reg.counter("io.fetch.spans").value() - spans_before == len(spans)
    )
    assert reg.counter("io.fetch.bytes").value() - bytes_before == sum(
        n for _b, n in spans
    )
    # each parallel-path completion observed one consumer wait
    assert (
        reg.histogram("io.fetch.span_wait_seconds").snapshot()["count"]
        > wait_before
    )
    assert reg.gauge("io.fetch.concurrency_peak").value() >= 1


def test_http_seek_counts_reopen():
    """HttpReadStream.seek() to a non-current offset over a LIVE
    connection tears it down — counted as io.fetch.reopens so a
    serial-fallback seek storm is visible (ISSUE 9 satellite)."""
    from dmlc_core_tpu.io.cloudfs import HttpReadStream

    class _Resp:
        def close(self):
            pass

    s = HttpReadStream("http://example.invalid/x", size=100)
    before = spanfetch.reopens_total()
    s._resp = _Resp()
    s.seek(37)  # live connection + new offset: one reopen
    assert spanfetch.reopens_total() - before == 1
    s.seek(37)  # same offset: no-op
    assert spanfetch.reopens_total() - before == 1
    s.seek(55)  # no live connection: repositioning is free
    assert spanfetch.reopens_total() - before == 1
    s.close()


def test_splitter_engages_fetcher_for_remote_only(tmp_path, monkeypatch):
    from tests.test_split_gather import make_indexed_rec, records_of

    # the ambient env must not decide this test (a developer exporting
    # the serial baseline would otherwise see the remote assert fail)
    monkeypatch.delenv("DMLC_FETCH_THREADS", raising=False)
    records = records_of(60)
    p, idx = make_indexed_rec(str(tmp_path), records)
    local = io_split.IndexedRecordIOSplitter(
        p, idx, 0, 1, shuffle="window", window=16, seed=2
    )
    assert local._get_fetcher() is None  # local: mmap fast path owns it
    local.close()
    remote = io_split.IndexedRecordIOSplitter(
        f"fault://seed=8{p}", idx, 0, 1, shuffle="window", window=16,
        seed=2,
    )
    assert remote._get_fetcher() is not None
    remote.close()
    # DMLC_FETCH_THREADS=1 pins the serial baseline even on remote
    monkeypatch.setenv("DMLC_FETCH_THREADS", "1")
    serial = io_split.IndexedRecordIOSplitter(
        f"fault://seed=8{p}", idx, 0, 1, shuffle="window", window=16,
        seed=2,
    )
    assert serial._get_fetcher() is None
    serial.close()


def test_fetch_threads_env_and_default(monkeypatch):
    monkeypatch.setenv("DMLC_FETCH_THREADS", "7")
    assert spanfetch.fetch_threads() == 7
    monkeypatch.delenv("DMLC_FETCH_THREADS")
    n = spanfetch.fetch_threads()
    assert 2 <= n <= 16
    monkeypatch.setenv("DMLC_FETCH_INFLIGHT_MB", "3")
    assert spanfetch.inflight_budget_bytes() == 3 << 20


def test_remote_window_io_stats_carry_fetch_shape(tmp_path, monkeypatch):
    """A remote windowed drain reports the concurrent-fetch shape:
    fetch_spans/fetch_bytes/fetch_concurrency_peak next to the classic
    span/seek counters, and the drained bytes equal the local drain's."""
    monkeypatch.setenv("DMLC_FETCH_THREADS", "4")
    from tests.test_split_gather import (
        drain_records,
        make_indexed_rec,
        records_of,
    )

    records = records_of(120)
    p, idx = make_indexed_rec(str(tmp_path), records)
    ref = io_split.IndexedRecordIOSplitter(
        p, idx, 0, 1, shuffle="window", window=24, merge_gap=0, seed=9
    )
    want = drain_records(ref)
    ref.close()
    s = io_split.IndexedRecordIOSplitter(
        f"fault://seed=9{p}", idx, 0, 1, shuffle="window", window=24,
        merge_gap=0, seed=9,
    )
    got = drain_records(s)
    stats = s.io_stats()
    s.close()
    assert got == want
    assert stats["fetch_spans"] > 0
    assert stats["fetch_bytes"] > 0
    assert stats["fetch_concurrency_peak"] >= 1
    assert stats["reopens"] == 0  # no HTTP streams in this drain
