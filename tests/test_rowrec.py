"""rowrec codec + RecordIO→ELL staging: parity, sharding, multipart.

Covers the RecordIO→HBM path (BASELINE.md north star #2): the rowrec
payload codec (data/rowrec.py), the generic RowRecParser, and the fused
native kernel (native/fastparse.cc dmlc_parse_rowrec_ell +
staging/fused.py FusedEllRowRecBatches), which must produce identical
batches to RowRecParser → FixedShapeBatcher('ell') composed.

Multipart records (payloads containing the aligned RecordIO magic word)
and records straddling chunk windows mirror the reference's stress cases
(reference test/unittest/unittest_inputsplit.cc:147-190).
"""

import os
import struct

import numpy as np
import pytest

from dmlc_core_tpu.data import create_parser, native
from dmlc_core_tpu.data.rowrec import (
    decode_record,
    decode_records,
    encode_row,
    encode_rows,
    write_rowrec,
)
from dmlc_core_tpu.data.row_block import RowBlock
from dmlc_core_tpu.io.recordio import (
    KMAGIC,
    RecordIOReader,
    RecordIOWriter,
)
from dmlc_core_tpu.io.stream import FileStream, MemoryStream
from dmlc_core_tpu.staging import BatchSpec, FixedShapeBatcher
from dmlc_core_tpu.utils.logging import Error

MAGIC_F32 = struct.unpack("<f", struct.pack("<I", KMAGIC))[0]  # collides


def _random_block(rng, n_rows, max_nnz=12, max_index=1000, magic_every=0):
    """Random ragged RowBlock; every `magic_every`-th value is the float
    whose bits equal the RecordIO magic word, forcing multipart frames."""
    nnz = rng.integers(1, max_nnz + 1, n_rows)
    offset = np.zeros(n_rows + 1, dtype=np.int64)
    offset[1:] = np.cumsum(nnz)
    total = int(offset[-1])
    index = rng.integers(0, max_index, total).astype(np.uint32)
    value = rng.normal(size=total).astype(np.float32)
    if magic_every:
        value[::magic_every] = MAGIC_F32
    return RowBlock(
        offset=offset,
        label=rng.integers(0, 2, n_rows).astype(np.float32),
        index=index,
        value=value,
        weight=rng.uniform(0.5, 2.0, n_rows).astype(np.float32),
    )


def _write_rec(path, block):
    stream = FileStream(path, "w")
    n = write_rowrec(stream, [block])
    stream.close()
    return n


def test_codec_roundtrip_single():
    payload = encode_row(1.0, np.array([3, 7, 9]), np.array([0.5, -1.5, 2.0]))
    label, weight, idx, val = decode_record(payload)
    assert label == 1.0 and weight == 1.0
    np.testing.assert_array_equal(idx, [3, 7, 9])
    np.testing.assert_array_equal(val, [0.5, -1.5, 2.0])


def test_codec_roundtrip_block():
    rng = np.random.default_rng(0)
    blk = _random_block(rng, 50)
    payloads = encode_rows(blk)
    assert len(payloads) == 50
    out = decode_records(payloads)
    np.testing.assert_array_equal(out.offset, blk.offset)
    np.testing.assert_array_equal(out.label, blk.label)
    np.testing.assert_array_equal(out.index, blk.index)
    np.testing.assert_array_equal(out.value, blk.value)
    np.testing.assert_array_equal(out.weight, blk.weight)


def test_codec_rejects_truncated_payload():
    payload = encode_row(1.0, np.array([3, 7]), np.array([0.5, 1.5]))
    with pytest.raises(Error):
        decode_record(payload[:8])
    with pytest.raises(Error):
        decode_record(payload[:-4])  # declared nnz exceeds payload


def test_magic_collision_roundtrips_via_recordio():
    """Payloads containing the aligned magic word must survive the
    writer's multipart escape (reference src/recordio.cc:11-51)."""
    rng = np.random.default_rng(1)
    blk = _random_block(rng, 40, magic_every=5)
    ms = MemoryStream()
    writer = RecordIOWriter(ms)
    payloads = encode_rows(blk)
    for p in payloads:
        writer.write_record(p)
    assert writer.except_counter > 0, "test data produced no collisions"
    ms.seek(0)
    back = list(RecordIOReader(ms))
    assert [bytes(b) for b in back] == [bytes(p) for p in payloads]
    out = decode_records(back)
    np.testing.assert_array_equal(out.value, blk.value)


def test_rowrec_parser_end_to_end(tmp_path):
    rng = np.random.default_rng(2)
    blk = _random_block(rng, 300)
    path = str(tmp_path / "data.rec")
    assert _write_rec(path, blk) == 300
    parser = create_parser(path, type="rowrec", threaded=False)
    blocks = list(iter(parser))
    parser.close()
    total = sum(b.size for b in blocks)
    assert total == 300
    merged = RowBlock.concat(blocks) if len(blocks) > 1 else blocks[0]
    np.testing.assert_array_equal(merged.label, blk.label)
    np.testing.assert_array_equal(merged.index, blk.index)
    np.testing.assert_allclose(merged.value, blk.value)


def test_rowrec_parser_sharded_exact_cover(tmp_path):
    """Every row lands in exactly one shard (reference distributed-split
    pattern, unittest_inputsplit.cc:116-145)."""
    rng = np.random.default_rng(3)
    blk = _random_block(rng, 500)
    path = str(tmp_path / "data.rec")
    _write_rec(path, blk)
    labels = []
    for part in range(4):
        parser = create_parser(
            path, part_index=part, num_parts=4, type="rowrec", threaded=False
        )
        for b in iter(parser):
            labels.append(b.label)
        parser.close()
    got = np.concatenate(labels)
    assert len(got) == 500
    np.testing.assert_array_equal(np.sort(got), np.sort(blk.label))


# -- fused native kernel ------------------------------------------------------

fused = pytest.mark.skipif(
    not native.HAS_ELL, reason="native fused ELL kernel not built"
)


def _generic_ell(path, spec, part_index=0, num_parts=1):
    parser = create_parser(
        path, part_index, num_parts, type="rowrec", threaded=False
    )
    out = list(FixedShapeBatcher(spec).batches(iter(parser)))
    parser.close()
    return out


def _fused_ell(path, spec, part_index=0, num_parts=1, ring=8):
    from dmlc_core_tpu.staging import FusedEllRowRecBatches

    stream = FusedEllRowRecBatches(path, spec, part_index, num_parts, ring)
    # copy: ring buffers are recycled
    out = [
        type(b)(
            labels=b.labels.copy(), weights=b.weights.copy(),
            n_valid=b.n_valid, indices=b.indices.copy(),
            values=b.values.copy(), nnz=b.nnz.copy(),
        )
        for b in stream
    ]
    tr = stream.truncated_nnz
    stream.close()
    return out, tr


def _assert_batches_equal(fused_batches, generic_batches):
    assert len(fused_batches) == len(generic_batches)
    for f, g in zip(fused_batches, generic_batches):
        assert f.n_valid == g.n_valid
        np.testing.assert_array_equal(f.labels, g.labels)
        np.testing.assert_array_equal(f.weights, g.weights)
        np.testing.assert_array_equal(f.nnz, g.nnz)
        np.testing.assert_array_equal(f.indices, g.indices)
        np.testing.assert_array_equal(f.values, g.values)


@fused
@pytest.mark.parametrize("value_dtype", ["float32", "float16"])
def test_fused_matches_generic(tmp_path, value_dtype):
    rng = np.random.default_rng(4)
    blk = _random_block(rng, 700, max_nnz=8)
    path = str(tmp_path / "data.rec")
    _write_rec(path, blk)
    spec = BatchSpec(
        batch_size=128, layout="ell", max_nnz=8,
        value_dtype=np.dtype(value_dtype),
    )
    fused_b, _ = _fused_ell(path, spec)
    spec2 = BatchSpec(
        batch_size=128, layout="ell", max_nnz=8,
        value_dtype=np.dtype(value_dtype),
    )
    generic_b = _generic_ell(path, spec2)
    _assert_batches_equal(fused_b, generic_b)


@fused
def test_fused_truncation_counts(tmp_path):
    rng = np.random.default_rng(5)
    blk = _random_block(rng, 100, max_nnz=10)
    path = str(tmp_path / "data.rec")
    _write_rec(path, blk)
    spec = BatchSpec(batch_size=32, layout="ell", max_nnz=4)
    fused_b, fused_tr = _fused_ell(path, spec)
    gspec = BatchSpec(batch_size=32, layout="ell", max_nnz=4)
    batcher = FixedShapeBatcher(gspec)
    parser = create_parser(path, type="rowrec", threaded=False)
    generic_b = list(batcher.batches(iter(parser)))
    parser.close()
    assert fused_tr == batcher.truncated_nnz > 0
    _assert_batches_equal(fused_b, generic_b)


@fused
def test_fused_multipart_and_tiny_windows(tmp_path):
    """Multipart chains + records straddling mmap windows: force a small
    window so nearly every record crosses a boundary (reference chunk
    straddle stress, unittest_inputsplit.cc:147-190)."""
    from dmlc_core_tpu.staging import FusedEllRowRecBatches

    rng = np.random.default_rng(6)
    blk = _random_block(rng, 200, max_nnz=16, magic_every=7)
    path = str(tmp_path / "data.rec")
    _write_rec(path, blk)
    spec = BatchSpec(batch_size=64, layout="ell", max_nnz=16)
    stream = FusedEllRowRecBatches(path, spec)
    assert stream._mmap
    stream._split._chunk = 64  # tiny raw windows
    stream._split._width = 64
    got_labels = []
    for b in stream:
        got_labels.append(b.labels[: b.n_valid].copy())
    stream.close()
    np.testing.assert_array_equal(np.concatenate(got_labels), blk.label)
    assert stream.bad_records == 0


@fused
def test_fused_sharded_exact_cover(tmp_path):
    rng = np.random.default_rng(7)
    blk = _random_block(rng, 600, max_nnz=6)
    path = str(tmp_path / "data.rec")
    _write_rec(path, blk)
    spec = lambda: BatchSpec(batch_size=100, layout="ell", max_nnz=6)
    rows = []
    for part in range(3):
        batches, _ = _fused_ell(path, spec(), part, 3)
        for b in batches:
            rows.append(b.labels[: b.n_valid])
        # parity per shard too
        _assert_batches_equal(batches, _generic_ell(path, spec(), part, 3))
    got = np.concatenate(rows)
    assert len(got) == 600
    np.testing.assert_array_equal(np.sort(got), np.sort(blk.label))


@fused
def test_fused_corrupt_stream_raises(tmp_path):
    rng = np.random.default_rng(8)
    blk = _random_block(rng, 50)
    path = str(tmp_path / "data.rec")
    _write_rec(path, blk)
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) - 6)
        f.write(b"\xde\xad")  # corrupt the final record's payload tail
    data = open(path, "rb").read()
    # corrupting payload bytes mid-file instead: flip a magic word
    pos = data.index(struct.pack("<I", KMAGIC), 100)
    corrupted = data[:pos] + b"\x00\x00\x00\x00" + data[pos + 4:]
    bad = str(tmp_path / "bad.rec")
    open(bad, "wb").write(corrupted)
    spec = BatchSpec(batch_size=16, layout="ell", max_nnz=12)
    with pytest.raises(Error):
        _fused_ell(bad, spec)


def test_ell_batches_dispatcher_fallback(tmp_path, monkeypatch):
    """ell_batches must fall back to the generic path when the kernel is
    unavailable and produce the same batches."""
    from dmlc_core_tpu.staging import ell_batches

    rng = np.random.default_rng(9)
    blk = _random_block(rng, 150, max_nnz=5)
    path = str(tmp_path / "data.rec")
    _write_rec(path, blk)

    def run():
        spec = BatchSpec(batch_size=50, layout="ell", max_nnz=5)
        stream = ell_batches(path, spec)
        out = [
            type(b)(
                labels=b.labels.copy(), weights=b.weights.copy(),
                n_valid=b.n_valid, indices=b.indices.copy(),
                values=b.values.copy(), nnz=b.nnz.copy(),
            )
            for b in stream
        ]
        stream.close()
        return out

    with_kernel = run()
    monkeypatch.setattr(native, "HAS_ELL", False)
    without_kernel = run()
    _assert_batches_equal(with_kernel, without_kernel)


def _labels_in_order(path_with_args, spec_fn, use_fused):
    from dmlc_core_tpu.staging import ell_batches

    if not use_fused:
        parser = create_parser(path_with_args, type="rowrec", threaded=False)
        out = []
        for b in iter(parser):
            out.extend(b.label.tolist())
        parser.close()
        return out
    stream = ell_batches(path_with_args, spec_fn())
    out = []
    for b in stream:
        out.extend(b.labels[: b.n_valid].tolist())
    stream.close()
    return out


@pytest.mark.parametrize("use_fused", [False, True])
def test_epoch_shuffle_via_uri(tmp_path, use_fused):
    """?shuffle_parts=N&seed=S macro-shuffles rowrec epochs (reference
    input_split_shuffle.h) on both the generic and fused paths."""
    if use_fused and not native.HAS_ELL:
        pytest.skip("native fused ELL kernel not built")
    n, k = 400, 3
    rng = np.random.default_rng(20)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 50, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    path = str(tmp_path / "s.rec")
    _write_rec(path, blk)
    spec = lambda: BatchSpec(batch_size=64, layout="ell", max_nnz=k)

    plain = _labels_in_order(path, spec, use_fused)
    s1 = _labels_in_order(path + "?shuffle_parts=8&seed=1", spec, use_fused)
    s1b = _labels_in_order(path + "?shuffle_parts=8&seed=1", spec, use_fused)
    s2 = _labels_in_order(path + "?shuffle_parts=8&seed=2", spec, use_fused)
    # every row exactly once, deterministic per seed, reordered vs plain
    for got in (plain, s1, s2):
        assert sorted(got) == list(range(n))
    assert s1 == s1b
    assert s1 != plain and s2 != s1


def test_shuffle_with_cachefile_refused(tmp_path):
    """Epoch shuffle + disk cache would freeze epoch-1 order into the
    cache — refused on every route that combines them."""
    from dmlc_core_tpu.data import create_row_block_iter
    from dmlc_core_tpu.io import split as io_split

    rng = np.random.default_rng(21)
    blk = _random_block(rng, 20)
    path = str(tmp_path / "c.rec")
    _write_rec(path, blk)
    with pytest.raises(Error, match="freeze"):
        io_split.create(path + "?shuffle_parts=4#cachef", 0, 1, type="recordio")
    with pytest.raises(Error, match="freeze"):
        create_row_block_iter(
            path + "?format=rowrec&shuffle_parts=4#" + str(tmp_path / "cache")
        )


def test_vectorized_framer_byte_identical():
    """encode_block_frames output must be byte-for-byte what
    RecordIOWriter emits for the same payloads, offsets included."""
    from dmlc_core_tpu.data.rowrec import encode_block_frames, encode_rows

    rng = np.random.default_rng(31)
    blk = _random_block(rng, 300, max_nnz=9)
    fast = encode_block_frames(blk)
    assert fast is not None
    framed, offsets = fast
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    slow_offsets = []
    for payload in encode_rows(blk):
        slow_offsets.append(w.bytes_written)
        w.write_record(payload)
    assert framed == ms.getvalue()
    np.testing.assert_array_equal(offsets, slow_offsets)


def test_vectorized_framer_collision_fallback():
    """Blocks whose payloads contain the aligned magic word must decline
    the fast path (the writer's multipart escape is required) — and
    write_rowrec output stays correct either way."""
    from dmlc_core_tpu.data.rowrec import encode_block_frames

    rng = np.random.default_rng(32)
    blk = _random_block(rng, 60, magic_every=7)
    assert encode_block_frames(blk) is None
    ms = MemoryStream()
    assert write_rowrec(ms, [blk]) == 60
    ms.seek(0)
    out = decode_records(RecordIOReader(ms))
    np.testing.assert_array_equal(out.value, blk.value)


def test_vectorized_framer_sliced_block():
    """RowBlock.slice rebases offsets and arrays (row_block.py slice
    contract); framing a slice yields exactly those rows."""
    from dmlc_core_tpu.data.rowrec import encode_block_frames

    rng = np.random.default_rng(33)
    blk = _random_block(rng, 100, max_nnz=5)
    part = blk.slice(40, 80)
    fast = encode_block_frames(part)
    assert fast is not None
    framed, _ = fast
    ms = MemoryStream()
    ms.write(framed)
    ms.seek(0)
    out = decode_records(RecordIOReader(ms))
    np.testing.assert_array_equal(out.label, blk.label[40:80])
    np.testing.assert_array_equal(
        out.value, blk.value[blk.offset[40]:blk.offset[80]]
    )


def test_fused_ell_over_remote_uri():
    """The fused ELL producer must compose with non-local URIs (object
    stores) through the RecordIO splitter — the mmap fast path is a
    local-file optimization, not a requirement. mem:// stands in for
    s3://gs:// (same FileSystem interface, hermetic)."""
    if not native.HAS_ELL:
        pytest.skip("native fused ELL kernel not built")
    from dmlc_core_tpu.io.filesystem import MemoryFileSystem
    from dmlc_core_tpu.io.stream import Stream
    from dmlc_core_tpu.staging import BatchSpec, ell_batches

    MemoryFileSystem.reset()
    n, k = 250, 3
    rng = np.random.default_rng(12)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 70, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    with Stream.create("mem://bucket/data.rec", "w") as f:
        write_rowrec(f, [blk])

    spec = BatchSpec(batch_size=40, layout="ell", max_nnz=k)
    stream = ell_batches("mem://bucket/data.rec", spec)
    assert stream._mmap is False  # fused producer, splitter path
    labels = [x for b in stream for x in b.labels[: b.n_valid].tolist()]
    stream.close()
    assert sorted(labels) == list(range(n))
    # sharded remote reads cover exactly
    halves = []
    for part in range(2):
        s = ell_batches("mem://bucket/data.rec", spec,
                        part_index=part, num_parts=2)
        halves.extend(x for b in s for x in b.labels[: b.n_valid].tolist())
        s.close()
    assert sorted(halves) == list(range(n))
    MemoryFileSystem.reset()


def test_indexed_rowrec_via_uri_sugar(tmp_path):
    """?index=<uri>&shuffle=1 reaches count-indexed sharding + per-epoch
    shuffled batched reads from any rowrec consumer (reference
    indexed_recordio_split.cc semantics through the URI)."""
    from dmlc_core_tpu.staging import ell_batches

    n, k = 300, 4
    rng = np.random.default_rng(22)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 80, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.rec.idx")
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        assert write_rowrec(f, [blk], index_stream=fi) == n

    spec = lambda: BatchSpec(batch_size=50, layout="ell", max_nnz=k)

    def labels(uri):
        stream = ell_batches(uri, spec())
        out = []
        for b in stream:
            out.extend(b.labels[: b.n_valid].tolist())
        stream.close()
        return out

    # count-based sharding: EXACT halves regardless of byte sizes
    p0 = labels(f"{rec}?index={idx}")
    s0 = ell_batches(f"{rec}?index={idx}", spec(), part_index=0, num_parts=2)
    s1 = ell_batches(f"{rec}?index={idx}", spec(), part_index=1, num_parts=2)
    half0 = [x for b in s0 for x in b.labels[: b.n_valid].tolist()]
    half1 = [x for b in s1 for x in b.labels[: b.n_valid].tolist()]
    s0.close(); s1.close()
    assert sorted(p0) == list(range(n))
    assert len(half0) == len(half1) == n // 2
    assert sorted(half0 + half1) == list(range(n))

    # shuffled reads: full coverage, deterministic per seed, reordered
    sh1 = labels(f"{rec}?index={idx}&shuffle=1&seed=5")
    sh1b = labels(f"{rec}?index={idx}&shuffle=1&seed=5")
    sh2 = labels(f"{rec}?index={idx}&shuffle=1&seed=6")
    assert sorted(sh1) == list(range(n)) and sh1 == sh1b
    assert sh1 != p0 and sh2 != sh1

    # a cachefile would freeze the first epoch's shuffle order (same
    # guard the shuffle_parts sugar has) → refused up front
    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.utils.logging import Error as DmlcError

    with pytest.raises(DmlcError, match="cachefile"):
        io_split.create(
            f"{rec}?index={idx}&shuffle=1#{tmp_path}/c", type="recordio"
        )
    with pytest.raises(DmlcError, match="shuffle="):
        io_split.create(f"{rec}?index={idx}&shuffle=true", type="recordio")

    # explicit kwargs beat URI options (None-sentinel contract)
    s = io_split.create(
        f"{rec}?index={idx}&batch_size=64&shuffle=1",
        type="recordio", batch_size=32, shuffle=False, threaded=False,
    )
    assert s.batch_size == 32 and s.shuffle is False
    s.close()


def test_indexed_sugar_composes_with_threaded_fanout(tmp_path):
    """?index=&shuffle= + nthread>1 (ShardedFusedBatches): every row
    lands exactly once across the interleaved count-indexed sub-shards."""
    from dmlc_core_tpu.staging import ell_batches

    n, k = 600, 3
    rng = np.random.default_rng(5)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 50, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec, idx = str(tmp_path / "t.rec"), str(tmp_path / "t.idx")
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        write_rowrec(f, [blk], index_stream=fi)
    spec = BatchSpec(batch_size=50, layout="ell", max_nnz=k)
    s = ell_batches(f"{rec}?index={idx}&shuffle=1&seed=2", spec, nthread=2)
    labels = [x for b in s for x in b.labels[: b.n_valid].tolist()]
    s.close()
    assert sorted(labels) == list(range(n))


def test_indexed_rowrec_sugar_on_parser_path(tmp_path):
    """?index=&shuffle= must work through create_row_block_iter /
    create_parser too, not only the fused native path: the registry
    re-attaches query args so io_split.create is the single resolver."""
    from dmlc_core_tpu.data import create_row_block_iter

    n, k = 200, 2
    rng = np.random.default_rng(7)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 50, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "p.rec")
    idx = str(tmp_path / "p.rec.idx")
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        write_rowrec(f, [blk], index_stream=fi)

    def labels(uri, **kw):
        it = create_row_block_iter(uri, **kw)
        out = []
        for b in it:
            out.extend(np.asarray(b.label).tolist())
        return out

    base = f"{rec}?format=rowrec&index={idx}"
    plain = labels(base)
    assert sorted(plain) == list(range(n))
    sh = labels(base + "&shuffle=1&seed=9")
    assert sorted(sh) == list(range(n)) and sh != plain
    # count-exact halves through the parser path as well
    h0 = labels(base, part_index=0, num_parts=2)
    h1 = labels(base, part_index=1, num_parts=2)
    assert len(h0) == len(h1) == n // 2
    assert sorted(h0 + h1) == list(range(n))
    # shuffle + cachefile refused on this path too
    with pytest.raises(Exception, match="cachefile|shuffl"):
        create_row_block_iter(base + f"&shuffle=1#{tmp_path}/cache")
