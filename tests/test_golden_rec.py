"""Golden RecordIO evidence: bit-compat proven against fixed bytes.

Two tiers (VERDICT r2 missing #5 — bit-compat must not be self-attested):

1. Hand-authored golden frames: byte strings written out explicitly from
   the documented layout (reference include/dmlc/recordio.h:16-45 —
   [kMagic][cflag<<29|len][data][pad-to-4]), never produced by the code
   under test. The writer must emit exactly these bytes; the readers
   must decode them.
2. The reference-PRODUCED artifact: when the upstream checkout is
   present (/root/reference), decode its checked-in sample.rec
   (test/unittest/sample.rec) and re-encode it — the output must be
   byte-identical, proving framing compatibility against an artifact
   the other implementation wrote.

Plus the multipart-record-straddles-chunk stress at the splitter level
(reference unittest_inputsplit.cc:147-190).
"""

import os
import struct

import numpy as np
import pytest

from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.recordio import (
    KMAGIC,
    RecordIOChunkReader,
    RecordIOReader,
    RecordIOWriter,
)
from dmlc_core_tpu.io.stream import FileStream, MemoryStream

REFERENCE_REC = "/root/reference/test/unittest/sample.rec"
MAGIC_BYTES = struct.pack("<I", KMAGIC)


def _frame(cflag: int, payload: bytes) -> bytes:
    """One frame straight from the spec (recordio.h:16-45), by hand."""
    lrec = ((cflag & 7) << 29) | len(payload)
    pad = (4 - (len(payload) & 3)) & 3
    return MAGIC_BYTES + struct.pack("<I", lrec) + payload + b"\x00" * pad


# records → the exact bytes the format mandates for them
GOLDEN_RECORDS = [
    b"hello world",                      # plain, needs 1 pad byte
    b"",                                 # empty record
    b"abcd",                             # aligned, no padding
    b"12" + MAGIC_BYTES + b"5678",       # UNALIGNED magic: single frame
    MAGIC_BYTES + b"tail",               # aligned magic at 0: multipart
    b"eggs" + MAGIC_BYTES,               # aligned magic at end: multipart
]
GOLDEN_BYTES = (
    _frame(0, b"hello world")
    + _frame(0, b"")
    + _frame(0, b"abcd")
    + _frame(0, b"12" + MAGIC_BYTES + b"5678")
    # the writer elides each aligned in-payload magic and splits there:
    # cflag 1 (start) then cflag 3 (end)
    + _frame(1, b"") + _frame(3, b"tail")
    + _frame(1, b"eggs") + _frame(3, b"")
)


def test_writer_emits_golden_bytes():
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    for rec in GOLDEN_RECORDS:
        w.write_record(rec)
    assert ms.getvalue() == GOLDEN_BYTES
    assert w.except_counter == 2  # exactly the two aligned collisions


def test_reader_decodes_golden_bytes():
    ms = MemoryStream(GOLDEN_BYTES)
    assert [bytes(r) for r in RecordIOReader(ms)] == GOLDEN_RECORDS


def test_chunk_reader_decodes_golden_bytes():
    got = [bytes(r) for r in RecordIOChunkReader(GOLDEN_BYTES, 0, 1)]
    assert got == GOLDEN_RECORDS


@pytest.mark.skipif(
    not os.path.exists(REFERENCE_REC),
    reason="upstream reference checkout not present",
)
def test_reference_artifact_roundtrips_bit_identical():
    """Decode the artifact the REFERENCE implementation wrote, re-encode
    it with this writer: the bytes must match exactly."""
    orig = open(REFERENCE_REC, "rb").read()
    with FileStream(REFERENCE_REC, "r") as f:
        records = [bytes(r) for r in RecordIOReader(f)]
    assert len(records) == 10  # upstream unittest_inputsplit.cc:159-190
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    for rec in records:
        w.write_record(rec)
    assert ms.getvalue() == orig


def test_multipart_straddles_split_chunks(tmp_path):
    """Multipart chains must survive RecordIOSplitter chunking with tiny
    buffers and sharding (reference unittest_inputsplit.cc:147-190)."""
    rng = np.random.default_rng(0)
    records = []
    for i in range(60):
        body = bytearray(rng.bytes(64))
        if i % 3 == 0:
            # plant aligned magics to force multipart chains
            body[8:12] = MAGIC_BYTES
            body[32:36] = MAGIC_BYTES
        records.append(bytes(body) + str(i).encode())
    path = str(tmp_path / "straddle.rec")
    with FileStream(path, "w") as f:
        w = RecordIOWriter(f)
        for rec in records:
            w.write_record(rec)
        assert w.except_counter > 0
    for num_parts in (1, 2, 3):
        got = []
        for part in range(num_parts):
            sp = io_split.create(path, part, num_parts, type="recordio")
            sp.hint_chunk_size(256)  # force many tiny chunks
            got.extend(bytes(r) for r in sp)
            sp.close()
        assert sorted(got) == sorted(records), f"num_parts={num_parts}"


def test_multipart_chain_straddles_chunk_reader_subsplit():
    """Regression (the classic reference edge case, previously
    untested): a multi-part ESCAPED record (cflag 1/2/3 magic-collision
    chain) whose continuation frames straddle a RecordIOChunkReader
    sub-split (part_index/num_parts) boundary must be reassembled
    EXACTLY ONCE — by the part owning its START head — and never
    duplicated (a part whose range begins inside the chain must skip
    forward past it) or dropped (the owning part must read through its
    own range end to finish the chain)."""
    rng = np.random.default_rng(7)
    small = b"head-record"
    # a big record with aligned magics planted every 32 bytes: the
    # writer splits it into many cflag 1/2/3 parts spread over most of
    # the chunk, so ANY mid-chunk boundary lands between chain frames
    body = bytearray(rng.bytes(4096))
    for off in range(64, 4000, 32):
        body[off : off + 4] = MAGIC_BYTES
    big = bytes(body)
    tail = b"tail-record"
    records = [small, big, tail]
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    frame_spans = []
    for r in records:
        start = w.bytes_written
        w.write_record(r)
        frame_spans.append((start, w.bytes_written))
    assert w.except_counter > 50  # the chain really is many parts
    chunk = ms.getvalue()
    size = len(chunk)
    for num_parts in (2, 3, 4, 6):
        # at least one sub-split boundary must land strictly inside the
        # big record's chain for the test to exercise the edge
        nstep = ((size + num_parts - 1) // num_parts + 3) & ~3
        bounds = [min(size, nstep * p) for p in range(1, num_parts)]
        assert any(
            frame_spans[1][0] < b < frame_spans[1][1] for b in bounds
        ), f"num_parts={num_parts} boundary missed the chain"
        per_part = [
            [bytes(r) for r in RecordIOChunkReader(chunk, p, num_parts)]
            for p in range(num_parts)
        ]
        got = [r for part in per_part for r in part]
        assert got == records, f"num_parts={num_parts}: {len(got)} records"
        # exactly-once, owned by the part whose range holds the START
        assert sum(big in part for part in per_part) == 1
