"""Golden on-disk compressed-block format: a pre-built zlib `.rec` +
`.idx` pair is CHECKED IN under tests/data/ and must decode byte-exact
forever — pinning the container format (frame cflags, block header,
crc, index sidecar semantics) across future PRs. The expected records
are reconstructed deterministically here, never read back from the
code under test's own writer output.

(The encode direction is deliberately NOT pinned: compressed bytes may
differ across zlib builds. The contract is the decode of these exact
bytes.)
"""

import os
import struct

import numpy as np
import pytest

from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.codec import BLOCK_HEADER, crc32, decode_block
from dmlc_core_tpu.io.recordio import (
    KMAGIC,
    CFLAG_COMPRESSED,
    RecordIOReader,
    decode_flag,
    decode_length,
    scan_compressed_blob,
)
from dmlc_core_tpu.io.stream import FileStream

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")
GOLDEN_REC = os.path.join(DATA_DIR, "golden_zlib.rec")
GOLDEN_IDX = GOLDEN_REC + ".idx"
MAGIC = struct.pack("<I", KMAGIC)


def golden_records():
    """The exact record set the artifact was built from (generator
    seed 20260803; magic collisions every 7th record, one empty)."""
    rng = np.random.default_rng(20260803)
    out = []
    for i in range(40):
        body = bytearray(rng.bytes(24 + (i * 5) % 41))
        if i % 7 == 0:
            body[8:12] = MAGIC
        out.append(bytes(body) + b"#%d" % i)
    out[3] = b""
    return out


def test_artifact_present_and_nonempty():
    assert os.path.getsize(GOLDEN_REC) > 0
    assert os.path.getsize(GOLDEN_IDX) > 0


def test_golden_decode_byte_exact():
    with FileStream(GOLDEN_REC, "r") as f:
        assert list(RecordIOReader(f)) == golden_records()


def test_golden_frame_and_block_header_layout():
    """The first frame must be a compressed-block head with a valid
    version-1 zlib block header whose crc matches its decoded bytes —
    field-level pinning, independent of the reader implementation."""
    raw = open(GOLDEN_REC, "rb").read()
    magic, lrec = struct.unpack("<II", raw[:8])
    assert magic == KMAGIC
    cflag = decode_flag(lrec)
    assert cflag & CFLAG_COMPRESSED and (cflag & 3) <= 1
    assert decode_length(lrec) <= len(raw) - 8
    blob, _end = scan_compressed_blob(memoryview(raw), 0)
    codec_id, version, reserved, n_records, raw_len, want_crc = (
        BLOCK_HEADER.unpack_from(blob)
    )
    assert (codec_id, version, reserved) == (1, 1, 0)  # zlib, v1
    assert n_records > 0 and raw_len > 0
    decoded, n = decode_block(blob)
    assert n == n_records and len(decoded) == raw_len
    assert crc32(decoded) == want_crc


def test_golden_index_sidecar_block_semantics():
    """Sidecar format pin: ``key<TAB><block>:<in>`` lines, keys 0..39
    in order, block offsets pointing at compressed frame heads."""
    lines = open(GOLDEN_IDX).read().splitlines()
    assert len(lines) == 40
    raw = open(GOLDEN_REC, "rb").read()
    for i, line in enumerate(lines):
        key, _, off = line.partition("\t")
        assert int(key) == i
        block, _, inoff = off.partition(":")
        b, o = int(block), int(inoff)
        assert 0 <= b < len(raw) and o >= 0
        fmagic, flrec = struct.unpack("<II", raw[b : b + 8])
        assert fmagic == KMAGIC
        assert decode_flag(flrec) & CFLAG_COMPRESSED


@pytest.mark.parametrize("shuffle", ("0", "record", "window"))
def test_golden_reads_through_indexed_splitter(shuffle):
    sp = io_split.create(
        f"{GOLDEN_REC}?index={GOLDEN_IDX}&shuffle={shuffle}&seed=1"
        f"&window=16",
        0, 1, type="recordio", threaded=False,
    )
    got = sorted(bytes(r) for r in sp)
    sp.close()
    assert got == sorted(golden_records())
