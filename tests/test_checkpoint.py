"""Checkpoint/resume: pytree roundtrips over URIs, retention, training
resume equivalence, and checkpointing to (fake) S3."""

import os

import numpy as np

from dmlc_core_tpu.checkpoint import Checkpointer, load_pytree, save_pytree


def test_pytree_roundtrip_local(tmp_path):
    tree = {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.float32(1.5),
        "meta": {"step": 7, "name": "run1"},
        "stack": [np.ones(2), np.zeros(3)],
    }
    uri = str(tmp_path / "ck.bin")
    save_pytree(uri, tree)
    back = load_pytree(uri)
    np.testing.assert_array_equal(back["w"], tree["w"])
    assert back["meta"]["step"] == 7 and back["meta"]["name"] == "run1"
    np.testing.assert_array_equal(back["stack"][1], np.zeros(3))


def test_jax_params_roundtrip(tmp_path):
    import jax

    from dmlc_core_tpu.models import LogisticRegression

    model = LogisticRegression(16)
    params = model.init(jax.random.PRNGKey(0))
    uri = str(tmp_path / "params.bin")
    save_pytree(uri, params)
    back = load_pytree(uri)
    np.testing.assert_allclose(back["w"], np.asarray(params["w"]))


def test_checkpointer_steps_retention_resume(tmp_path):
    ck = Checkpointer(str(tmp_path / "ckpts"), keep=2, process_index=0)
    assert ck.latest_step() is None
    for step in [1, 5, 9]:
        ck.save(step, {"w": np.full(3, step, np.float32)})
    assert ck.steps() == [5, 9]  # pruned to keep=2
    step, tree = ck.restore()
    assert step == 9
    np.testing.assert_array_equal(tree["w"], [9, 9, 9])
    step5, tree5 = ck.restore(5)
    np.testing.assert_array_equal(tree5["w"], [5, 5, 5])
    # non-writer processes skip the write
    ck1 = Checkpointer(str(tmp_path / "ckpts"), process_index=1)
    assert ck1.save(11, {"w": np.zeros(1)}) is None
    assert ck1.latest_step() == 9
    # no .tmp leftovers (atomic rename)
    assert not [f for f in os.listdir(tmp_path / "ckpts") if ".tmp" in f]


def test_training_resume_equivalence(tmp_path):
    """Train 10 steps straight == train 5, checkpoint, restore, train 5."""
    import jax

    from dmlc_core_tpu.models import LogisticRegression
    from tests.test_models import synth_batch

    rng = np.random.default_rng(0)
    model = LogisticRegression(16)
    step = jax.jit(lambda p, b: model.sgd_step(p, b, lr=0.2))
    batches = [synth_batch(rng, batch=32, d=16)[0] for _ in range(10)]

    p_straight = model.init(jax.random.PRNGKey(0))
    for b in batches:
        p_straight, _ = step(p_straight, b)

    p = model.init(jax.random.PRNGKey(0))
    ck = Checkpointer(str(tmp_path / "ck"), process_index=0)
    for b in batches[:5]:
        p, _ = step(p, b)
    ck.save(5, p)
    _, p2 = ck.restore()
    p2 = {k: np.asarray(v) for k, v in p2.items()}
    for b in batches[5:]:
        p2, _ = step(p2, b)
    np.testing.assert_allclose(
        np.asarray(p_straight["w"]), np.asarray(p2["w"]), rtol=1e-6
    )


def test_checkpoint_to_fake_s3(monkeypatch):
    from tests.test_cloudfs import FakeS3Handler, _Server
    from dmlc_core_tpu.io.cloudfs import reset_singletons

    FakeS3Handler.STORE = {}
    FakeS3Handler.UPLOADS = {}
    srv = _Server(FakeS3Handler)
    monkeypatch.setenv("S3_ENDPOINT", srv.url)
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", FakeS3Handler.ACCESS)
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", FakeS3Handler.SECRET)
    reset_singletons()
    try:
        ck = Checkpointer("s3://bkt/run1", process_index=0)
        ck.save(3, {"w": np.ones(4, np.float32)})
        assert ck.latest_step() == 3
        step, tree = ck.restore()
        assert step == 3
        np.testing.assert_array_equal(tree["w"], np.ones(4))
    finally:
        reset_singletons()
        srv.stop()
