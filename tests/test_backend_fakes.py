"""ssh / slurm backend integration via fake binaries (extends the
tpu-pod fake-gcloud pattern, VERDICT r4 weak #7: command-builder-only
backends get real submit → rendezvous coverage).

The fakes execute the payload locally with the same arg surface the
real binaries expose: `ssh ... host remote_cmd` runs remote_cmd in a
shell; `srun --ntasks=N --export=ALL,K=V,... cmd` spawns N local
copies with the exported env. Workers are real rabit clients driving
the real tracker.
"""

import importlib
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAKE_SSH = """#!/bin/sh
# ssh stand-in: skip options (-o X, -p N), then host, then the command
while [ $# -gt 0 ]; do
  case "$1" in
    -o|-p) shift 2 ;;
    -*) shift ;;
    *) break ;;
  esac
done
host="$1"; shift
exec sh -c "$@"
"""

FAKE_SRUN = """#!/usr/bin/env python3
import os, subprocess, sys

args = sys.argv[1:]
ntasks = 1
cmd = []
for i, a in enumerate(args):
    if a.startswith("--ntasks="):
        ntasks = int(a.split("=", 1)[1])
    elif a.startswith("--nodes="):
        pass
    elif a.startswith("--export="):
        spec = a.split("=", 1)[1]
        for kv in spec.split(",")[1:]:  # first token is ALL
            k, v = kv.split("=", 1)
            os.environ[k] = v
    else:
        cmd = args[i:]
        break
procs = []
for rank in range(ntasks):
    env = dict(os.environ)
    env["SLURM_PROCID"] = str(rank)
    procs.append(subprocess.Popen(cmd, env=env))
codes = [p.wait() for p in procs]  # wait for ALL tasks, like real srun
sys.exit(next((c for c in codes if c), 0))
"""

WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.tracker.client import RabitWorker
w = RabitWorker()
rank = w.start()
with open({out!r} + str(rank), "w") as f:
    f.write("%s %s %s" % (rank, os.environ["DMLC_ROLE"],
                          os.environ.get("DMLC_JOB_CLUSTER")))
w.shutdown()
"""


from conftest import install_fake_binary as _install  # noqa: E402


def _worker_script(tmp_path, out):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO, out=out))
    return script


def _check_ranks(out, n, cluster):
    got = set()
    for r in range(n):
        rank, role, job_cluster = open(out + str(r)).read().split()
        got.add(int(rank))
        assert role == "worker" and job_cluster == cluster
    assert got == set(range(n))


@pytest.mark.slow
def test_ssh_submit_end_to_end(tmp_path, monkeypatch):
    _install(tmp_path, monkeypatch, "ssh", FAKE_SSH)
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1\n127.0.0.1:2222  # comment\n")
    out = str(tmp_path / "rank")
    script = _worker_script(tmp_path, out)
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main([
        "--cluster", "ssh", "--num-workers", "2",
        "--host-file", str(hosts), "--host-ip", "127.0.0.1",
        sys.executable, str(script),
    ])
    _check_ranks(out, 2, "ssh")


@pytest.mark.slow
def test_slurm_submit_end_to_end(tmp_path, monkeypatch):
    _install(tmp_path, monkeypatch, "srun", FAKE_SRUN)
    out = str(tmp_path / "rank")
    script = _worker_script(tmp_path, out)
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main([
        "--cluster", "slurm", "--num-workers", "2",
        "--host-ip", "127.0.0.1",
        sys.executable, str(script),
    ])
    _check_ranks(out, 2, "slurm")


FAKE_QSUB = """#!/usr/bin/env python3
# qsub stand-in: parse `-t 1-N`, run the array script N times locally
# (detached, like a queued array job) with SGE_TASK_ID set.
import subprocess, sys

args = sys.argv[1:]
lo, hi = 1, 1
script = args[-1]
i = 0
while i < len(args) - 1:
    if args[i] == "-t":
        lo, hi = (int(x) for x in args[i + 1].split("-"))
        i += 2
    elif args[i] in ("-q", "-N", "-o", "-e", "-S"):
        i += 2
    else:
        i += 1
import os
for tid in range(lo, hi + 1):
    subprocess.Popen(["bash", script], env={"SGE_TASK_ID": str(tid),
                                            "PATH": os.environ["PATH"]})
sys.exit(0)  # real qsub returns once the job is queued
"""

FAKE_MESOS_EXECUTE = """#!/usr/bin/env python3
# mesos-execute stand-in: apply --env= and run --command= locally,
# blocking until the task exits (like the real CLI).
import os, subprocess, sys

env = dict(os.environ)
cmd = None
for a in sys.argv[1:]:
    if a.startswith("--env="):
        for kv in a[len("--env="):].split(";"):
            k, v = kv.split("=", 1)
            env[k] = v
    elif a.startswith("--command="):
        cmd = a[len("--command="):]
sys.exit(subprocess.call(cmd, shell=True, env=env))
"""


@pytest.mark.slow
def test_sge_submit_end_to_end(tmp_path, monkeypatch):
    _install(tmp_path, monkeypatch, "qsub", FAKE_QSUB)
    monkeypatch.chdir(tmp_path)  # the backend writes rundmlc.sh to cwd
    out = str(tmp_path / "rank")
    script = _worker_script(tmp_path, out)
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main([
        "--cluster", "sge", "--num-workers", "2",
        "--host-ip", "127.0.0.1",
        sys.executable, str(script),
    ])
    _check_ranks(out, 2, "sge")


@pytest.mark.slow
def test_mesos_submit_end_to_end(tmp_path, monkeypatch):
    _install(tmp_path, monkeypatch, "mesos-execute", FAKE_MESOS_EXECUTE)
    monkeypatch.setenv("MESOS_MASTER", "fake-master:5050")
    out = str(tmp_path / "rank")
    script = _worker_script(tmp_path, out)
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main([
        "--cluster", "mesos", "--num-workers", "2",
        "--host-ip", "127.0.0.1",
        sys.executable, str(script),
    ])
    _check_ranks(out, 2, "mesos")


FAKE_KUBECTL = """#!/usr/bin/env python3
# kubectl stand-in: `kubectl apply -n NS -f -` reads a JSON v1 List of
# Job manifests on stdin and runs each container command locally
# (detached, like the cluster's job controller would).
import json, os, subprocess, sys

bundle = json.load(sys.stdin)
for manifest in bundle["items"]:
    spec = manifest["spec"]["template"]["spec"]["containers"][0]
    env = dict(os.environ)
    for kv in spec["env"]:
        env[kv["name"]] = kv["value"]
    subprocess.Popen(spec["command"], env=env)
    print("job.batch/%s created" % manifest["metadata"]["name"])
sys.exit(0)
"""


@pytest.mark.slow
def test_kubernetes_submit_end_to_end(tmp_path, monkeypatch):
    """Without the python kubernetes client installed, submission falls
    back to `kubectl apply -f -` with the JSON manifests — driven here
    end to end by a fake kubectl that runs the container command."""
    _install(tmp_path, monkeypatch, "kubectl", FAKE_KUBECTL)
    # pin the fallback deterministically: a host with the python client
    # installed would otherwise submit to a REAL cluster here
    monkeypatch.setitem(sys.modules, "kubernetes", None)
    out = str(tmp_path / "rank")
    script = _worker_script(tmp_path, out)
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main([
        "--cluster", "kubernetes", "--num-workers", "2",
        "--host-ip", "127.0.0.1",
        sys.executable, str(script),
    ])
    _check_ranks(out, 2, "kubernetes")


FAKE_MPIRUN = """#!/usr/bin/env python3
# mpirun stand-in (openmpi arg surface): `mpirun -n N -x K=V ... cmd`
# spawns N local copies with the -x env applied, waits for all.
import os, subprocess, sys

args = sys.argv[1:]
n = 1
env = dict(os.environ)
cmd = []
i = 0
while i < len(args):
    a = args[i]
    if a == "--version":
        print("mpirun (Open MPI) 4.1-fake"); sys.exit(0)
    if a == "-n":
        n = int(args[i + 1]); i += 2
    elif a == "-x":
        k, v = args[i + 1].split("=", 1); env[k] = v; i += 2
    elif a == "--hostfile":
        i += 2
    else:
        cmd = args[i:]; break
procs = [subprocess.Popen(cmd, env=env) for _ in range(n)]
codes = [p.wait() for p in procs]
sys.exit(next((c for c in codes if c), 0))
"""


@pytest.mark.slow
def test_mpi_submit_end_to_end(tmp_path, monkeypatch):
    _install(tmp_path, monkeypatch, "mpirun", FAKE_MPIRUN)
    out = str(tmp_path / "rank")
    script = _worker_script(tmp_path, out)
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main([
        "--cluster", "mpi", "--num-workers", "2",
        "--host-ip", "127.0.0.1",
        sys.executable, str(script),
    ])
    _check_ranks(out, 2, "mpi")
