"""tpu-pod backend integration (VERDICT r4 #7): a fake ``gcloud`` that
executes the ``--command`` payload locally, driven through the REAL
submit → tracker rendezvous → Supervisor pipeline, at the same depth as
``test_local_submit_end_to_end``:

- 2-worker submit: both contracts exported (DMLC_* + JAX_* coordinator
  env), ranks rendezvous through the real tracker;
- one injected worker death on its first attempt: the Supervisor
  relaunches with the same task id (pinned placement) and the job
  completes;
- a worker that always dies: the failure budget trips and the pinned
  placement (allow_replacement=False) aborts the job instead of
  wedging the rendezvous wait.
"""

import importlib
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAKE_GCLOUD = """#!/bin/sh
# gcloud stand-in: find the --command payload and run it locally.
# Everything else (compute tpus tpu-vm ssh <name> --worker N ...) is
# accepted and ignored, matching the real CLI's shape.
prev=""
cmd=""
for a in "$@"; do
  if [ "$prev" = "--command" ]; then cmd="$a"; fi
  prev="$a"
done
if [ -z "$cmd" ]; then echo "fake gcloud: no --command" >&2; exit 2; fi
exec sh -c "$cmd"
"""

WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
# record every attempt before doing anything that can fail
with open({out!r} + "_attempts", "a") as f:
    f.write("%s:%s\\n" % (os.environ["DMLC_TASK_ID"],
                          os.environ["DMLC_NUM_ATTEMPT"]))
mode = {mode!r}
tid = int(os.environ["DMLC_TASK_ID"])
att = int(os.environ["DMLC_NUM_ATTEMPT"])
if mode == "die_once" and tid == 1 and att == 0:
    os._exit(1)  # killed before rendezvous; Supervisor must relaunch
if mode == "die_always" and tid == 1:
    os._exit(1)
from dmlc_core_tpu.tracker.client import RabitWorker
w = RabitWorker()
rank = w.start()
with open({out!r} + str(rank), "w") as f:
    f.write("%s %s %s %s" % (
        rank,
        os.environ["DMLC_ROLE"],
        os.environ["JAX_COORDINATOR_ADDRESS"],
        os.environ["JAX_PROCESS_ID"],
    ))
w.shutdown()
"""


@pytest.fixture()
def fake_gcloud(tmp_path, monkeypatch):
    from conftest import install_fake_binary

    return install_fake_binary(tmp_path, monkeypatch, "gcloud", FAKE_GCLOUD)


@pytest.fixture(autouse=True)
def _generous_grace(monkeypatch):
    # anti-wedge grace: on the 1-vCPU CI host a loaded machine can
    # stretch worker shutdown well past the 10s default, and a grace
    # trip aborts the job as "not a rabit client" (observed flake)
    monkeypatch.setenv("DMLC_RENDEZVOUS_GRACE", "60")


def _submit(tmp_path, mode, out):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO, out=out, mode=mode))
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main([
        "--cluster", "tpu-pod", "--num-workers", "2",
        "--tpu-name", "fake-pod", "--tpu-zone", "nowhere-1a",
        "--host-ip", "127.0.0.1",
        sys.executable, str(script),
    ])


@pytest.mark.slow
def test_tpu_pod_submit_end_to_end(tmp_path, fake_gcloud, monkeypatch):
    monkeypatch.setenv("DMLC_MAX_ATTEMPT", "3")
    out = str(tmp_path / "rank")
    _submit(tmp_path, "ok", out)
    for r in range(2):
        rank, role, coord, pid = open(out + str(r)).read().split()
        assert int(rank) == r and role == "worker"
        # the jax.distributed contract rode the env exports
        assert coord.endswith(":8476")
        assert 0 <= int(pid) < 2
    attempts = open(out + "_attempts").read().splitlines()
    assert sorted(attempts) == ["0:0", "1:0"]


@pytest.mark.slow
def test_tpu_pod_relaunch_same_task_id_after_kill(
    tmp_path, fake_gcloud, monkeypatch
):
    """Supervised relaunch keeps the task id (= pod host = InputSplit
    part). The worker dies BEFORE rendezvous, so this covers the
    Supervisor x tracker composition, not rank reclaim — that path is
    drilled in test_tracker.py's pod-scale drill."""
    monkeypatch.setenv("DMLC_MAX_ATTEMPT", "3")
    out = str(tmp_path / "rank")
    _submit(tmp_path, "die_once", out)
    got = {int(open(out + str(r)).read().split()[0]) for r in range(2)}
    assert got == {0, 1}
    attempts = sorted(open(out + "_attempts").read().splitlines())
    # worker 1 died on attempt 0 and came back as attempt 1, same task id
    assert attempts == ["0:0", "1:0", "1:1"]


@pytest.mark.slow
def test_tpu_pod_pinned_placement_aborts_past_budget(
    tmp_path, fake_gcloud, monkeypatch
):
    from dmlc_core_tpu.tracker.supervisor import JobAborted

    monkeypatch.setenv("DMLC_MAX_ATTEMPT", "2")
    out = str(tmp_path / "rank")
    with pytest.raises(JobAborted):
        _submit(tmp_path, "die_always", out)
    attempts = sorted(open(out + "_attempts").read().splitlines())
    # budget of 2 attempts for task 1, then abort — no replacement host
    # (fixed placement: JAX process i must run on pod host i)
    assert attempts.count("1:0") == 1 and attempts.count("1:1") == 1
    assert not any(a.startswith("1:2") for a in attempts)
