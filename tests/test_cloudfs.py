"""Cloud filesystem tests against in-process fake servers.

The reference tests S3 against real buckets (test/README.md); we keep
tests hermetic: a Range-supporting HTTP server, a fake S3 implementing
object GET/HEAD/PUT, ListObjectsV2 and multipart upload (verifying SigV4
Authorization headers), and a fake WebHDFS namenode.
"""

import hashlib
import json
import os
import threading
import urllib.parse
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.cloudfs import (
    GCSFileSystem,
    SigV4Signer,
    WebHdfsFileSystem,
    reset_singletons,
)
from dmlc_core_tpu.io.filesystem import FileSystem
from dmlc_core_tpu.io.stream import Stream


# -- infrastructure ----------------------------------------------------------

class _Server:
    def __init__(self, handler_cls):
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _range_bounds(header, total):
    # "bytes=a-" or "bytes=a-b"
    spec = header.split("=", 1)[1]
    a, _, b = spec.partition("-")
    start = int(a)
    end = int(b) + 1 if b else total
    return start, min(end, total)


class RangeFileHandler(BaseHTTPRequestHandler):
    """Serves FILES dict with Range support."""

    FILES = {}

    def log_message(self, *a):
        pass

    def _serve(self, send_body=True):
        path = urllib.parse.urlsplit(self.path).path
        data = self.FILES.get(path)
        if data is None:
            self.send_error(404)
            return
        rng = self.headers.get("Range")
        if rng:
            start, end = _range_bounds(rng, len(data))
            if start >= len(data):
                self.send_error(416)
                return
            body = data[start:end]
            self.send_response(206)
            self.send_header(
                "Content-Range", f"bytes {start}-{end - 1}/{len(data)}"
            )
        else:
            body = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if send_body:
            self.wfile.write(body)

    def do_GET(self):
        self._serve()

    def do_HEAD(self):
        self._serve(send_body=False)


class FakeS3Handler(BaseHTTPRequestHandler):
    """Minimal S3: path-style /bucket/key; GET/HEAD/PUT objects with Range,
    ListObjectsV2, multipart upload, server-side copy. Asserts SigV4
    Authorization headers. FAIL_GET / FAIL_PART_PUT script N consecutive
    500s before success (the transient-failure shapes the retry layer
    must heal)."""

    STORE = {}
    UPLOADS = {}
    REQUIRE_AUTH = True
    SAW_AUTH = []
    ACCESS = "AKIDTEST"
    SECRET = "sekrit"
    REGION = "us-east-1"
    FAIL_GET = 0
    FAIL_PART_PUT = 0

    def log_message(self, *a):
        pass

    def _check_auth(self):
        """Recompute SigV4 from the WIRE request (method/path/query/headers
        as received) — like real S3 — so canonicalization bugs
        (double-encoding, query re-encoding) fail here, not in prod."""
        auth = self.headers.get("Authorization", "")
        self.SAW_AUTH.append(auth)
        if not self.REQUIRE_AUTH:
            return True
        if not auth.startswith("AWS4-HMAC-SHA256"):
            self.send_error(403, "missing sigv4")
            return False
        amz = self.headers["x-amz-date"]
        now = datetime.strptime(amz, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=timezone.utc
        )
        signed_list = auth.split("SignedHeaders=")[1].split(",")[0].split(";")
        extra = {
            name: self.headers[name]
            for name in signed_list
            if name not in ("host", "x-amz-date", "x-amz-content-sha256")
        }
        url = f"http://{self.headers['Host']}{self.path}"
        expected = SigV4Signer(self.ACCESS, self.SECRET, self.REGION).sign(
            self.command,
            url,
            extra,
            payload_hash=self.headers["x-amz-content-sha256"],
            now=now,
        )["Authorization"]
        if expected != auth:
            self.send_error(403, "SignatureDoesNotMatch")
            return False
        return True

    def _key(self):
        parsed = urllib.parse.urlsplit(self.path)
        return parsed.path.lstrip("/"), urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True
        )

    def do_HEAD(self):
        if not self._check_auth():
            return
        key, _ = self._key()
        data = self.STORE.get(key)
        if data is None:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        if type(self).FAIL_GET > 0:
            type(self).FAIL_GET -= 1
            self.send_error(500, "InternalError (scripted)")
            return
        if not self._check_auth():
            return
        key, q = self._key()
        if "list-type" in q:
            bucket = key.rstrip("/")
            prefix = q.get("prefix", [""])[0]
            delim = q.get("delimiter", [""])[0]
            contents, prefixes = [], set()
            for k, v in sorted(self.STORE.items()):
                b, _, rest = k.partition("/")
                if b != bucket or not rest.startswith(prefix):
                    continue
                tail = rest[len(prefix):]
                if delim and delim in tail:
                    prefixes.add(prefix + tail.split(delim)[0] + delim)
                else:
                    contents.append(
                        f"<Contents><Key>{rest}</Key>"
                        f"<Size>{len(v)}</Size></Contents>"
                    )
            cps = "".join(
                f"<CommonPrefixes><Prefix>{p}</Prefix></CommonPrefixes>"
                for p in sorted(prefixes)
            )
            body = (
                "<ListBucketResult><IsTruncated>false</IsTruncated>"
                + "".join(contents) + cps + "</ListBucketResult>"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        data = self.STORE.get(key)
        if data is None:
            self.send_error(404)
            return
        rng = self.headers.get("Range")
        if rng:
            start, end = _range_bounds(rng, len(data))
            if start >= len(data):
                self.send_error(416)
                return
            body = data[start:end]
            self.send_response(206)
            self.send_header(
                "Content-Range", f"bytes {start}-{end - 1}/{len(data)}"
            )
        else:
            body = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n)

    def do_PUT(self):
        if not self._check_auth():
            return
        key, q = self._key()
        body = self._body()
        if "partNumber" in q:
            if type(self).FAIL_PART_PUT > 0:
                type(self).FAIL_PART_PUT -= 1
                self.send_error(500, "InternalError (scripted)")
                return
            uid = q["uploadId"][0]
            pn = int(q["partNumber"][0])
            self.UPLOADS.setdefault(uid, {})[pn] = body
            etag = f'"{hashlib.md5(body).hexdigest()}"'
            self.send_response(200)
            self.send_header("ETag", etag)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        src = self.headers.get("x-amz-copy-source")
        if src:
            src_key = urllib.parse.unquote(src).lstrip("/")
            if src_key not in self.STORE:
                self.send_error(404)
                return
            self.STORE[key] = self.STORE[src_key]
            out = b"<CopyObjectResult/>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
            return
        self.STORE[key] = body
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if not self._check_auth():
            return
        key, _ = self._key()
        self.STORE.pop(key, None)  # S3 DELETE is idempotent: 204 either way
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    BATCH_DELETES = 0

    def do_POST(self):
        if not self._check_auth():
            return
        key, q = self._key()
        body_bytes = self._body()
        if "delete" in q:
            # DeleteObjects: Content-MD5 mandatory, like real S3
            import base64 as b64mod
            import xml.etree.ElementTree as ETmod

            want = b64mod.b64encode(
                hashlib.md5(body_bytes).digest()
            ).decode()
            if self.headers.get("Content-MD5") != want:
                self.send_error(400, "InvalidDigest")
                return
            type(self).BATCH_DELETES += 1
            bucket = key.split("/", 1)[0]
            root = ETmod.fromstring(body_bytes)
            for obj in root.iter("Object"):
                k = obj.findtext("Key") or ""
                self.STORE.pop(f"{bucket}/{k}", None)
            out = b"<DeleteResult/>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
            return
        if "uploads" in q:
            uid = f"upl{len(self.UPLOADS)}"
            self.UPLOADS[uid] = {}
            body = (
                f"<InitiateMultipartUploadResult><UploadId>{uid}"
                "</UploadId></InitiateMultipartUploadResult>"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        uid = q["uploadId"][0]
        parts = self.UPLOADS.pop(uid)
        self.STORE[key] = b"".join(parts[i] for i in sorted(parts))
        body = b"<CompleteMultipartUploadResult/>"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class FakeWebHdfsHandler(BaseHTTPRequestHandler):
    """Read ops plus the write surface: CREATE/APPEND answer the
    namenode request with a 307 redirect to a fake 'datanode' path on
    the same server (the real WebHDFS two-step), RENAME moves keys,
    DELETE removes them."""

    FILES = {"/data/a.txt": b"alpha\nbeta\ngamma\n"}
    _DN = "/webhdfs/dn/v1"  # fake datanode prefix

    def log_message(self, *a):
        pass

    def _parsed(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = urllib.parse.parse_qs(parsed.query)
        return parsed.path, q

    def _redirect_to_dn(self, path, q):
        loc = (
            f"http://{self.headers['Host']}{self._DN}{path}"
            + "?" + urllib.parse.urlencode({k: v[0] for k, v in q.items()})
        )
        self.send_response(307)
        self.send_header("Location", loc)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        path, q = self._parsed()
        op = q.get("op", [""])[0]
        if path.startswith(self._DN):
            # datanode leg: the payload lands
            n = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(n)
            hpath = path[len(self._DN):]
            if op == "CREATE":
                self.FILES[hpath] = data
                self._json({}, code=201)
            else:
                self.send_error(400, f"bad datanode op {op}")
            return
        assert path.startswith("/webhdfs/v1")
        hpath = path[len("/webhdfs/v1"):]
        if op == "CREATE":
            self._redirect_to_dn(hpath, q)
            return
        if op == "RENAME":
            dst = q["destination"][0]
            if hpath not in self.FILES:
                self._json({"boolean": False})
                return
            if dst in self.FILES:
                # HDFS refuses to rename over an existing file
                self._json({"boolean": False})
                return
            self.FILES[dst] = self.FILES.pop(hpath)
            self._json({"boolean": True})
            return
        self.send_error(400, f"bad PUT op {op}")

    def do_POST(self):
        path, q = self._parsed()
        op = q.get("op", [""])[0]
        if path.startswith(self._DN):
            n = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(n)
            hpath = path[len(self._DN):]
            if op == "APPEND":
                self.FILES[hpath] = self.FILES.get(hpath, b"") + data
                self._json({})
            else:
                self.send_error(400, f"bad datanode op {op}")
            return
        assert path.startswith("/webhdfs/v1")
        if op == "APPEND":
            self._redirect_to_dn(path[len("/webhdfs/v1"):], q)
            return
        self.send_error(400, f"bad POST op {op}")

    def do_DELETE(self):
        path, q = self._parsed()
        assert path.startswith("/webhdfs/v1")
        hpath = path[len("/webhdfs/v1"):]
        self._json({"boolean": self.FILES.pop(hpath, None) is not None})

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        assert parsed.path.startswith("/webhdfs/v1")
        path = parsed.path[len("/webhdfs/v1"):]
        q = urllib.parse.parse_qs(parsed.query)
        op = q["op"][0]
        if op == "GETFILESTATUS":
            if path in self.FILES:
                st = {"type": "FILE", "length": len(self.FILES[path])}
            elif any(k.startswith(path.rstrip("/") + "/") for k in self.FILES):
                st = {"type": "DIRECTORY", "length": 0}
            else:
                self.send_error(404)
                return
            body = json.dumps({"FileStatus": st}).encode()
        elif op == "LISTSTATUS":
            base = path.rstrip("/")
            entries = [
                {
                    "pathSuffix": k[len(base) + 1:],
                    "type": "FILE",
                    "length": len(v),
                }
                for k, v in sorted(self.FILES.items())
                if k.startswith(base + "/")
            ]
            body = json.dumps({"FileStatuses": {"FileStatus": entries}}).encode()
        elif op == "OPEN":
            data = self.FILES.get(path)
            if data is None:
                self.send_error(404)
                return
            offset = int(q.get("offset", ["0"])[0])
            body = data[offset:]
        else:
            self.send_error(400, f"bad op {op}")
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


# -- http(s) -----------------------------------------------------------------

@pytest.fixture
def http_server():
    RangeFileHandler.FILES = {
        "/f.txt": b"0123456789" * 100,
        "/data.libsvm": b"".join(b"%d 0:1 1:2\n" % (i,) for i in range(50)),
    }
    srv = _Server(RangeFileHandler)
    yield srv
    srv.stop()


def test_http_read_and_seek(http_server):
    s = Stream.create(f"{http_server.url}/f.txt", "r")
    assert s.read(10) == b"0123456789"
    s.seek(995)
    assert s.read(10) == b"56789"  # across the end
    s.seek(0)
    assert len(s.read()) == 1000
    s.close()


def test_http_sharded_split(http_server):
    """InputSplit over http:// — remote byte-range sharding end to end."""
    uri = f"{http_server.url}/data.libsvm"
    labels = []
    for rank in range(2):
        sp = io_split.create(uri, rank, 2, type="text")
        for rec in sp:
            labels.append(int(rec.split()[0]))
        sp.close()
    assert sorted(labels) == list(range(50))


# -- sigv4 -------------------------------------------------------------------

def test_sigv4_stable_signature():
    """Golden snapshot with a pinned clock: catches accidental changes to
    the canonicalization."""
    signer = SigV4Signer("AKIDEXAMPLE", "SECRET", "us-east-1", "s3")
    now = datetime(2026, 1, 2, 3, 4, 5, tzinfo=timezone.utc)
    h = signer.sign(
        "GET", "https://bucket.s3.us-east-1.amazonaws.com/key.txt", {},
        now=now,
    )
    assert h["x-amz-date"] == "20260102T030405Z"
    assert h["Authorization"].startswith(
        "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20260102/us-east-1/s3/"
        "aws4_request, SignedHeaders=host;x-amz-content-sha256;x-amz-date,"
    )
    sig = h["Authorization"].rsplit("Signature=", 1)[1]
    assert len(sig) == 64 and int(sig, 16) >= 0
    # deterministic given the pinned clock
    h2 = signer.sign(
        "GET", "https://bucket.s3.us-east-1.amazonaws.com/key.txt", {},
        now=now,
    )
    assert h2["Authorization"] == h["Authorization"]


# -- s3 ----------------------------------------------------------------------

@pytest.fixture
def s3(monkeypatch):
    FakeS3Handler.STORE = {}
    FakeS3Handler.UPLOADS = {}
    FakeS3Handler.SAW_AUTH = []
    FakeS3Handler.FAIL_GET = 0
    FakeS3Handler.FAIL_PART_PUT = 0
    srv = _Server(FakeS3Handler)
    monkeypatch.setenv("S3_ENDPOINT", srv.url)
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIDTEST")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "sekrit")
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    # retry backoff at test speed (policies read env at construction)
    monkeypatch.setenv("DMLC_RETRY_BASE_SECS", "0.001")
    monkeypatch.setenv("DMLC_RETRY_CAP_SECS", "0.01")
    reset_singletons()
    yield srv
    reset_singletons()
    srv.stop()


def test_s3_write_read_roundtrip(s3):
    fs = FileSystem.get_instance("s3://bkt/dir/a.bin")
    payload = bytes(range(256)) * 10
    w = fs.open("s3://bkt/dir/a.bin", "w")
    w.write(payload)
    w.close()
    assert FakeS3Handler.STORE["bkt/dir/a.bin"] == payload
    r = fs.open("s3://bkt/dir/a.bin", "r")
    assert r.read() == payload
    r.seek(100)
    assert r.read(5) == payload[100:105]
    r.close()
    assert all(
        a.startswith("AWS4-HMAC-SHA256") for a in FakeS3Handler.SAW_AUTH
    )


def test_s3_delete_object_and_prefix(s3):
    FakeS3Handler.BATCH_DELETES = 0
    FakeS3Handler.STORE.update(
        {
            "bkt/ck/a.bin": b"a",
            "bkt/ck/sub/b.bin": b"b",
            "bkt/ck/sub/c d+e.bin": b"c",  # key needing XML/URL care
            "bkt/keep.txt": b"k",
        }
    )
    fs = FileSystem.get_instance("s3://bkt/ck")
    fs.delete("s3://bkt/ck/a.bin")
    assert "bkt/ck/a.bin" not in FakeS3Handler.STORE
    # recursive prefix sweep rides ONE DeleteObjects POST, not
    # per-object round trips (checkpoint retention on object stores)
    fs.delete("s3://bkt/ck", recursive=True)
    assert [k for k in FakeS3Handler.STORE if k.startswith("bkt/ck")] == []
    assert "bkt/keep.txt" in FakeS3Handler.STORE
    assert FakeS3Handler.BATCH_DELETES == 1


def test_s3_multipart_upload(s3, monkeypatch):
    monkeypatch.setenv("DMLC_S3_WRITE_BUFFER_BYTES", "1024")
    fs = FileSystem.get_instance("s3://bkt/big.bin")
    payload = os.urandom(5000)
    w = fs.open("s3://bkt/big.bin", "w")
    w.write(payload)
    w.close()
    assert FakeS3Handler.STORE["bkt/big.bin"] == payload


def test_s3_list_and_stat(s3):
    FakeS3Handler.STORE.update(
        {
            "bkt/d/x.txt": b"xx",
            "bkt/d/y.txt": b"yyy",
            "bkt/d/sub/z.txt": b"z",
            "bkt/other.txt": b"o",
        }
    )
    fs = FileSystem.get_instance("s3://bkt/d")
    listing = {f.path: (f.size, f.type) for f in fs.list_directory("s3://bkt/d")}
    assert listing["s3://bkt/d/x.txt"] == (2, "file")
    assert listing["s3://bkt/d/y.txt"] == (3, "file")
    assert listing["s3://bkt/d/sub/"] == (0, "directory")
    info = fs.get_path_info("s3://bkt/d/x.txt")
    assert info.size == 2 and info.type == "file"
    assert fs.get_path_info("s3://bkt/d").type == "directory"


def test_s3_sharded_parse(s3, tmp_path):
    """The reference's distributed-shard test pattern over fake S3."""
    lines = b"".join(b"%d 0:1 2:2\n" % (i,) for i in range(40))
    FakeS3Handler.STORE["bkt/train.libsvm"] = lines
    from dmlc_core_tpu import data as D

    labels = []
    for rank in range(2):
        parser = D.create_parser(
            "s3://bkt/train.libsvm", rank, 2, type="libsvm", threaded=False
        )
        for blk in parser:
            labels.extend(blk.label.astype(int).tolist())
        parser.close()
    assert sorted(labels) == list(range(40))


def test_gcs_uses_same_wire(s3, monkeypatch):
    monkeypatch.setenv("GCS_ENDPOINT", s3.url)
    monkeypatch.setenv("GS_ACCESS_KEY_ID", "GOOGTEST")
    monkeypatch.setenv("GS_SECRET_ACCESS_KEY", "gsekrit")
    monkeypatch.setattr(FakeS3Handler, "ACCESS", "GOOGTEST")
    monkeypatch.setattr(FakeS3Handler, "SECRET", "gsekrit")
    reset_singletons()
    FakeS3Handler.STORE["gbkt/obj.txt"] = b"gcs-data"
    fs = FileSystem.get_instance("gs://gbkt/obj.txt")
    assert isinstance(fs, GCSFileSystem)
    r = fs.open("gs://gbkt/obj.txt", "r")
    assert r.read() == b"gcs-data"
    r.close()


# -- gs:// ADC (metadata server / service-account JWT) -----------------------


class FakeMetadataHandler(BaseHTTPRequestHandler):
    """GCE metadata server: /computeMetadata/v1/.../token with the
    mandatory Metadata-Flavor header."""

    TOKEN = "meta-token-1"
    EXPIRES_IN = 3600
    CALLS = 0

    def log_message(self, *a):
        pass

    def do_GET(self):
        type(self).CALLS += 1
        if self.headers.get("Metadata-Flavor") != "Google":
            self.send_error(403, "missing Metadata-Flavor")
            return
        body = json.dumps({
            "access_token": self.TOKEN,
            "expires_in": self.EXPIRES_IN,
            "token_type": "Bearer",
        }).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class FakeGcsBearerHandler(BaseHTTPRequestHandler):
    """GCS XML API accepting ONLY Bearer auth (no SigV4): GET/HEAD
    objects from STORE; records the Authorization headers seen."""

    STORE = {}
    EXPECT_TOKEN = "meta-token-1"
    SAW_AUTH = []
    ALLOW_ANON = False

    def log_message(self, *a):
        pass

    def _key(self):
        return urllib.parse.unquote(self.path.split("?", 1)[0].lstrip("/"))

    def _authed(self):
        auth = self.headers.get("Authorization", "")
        type(self).SAW_AUTH.append(auth)
        if self.ALLOW_ANON and not auth:
            return True
        if auth != f"Bearer {self.EXPECT_TOKEN}":
            self.send_error(401, "bad bearer")
            return False
        return True

    def do_HEAD(self):
        if not self._authed():
            return
        data = self.STORE.get(self._key())
        if data is None:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        if not self._authed():
            return
        data = self.STORE.get(self._key())
        if data is None:
            self.send_error(404)
            return
        rng = self.headers.get("Range")
        if rng:
            a, b = _range_bounds(rng, len(data))
            chunk = data[a:b + 1]
            self.send_response(206)
        else:
            chunk = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(chunk)))
        self.end_headers()
        self.wfile.write(chunk)


@pytest.fixture
def gcs_adc(monkeypatch):
    """Fake metadata server + Bearer-only GCS endpoint; no HMAC keys."""
    for var in ("GS_ACCESS_KEY_ID", "GS_SECRET_ACCESS_KEY",
                "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
                "S3_ACCESS_KEY", "S3_SECRET_KEY",
                "GOOGLE_APPLICATION_CREDENTIALS"):
        monkeypatch.delenv(var, raising=False)
    FakeMetadataHandler.CALLS = 0
    FakeMetadataHandler.EXPIRES_IN = 3600
    FakeGcsBearerHandler.STORE = {}
    FakeGcsBearerHandler.SAW_AUTH = []
    FakeGcsBearerHandler.ALLOW_ANON = False
    meta = _Server(FakeMetadataHandler)
    gcs = _Server(FakeGcsBearerHandler)
    monkeypatch.setenv("GCE_METADATA_HOST", f"127.0.0.1:{meta.port}")
    monkeypatch.setenv("GCS_ENDPOINT", gcs.url)
    reset_singletons()
    yield meta, gcs
    reset_singletons()
    meta.stop()
    gcs.stop()


def test_gcs_metadata_server_token(gcs_adc):
    meta, gcs = gcs_adc
    FakeGcsBearerHandler.STORE["bkt/data.txt"] = b"adc-bytes"
    fs = FileSystem.get_instance("gs://bkt/data.txt")
    assert isinstance(fs, GCSFileSystem) and fs.signer is None
    r = fs.open("gs://bkt/data.txt", "r")
    assert r.read() == b"adc-bytes"
    r.close()
    assert all(
        a == "Bearer meta-token-1" for a in FakeGcsBearerHandler.SAW_AUTH
    )
    # token is cached across requests: one metadata fetch, many GETs
    fs.get_path_info("gs://bkt/data.txt")
    assert FakeMetadataHandler.CALLS == 1


def test_gcs_metadata_token_refresh_deadlines(gcs_adc):
    import time as time_mod

    from dmlc_core_tpu.io.cloudfs import MetadataServerToken

    # a short-lived token is still reused for half its life (no
    # per-request refetch storm when expires_in counts below the margin)
    FakeMetadataHandler.EXPIRES_IN = 1
    tok = MetadataServerToken()
    assert tok.token() == "meta-token-1"
    assert tok.token() == "meta-token-1"
    assert FakeMetadataHandler.CALLS == 1
    time_mod.sleep(0.6)  # past the soft deadline (ttl/2)
    assert tok.token() == "meta-token-1"
    assert FakeMetadataHandler.CALLS == 2


def test_gcs_stale_token_survives_refresh_hiccup(gcs_adc):
    """A mid-run metadata-server failure must serve the still-valid
    cached token (we refresh early), not kill the job."""
    meta, _ = gcs_adc
    from dmlc_core_tpu.io.cloudfs import MetadataServerToken

    FakeMetadataHandler.EXPIRES_IN = 3600
    tok = MetadataServerToken()
    assert tok.token() == "meta-token-1"
    meta.stop()  # metadata server goes away mid-run
    tok._refresh_at = 0.0  # force a refresh attempt
    assert tok.token() == "meta-token-1"  # stale-but-valid wins


def test_gcs_falls_back_anonymous_off_gce(monkeypatch):
    """No creds + unreachable metadata server → anonymous requests (public
    buckets), with the failed probe cached, not retried per request."""
    for var in ("GS_ACCESS_KEY_ID", "GS_SECRET_ACCESS_KEY",
                "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
                "S3_ACCESS_KEY", "S3_SECRET_KEY",
                "GOOGLE_APPLICATION_CREDENTIALS"):
        monkeypatch.delenv(var, raising=False)
    FakeGcsBearerHandler.STORE = {"pub/obj": b"public"}
    FakeGcsBearerHandler.SAW_AUTH = []
    FakeGcsBearerHandler.ALLOW_ANON = True
    gcs = _Server(FakeGcsBearerHandler)
    # a dead port: connection refused, fast
    monkeypatch.setenv("GCE_METADATA_HOST", "127.0.0.1:9")
    monkeypatch.setenv("GCS_ENDPOINT", gcs.url)
    reset_singletons()
    try:
        fs = FileSystem.get_instance("gs://pub/obj")
        r = fs.open("gs://pub/obj", "r")
        assert r.read() == b"public"
        r.close()
        assert fs._oauth_failed  # probe failure cached
        assert FakeGcsBearerHandler.SAW_AUTH[-1] == ""
    finally:
        reset_singletons()
        gcs.stop()


def test_gcs_adc_checkpoint_lifecycle(gcs_adc, monkeypatch):
    """The TPU-VM deployment story end to end: Checkpointer over gs://
    with metadata-server credentials — save, list, restore, retention
    (DELETEs ride the same Bearer auth)."""
    import numpy as np

    from dmlc_core_tpu.checkpoint import Checkpointer

    meta, gcs = gcs_adc
    # extend the Bearer fake with enough surface for checkpoints
    store = FakeGcsBearerHandler.STORE

    def do_PUT(self):
        if not self._authed():
            return
        src = self.headers.get("x-goog-copy-source")
        if src:
            # server-side copy: the checkpoint tmp-key commit path
            store[self._key()] = store[
                urllib.parse.unquote(src).lstrip("/")
            ]
            out = b"<CopyObjectResult/>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
            return
        n = int(self.headers.get("Content-Length", "0"))
        store[self._key()] = self.rfile.read(n)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if not self._authed():
            return
        store.pop(self._key(), None)
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        if "?" in self.path and "list-type" in self.path:
            if not self._authed():
                return
            # minimal ListObjectsV2 over the flat store
            q = urllib.parse.parse_qs(self.path.split("?", 1)[1])
            prefix = q.get("prefix", [""])[0]
            delim = q.get("delimiter", [""])[0]
            bucket = self.path.lstrip("/").split("?", 1)[0].rstrip("/")
            keys = [k[len(bucket) + 1:] for k in store
                    if k.startswith(f"{bucket}/{prefix}")]
            contents, prefixes = [], set()
            for k in keys:
                rest = k[len(prefix):]
                if delim and delim in rest:
                    prefixes.add(prefix + rest.split(delim, 1)[0] + delim)
                else:
                    contents.append(k)
            body = (
                "<ListBucketResult>"
                + "".join(
                    f"<Contents><Key>{k}</Key><Size>"
                    f"{len(store[f'{bucket}/{k}'])}</Size></Contents>"
                    for k in contents
                )
                + "".join(
                    f"<CommonPrefixes><Prefix>{p}</Prefix></CommonPrefixes>"
                    for p in sorted(prefixes)
                )
                + "<IsTruncated>false</IsTruncated></ListBucketResult>"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        type(self)._plain_get(self)

    monkeypatch.setattr(FakeGcsBearerHandler, "_plain_get",
                        FakeGcsBearerHandler.do_GET, raising=False)
    monkeypatch.setattr(FakeGcsBearerHandler, "do_GET", do_GET)
    monkeypatch.setattr(FakeGcsBearerHandler, "do_PUT", do_PUT,
                        raising=False)
    monkeypatch.setattr(FakeGcsBearerHandler, "do_DELETE", do_DELETE,
                        raising=False)

    ck = Checkpointer("gs://bkt/run", keep=2, process_index=0)
    for s in (1, 2, 3):
        ck.save(s, {"w": np.full(4, s, np.float32)})
    assert ck.steps() == [2, 3]  # retention deleted step 1 over Bearer
    step, tree = ck.restore()
    assert step == 3
    np.testing.assert_array_equal(tree["w"], np.full(4, 3.0))
    # EVERY request rode Bearer auth — no `if a` filter: ALLOW_ANON is
    # False, so an anonymous request is never legitimate here and must
    # fail this assertion, not be exempted from it
    assert FakeGcsBearerHandler.SAW_AUTH
    assert all(
        a == "Bearer meta-token-1" for a in FakeGcsBearerHandler.SAW_AUTH
    )


class FakeTokenEndpointHandler(BaseHTTPRequestHandler):
    """OAuth2 token endpoint verifying the RS256 jwt-bearer assertion
    against the test keypair before minting a token."""

    PUBLIC_KEY = None  # set by the test
    TOKEN = "sa-token-9"
    LAST_CLAIMS = None

    def log_message(self, *a):
        pass

    def do_POST(self):
        import base64 as b64mod

        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        n = int(self.headers.get("Content-Length", "0"))
        form = urllib.parse.parse_qs(self.rfile.read(n).decode())
        assert form["grant_type"] == [
            "urn:ietf:params:oauth:grant-type:jwt-bearer"
        ]
        jwt = form["assertion"][0]
        signing_input, sig_b64 = jwt.rsplit(".", 1)
        pad = "=" * (-len(sig_b64) % 4)
        sig = b64mod.urlsafe_b64decode(sig_b64 + pad)
        # raises InvalidSignature → 500 → test fails, which is the point
        self.PUBLIC_KEY.verify(
            sig, signing_input.encode(), padding.PKCS1v15(), hashes.SHA256()
        )
        claims_b64 = signing_input.split(".")[1]
        pad = "=" * (-len(claims_b64) % 4)
        type(self).LAST_CLAIMS = json.loads(
            b64mod.urlsafe_b64decode(claims_b64 + pad)
        )
        body = json.dumps({
            "access_token": self.TOKEN,
            "expires_in": 3600,
            "token_type": "Bearer",
        }).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_gcs_service_account_jwt(tmp_path, monkeypatch):
    pytest.importorskip("cryptography", reason="cryptography not installed")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()
    sa = {
        "type": "service_account",
        "client_email": "svc@proj.iam.gserviceaccount.com",
        "private_key": pem,
        "token_uri": "http://unused.invalid/token",
    }
    sa_path = tmp_path / "sa.json"
    sa_path.write_text(json.dumps(sa))

    FakeTokenEndpointHandler.PUBLIC_KEY = key.public_key()
    FakeTokenEndpointHandler.LAST_CLAIMS = None
    tok_srv = _Server(FakeTokenEndpointHandler)
    FakeGcsBearerHandler.STORE = {"b/k": b"sa-bytes"}
    FakeGcsBearerHandler.SAW_AUTH = []
    FakeGcsBearerHandler.ALLOW_ANON = False
    FakeGcsBearerHandler.EXPECT_TOKEN = "sa-token-9"
    gcs = _Server(FakeGcsBearerHandler)
    for var in ("GS_ACCESS_KEY_ID", "GS_SECRET_ACCESS_KEY",
                "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
                "S3_ACCESS_KEY", "S3_SECRET_KEY"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS", str(sa_path))
    monkeypatch.setenv("GCS_TOKEN_URI", f"{tok_srv.url}/token")
    monkeypatch.setenv("GCS_ENDPOINT", gcs.url)
    reset_singletons()
    try:
        fs = FileSystem.get_instance("gs://b/k")
        r = fs.open("gs://b/k", "r")
        assert r.read() == b"sa-bytes"
        r.close()
        claims = FakeTokenEndpointHandler.LAST_CLAIMS
        assert claims["iss"] == "svc@proj.iam.gserviceaccount.com"
        assert claims["aud"] == f"{tok_srv.url}/token"
        assert claims["exp"] - claims["iat"] == 3600
        assert "devstorage" in claims["scope"]
    finally:
        reset_singletons()
        FakeGcsBearerHandler.EXPECT_TOKEN = "meta-token-1"
        tok_srv.stop()
        gcs.stop()


# -- webhdfs -----------------------------------------------------------------

@pytest.fixture
def webhdfs(monkeypatch):
    FakeWebHdfsHandler.FILES = {"/data/a.txt": b"alpha\nbeta\ngamma\n"}
    srv = _Server(FakeWebHdfsHandler)
    monkeypatch.setenv("DMLC_WEBHDFS_PORT", str(srv.port))
    reset_singletons()
    yield srv
    reset_singletons()
    srv.stop()


def test_webhdfs_stat_list_read(webhdfs):
    fs = FileSystem.get_instance("hdfs://127.0.0.1:8020/data/a.txt")
    assert isinstance(fs, WebHdfsFileSystem)
    info = fs.get_path_info("hdfs://127.0.0.1:8020/data/a.txt")
    assert info.size == len(b"alpha\nbeta\ngamma\n") and info.type == "file"
    listing = fs.list_directory("hdfs://127.0.0.1:8020/data")
    assert [f.path for f in listing] == ["hdfs://127.0.0.1:8020/data/a.txt"]
    r = fs.open("hdfs://127.0.0.1:8020/data/a.txt", "r")
    assert r.read(5) == b"alpha"
    r.seek(6)
    assert r.read(4) == b"beta"
    r.close()


def test_s3_key_with_special_chars(s3):
    """Keys needing percent-encoding sign correctly (the fake server
    verifies from the wire form, catching double-encoding)."""
    fs = FileSystem.get_instance("s3://bkt/x")
    key_uri = "s3://bkt/dir/my file+v2.txt"
    w = fs.open(key_uri, "w")
    w.write(b"special")
    w.close()
    r = fs.open(key_uri, "r")
    assert r.read() == b"special"


# -- transient-failure retry against the fake servers -------------------------


def test_s3_get_heals_consecutive_5xx(s3):
    """Acceptance: a 3-consecutive-5xx S3 GET succeeds via retry, with
    the healed retries visible in the global counters."""
    from dmlc_core_tpu.io import retry

    payload = bytes(range(256)) * 8
    FakeS3Handler.STORE["bkt/flaky.bin"] = payload
    fs = FileSystem.get_instance("s3://bkt/flaky.bin")
    before = retry.stats()
    FakeS3Handler.FAIL_GET = 3
    r = fs.open("s3://bkt/flaky.bin", "r")
    assert r.read() == payload
    r.close()
    delta = retry.stats_delta(before)
    assert delta["retries"] >= 3
    assert delta["backoff_secs"] > 0


def test_s3_retry_exhaustion_reraises_last_error(s3, monkeypatch):
    """Past the attempt cap the LAST error surfaces (an HTTP 500 here),
    not a generic retry wrapper message."""
    from dmlc_core_tpu.io.retry import HttpError

    monkeypatch.setenv("DMLC_RETRY_ATTEMPTS", "3")
    FakeS3Handler.STORE["bkt/dead.bin"] = b"x"
    fs = FileSystem.get_instance("s3://bkt/dead.bin")
    FakeS3Handler.FAIL_GET = 50  # more than any budget
    with pytest.raises(HttpError, match="HTTP 500") as ei:
        r = fs.open("s3://bkt/dead.bin", "r")
        r.read()
    assert ei.value.status == 500
    assert FakeS3Handler.FAIL_GET >= 40, "attempt cap did not bound retries"


def test_s3_multipart_failed_part_retries_that_part(s3, monkeypatch):
    """Acceptance: a failed multipart part upload re-uploads THE PART
    (same partNumber) and the completed object is byte-identical."""
    from dmlc_core_tpu.io import retry

    monkeypatch.setenv("DMLC_S3_WRITE_BUFFER_BYTES", "1024")
    reset_singletons()
    fs = FileSystem.get_instance("s3://bkt/big2.bin")
    payload = os.urandom(5000)
    before = retry.stats()
    FakeS3Handler.FAIL_PART_PUT = 2
    w = fs.open("s3://bkt/big2.bin", "w")
    w.write(payload)
    w.close()
    assert FakeS3Handler.STORE["bkt/big2.bin"] == payload
    assert retry.stats_delta(before)["retries"] >= 2


def test_s3_server_side_copy(s3):
    FakeS3Handler.STORE["bkt/src key.bin"] = b"copy-me"
    fs = FileSystem.get_instance("s3://bkt/x")
    fs.copy("s3://bkt/src key.bin", "s3://bkt/dst.bin")
    assert FakeS3Handler.STORE["bkt/dst.bin"] == b"copy-me"


def test_s3_atomic_checkpoint_write(s3):
    """checkpoint._write_atomic on a remote URI: tmp key + length verify
    + server-side rename; no .tmp debris after a clean commit."""
    import numpy as np

    from dmlc_core_tpu.checkpoint import _write_atomic, load_pytree

    tree = {"w": np.arange(16, dtype=np.float32)}
    _write_atomic("s3://bkt/ck/model.bin", tree)
    assert "bkt/ck/model.bin" in FakeS3Handler.STORE
    assert "bkt/ck/model.bin.tmp" not in FakeS3Handler.STORE
    out = load_pytree("s3://bkt/ck/model.bin")
    np.testing.assert_array_equal(out["w"], tree["w"])


# -- webhdfs writes -----------------------------------------------------------


def test_webhdfs_write_roundtrip(webhdfs, monkeypatch):
    """The two-step CREATE redirect → datanode PUT, then APPEND parts:
    hdfs:// is no longer read-only (the reference backend writes)."""
    monkeypatch.setenv("DMLC_WEBHDFS_WRITE_BUFFER_BYTES", "1024")
    payload = bytes(range(256)) * 10  # 2560 bytes -> CREATE + 2 APPENDs
    w = Stream.create("hdfs://127.0.0.1:8020/data/out.bin", "w")
    w.write(payload)
    w.close()
    assert FakeWebHdfsHandler.FILES["/data/out.bin"] == payload
    r = Stream.create("hdfs://127.0.0.1:8020/data/out.bin", "r")
    assert r.read() == payload
    r.close()


def test_webhdfs_write_empty_file_lands(webhdfs):
    w = Stream.create("hdfs://127.0.0.1:8020/data/empty.bin", "w")
    w.close()
    assert FakeWebHdfsHandler.FILES["/data/empty.bin"] == b""


def test_webhdfs_rename_and_atomic_checkpoint(webhdfs):
    import numpy as np

    from dmlc_core_tpu.checkpoint import _write_atomic, load_pytree

    fs = FileSystem.get_instance("hdfs://127.0.0.1:8020/x")
    FakeWebHdfsHandler.FILES["/data/a2.txt"] = b"move-me"
    fs.rename(
        "hdfs://127.0.0.1:8020/data/a2.txt",
        "hdfs://127.0.0.1:8020/data/b2.txt",
    )
    assert "/data/a2.txt" not in FakeWebHdfsHandler.FILES
    assert FakeWebHdfsHandler.FILES["/data/b2.txt"] == b"move-me"
    # rename over an existing destination deletes it first (re-save)
    FakeWebHdfsHandler.FILES["/data/c2.txt"] = b"old"
    FakeWebHdfsHandler.FILES["/data/b3.txt"] = b"new"
    fs.rename(
        "hdfs://127.0.0.1:8020/data/b3.txt",
        "hdfs://127.0.0.1:8020/data/c2.txt",
    )
    assert FakeWebHdfsHandler.FILES["/data/c2.txt"] == b"new"
    tree = {"w": np.full(8, 3, dtype=np.int64)}
    _write_atomic("hdfs://127.0.0.1:8020/ck/model.bin", tree)
    assert "/ck/model.bin" in FakeWebHdfsHandler.FILES
    assert "/ck/model.bin.tmp" not in FakeWebHdfsHandler.FILES
    out = load_pytree("hdfs://127.0.0.1:8020/ck/model.bin")
    np.testing.assert_array_equal(out["w"], tree["w"])
