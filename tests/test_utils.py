"""Tests for utils: logging/CHECK, env, common helpers.

Modeled on reference test/unittest/unittest_logging.cc and unittest_env.cc.
"""

import os
import threading

import pytest

from dmlc_core_tpu.utils import (
    Error,
    check,
    check_eq,
    check_lt,
    check_notnull,
    get_env,
    set_env,
    hash_combine,
    split_string,
    log_fatal,
    set_log_sink,
    ThreadException,
)
from dmlc_core_tpu.utils.common import run_parallel


def test_check_raises_error():
    check(True)
    with pytest.raises(Error):
        check(False, "boom")
    with pytest.raises(Error, match="=="):
        check_eq(1, 2)
    check_eq(3, 3)
    with pytest.raises(Error):
        check_lt(5, 5)
    assert check_notnull("x") == "x"
    with pytest.raises(Error):
        check_notnull(None)


def test_log_fatal_raises_and_sink_captures():
    captured = []
    set_log_sink(lambda sev, msg: captured.append((sev, msg)))
    try:
        with pytest.raises(Error, match="die"):
            log_fatal("die")
    finally:
        set_log_sink(None)
    assert captured == [("FATAL", "die")]


def test_typed_env_roundtrip():
    # reference unittest_env.cc pattern: set then typed get
    set_env("DMLC_TPU_TEST_INT", 42)
    assert get_env("DMLC_TPU_TEST_INT", 0) == 42
    set_env("DMLC_TPU_TEST_BOOL", True)
    assert get_env("DMLC_TPU_TEST_BOOL", False) is True
    os.environ["DMLC_TPU_TEST_BOOL"] = "false"
    assert get_env("DMLC_TPU_TEST_BOOL", True) is False
    assert get_env("DMLC_TPU_TEST_MISSING", 1.5) == 1.5
    assert get_env("DMLC_TPU_TEST_INT", "z") == "42"


def test_split_and_hash_combine():
    assert split_string("a,b,,c", ",") == ["a", "b", "", "c"]
    assert split_string("", ",") == []
    h1 = hash_combine(0, 1)
    h2 = hash_combine(h1, 2)
    assert h1 != h2
    assert 0 <= h2 < 2**64


def test_thread_exception_propagates():
    # reference OMPException (common.h:53-87): worker exception rethrown on caller
    def bad():
        raise ValueError("worker died")

    with pytest.raises(ValueError, match="worker died"):
        run_parallel([bad, lambda: None])


def test_thread_exception_first_wins():
    exc = ThreadException()
    order = []

    def fail(tag):
        order.append(tag)
        raise RuntimeError(tag)

    t1 = threading.Thread(target=exc.wrap(fail), args=("a",))
    t1.start()
    t1.join()
    t2 = threading.Thread(target=exc.wrap(fail), args=("b",))
    t2.start()
    t2.join()
    with pytest.raises(RuntimeError, match="a"):
        exc.rethrow()
