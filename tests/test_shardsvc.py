"""Dynamic shard service (tracker/shardsvc.py + io/split.py
DynamicShardSource, docs/sharding.md): ledger exactly-once semantics
with a fake clock, the lease protocol over real tracker sockets, the
worker driver's bit-identity with the static path, heartbeat-ridden
lease renewal, and the chaos drill — a worker killed mid-lease under
``fault://`` with supervisor relaunch, reclaimed micro-shards re-served
exactly once, totals equal to a clean static run."""

import hashlib
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
from dmlc_core_tpu.io.stream import FileStream
from dmlc_core_tpu.tracker import shardsvc
from dmlc_core_tpu.tracker.shardsvc import (
    ShardLeaseClient,
    ShardLedger,
    ShardService,
)
from dmlc_core_tpu.tracker.supervisor import Supervisor
from dmlc_core_tpu.tracker.tracker import RabitTracker
from dmlc_core_tpu.utils.logging import Error

N_ROWS = 3000


@pytest.fixture
def corpus(tmp_path):
    rec = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi)
        for i in range(N_ROWS):
            w.write_record(b"%06d|" % i + b"p" * 25, i)
        w.flush_block()
    return rec, idx


@pytest.fixture
def tracker(monkeypatch):
    """A live tracker whose shard service the env points at."""
    monkeypatch.setenv("DMLC_SHARD_OVERSPLIT", "4")
    t = RabitTracker("127.0.0.1", 1)
    t.start(1)
    monkeypatch.setenv("DMLC_TRACKER_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_TRACKER_PORT", str(t.port))
    monkeypatch.setenv("DMLC_TASK_ID", "0")
    # a RabitWorker.start() elsewhere in this process binds the lease
    # identity to ITS rendezvous rank — don't let it leak in here
    monkeypatch.delenv("DMLC_SHARD_RANK", raising=False)
    yield t
    t.close()


def drain_sha(split, gather=False, batch=512):
    """(rows, sha256) of a split's full emission, in emission order."""
    h = hashlib.sha256()
    rows = 0
    if gather:
        while True:
            g = split.next_gather_batch(batch)
            if g is None:
                break
            buf, starts, sizes = g
            flat = buf.reshape(-1) if buf.ndim > 1 else buf
            for s, z in zip(starts.tolist(), sizes.tolist()):
                h.update(flat[s : s + z].tobytes())
            rows += len(starts)
    else:
        while True:
            rec = split.next_record()
            if rec is None:
                break
            h.update(rec)
            rows += 1
    return rows, h.hexdigest()


# -- ledger unit (fake clock) --------------------------------------------------

def test_ledger_grant_done_exactly_once():
    led = ShardLedger(epoch=0, n_shards=4)
    now = 100.0
    leases = [led.grant(0, now, ttl=10.0) for _ in range(4)]
    assert sorted(l.shard for l in leases) == [0, 1, 2, 3]
    assert led.grant(0, now, ttl=10.0) is None  # everything leased
    assert not led.complete()
    for l in leases:
        status, secs = led.record_done(l.shard, 0, now + 1.0)
        assert status == "recorded" and secs == 1.0
    assert led.complete()
    assert led.record_done(2, 1, now + 2.0) == ("duplicate", None)
    assert led.duplicates == 1


def test_ledger_rejects_done_for_never_granted_shard():
    # a done with no grant history (not leased, never reclaimed) is a
    # client bug; accepting it would mark undrained data complete
    led = ShardLedger(epoch=0, n_shards=4)
    led.grant(0, 100.0, ttl=10.0)  # shard 0 leased, 1-3 still queued
    with pytest.raises(ValueError, match="never granted"):
        led.record_done(1, 0, 101.0)
    assert not led.done and led.queue_depth() == 3


def test_ledger_expiry_reclaim_and_steal():
    led = ShardLedger(epoch=0, n_shards=2)
    l0 = led.grant(0, 100.0, ttl=5.0)
    led.grant(1, 100.0, ttl=5.0)
    # rank 1 renews, rank 0 goes silent
    assert led.renew_rank(1, 104.0, ttl=5.0) == 1
    assert led.reclaim_expired(106.0) == [l0.shard]
    assert led.reclaimed == 1
    # the reclaimed shard is re-granted FIRST (queue front), to a
    # different rank → stolen
    l0b = led.grant(1, 106.0, ttl=5.0)
    assert l0b.shard == l0.shard and led.stolen == 1
    # the original (dead-slow but alive) holder finishes first: first
    # completion wins, the thief's later done is the duplicate
    assert led.record_done(l0.shard, 0, 107.0)[0] == "recorded"
    assert led.record_done(l0.shard, 1, 108.0)[0] == "duplicate"


def test_ledger_never_regrants_a_completed_shard():
    """A reclaimed holder finishing LATE marks the shard done while
    its queue entry survives — the next grant must discard it, never
    hand a full lease on an already-committed shard (a thief would
    re-emit every record, not just duplicate the accounting)."""
    led = ShardLedger(epoch=0, n_shards=2)
    l0 = led.grant(0, 100.0, ttl=5.0)
    # rank 0 stalls past the TTL: shard back on the queue front
    assert led.reclaim_expired(106.0) == [l0.shard]
    assert led.queue_depth() == 2
    # ...then finishes anyway (first finisher wins, shard still queued)
    assert led.record_done(l0.shard, 0, 107.0)[0] == "recorded"
    # the next two grants must be the OTHER shard, then nothing
    l1 = led.grant(1, 107.0, ttl=5.0)
    assert l1 is not None and l1.shard != l0.shard
    assert led.grant(1, 107.0, ttl=5.0) is None
    assert led.record_done(l1.shard, 1, 108.0)[0] == "recorded"
    assert led.complete()


def test_ledger_voluntary_release():
    led = ShardLedger(epoch=0, n_shards=2)
    l0 = led.grant(0, 100.0, ttl=30.0)
    # only the holder can release; a stranger's release is a no-op
    assert not led.release(l0.shard, rank=1)
    assert led.release(l0.shard, rank=0)
    assert led.queue_depth() == 2 and led.reclaimed == 1
    # released = reclaimed semantics: re-grant to another rank = stolen
    l0b = led.grant(1, 101.0, ttl=30.0)
    assert l0b.shard == l0.shard and l0b.stolen
    # a done shard can't be released back out of the ledger
    assert led.record_done(l0.shard, 1, 102.0)[0] == "recorded"
    assert not led.release(l0.shard, rank=1)


def test_ledger_reclaim_rank_immediate():
    led = ShardLedger(epoch=0, n_shards=4)
    for _ in range(2):
        led.grant(0, 100.0, ttl=30.0)
    led.grant(1, 100.0, ttl=30.0)
    shards = led.reclaim_rank(0)
    assert len(shards) == 2 and led.queue_depth() == 1 + 2
    # rank 1's lease untouched
    assert len(led.leases) == 1


def test_service_wait_then_done_and_renew_semantics():
    clock = [1000.0]
    svc = ShardService(n_workers=1, oversplit=2, ttl=8.0, clock=lambda: clock[0])
    a = svc.lease(0, 0, None)
    b = svc.lease(0, 0, None)
    assert {a["status"], b["status"]} == {"lease"}
    w = svc.lease(0, 0, None)
    assert w["status"] == "wait" and 0.05 <= w["backoff"] <= 1.0
    assert svc.renew(0, 0)["renewed"] == 2
    assert svc.done(0, 0, a["shard"])["status"] == "recorded"
    assert svc.done(0, 0, b["shard"])["epoch_complete"] is True
    assert svc.lease(0, 0, None)["status"] == "done"
    # a new epoch is a fresh ledger
    assert svc.lease(0, 1, None)["status"] == "lease"
    # renewing leases that already expired reports them lost
    clock[0] += 100.0
    assert svc.renew(0, 1)["status"] == "lost"


def test_service_rejects_stale_dataset_done_after_switch():
    """Epoch numbers restart at a dataset switch, so a straggler's
    done/release from the OLD dataset carries shard numbers that land
    on the NEW ledger — the fileset signature riding the request is
    what keeps them off it (undrained validation data must never be
    marked complete by a late train worker)."""
    svc = ShardService(n_workers=1, oversplit=2, ttl=30.0)
    a = svc.lease(0, 0, "train")
    b = svc.lease(0, 0, "train")
    assert svc.done(0, 0, a["shard"], "train")["status"] == "recorded"
    assert svc.done(0, 0, b["shard"], "train")["status"] == "recorded"
    # train drained: the next signature switches the dataset
    v = svc.lease(0, 0, "val")
    assert v["status"] == "lease"
    # a train straggler's done/release for the val-leased shard: rejected
    stale = svc.done(0, 0, v["shard"], "train")
    assert stale["status"] == "error" and "dataset switch" in stale["error"]
    rel = svc.release(0, 0, v["shard"], "train")
    assert rel["status"] == "error"
    assert svc._epochs[0].leases  # val lease untouched
    # the val worker's own done (current signature) still lands
    assert svc.done(0, 0, v["shard"], "val")["status"] == "recorded"


def test_service_all_complete_gates_partial_epochs():
    """all_complete() is submit's downgrade gate for shard-only jobs:
    False before any work, False while a live ledger has undrained
    shards (workers exiting 0 mid-epoch stay a loud verdict), True only
    once every live ledger is fully accounted."""
    svc = ShardService(n_workers=1, oversplit=2, ttl=30.0)
    assert not svc.all_complete()  # no shard work happened at all
    a = svc.lease(0, 0, None)
    assert not svc.all_complete()  # partial epoch
    assert svc.done(0, 0, a["shard"])["status"] == "recorded"
    assert not svc.all_complete()  # one shard still queued
    b = svc.lease(0, 0, None)
    assert svc.done(0, 0, b["shard"])["status"] == "recorded"
    assert svc.all_complete()


def test_service_ledger_eviction_never_orphans_live_work():
    """Two eviction holes: (a) an epoch BEHIND the live window must be
    refused, not created-then-evicted in the same call (grant() would
    hand out leases whose dones can never land); (b) advancing the
    window must never evict a ledger with live leaseholders (their
    renews/dones would hit a vanished ledger)."""
    clock = [1000.0]
    svc = ShardService(
        n_workers=1, oversplit=1, ttl=30.0, clock=lambda: clock[0]
    )
    # fill the live window: epochs 1..keep_epochs, one live lease each
    for ep in range(1, svc.keep_epochs + 1):
        assert svc.lease(0, ep, None)["status"] == "lease"
    # (a) behind the window: loud error, no orphaned grant
    assert svc.lease(0, 0, None)["status"] == "error"
    # (b) ahead of the window: evicting epoch 1 would strand its live
    # leaseholder, so the newcomer's epoch is refused instead
    assert svc.lease(1, svc.keep_epochs + 1, None)["status"] == "error"
    # epoch 1's holder is untouched — its done still lands...
    shard = next(iter(svc._epochs[1].leases))
    assert svc.done(0, 1, shard)["status"] == "recorded"
    # ...and with the oldest ledger complete the window advances again
    assert svc.lease(1, svc.keep_epochs + 1, None)["status"] == "lease"


def test_service_handle_is_unkillable():
    svc = ShardService(n_workers=2, oversplit=1)
    # negative rank = protocol placeholder, never a lease holder
    assert json.loads(svc.handle("shard_lease", -1, "{}"))["status"] == "error"
    assert json.loads(svc.handle("shard_lease", 0, "not json"))["status"] == (
        "error"
    )
    assert json.loads(svc.handle("shard_done", 0, "{}"))["status"] == "error"
    assert json.loads(svc.handle("shard_lease", 0, "[1,2]"))["status"] == (
        "error"
    )
    ok = json.loads(svc.handle("shard_lease", 0, '{"epoch": 0}'))
    assert ok["status"] == "lease" and ok["num_shards"] == 2
    # a rank ABOVE n_workers joined mid-epoch: geometry is already
    # pinned, the newcomer just drains the queue (elastic join,
    # docs/sharding.md)
    ok = json.loads(svc.handle("shard_lease", 7, '{"epoch": 0}'))
    assert ok["status"] == "lease" and ok["num_shards"] == 2


# -- wire protocol over a real tracker ----------------------------------------

def test_lease_protocol_end_to_end(tracker):
    c = ShardLeaseClient("127.0.0.1", tracker.port, rank=0)
    seen = []
    while True:
        r = c.lease(0, fileset="sig")
        if r["status"] != "lease":
            break
        seen.append(r["shard"])
        assert r["num_shards"] == 4 and r["ttl"] > 0
    assert sorted(seen) == [0, 1, 2, 3]
    assert c.renew(0)["status"] == "ok"
    for s in seen:
        assert c.done(0, s)["status"] == "recorded"
    assert c.lease(0, fileset="sig")["status"] == "done"
    # every live ledger drained: a NEW signature is a sequential dataset
    # switch (train → validation) — epochs and geometry start fresh
    r = c.lease(0, fileset="other")
    assert r["status"] == "lease"
    # ...but with that lease outstanding the ledger is incomplete, so a
    # third signature means concurrent different datasets: loud error
    assert c.lease(0, fileset="third")["status"] == "error"
    c.release(0, r["shard"])
    # end-of-job report carries the shard shape
    tracker.close()
    tracker.join()
    assert tracker.metrics_report is not None
    assert tracker.metrics_report["shards"]["completed"] == 4


def test_heartbeat_renews_leases(tracker):
    c = ShardLeaseClient("127.0.0.1", tracker.port, rank=0)
    r = c.lease(0)
    assert r["status"] == "lease"
    led = tracker.shards._epochs[0]
    before = led.leases[r["shard"]].expires
    time.sleep(0.05)
    # a metrics heartbeat (NOT an explicit renew) must extend the lease
    from dmlc_core_tpu.tracker.client import RabitWorker

    w = RabitWorker("127.0.0.1", tracker.port)
    w.rank = 0  # heartbeat() requires an assigned rank
    w.heartbeat({"counters": {}, "gauges": {}, "histograms": {}})
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if led.leases[r["shard"]].expires > before:
            break
        time.sleep(0.01)
    assert led.leases[r["shard"]].expires > before


# -- DynamicShardSource driver -------------------------------------------------

@pytest.mark.parametrize("mode,gather", [("record", True), ("", False)])
def test_dynamic_drain_bit_identical_to_static(tracker, corpus, mode, gather):
    """Dynamic placement must not change shard content: a one-worker
    dynamic drain (leases arrive in shard order 0..M-1) equals the
    concatenation of static ``(i, M)`` drains bit-for-bit — shuffled
    (per-shard (seed, epoch) permutation) AND sequential."""
    rec, idx = corpus
    q = f"?index={idx}&seed=5" + (f"&shuffle={mode}" if mode else "")
    src = io_split.create(rec + q + "&dynamic_shards=1",
                          type="recordio", threaded=False)
    assert src.supports_gather() == gather
    rows, sha = drain_sha(src, gather=gather)
    M = src.num_shards
    stats = src.io_stats()
    src.close()
    assert rows == N_ROWS
    assert stats["leases"] == M and stats["shards_recorded"] == M
    assert stats["mode"].startswith("dynamic:")
    # static reference: the same M parts drained in order through the
    # same emission path, hashed as one stream
    h = hashlib.sha256()
    total = 0
    for i in range(M):
        sp = io_split.create(rec + q, type="recordio", part_index=i,
                             num_parts=M, threaded=False)
        if gather:
            while True:
                g = sp.next_gather_batch(512)
                if g is None:
                    break
                buf, starts, sizes = g
                flat = buf.reshape(-1) if buf.ndim > 1 else buf
                for s, z in zip(starts.tolist(), sizes.tolist()):
                    h.update(flat[s : s + z].tobytes())
                total += len(starts)
        else:
            while True:
                r = sp.next_record()
                if r is None:
                    break
                h.update(r)
                total += 1
        sp.close()
    assert total == N_ROWS
    assert h.hexdigest() == sha, "dynamic emission diverged from static"


def test_dynamic_threaded_wraps_per_shard_readahead(tracker, corpus):
    """``threaded=True`` (the default) gives each leased non-windowed
    micro-shard the same ThreadedInputSplit a static drain would get,
    and the drain stays bit-identical to the bare path."""
    from dmlc_core_tpu.io.split import ThreadedInputSplit

    rec, idx = corpus
    uri = rec + f"?index={idx}&dynamic_shards=1"
    src = io_split.create(uri, type="recordio", threaded=True)
    # the probe (never read) must stay bare — an eager read-ahead
    # thread on it would drain the whole set in the background
    assert not isinstance(src._get_probe(), ThreadedInputSplit)
    shard0 = src._make_splitter(0, 1, 0)
    assert isinstance(shard0, ThreadedInputSplit)
    shard0.close()
    rows, sha = drain_sha(src)
    src.close()
    src2 = io_split.create(uri, type="recordio", threaded=False)
    src2.epoch = 1  # fresh ledger; same content (no shuffle)
    rows2, sha2 = drain_sha(src2)
    src2.close()
    assert rows == rows2 == N_ROWS and sha == sha2


def test_two_workers_split_the_epoch_exactly_once(corpus, monkeypatch):
    """Two concurrent drivers (distinct ranks) over one ledger: every
    record exactly once across them, commits exactly-once per
    micro-shard."""
    rec, idx = corpus
    monkeypatch.setenv("DMLC_SHARD_OVERSPLIT", "4")
    t = RabitTracker("127.0.0.1", 2)
    t.start(2)
    results = {}
    recorded = []
    lock = threading.Lock()

    def one(rank):
        client = ShardLeaseClient("127.0.0.1", t.port, rank=rank)
        src = io_split.DynamicShardSource(
            make_splitter=lambda shard, M, ep: io_split.IndexedRecordIOSplitter(
                rec, idx, shard, M, shuffle="record", seed=9, epoch=ep,
            ),
            client=client,
            windowed_hint=True,
        )

        def on_done(shard, status):
            with lock:
                recorded.append((shard, status))

        src.on_shard_done = on_done
        rows, _sha = drain_sha(src, gather=True)
        results[rank] = rows
        src.close()

    try:
        threads = [threading.Thread(target=one, args=(r,)) for r in range(2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    finally:
        t.close()
    assert sum(results.values()) == N_ROWS
    statuses = [s for _, s in recorded]
    assert statuses.count("recorded") == 8 == len(statuses)
    assert sorted(s for s, _ in recorded) == list(range(8))


def test_epoch_advance_and_fresh_ledger(tracker, corpus):
    rec, idx = corpus
    uri = f"{rec}?index={idx}&shuffle=record&seed=2&dynamic_shards=1"
    src = io_split.create(uri, type="recordio", threaded=False)
    r0, sha0 = drain_sha(src, gather=True)
    src.before_first()
    r1, sha1 = drain_sha(src, gather=True)
    src.close()
    assert r0 == r1 == N_ROWS
    assert sha0 != sha1  # different epoch → different permutation
    assert tracker.shards.summary()["completed"] == 8


def test_create_sugar_and_guards(tracker, corpus):
    rec, idx = corpus
    # reset_partition is a static-placement concept
    src = io_split.create(f"{rec}?index={idx}&dynamic_shards=1",
                          type="recordio", threaded=False)
    with pytest.raises(Error):
        src.reset_partition(0, 2)
    # whole-set introspection works without a lease
    assert src.total_size() == os.path.getsize(rec)
    src.close()
    # skip_records needs static sharding
    with pytest.raises(Error):
        io_split.create(
            f"{rec}?index={idx}&dynamic_shards=1&skip_records=8",
            type="recordio", threaded=False,
        )


def test_close_releases_live_lease_immediately(tracker, corpus):
    """close() with an unfinished shard hands the lease back via
    cmd=shard_release — a peer leases it NOW, without waiting out a
    TTL (which heartbeats could extend forever)."""
    rec, idx = corpus
    uri = f"{rec}?index={idx}&shuffle=record&seed=3&dynamic_shards=1"
    src = io_split.create(uri, type="recordio", threaded=False)
    assert src.next_record() is not None  # live lease on one shard
    held = src.current_shard
    src.close()
    s = tracker.shards.summary()
    assert s["reclaimed"] == 1 and s["queue_depth"] == s["n_shards"]
    # a peer drains the whole epoch, including the released shard
    peer = io_split.create(uri, type="recordio", threaded=False)
    rows, _ = drain_sha(peer)
    stats = peer.io_stats()
    peer.close()
    assert rows == N_ROWS and stats["shards_recorded"] == s["n_shards"]
    assert held in range(s["n_shards"])


def test_fileset_signature_normalizes_local_uri_forms(corpus, monkeypatch):
    """file:///d/x.rec, /d/x.rec and a fault://-wrapped /d/x.rec are
    the SAME dataset: their fileset signatures must agree or the chaos
    topology (one wrapped worker among clean peers) gets the hard
    'not reading the same dataset' error."""
    rec, idx = corpus
    seen = []

    class _Probe:
        def __init__(self):
            self.rank = 0

        def lease(self, epoch, fileset=None):
            seen.append(fileset)
            return {"status": "done"}

    for form in (
        f"{rec}?index={idx}&dynamic_shards=1",
        f"file://{rec}?index={idx}&dynamic_shards=1",
        f"fault://latency_ms=1,seed=5{rec}?index={idx}&dynamic_shards=1",
    ):
        monkeypatch.setenv("DMLC_TRACKER_URI", "127.0.0.1")
        monkeypatch.setenv("DMLC_TRACKER_PORT", "1")  # never dialed
        src = io_split.create(form, type="recordio", threaded=False)
        src._client = _Probe()
        assert src.next_record() is None  # probe answers done
        src.close()
    assert len(seen) == 3 and len(set(seen)) == 1, seen


def test_create_without_tracker_fails_loudly(corpus, monkeypatch):
    rec, idx = corpus
    monkeypatch.delenv("DMLC_TRACKER_URI", raising=False)
    monkeypatch.delenv("DMLC_TRACKER_PORT", raising=False)
    with pytest.raises(Error, match="DMLC_TRACKER_URI"):
        io_split.create(f"{rec}?index={idx}&dynamic_shards=1",
                        type="recordio", threaded=False)


def test_supervisor_hook_reclaims_leases(tracker):
    c = ShardLeaseClient("127.0.0.1", tracker.port, rank=0)
    assert c.lease(0)["status"] == "lease"
    assert c.lease(0)["status"] == "lease"
    # the supervisor's on_task_failure target resolves the live service
    shardsvc.reclaim_task(0, "localhost")
    assert tracker.shards.summary()["reclaimed"] == 2
    assert tracker.shards.summary()["queue_depth"] == 4


def test_reclaim_task_translates_task_id_to_rank(tracker):
    """Rendezvous ranks are connect-order, not task ids: the tracker
    feeds the translation at rank assignment, so a task-keyed
    supervisor reclaim lands on the rank that holds the leases."""
    # task "3" rendezvoused and was assigned rank 1; its leases are
    # held by rank 1
    tracker.shards.note_task_rank("3", 1)
    c = ShardLeaseClient("127.0.0.1", tracker.port, rank=1)
    assert c.lease(0)["status"] == "lease"
    # a peer (task 0 == rank 0) holds its own lease — must survive
    peer = ShardLeaseClient("127.0.0.1", tracker.port, rank=0)
    assert peer.lease(0)["status"] == "lease"
    shardsvc.reclaim_task(3, "localhost")
    assert tracker.shards.summary()["reclaimed"] == 1
    led = tracker.shards._epochs[0]
    assert [l.rank for l in led.leases.values()] == [0]


def test_lease_client_repins_rank_from_env(tracker, monkeypatch):
    """A client constructed BEFORE RabitWorker.start() must not freeze
    the pre-rendezvous task id: the defaulted rank is re-read at every
    new lease, so the first lease after start() carries the rendezvous
    rank the heartbeat renews by."""
    monkeypatch.setenv("DMLC_TASK_ID", "0")
    c = ShardLeaseClient("127.0.0.1", tracker.port)  # defaulted rank
    assert c.rank == 0
    monkeypatch.setenv("DMLC_SHARD_RANK", "5")  # start() ran
    assert c.lease(0)["status"] == "lease"
    assert c.rank == 5
    led = tracker.shards._epochs[0]
    assert [l.rank for l in led.leases.values()] == [5]
    # an explicit rank never re-pins
    c2 = ShardLeaseClient("127.0.0.1", tracker.port, rank=2)
    assert c2.lease(0)["status"] == "lease"
    assert c2.rank == 2


def test_summary_counts_evicted_epochs():
    """Whole-job accounting must survive the keep_epochs ledger cap:
    counters from evicted ledgers fold into retired totals instead of
    silently vanishing from the end-of-job report."""
    clk = [100.0]
    svc = ShardService(1, oversplit=2, ttl=30.0, clock=lambda: clk[0])
    n_epochs = ShardService.keep_epochs + 4
    for ep in range(n_epochs):
        for _ in range(2):
            resp = svc.lease(0, ep, None)
            assert resp["status"] == "lease"
            clk[0] += 1.0
            assert svc.done(0, ep, resp["shard"])["status"] == "recorded"
    s = svc.summary()
    assert s["epochs_retired"] == 4
    assert len(s["epochs"]) == ShardService.keep_epochs
    assert s["granted"] == 2 * n_epochs
    assert s["completed"] == 2 * n_epochs
    assert s["reclaimed"] == 0 and s["duplicates"] == 0


SHARD_ONLY_WORKER = """\
import os, sys
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.io import split as io_split

src = io_split.create({uri!r}, type="recordio", threaded=False)
n = 0
while src.next_record() is not None:
    n += 1
src.close()
print("drained", n, flush=True)
"""


def test_submit_shard_only_job_finishes_clean(corpus, tmp_path):
    """A payload that speaks ONLY the shard-lease protocol (no rabit
    rendezvous — the docs/sharding.md quick-start shape) must exit the
    local backend cleanly: the anti-wedge heuristic's typed verdict
    (RendezvousNeverCompleted) is downgraded to a clean finish when the
    tracker's shard service did the job's accounting."""
    rec, idx = corpus
    script = tmp_path / "worker.py"
    uri = f"{rec}?index={idx}&shuffle=record&seed=2&dynamic_shards=1"
    script.write_text(SHARD_ONLY_WORKER.format(repo=REPO, uri=uri))
    env = os.environ.copy()
    env.update({
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "DMLC_RENDEZVOUS_GRACE": "1",
        "DMLC_SHARD_OVERSPLIT": "2",
    })
    for k in ("DMLC_TRACKER_URI", "DMLC_TRACKER_PORT"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
         "--cluster", "local", "--num-workers", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    drained = sum(
        int(line.split()[-1])
        for line in proc.stdout.splitlines()
        if line.startswith("drained")
    )
    assert drained == N_ROWS
    assert "finished via the shard service" in proc.stderr


# -- chaos: kill a leaseholder mid-epoch --------------------------------------

CHAOS_WORKER = """\
import hashlib, json, os, sys
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.io import split as io_split

out = {out!r}
task = os.environ["DMLC_TASK_ID"]
attempt = int(os.environ.get("DMLC_NUM_ATTEMPT", "0"))
src = io_split.create({uri!r}, type="recordio", threaded=False)
cur = {{}}

def on_lease(shard, num_shards):
    cur["shard"], cur["h"], cur["rows"] = shard, hashlib.sha256(), 0

def on_done(shard, status):
    # commit ONLY on the exactly-once ack: this is the accounting the
    # ledger guarantees cluster-wide
    if status == "recorded":
        p = os.path.join(out, "shard_%d.json" % shard)
        tmp = p + ".tmp%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump({{"rows": cur["rows"], "sha": cur["h"].hexdigest(),
                       "task": task, "attempt": attempt}}, f)
        os.replace(tmp, p)

src.on_lease = on_lease
src.on_shard_done = on_done
n = 0
while True:
    rec = src.next_record()
    if rec is None:
        break
    cur["h"].update(rec)
    cur["rows"] += 1
    n += 1
    if task == "0" and attempt == 0 and n >= 37:
        # die MID-LEASE: a partially drained micro-shard is in flight
        os._exit(9)
src.close()
"""


def test_chaos_kill_mid_lease_exactly_once(corpus, tmp_path, monkeypatch):
    """The acceptance drill: 3 workers drain under ``fault://`` chaos,
    one is killed mid-lease; the supervisor's failure hook reclaims its
    lease, the relaunched worker (plus thieves) completes the epoch,
    every micro-shard is committed EXACTLY once, and the committed
    totals equal a clean static run shard-for-shard."""
    rec, idx = corpus
    monkeypatch.setenv("DMLC_SHARD_LEASE_TTL", "2.0")
    monkeypatch.setenv("DMLC_SHARD_OVERSPLIT", "4")
    tracker = RabitTracker("127.0.0.1", 3)
    tracker.start(3)
    out = tmp_path / "out"
    out.mkdir()
    # fault:// chaos on the data path: seeded resets healed by the
    # retry layer while leases move around
    uri = (
        f"fault://resets=1,seed=11{rec}?index={idx}"
        f"&shuffle=record&seed=4&dynamic_shards=1"
    )
    script = tmp_path / "worker.py"
    script.write_text(CHAOS_WORKER.format(repo=REPO, out=str(out), uri=uri))

    def launch(task_id, host, attempt):
        env = os.environ.copy()
        env.update({
            "DMLC_TRACKER_URI": "127.0.0.1",
            "DMLC_TRACKER_PORT": str(tracker.port),
            "DMLC_TASK_ID": str(task_id),
            "DMLC_NUM_ATTEMPT": str(attempt),
            "JAX_PLATFORMS": "cpu",
        })
        return subprocess.Popen([sys.executable, str(script)], env=env)

    sup = Supervisor(
        launch, hosts=["localhost"], max_attempt=3,
        host_fail_limit=float("inf"), relaunch_backoff=0.1,
        on_task_failure=shardsvc.reclaim_task,
    )
    try:
        sup.run(3)
    finally:
        summary = tracker.shards.summary()
        tracker.close()
    M = summary["n_shards"]
    files = sorted(out.glob("shard_*.json"))
    assert len(files) == M, f"committed {len(files)}/{M} micro-shards"
    committed = {
        int(f.name.split("_")[1].split(".")[0]): json.loads(f.read_text())
        for f in files
    }
    # the victim held a lease when it died: reclaimed >= 1, and the
    # epoch still completed exactly-once
    assert sup.relaunches >= 1
    assert summary["reclaimed"] >= 1
    assert summary["completed"] == M
    # clean static reference, shard for shard
    total = 0
    for i in range(M):
        sp = io_split.create(
            f"{rec}?index={idx}&shuffle=record&seed=4",
            type="recordio", part_index=i, num_parts=M, threaded=False,
        )
        rows, sha = drain_sha(sp)
        sp.close()
        total += rows
        assert committed[i]["rows"] == rows, f"shard {i} row count"
        assert committed[i]["sha"] == sha, f"shard {i} bytes"
    assert sum(c["rows"] for c in committed.values()) == total == N_ROWS
